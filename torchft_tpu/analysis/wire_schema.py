"""Wire-schema extractor + drift lint (tft-verify leg 2, pass id
``wire-drift``).

The framed-JSON coordination protocol is implemented three times: the
Python clients (``torchft_tpu/coordination.py``), the native servers
(``native/lighthouse.cc`` / ``manager.cc`` / ``store.cc``), and the prose
in ``docs/protocol.md``.  Nothing kept them in sync until now — a field
renamed on one side silently degrades to its wire default on the other
(every ``from_dict``/``Json::get`` read is total), which is exactly the
failure mode that never shows up in unit tests.

This pass extracts each side into one canonical schema:

* **Python** — ``ast`` over the client classes: every
  ``self._client.call("method", {...})`` site yields the method's param
  names + types (from dict literals, ``params["k"] = v`` build-up, and
  the enclosing signature's annotations); ``result["k"]`` subscripts and
  ``Struct.from_dict(result)`` yield the result fields the client relies
  on; ``to_dict``/``from_dict`` dataclasses yield the shared structs.
* **Native** — a dispatch-aware scan of the ``.cc`` sources: each
  ``method == "name"`` arm is resolved to its handler body (brace
  matching), where ``params.get("k").as_T()`` reads give params + types
  and ``out["k"] = ...`` writes give result fields;
  ``Struct::to_json``/``from_json`` give the native struct surface; the
  native manager's own lighthouse calls (``client.call("m", params)``)
  are checked as a third client.
* **Docs** — the "Wire surface" table in ``docs/protocol.md`` must carry
  one ``| server | method |`` row per method.

The merged schema is written to ``torchft_tpu/analysis/protocol.lock``
(committed, shipped as package data) by ``tft-verify --write-lock``; the
lint then reports missing/dead/mistyped fields, undocumented methods,
and any divergence between the tree and the committed lock.
``tests/test_wire_schema.py`` generates round-trip conformance tests
from the lock file and seeds a drift on every side to prove the gate
bites.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Any, Dict, Iterable, Iterator, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import Finding, LintPass, Project, SelftestError

__all__ = [
    "PASS",
    "LOCK_VERSION",
    "WIRE_FRAMING",
    "extract_python",
    "extract_native",
    "build_lock",
    "lock_path",
    "load_lock",
    "run_checks",
]

PASS_ID = "wire-drift"

LOCK_VERSION = 1

#: One-line framing contract, embedded in the lock so a framing change is
#: itself a lock drift (coordination.py module docstring + native/net.h).
WIRE_FRAMING = (
    "4-byte big-endian length + UTF-8 JSON; request "
    '{"method","params","timeout_ms","traceparent"?}; reply '
    '{"ok","result"} | {"ok","error","code"?}; max frame 512 MiB'
)

#: canonical wire types
_TYPES = ("string", "int", "bool", "double", "object", "array", "any")

#: Python client class -> server name it speaks to
_CLIENT_SERVERS = {
    "LighthouseClient": "lighthouse",
    "ManagerClient": "manager",
    "StoreClient": "store",
}

#: native source file -> server whose dispatch it holds
_NATIVE_SERVERS = {
    "lighthouse.cc": "lighthouse",
    "manager.cc": "manager",
    "store.cc": "store",
}

#: shared struct names (Python dataclasses with to_dict/from_dict,
#: native StructName::to_json/from_json)
_STRUCTS = ("QuorumMember", "Quorum", "QuorumResult")


# ---------------------------------------------------------------------------
# schema model (plain dicts so the lock is trivially JSON)
# ---------------------------------------------------------------------------
#
# servers: {server: {method: {"params": {name: type}, "result": [name],
#                             "result_struct": str|None}}}
# structs: {name: {field: type}}

Schema = Dict[str, Any]


def _empty_schema() -> Schema:
    return {"servers": {}, "structs": {}}


def _method(schema: Schema, server: str, method: str) -> Dict[str, Any]:
    srv = schema["servers"].setdefault(server, {})
    return srv.setdefault(
        method, {"params": {}, "result": [], "result_struct": None}
    )


# ---------------------------------------------------------------------------
# Python extraction
# ---------------------------------------------------------------------------


def _canon_annotation(text: str) -> str:
    """Canonical wire type for a Python annotation (best effort)."""
    t = text.strip().strip("\"'")
    # containers first: List[int] is an array, not an int
    if re.search(r"\b(Dict|dict|Mapping)\b", t):
        return "object"
    if re.search(r"\b(List|list|Sequence|Tuple|tuple)\b", t):
        return "array"
    if re.search(r"\bbool\b", t):
        return "bool"
    if re.search(r"\bint\b", t):
        return "int"
    if re.search(r"\bfloat\b", t):
        return "double"
    if re.search(r"\bstr\b", t):
        return "string"
    return "any"


def _annotation_text(node: Optional[ast.AST]) -> str:
    if node is None:
        return ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.10+
        return ""


def _value_type(node: ast.AST, arg_types: Dict[str, str]) -> str:
    """Canonical wire type of a param-value expression."""
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool):
            return "bool"
        if isinstance(node.value, int):
            return "int"
        if isinstance(node.value, float):
            return "double"
        if isinstance(node.value, str):
            return "string"
        return "any"
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            return {
                "int": "int",
                "bool": "bool",
                "float": "double",
                "str": "string",
                "dict": "object",
                "list": "array",
            }.get(fn.id, "any")
        if isinstance(fn, ast.Attribute):
            if fn.attr == "to_dict":
                return "object"
            if fn.attr == "dumps":
                return "string"
        return "any"
    if isinstance(node, ast.Name):
        return arg_types.get(node.id, "any")
    if isinstance(node, ast.Dict):
        return "object"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "array"
    return "any"


def _is_rpc_call(node: ast.Call) -> bool:
    """``<something>.call("method", params, ...)`` with a literal method."""
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "call"
        and len(node.args) >= 2
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    )


def extract_python(source: str, filename: str = "coordination.py") -> Schema:
    """Schema seen by the Python clients in ``source``."""
    schema = _empty_schema()
    tree = ast.parse(source, filename=filename)

    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        if cls.name in _STRUCTS:
            _extract_py_struct(schema, cls)
        server = _CLIENT_SERVERS.get(cls.name)
        if server is None:
            continue
        for fn in [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            _extract_py_client_method(schema, server, fn)
    return schema


def _extract_py_struct(schema: Schema, cls: ast.ClassDef) -> None:
    fields: Dict[str, str] = {}
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            fields[node.target.id] = _canon_annotation(
                _annotation_text(node.annotation)
            )
    # cross-check the wire accessors against the annotations: a field in
    # to_dict/from_dict but not the dataclass (or vice versa) is drift
    # INSIDE the Python side; surfaced via the merged field set.
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                fields.setdefault(node.args[0].value, "any")
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    fields.setdefault(key.value, "any")
    schema["structs"][cls.name] = fields


def _extract_py_client_method(
    schema: Schema, server: str, fn: ast.AST
) -> None:
    assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    arg_types = {
        a.arg: _canon_annotation(_annotation_text(a.annotation))
        for a in list(fn.args.args) + list(fn.args.kwonlyargs)
    }
    # params["k"] = v build-up (one shared `params` dict per method here)
    built: Dict[str, str] = {}
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
            and isinstance(node.targets[0].slice, ast.Constant)
            and isinstance(node.targets[0].slice.value, str)
        ):
            built[node.targets[0].slice.value] = _value_type(
                node.value, arg_types
            )
    # the RPC call sites
    result_vars: Dict[str, str] = {}  # var name -> method
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _is_rpc_call(call) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                result_vars[node.targets[0].id] = call.args[0].value  # type: ignore[union-attr]
        if not (isinstance(node, ast.Call) and _is_rpc_call(node)):
            continue
        method_name = node.args[0].value  # type: ignore[union-attr]
        assert isinstance(method_name, str)
        m = _method(schema, server, method_name)
        params_arg = node.args[1]
        if isinstance(params_arg, ast.Dict):
            for key, val in zip(params_arg.keys, params_arg.values):
                if isinstance(key, ast.Constant) and isinstance(
                    key.value, str
                ):
                    m["params"].setdefault(
                        key.value, _value_type(val, arg_types)
                    )
        elif isinstance(params_arg, ast.Name):
            for k, t in built.items():
                m["params"].setdefault(k, t)
            # seed-literal dict the name was initialized from (plain or
            # annotated assignment — ``params: Dict[...] = {...}``)
            for sub in ast.walk(fn):
                tgt: Optional[ast.expr] = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    tgt = sub.targets[0]
                elif isinstance(sub, ast.AnnAssign):
                    tgt = sub.target
                if (
                    tgt is not None
                    and isinstance(tgt, ast.Name)
                    and tgt.id == params_arg.id
                    and isinstance(sub.value, ast.Dict)
                ):
                    for key, val in zip(sub.value.keys, sub.value.values):
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            m["params"].setdefault(
                                key.value, _value_type(val, arg_types)
                            )
    # result field reads: result["k"] subscripts and Struct.from_dict(result)
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            base = node.value
            if isinstance(base, ast.Name) and base.id in result_vars:
                m = _method(schema, server, result_vars[base.id])
                if node.slice.value not in m["result"]:
                    m["result"].append(node.slice.value)
            elif isinstance(base, ast.Call) and _is_rpc_call(base):
                m = _method(schema, server, base.args[0].value)  # type: ignore[arg-type]
                if node.slice.value not in m["result"]:
                    m["result"].append(node.slice.value)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "from_dict"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in _STRUCTS
            and node.args
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in result_vars
        ):
            m = _method(schema, server, result_vars[node.args[0].id])
            m["result_struct"] = node.func.value.id


def extract_py_envelope(source: str) -> "Set[str]":
    """Request-envelope fields the Python ``_RpcClient`` sends: string
    keys of dict literals passed to ``json.dumps`` inside
    ``_RpcClient.call`` plus ``<var>["k"] = ...`` build-up there.  Empty
    when the source has no ``_RpcClient`` (mini projects)."""
    fields: "Set[str]" = set()
    tree = ast.parse(source)
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        if cls.name != "_RpcClient":
            continue
        for fn in ast.walk(cls):
            if not (
                isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name == "call"
            ):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "dumps"
                    and node.args
                ):
                    arg = node.args[0]
                    if isinstance(arg, ast.Dict):
                        for key in arg.keys:
                            if isinstance(key, ast.Constant) and isinstance(
                                key.value, str
                            ):
                                fields.add(key.value)
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].slice, ast.Constant)
                    and isinstance(node.targets[0].slice.value, str)
                ):
                    fields.add(node.targets[0].slice.value)
                # seed dict of the variable later dumped (req = {...})
                if (
                    isinstance(node, ast.AnnAssign)
                    and isinstance(node.value, ast.Dict)
                ) or (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Dict)
                ):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            fields.add(key.value)
    return fields


_ENVELOPE_READ_RE = re.compile(r'\breq\.get\("(\w+)"\)')
_ENVELOPE_WRITE_RE = re.compile(r'\breq\["(\w+)"\]\s*=')


def extract_native_envelope(
    sources: Dict[str, str]
) -> "Tuple[Set[str], Set[str]]":
    """(reads, writes) of the request envelope on the native side:
    ``req.get("k")`` in the server's ``serve_conn`` and ``req["k"] =``
    in the clients (``RpcClient::call`` / ``call_rpc``) — all in
    net.cc, where the one framed-envelope implementation lives."""
    reads: "Set[str]" = set()
    writes: "Set[str]" = set()
    for fname, text in sources.items():
        if os.path.basename(fname) != "net.cc":
            continue
        body = _function_body(text, "serve_conn")
        for m in _ENVELOPE_READ_RE.finditer(body or text):
            reads.add(m.group(1))
        for m in _ENVELOPE_WRITE_RE.finditer(text):
            writes.add(m.group(1))
    return reads, writes


# ---------------------------------------------------------------------------
# native extraction
# ---------------------------------------------------------------------------

_DISPATCH_RE = re.compile(r'method\s*==\s*"(\w+)"\s*\)')
_PARAM_READ_RE = re.compile(r'params\.get\("([^"]+)"\)(?:\.(as_\w+)\()?')
_RESULT_WRITE_RE = re.compile(r'\bout\["([^"]+)"\]\s*=')
_RETURN_STRUCT_RE = re.compile(r"\breturn\s+(\w+)\.to_json\(\)")
_CLIENT_CALL_RE = re.compile(r'\.call\("(\w+)",\s*(\w+)')

_AS_TYPES = {
    "as_string": "string",
    "as_int": "int",
    "as_bool": "bool",
    "as_double": "double",
    "as_array": "array",
    "as_object": "object",
}


def _match_braces(text: str, open_idx: int) -> int:
    """Index one past the brace block opening at ``open_idx`` ('{')."""
    depth = 0
    i = open_idx
    while i < len(text):
        c = text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                return i + 1
        elif c == '"':  # skip string literals
            i += 1
            while i < len(text) and text[i] != '"':
                i += 2 if text[i] == "\\" else 1
        i += 1
    return len(text)


def _function_body(text: str, name: str) -> str:
    """Body of the member/function definition ``...::name(...) {...}``
    ('' when not found). Skips prototypes (no ``{`` before ``;``)."""
    for m in re.finditer(r"::" + re.escape(name) + r"\s*\(", text):
        i = m.end()
        depth = 1
        while i < len(text) and depth:  # skip the parameter list
            depth += text[i] == "("
            depth -= text[i] == ")"
            i += 1
        j = i
        while j < len(text) and text[j] not in "{;":
            j += 1
        if j < len(text) and text[j] == "{":
            return text[j:_match_braces(text, j)]
    return ""


def _dispatch_arm(text: str, idx: int) -> str:
    """The statement/block guarded by the ``method == "..."`` test at
    ``idx``: a brace block, or the single statement up to ``;``."""
    i = idx
    while i < len(text) and text[i] not in "{;":
        i += 1
    if i < len(text) and text[i] == "{":
        return text[i:_match_braces(text, i)]
    return text[idx : i + 1]


def _collect_handler(schema: Schema, server: str, method: str, body: str) -> None:
    m = _method(schema, server, method)
    for pm in _PARAM_READ_RE.finditer(body):
        name, as_t = pm.group(1), pm.group(2)
        m["params"].setdefault(
            name, _AS_TYPES.get(as_t or "", "object" if not as_t else "any")
        )
    for rm in _RESULT_WRITE_RE.finditer(body):
        if rm.group(1) not in m["result"]:
            m["result"].append(rm.group(1))
    rs = _RETURN_STRUCT_RE.search(body)
    if rs is not None:
        var = rs.group(1)
        decl = re.search(r"\b(\w+)\s+" + re.escape(var) + r"\s*[;({=]", body)
        if decl is not None and decl.group(1) in _STRUCTS:
            m["result_struct"] = decl.group(1)


def extract_native(sources: Dict[str, str]) -> Tuple[Schema, Schema]:
    """(server schema, client schema) from ``{filename: text}`` native
    sources.  The client schema records params the native code SENDS
    (e.g. the manager's heartbeat piggyback to the lighthouse), keyed by
    method name under server ``"?"`` — resolved against the lock by the
    checks, not here."""
    schema = _empty_schema()
    client = _empty_schema()
    for fname, text in sources.items():
        server = _NATIVE_SERVERS.get(os.path.basename(fname))
        if server is not None:
            for dm in _DISPATCH_RE.finditer(text):
                method = dm.group(1)
                arm = _dispatch_arm(text, dm.end())
                # params read inline in the dispatch statement itself
                _collect_handler(schema, server, method, arm)
                ret = re.search(r"\breturn\s+(\w+)\s*\(", arm)
                if ret is not None and not arm.lstrip().startswith("{"):
                    body = _function_body(text, ret.group(1))
                    if body:
                        _collect_handler(schema, server, method, body)
        # struct to_json / from_json surfaces (member fns; scoped per struct)
        for struct in _STRUCTS:
            fields = schema["structs"].setdefault(struct, {})
            for m in re.finditer(
                re.escape(struct) + r"::to_json\s*\(", text
            ):
                brace = text.find("{", m.end())
                if brace < 0:
                    continue
                body = text[brace : _match_braces(text, brace)]
                for w in re.finditer(r'\bj\["([^"]+)"\]\s*=', body):
                    fields.setdefault(w.group(1), "any")
            for m in re.finditer(
                re.escape(struct) + r"::from_json\s*\(", text
            ):
                brace = text.find("{", m.end())
                if brace < 0:
                    continue
                body = text[brace : _match_braces(text, brace)]
                for r in re.finditer(
                    r'\bj\.get\("([^"]+)"\)(?:\.(as_\w+)\()?', body
                ):
                    t = _AS_TYPES.get(r.group(2) or "", "any")
                    prev = fields.get(r.group(1))
                    fields[r.group(1)] = t if prev in (None, "any") else prev
        # native client call sites: ``<x>.call("method", <var>...)`` with
        # ``<var>["k"] = ...`` builds, scoped to the ENCLOSING top-level
        # function (the previous column-0 closing brace bounds it — a
        # wider window would blame one RPC for a sibling's params)
        for cm in _CLIENT_CALL_RE.finditer(text):
            method, var = cm.group(1), cm.group(2)
            start = text.rfind("\n}", 0, cm.start())
            window = text[max(start, 0) : cm.start()]
            mm = _method(client, "?", method)
            for pw in re.finditer(
                r"\b" + re.escape(var) + r'\["([^"]+)"\]\s*=', window
            ):
                mm["params"].setdefault(pw.group(1), "any")
    # drop empty struct entries for files that never define them
    schema["structs"] = {
        k: v for k, v in schema["structs"].items() if v
    }
    return schema, client


# ---------------------------------------------------------------------------
# lock build / load
# ---------------------------------------------------------------------------


def _merge_types(native_t: str, py_t: str) -> str:
    if native_t != "any":
        return native_t
    return py_t


def build_lock(
    py_source: str, native_sources: Dict[str, str]
) -> Dict[str, Any]:
    """The canonical lock document: native truth merged with Python types
    where the native side is untyped."""
    py = extract_python(py_source)
    native, _client = extract_native(native_sources)
    servers: Dict[str, Any] = {}
    for server in sorted(
        set(native["servers"]) | set(py["servers"])
    ):
        nsrv = native["servers"].get(server, {})
        psrv = py["servers"].get(server, {})
        methods: Dict[str, Any] = {}
        for method in sorted(set(nsrv) | set(psrv)):
            nm = nsrv.get(method, {"params": {}, "result": [], "result_struct": None})
            pm = psrv.get(method, {"params": {}, "result": [], "result_struct": None})
            params = {
                k: _merge_types(
                    nm["params"].get(k, "any"), pm["params"].get(k, "any")
                )
                for k in sorted(set(nm["params"]) | set(pm["params"]))
            }
            methods[method] = {
                "params": params,
                "result": sorted(set(nm["result"]) | set(pm["result"])),
                "result_struct": nm["result_struct"] or pm["result_struct"],
            }
        servers[server] = methods
    structs: Dict[str, Any] = {}
    for name in sorted(set(native["structs"]) | set(py["structs"])):
        nf = native["structs"].get(name, {})
        pf = py["structs"].get(name, {})
        structs[name] = {
            k: _merge_types(nf.get(k, "any"), pf.get(k, "any"))
            for k in sorted(set(nf) | set(pf))
        }
    # request-envelope surface (method/params/timeout_ms + the tracing
    # traceparent field): union of what the Python client sends and the
    # native server reads — per-side drift is flagged by run_checks.
    py_env = extract_py_envelope(py_source)
    n_reads, n_writes = extract_native_envelope(native_sources)
    return {
        "version": LOCK_VERSION,
        "framing": WIRE_FRAMING,
        "envelope": sorted(py_env | n_reads | n_writes),
        "servers": servers,
        "structs": structs,
    }


def lock_path(coordination_py: str) -> str:
    """Committed lock location: ``analysis/protocol.lock`` next to the
    package's ``coordination.py``."""
    return os.path.join(
        os.path.dirname(os.path.abspath(coordination_py)),
        "analysis",
        "protocol.lock",
    )


def default_lock_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "protocol.lock"
    )


def load_lock(path: str) -> Optional[Dict[str, Any]]:
    if not os.path.isfile(path):
        return None
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    assert isinstance(doc, dict)
    return doc


def dump_lock(lock: Dict[str, Any]) -> str:
    return json.dumps(lock, indent=2, sort_keys=True) + "\n"


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------


def _doc_row_re(server: str, method: str) -> "re.Pattern[str]":
    return re.compile(
        r"\|\s*" + re.escape(server) + r"\s*\|\s*`?" + re.escape(method) + r"`?\s*\|"
    )


def run_checks(
    py_source: str,
    native_sources: Dict[str, str],
    docs_text: str,
    committed_lock: Optional[Dict[str, Any]],
    py_file: str = "torchft_tpu/coordination.py",
    native_file_of: Optional[Dict[str, str]] = None,
    docs_file: str = "docs/protocol.md",
    lock_file: str = "torchft_tpu/analysis/protocol.lock",
) -> Iterator[Finding]:
    """All drift findings between the four surfaces."""
    native_file_of = native_file_of or {}

    def finding(code: str, file: str, message: str, symbol: str = "") -> Finding:
        return Finding(
            pass_id=PASS_ID,
            code=code,
            file=file,
            line=0,
            message=message,
            symbol=symbol,
        )

    py = extract_python(py_source)
    native, nclient = extract_native(native_sources)
    fresh = build_lock(py_source, native_sources)

    def nfile(server: str) -> str:
        for base, srv in _NATIVE_SERVERS.items():
            if srv == server and base in native_file_of:
                return native_file_of[base]
        return "native/"

    # ---- methods exist on both sides ------------------------------------
    for server, psrv in py["servers"].items():
        nsrv = native["servers"].get(server, {})
        for method in psrv:
            if method not in nsrv:
                yield finding(
                    "method-missing-native",
                    nfile(server),
                    f"Python client calls {server}.{method} but no native "
                    f"dispatch arm serves it",
                    f"{server}.{method}",
                )
    for server, nsrv in native["servers"].items():
        psrv = py["servers"].get(server, {})
        for method in nsrv:
            if method not in psrv:
                yield finding(
                    "method-dead-native",
                    py_file,
                    f"native {server} serves method {method!r} that no "
                    f"Python client calls (dead method, or a missing client "
                    f"binding)",
                    f"{server}.{method}",
                )

    # ---- per-method params + result ------------------------------------
    for server, psrv in py["servers"].items():
        nsrv = native["servers"].get(server, {})
        for method, pm in psrv.items():
            nm = nsrv.get(method)
            if nm is None:
                continue
            sym = f"{server}.{method}"
            for k, pt in pm["params"].items():
                if k not in nm["params"]:
                    yield finding(
                        "param-dead",
                        nfile(server),
                        f"{sym} param {k!r} is sent by the Python client "
                        f"but never read by the native handler",
                        f"{sym}.{k}",
                    )
                else:
                    nt = nm["params"][k]
                    if "any" not in (pt, nt) and pt != nt:
                        yield finding(
                            "type-mismatch",
                            py_file,
                            f"{sym} param {k!r}: Python sends {pt}, native "
                            f"reads {nt}",
                            f"{sym}.{k}",
                        )
            for k in nm["params"]:
                if k not in pm["params"]:
                    yield finding(
                        "param-missing",
                        py_file,
                        f"{sym} param {k!r} is read by the native handler "
                        f"but never sent by the Python client",
                        f"{sym}.{k}",
                    )
            for k in pm["result"]:
                if k not in nm["result"] and nm["result_struct"] is None:
                    yield finding(
                        "result-missing",
                        nfile(server),
                        f"{sym}: Python reads result[{k!r}] but the native "
                        f"handler never writes it",
                        f"{sym}.{k}",
                    )
            if (
                pm["result_struct"]
                and nm["result_struct"]
                and pm["result_struct"] != nm["result_struct"]
            ):
                yield finding(
                    "result-struct-mismatch",
                    py_file,
                    f"{sym}: Python parses the result as "
                    f"{pm['result_struct']}, native returns "
                    f"{nm['result_struct']}",
                    sym,
                )

    # ---- native client sends (manager -> lighthouse etc.) ---------------
    all_servers = fresh["servers"]
    for method, mm in nclient["servers"].get("?", {}).items():
        served_by = [s for s, ms in all_servers.items() if method in ms]
        if not served_by:
            yield finding(
                "method-missing-native",
                "native/",
                f"native client calls method {method!r} that no server "
                f"dispatches",
                method,
            )
            continue
        ok = any(
            set(mm["params"]) <= set(all_servers[s][method]["params"])
            for s in served_by
        )
        if not ok:
            extras = sorted(
                set(mm["params"])
                - set.union(
                    *(set(all_servers[s][method]["params"]) for s in served_by)
                )
            )
            yield finding(
                "param-dead",
                "native/",
                f"native client sends {method} param(s) {extras} that no "
                f"server handler reads",
                method,
            )

    # ---- structs ---------------------------------------------------------
    for name in sorted(set(py["structs"]) | set(native["structs"])):
        pf = py["structs"].get(name)
        nf = native["structs"].get(name)
        if pf is None or nf is None:
            continue  # struct only exists on one side (e.g. no native parse)
        for k, pt in pf.items():
            if k not in nf:
                yield finding(
                    "struct-field-missing",
                    nfile("lighthouse"),
                    f"struct {name} field {k!r} exists in Python but not in "
                    f"the native to_json/from_json surface",
                    f"{name}.{k}",
                )
            else:
                nt = nf[k]
                if "any" not in (pt, nt) and pt != nt:
                    yield finding(
                        "type-mismatch",
                        py_file,
                        f"struct {name} field {k!r}: Python {pt}, native {nt}",
                        f"{name}.{k}",
                    )
        for k in nf:
            if k not in pf:
                yield finding(
                    "struct-field-missing",
                    py_file,
                    f"struct {name} field {k!r} exists natively but not in "
                    f"the Python dataclass surface",
                    f"{name}.{k}",
                )

    # ---- request envelope (method/params/timeout_ms/traceparent) --------
    py_env = extract_py_envelope(py_source)
    n_reads, n_writes = extract_native_envelope(native_sources)
    if py_env and n_reads:
        # an empty side means this project form has no envelope surface
        # (mini/selftest trees, wheel installs) — nothing to cross-check
        for field_name in sorted(py_env - n_reads):
            yield finding(
                "envelope-field-dead",
                native_file_of.get("net.cc", "native/net.cc"),
                f"request-envelope field {field_name!r} is sent by the "
                f"Python client but never read by the native server "
                f"(serve_conn)",
                f"envelope.{field_name}",
            )
        for field_name in sorted(n_reads - py_env):
            yield finding(
                "envelope-field-missing",
                py_file,
                f"request-envelope field {field_name!r} is read by the "
                f"native server but never sent by the Python client",
                f"envelope.{field_name}",
            )
        for field_name in sorted(n_writes - n_reads):
            yield finding(
                "envelope-field-dead",
                native_file_of.get("net.cc", "native/net.cc"),
                f"request-envelope field {field_name!r} is written by the "
                f"native client but never read by the native server",
                f"envelope.{field_name}",
            )
        if docs_text:
            for field_name in sorted(py_env | n_reads):
                if not re.search(
                    "[`\"]" + re.escape(field_name) + "[`\"]", docs_text
                ):
                    yield finding(
                        "envelope-undocumented",
                        docs_file,
                        f"request-envelope field {field_name!r} is not "
                        f"documented in {docs_file} (backticked or quoted)",
                        f"envelope.{field_name}",
                    )

    # ---- docs ------------------------------------------------------------
    for server, methods in fresh["servers"].items():
        for method in methods:
            if not _doc_row_re(server, method).search(docs_text):
                yield finding(
                    "method-undocumented",
                    docs_file,
                    f"{server}.{method} has no `| {server} | {method} |` row "
                    f"in the {docs_file} wire-surface table",
                    f"{server}.{method}",
                )

    # ---- committed lock vs tree -----------------------------------------
    if committed_lock is None:
        yield finding(
            "lock-missing",
            lock_file,
            f"{lock_file} is not committed; generate it with "
            f"`tft-verify --write-lock`",
        )
    elif committed_lock != fresh:
        diffs = _lock_diff(committed_lock, fresh)
        for d in diffs[:20]:
            yield finding(
                "lock-drift",
                lock_file,
                f"committed protocol.lock disagrees with the tree: {d} "
                f"(review the change, then `tft-verify --write-lock`)",
                d.split(" ", 1)[0],
            )


def _lock_diff(a: Dict[str, Any], b: Dict[str, Any], prefix: str = "") -> List[str]:
    out: List[str] = []
    keys = sorted(set(a) | set(b))
    for k in keys:
        path = f"{prefix}{k}"
        if k not in a:
            out.append(f"{path} only in tree")
        elif k not in b:
            out.append(f"{path} only in lock")
        elif isinstance(a[k], dict) and isinstance(b[k], dict):
            out.extend(_lock_diff(a[k], b[k], path + "."))
        elif a[k] != b[k]:
            out.append(f"{path}: lock={a[k]!r} tree={b[k]!r}")
    return out


# ---------------------------------------------------------------------------
# LintPass wiring
# ---------------------------------------------------------------------------

_NATIVE_FILES = ("lighthouse.cc", "manager.cc", "store.cc", "capi.cc", "net.cc")


def gather_inputs(
    root: str, coordination_py: Optional[str] = None
) -> Tuple[str, Dict[str, str], Dict[str, str], str, Optional[Dict[str, Any]], str]:
    """(py_source, native_sources, native_file_of, docs_text, lock, lock_file)
    for a tree rooted at ``root``."""
    cpath = coordination_py or os.path.join(root, "torchft_tpu", "coordination.py")
    with open(cpath, encoding="utf-8") as fh:
        py_source = fh.read()
    native_sources: Dict[str, str] = {}
    native_file_of: Dict[str, str] = {}
    ndir = os.path.join(root, "native")
    for base in _NATIVE_FILES:
        path = os.path.join(ndir, base)
        if os.path.isfile(path):
            with open(path, encoding="utf-8") as fh:
                native_sources[base] = fh.read()
            native_file_of[base] = os.path.relpath(path, root)
    docs = os.path.join(root, "docs", "protocol.md")
    docs_text = ""
    if os.path.isfile(docs):
        with open(docs, encoding="utf-8") as fh:
            docs_text = fh.read()
    lpath = lock_path(cpath)
    lock = load_lock(lpath)
    return (
        py_source,
        native_sources,
        native_file_of,
        docs_text,
        lock,
        os.path.relpath(lpath, root),
    )


def _run(project: Project) -> Iterable[Finding]:
    cpath = project.find_file("coordination.py")
    if cpath is None:
        return []
    (
        py_source,
        native_sources,
        native_file_of,
        docs_text,
        lock,
        lock_file,
    ) = gather_inputs(project.root, cpath)
    if not native_sources:
        # a tree without native sources (e.g. a wheel install) has
        # nothing to cross-check; the committed lock is the contract
        return []
    return list(
        run_checks(
            py_source,
            native_sources,
            docs_text,
            lock,
            py_file=project.rel(cpath),
            native_file_of=native_file_of,
            lock_file=lock_file,
        )
    )


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

#: Minimal three-surface project the selftest (and the seeded-drift gate
#: in tests/test_wire_schema.py) materializes and perturbs.
MINI_PY = '''\
from dataclasses import dataclass
from typing import Any, Dict


@dataclass
class QuorumMember:
    replica_id: str
    step: int = 0

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "QuorumMember":
        return QuorumMember(
            replica_id=d.get("replica_id", ""),
            step=d.get("step", 0),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {"replica_id": self.replica_id, "step": self.step}


class LighthouseClient:
    def __init__(self, client):
        self._client = client

    def quorum(self, member: QuorumMember, timeout: float) -> Dict[str, Any]:
        result = self._client.call("quorum", {"member": member.to_dict()}, timeout)
        return result["quorum"]

    def heartbeat(self, replica_id: str, step: int, timeout: float) -> Dict[str, Any]:
        params: Dict[str, Any] = {"replica_id": replica_id}
        params["step"] = int(step)
        return self._client.call("heartbeat", params, timeout)
'''

MINI_CC = '''\
Json QuorumMember::to_json() const {
  Json j = Json::object();
  j["replica_id"] = replica_id;
  j["step"] = step;
  return j;
}

QuorumMember QuorumMember::from_json(const Json& j) {
  QuorumMember m;
  m.replica_id = j.get("replica_id").as_string();
  m.step = j.get("step").as_int();
  return m;
}

Json LighthouseServer::handle(const std::string& method, const Json& params,
                              int64_t timeout_ms) {
  if (method == "quorum") return rpc_quorum(params, timeout_ms);
  if (method == "heartbeat") {
    const std::string rid = params.get("replica_id").as_string();
    int64_t step = params.get("step").as_int(-1);
    Json out = Json::object();
    out["superseded"] = false;
    return out;
  }
  throw std::runtime_error("unknown method");
}

Json LighthouseServer::rpc_quorum(const Json& params, int64_t timeout_ms) {
  QuorumMember m = QuorumMember::from_json(params.get("member"));
  Json out = Json::object();
  out["quorum"] = m.to_json();
  return out;
}
'''

MINI_DOCS = """\
# protocol

## Wire surface

| server | method | notes |
|---|---|---|
| lighthouse | quorum | join the next quorum |
| lighthouse | heartbeat | liveness + progress |
"""

#: Envelope-surface mini project (the selftest's second half): a Python
#: _RpcClient building the request envelope and the native serve_conn /
#: RpcClient reading+writing it.
MINI_ENVELOPE_PY = '''\
import json


class _RpcClient:
    def call(self, method, params, timeout):
        req = {"method": method, "params": params, "timeout_ms": 1}
        req["traceparent"] = "00-x-y-01"
        payload = json.dumps(req).encode()
        return payload
'''

MINI_NET_CC = '''\
void RpcServer::serve_conn(int fd) {
  Json req = Json::parse(payload);
  int64_t timeout_ms = req.get("timeout_ms").as_int(60000);
  std::string method = req.get("method").as_string();
  TraceCtx ctx = parse_traceparent(req.get("traceparent").as_string());
  handle(method, req.get("params"), timeout_ms);
}

Json RpcClient::call(const std::string& method, const Json& params,
                     int64_t timeout_ms) {
  Json req = Json::object();
  req["method"] = method;
  req["params"] = params;
  req["timeout_ms"] = timeout_ms;
  req["traceparent"] = format_traceparent(current_trace());
  return req;
}
'''

MINI_ENVELOPE_DOCS = (
    MINI_DOCS
    + '\nEnvelope fields: `method`, `params`, `timeout_ms`, `traceparent`.\n'
)


def selftest() -> None:
    native = {"lighthouse.cc": MINI_CC}
    lock = build_lock(MINI_PY, native)

    def codes(py: str = MINI_PY, cc: str = MINI_CC, docs: str = MINI_DOCS,
              committed: Optional[Dict[str, Any]] = lock) -> Set[str]:
        return {
            f.code
            for f in run_checks(
                py, {"lighthouse.cc": cc}, docs, committed
            )
        }

    clean = codes()
    if clean:
        raise SelftestError(f"clean mini project yields findings: {clean}")
    # extraction sanity: the lock carries what the surfaces declare
    lh = lock["servers"]["lighthouse"]
    if set(lh) != {"quorum", "heartbeat"}:
        raise SelftestError(f"method extraction wrong: {sorted(lh)}")
    if lh["heartbeat"]["params"] != {"replica_id": "string", "step": "int"}:
        raise SelftestError(
            f"heartbeat param extraction wrong: {lh['heartbeat']['params']}"
        )
    if lock["structs"]["QuorumMember"] != {
        "replica_id": "string",
        "step": "int",
    }:
        raise SelftestError(
            f"struct extraction wrong: {lock['structs']['QuorumMember']}"
        )
    # each drift class is caught
    cases = {
        "param-dead": (
            MINI_PY.replace('params["step"] = int(step)',
                            'params["stepz"] = int(step)'),
            MINI_CC,
            MINI_DOCS,
        ),
        "struct-field-missing": (
            MINI_PY,
            MINI_CC.replace('j["step"] = step;', 'j["stepp"] = step;')
            .replace('m.step = j.get("step").as_int();',
                     'm.step = j.get("stepp").as_int();'),
            MINI_DOCS,
        ),
        "method-undocumented": (
            MINI_PY,
            MINI_CC,
            MINI_DOCS.replace("| lighthouse | heartbeat | liveness + progress |", ""),
        ),
        "type-mismatch": (
            MINI_PY.replace("replica_id: str", "replica_id: int"),
            MINI_CC,
            MINI_DOCS,
        ),
        "method-missing-native": (
            MINI_PY.replace('"heartbeat", params', '"heartbeatz", params'),
            MINI_CC,
            MINI_DOCS,
        ),
    }
    for expect, (py, cc, docs) in cases.items():
        got = codes(py, cc, docs)
        if expect not in got:
            raise SelftestError(
                f"seeded {expect} drift not caught (got {sorted(got)})"
            )
    # lock drift: committed lock from a different tree state
    stale = json.loads(json.dumps(lock))
    stale["structs"]["QuorumMember"]["renamed"] = stale["structs"][
        "QuorumMember"
    ].pop("step")
    got = codes(committed=stale)
    if "lock-drift" not in got:
        raise SelftestError(f"stale committed lock not caught (got {sorted(got)})")
    if "lock-missing" not in codes(committed=None):
        raise SelftestError("missing committed lock not caught")

    # ---- request-envelope surface ---------------------------------------
    env_py = MINI_PY + MINI_ENVELOPE_PY
    env_native = {"lighthouse.cc": MINI_CC, "net.cc": MINI_NET_CC}
    env_lock = build_lock(env_py, env_native)
    if env_lock["envelope"] != ["method", "params", "timeout_ms", "traceparent"]:
        raise SelftestError(
            f"envelope extraction wrong: {env_lock['envelope']}"
        )

    def env_codes(py: str = env_py, net: str = MINI_NET_CC) -> Set[str]:
        native = {"lighthouse.cc": MINI_CC, "net.cc": net}
        return {
            f.code
            for f in run_checks(
                py, native, MINI_ENVELOPE_DOCS,
                build_lock(py, native),
            )
        }

    clean = env_codes()
    if clean:
        raise SelftestError(f"clean envelope project yields findings: {clean}")
    got = env_codes(py=env_py.replace('req["traceparent"]', 'req["trace_parent"]'))
    if "envelope-field-dead" not in got or "envelope-field-missing" not in got:
        raise SelftestError(
            f"python-side envelope rename not caught (got {sorted(got)})"
        )
    got = env_codes(
        net=MINI_NET_CC.replace(
            'req.get("traceparent")', 'req.get("trace_parent")'
        )
    )
    if "envelope-field-dead" not in got or "envelope-field-missing" not in got:
        raise SelftestError(
            f"native-side envelope rename not caught (got {sorted(got)})"
        )
    docs_missing = {
        f.code
        for f in run_checks(
            env_py, env_native, MINI_DOCS, env_lock
        )
    }
    if "envelope-undocumented" not in docs_missing:
        raise SelftestError(
            f"undocumented envelope field not caught (got {sorted(docs_missing)})"
        )


PASS = LintPass(
    id=PASS_ID,
    doc=(
        "framed-JSON wire schema in sync across the Python clients, the "
        "native servers, docs/protocol.md, and the committed protocol.lock"
    ),
    run=_run,
    selftest=selftest,
)
