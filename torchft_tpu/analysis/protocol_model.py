"""Executable model of the torchft_tpu quorum protocol (tft-verify leg 1).

docs/protocol.md states the protocol's invariants in prose; the soaks
check them empirically on one interleaving per run.  This module is the
same per-step state machine — quorum formation (fast path, min_replicas
floor, majority guard, join timeout, shrink_only), reconfigure, heal,
allreduce, commit with the commit-failure quorum bump, plus crash /
restart / supersession churn — as a **pure-Python transition system**
small enough for :mod:`torchft_tpu.analysis.model_checker` to explore
every bounded interleaving.  No sockets, no threads, no clocks:
nondeterminism (message arrival order, heartbeat expiry, the join
timeout firing, a crash landing mid-phase) is explicit branching.

The spec lives here twice, deliberately:

* **behavior** — the transition functions, which a :class:`Mutation` can
  corrupt (skip the commit-failure quorum bump, heal from a stale
  source, drop the majority guard, ...);
* **invariants** — independent state predicates (`INVARIANTS`), never
  mutated.

The checker proves each mutation is caught by an invariant and that the
unmutated model's bounded state space is clean — the mutation gate in
tests/test_verify.py.  ROADMAP item 4 (online parallelism switching)
adds its states to this model before it adds them to the runtime.

Everything is hashable/immutable (NamedTuples) so the checker can
deduplicate visited states.
"""

from __future__ import annotations

from typing import Callable, Dict, FrozenSet, List, NamedTuple, Optional, Tuple

__all__ = [
    "ModelConfig",
    "Mutation",
    "MUTATIONS",
    "INVARIANTS",
    "Violation",
    "State",
    "initial_state",
    "enabled_transitions",
    "apply_transition",
    "check_invariants",
    "is_goal",
    "VoteState",
    "vote_initial",
    "vote_enabled",
    "vote_apply",
    "vote_check",
    "ResizeConfig",
    "ResizeState",
    "resize_initial",
    "resize_enabled",
    "resize_apply",
    "resize_check",
    "resize_is_goal",
    "ElectionConfig",
    "ElectionState",
    "election_initial",
    "election_enabled",
    "election_apply",
    "election_check",
    "election_is_goal",
    "RestoreConfig",
    "RestoreState",
    "restore_initial",
    "restore_enabled",
    "restore_apply",
    "restore_check",
    "restore_is_goal",
    "MODEL_PHASE_OPS",
]

# Replica phases of the per-step state machine (docs/protocol.md 1-5).
IDLE = "idle"
RECONF = "reconfigure"
HEAL = "heal"
READY = "ready"  # reconfigured, waiting for the cohort allreduce
VOTED = "voted"  # allreduce done (or failed), commit vote latched

# Model op -> Manager phase-histogram name (manager.PROTOCOL_PHASES), so
# counterexample traces render in torchft-diagnose with the vocabulary
# operators already know from real flight dumps.
MODEL_PHASE_OPS: "Dict[str, str]" = {
    "join": "quorum_rpc",
    "form": "quorum_rpc",
    "reconf": "pg_configure",
    "heal": "heal_recv",
    "reduce": "ring",
    "reduce_fail": "ring",
    "reduce_abort": "ring",
    "commit": "commit",
    "crash": "crash",
    "wedge": "crash",
    "restart": "quorum_rpc",
    "zombie_join": "quorum_rpc",
    "expire": "quorum_rpc",
    "timeout": "quorum_rpc",
    # resize (online-parallelism-switching) sub-model ops
    "stage": "reshard",
    "stage_fail": "reshard",
    "quorum": "quorum_rpc",
    "plan": "quorum_rpc",
    "commit_layout": "layout_commit",
    # election (coordination-plane HA) sub-model ops
    "e_candidate": "quorum_rpc",
    "e_grant": "quorum_rpc",
    "e_elect": "quorum_rpc",
    "e_form": "quorum_rpc",
    "e_crash": "crash",
    "e_expire": "quorum_rpc",
    # durable-store cold-restore sub-model ops
    "spill": "heal_send",
    "rot": "crash",
    "restore": "heal_recv",
}


class ModelConfig(NamedTuple):
    """One bounded scenario for the checker to explore exhaustively."""

    n_replicas: int = 2
    min_replicas: int = 1
    target_steps: int = 2  # goal: every live replica commits this many steps
    crash_budget: int = 0  # process deaths (heartbeat eventually expires)
    wedge_budget: int = 0  # trainer hangs; manager keeps heartbeating
    restart_budget: int = 0  # new incarnations of dead/wedged replicas
    # Transient collective failures (a transport error with everyone
    # alive): the whole cohort latches an error and votes no — the
    # commit-failure quorum-bump path with UNCHANGED membership.
    abort_budget: int = 0
    # Replicas that only heartbeat, never join (the partitioned side the
    # majority guard must keep from being outvoted by a minority quorum).
    bystanders: "FrozenSet[int]" = frozenset()
    # Replicas whose join requests carry shrink_only=True.
    shrink_only: "FrozenSet[int]" = frozenset()
    # Per-replica committed step at t0 ( () = everyone at step 0 ): lets a
    # scenario start mid-run with stragglers needing a heal.
    initial_steps: "Tuple[int, ...]" = ()
    # Quorum formations allowed per run (0 = unlimited).  The standard
    # context-bounding knob: protocol rounds, not interleavings, drive
    # the state-space depth, so capping formations keeps a scenario
    # exhaustive-within-bound instead of exponential.
    quorum_budget: int = 0


class Rep(NamedTuple):
    inc: int  # incarnation counter; rid = "r{i}:{inc}"
    alive: bool
    wedged: bool  # trainer hung: no protocol progress, heartbeats continue
    step: int
    state: int  # abstract "bitwise state": int evolved deterministically
    phase: str
    # quorum view delivered at formation: (quorum_id, ((rid, step), ...))
    view: "Optional[Tuple[int, Tuple[Tuple[str, int], ...]]]"
    heal_src: "Optional[str]"  # member rid assigned as recovery source
    vote: bool
    next_state: int  # allreduce output staged for commit
    commit_failures: int
    zombie: "Optional[str]"  # superseded-but-alive old incarnation's rid


class LH(NamedTuple):
    quorum_id: int
    # previous quorum membership: ((rid, step-at-formation), ...) sorted
    prev: "Optional[Tuple[Tuple[str, int], ...]]"
    # pending registrations: ((rid, (step, commit_failures, shrink)), ...)
    pending: "Tuple[Tuple[str, Tuple[int, int, bool]], ...]"
    hb: "FrozenSet[str]"  # fresh heartbeats
    evicted: "FrozenSet[str]"  # permanent supersession stamps
    join_fired: bool  # the join-timeout "no that flips to yes by time"


class Ghost(NamedTuple):
    """Spec-side bookkeeping the invariants read; never visible to the
    (mutable) behavior, so a mutation cannot corrupt the judge."""

    # formation record: (prev_qid, new_qid, membership_changed, commit_failure,
    #  n_participants, n_healthy, new_member_admitted_under_shrink, fast)
    last_form: "Optional[Tuple[int, int, bool, bool, int, int, bool, bool]]"
    # heal record: (dst_rid, src_rid, src_snapshot_step, view_max_step)
    last_heal: "Optional[Tuple[str, str, int, int]]"


class State(NamedTuple):
    lh: LH
    reps: "Tuple[Rep, ...]"
    ghost: Ghost
    crashes: int
    wedges: int
    restarts: int
    aborts: int
    forms: int  # quorum formations remaining (-1 = unlimited)


class Violation(NamedTuple):
    invariant: str
    message: str
    replica_id: str  # violating replica ("lighthouse" for formation rules)
    phase: str  # model op active when the violation appeared


class Mutation(NamedTuple):
    name: str
    doc: str
    catches: str  # invariant id expected to flag it


MUTATIONS: "Tuple[Mutation, ...]" = (
    Mutation(
        "skip_commit_failure_bump",
        "quorum formation does not bump quorum_id when a member reports "
        "commit_failures > 0 (docs/protocol.md step 1)",
        "quorum-id-bump",
    ),
    Mutation(
        "reuse_quorum_id",
        "quorum formation reuses an older quorum_id instead of advancing",
        "quorum-id-monotone",
    ),
    Mutation(
        "heal_from_stale",
        "quorum math assigns a recovery source that is NOT at max_step",
        "heal-source-max-step",
    ),
    Mutation(
        "drop_majority_guard",
        "quorum formation skips the majority-of-heartbeaters split-brain "
        "guard",
        "majority-guard",
    ),
    Mutation(
        "commit_despite_error",
        "a replica whose allreduce failed commits the step anyway with "
        "whatever partial state it has",
        "no-divergent-commit",
    ),
    Mutation(
        "zombie_rejoin",
        "the lighthouse forgets the supersession stamp: an evicted "
        "incarnation's retry re-registers it",
        "supersession",
    ),
    Mutation(
        "ignore_shrink_only",
        "a shrink_only quorum admits brand-new members anyway",
        "shrink-only",
    ),
    Mutation(
        "resend_vote",
        "should_commit votes are blindly re-sent after a broken "
        "connection (the idempotent=True path PR 2 forbids for votes)",
        "vote-integrity",
    ),
    Mutation(
        "commit_mixed_epochs",
        "a replica activates its staged layout even when the quorum's "
        "layout-epoch reports disagree (min < max) — a subset of the "
        "fleet switches parallelism while the rest keeps the old layout",
        "all-commit-same-epoch",
    ),
    Mutation(
        "reuse_epoch_after_rollback",
        "layout planning reuses a rolled-back (burned) epoch value "
        "instead of advancing past it — a straggler still holding the "
        "burned stage could later commit stale data under the fresh plan",
        "layout-epoch-monotone",
    ),
    Mutation(
        "two_leaders_same_term",
        "a lighthouse peer grants a leadership lease for a term it has "
        "already promised to a DIFFERENT candidate (the strict "
        "term-monotone grant rule dropped to >=) — two candidates can "
        "each assemble a majority at the same term",
        "at-most-one-leader-per-term",
    ),
    Mutation(
        "reuse_quorum_seq_after_takeover",
        "a freshly elected lighthouse leader mints quorum ids from its "
        "own local counter without the term prefix — its first ids "
        "repeat values the dead leader already served, so quorum_id "
        "regresses across the failover",
        "quorum-id-monotone-across-failover",
    ),
    Mutation(
        "serve_torn_blob",
        "cold restore skips the read-time digest verify: a torn or "
        "bit-rotted blob is served into the restored cut instead of "
        "being treated as a missing fragment that fails over",
        "restore-cut-complete",
    ),
    Mutation(
        "mix_versions_in_cut",
        "cold-restore cut selection takes the newest manifested version "
        "even when incomplete and fills its missing fragments from "
        "older versions' blobs — the restored state splices fragment "
        "versions across an outer sync",
        "restore-cut-consistent",
    ),
)

MUTATION_NAMES = frozenset(m.name for m in MUTATIONS)


def _rid(i: int, inc: int) -> str:
    return f"r{i}:{inc}"


def _owner(rid: str) -> int:
    return int(rid.split(":", 1)[0][1:])


def _logical(rid: str) -> str:
    return rid.split(":", 1)[0]


def initial_state(cfg: ModelConfig) -> State:
    # Canonical committed chain up to the highest initial step: step 0 is
    # state 0 on every replica (init_sync: everyone starts from the
    # primary's identical weights), later steps evolve deterministically.
    steps = cfg.initial_steps or tuple(0 for _ in range(cfg.n_replicas))
    assert len(steps) == cfg.n_replicas
    chain = [0]
    for k in range(1, max(steps) + 1):
        chain.append(_mix(chain[-1], k))
    reps = tuple(
        Rep(
            inc=0,
            alive=True,
            wedged=False,
            step=s,
            state=chain[s],
            phase=IDLE,
            view=None,
            heal_src=None,
            vote=False,
            next_state=0,
            commit_failures=0,
            zombie=None,
        )
        for s in steps
    )
    hb = frozenset(_rid(i, 0) for i in range(cfg.n_replicas))
    lh = LH(
        quorum_id=0,
        prev=None,
        pending=(),
        hb=hb,
        evicted=frozenset(),
        join_fired=False,
    )
    ghost = Ghost(last_form=None, last_heal=None)
    return State(
        lh=lh,
        reps=reps,
        ghost=ghost,
        crashes=cfg.crash_budget,
        wedges=cfg.wedge_budget,
        restarts=cfg.restart_budget,
        aborts=cfg.abort_budget,
        forms=cfg.quorum_budget if cfg.quorum_budget > 0 else -1,
    )


# ---------------------------------------------------------------------------
# transition enumeration
# ---------------------------------------------------------------------------

Transition = Tuple[str, int]  # (op, replica index; -1 for lighthouse ops)

#: ops that only rewrite the acting replica's private planning fields
#: (deterministic, commute with every other actor's transitions, invisible
#: to the invariants) — the checker's DPOR-style persistent-set reduction
#: expands only one of these when any is enabled.
INVISIBLE_OPS = frozenset({"reconf"})


def _pending_ids(lh: LH) -> "FrozenSet[str]":
    return frozenset(rid for rid, _ in lh.pending)


def _participants(lh: LH) -> "List[Tuple[str, Tuple[int, int, bool]]]":
    """Healthy registered participants, replica-id order."""
    return sorted((p for p in lh.pending if p[0] in lh.hb), key=lambda p: p[0])


def _form_guard(
    cfg: ModelConfig, lh: LH, mutations: "FrozenSet[str]"
) -> "Optional[Tuple[List[Tuple[str, Tuple[int, int, bool]]], bool]]":
    """quorum_compute (native/lighthouse.cc): (candidates, fast) when a
    quorum can form now, else None.  The fast path — every previous
    member is back — trusts previous-quorum continuity and precedes the
    min_replicas / majority / join-timeout guards, exactly like the
    implementation."""
    parts = _participants(lh)
    if not parts:
        return None
    candidates = parts
    shrink = any(p[1][2] for p in parts)
    if shrink and lh.prev is not None and "ignore_shrink_only" not in mutations:
        prev_ids = {rid for rid, _ in lh.prev}
        candidates = [p for p in parts if p[0] in prev_ids]
        if not candidates:
            return None
    part_ids = {p[0] for p in parts}
    if lh.prev is not None:
        prev_ids = {rid for rid, _ in lh.prev}
        if prev_ids <= part_ids:
            return candidates, True  # fast quorum: everyone previous is back
    if len(parts) < cfg.min_replicas:
        return None
    if "drop_majority_guard" not in mutations:
        if len(parts) <= len(lh.hb) // 2:
            return None  # split-brain guard
    if part_ids != lh.hb and not lh.join_fired:
        return None  # healthy stragglers: wait for the join timeout
    return candidates, False


def _live_max_step(cfg: ModelConfig, st: State) -> int:
    """Highest committed step held by any live, unwedged replica — the
    step the membership-overlap assumption centers on."""
    return max(
        (
            r.step
            for r in st.reps
            if r.alive and not r.wedged
        ),
        default=0,
    )


def _overlap_ok(cfg: ModelConfig, st: State, mutations: "FrozenSet[str]") -> bool:
    """The membership-overlap assumption (docs/protocol.md,
    'Assumptions'), both halves:

    1. the forming quorum includes a replica at the live max step (else
       a behind cohort would re-derive already-committed steps with
       different members), and
    2. it overlaps the PREVIOUS quorum's max-step cohort — checking (1)
       alone is provably too weak: the checker found a trace where the
       previous max-step member commits step N alone while a new quorum
       (whose own max-step member is only *reaching* step N-1's result)
       re-runs the step with a disjoint cohort, leaving two live
       replicas at step N with divergent state.

    The real deployment gets this from timing (join_timeout_ms + every
    trainer re-joining each step); the model, which explores ALL
    timings, encodes it as an environment constraint: formation waits
    while an admissible max-step replica is alive.  Once every such
    replica is dead or wedged, continuing from a lower step is genuine
    disaster recovery and is allowed."""
    guard = _form_guard(cfg, st.lh, mutations)
    if guard is None:
        return True
    candidates, _ = guard
    if max(m[0] for _, m in candidates) < _live_max_step(cfg, st):
        return False
    if st.lh.prev is not None:
        prev_max = max(s for _, s in st.lh.prev)
        prev_max_rids = {rid for rid, s in st.lh.prev if s == prev_max}
        live_prev_max = {
            rid
            for rid in prev_max_rids
            if st.reps[_owner(rid)].alive
            and not st.reps[_owner(rid)].wedged
            and _rid(_owner(rid), st.reps[_owner(rid)].inc) == rid
        }
        cand_ids = {rid for rid, _ in candidates}
        if live_prev_max and not (cand_ids & live_prev_max):
            return False
    return True


def enabled_transitions(
    cfg: ModelConfig, st: State, mutations: "FrozenSet[str]" = frozenset()
) -> "List[Transition]":
    out: "List[Transition]" = []
    lh = st.lh
    pend = _pending_ids(lh)
    # A replica keeps joining quorums while it is behind the bounded
    # target OR any live admissible peer is (a finished replica still
    # serves as a recovery source, exactly like a real trainer mid-run);
    # once the whole admissible fleet is at the target, joins stop and
    # the space is bounded.
    someone_behind = any(
        r.alive
        and not r.wedged
        and r.step < cfg.target_steps
        and _admissible(cfg, st, i, r)
        for i, r in enumerate(st.reps)
        if i not in cfg.bystanders
    )
    for i, r in enumerate(st.reps):
        rid = _rid(i, r.inc)
        if r.alive and not r.wedged and i not in cfg.bystanders:
            if (
                r.phase == IDLE
                and someone_behind
                and rid not in pend
                and rid not in lh.evicted
            ):
                out.append(("join", i))
            if r.phase == RECONF:
                out.append(("reconf", i))
            if r.phase == HEAL:
                out.append(("heal", i))
            if r.phase == VOTED:
                out.append(("commit", i))
        if r.alive and not r.wedged and st.crashes > 0:
            out.append(("crash", i))
        if r.alive and not r.wedged and st.wedges > 0:
            out.append(("wedge", i))
        if (not r.alive or r.wedged) and st.restarts > 0:
            out.append(("restart", i))
        if _expirable_rids(lh, i, r):
            out.append(("expire", i))
        # A superseded-but-alive zombie retries its join.  Correctly this
        # is a rejected no-op; only the zombie_rejoin mutation makes it a
        # distinct state, so only enumerate it under that mutation.
        if (
            r.zombie is not None
            and "zombie_rejoin" in mutations
            and r.zombie not in pend
        ):
            out.append(("zombie_join", i))
    if lh.pending and not lh.join_fired:
        parts = {p[0] for p in _participants(lh)}
        if parts and parts != lh.hb:
            out.append(("timeout", -1))
    if (
        st.forms != 0
        and _form_guard(cfg, lh, mutations) is not None
        and _overlap_ok(cfg, st, mutations)
    ):
        out.append(("form", -1))
    # allreduce: the cohort is every quorum member at the view's max_step
    # whose current incarnation reached READY; it completes atomically
    # when all of them are there, and fails for the survivors when a
    # cohort member died/wedged mid-collective.
    ready = [
        (i, r)
        for i, r in enumerate(st.reps)
        if r.phase == READY and r.alive and not r.wedged
    ]
    if ready:
        view = ready[0][1].view
        assert view is not None
        cohort = _cohort_of(view)
        live = {
            _rid(i, r.inc)
            for i, r in enumerate(st.reps)
            if r.phase == READY and r.alive and not r.wedged and r.view == view
        }
        if cohort <= live:
            out.append(("reduce", -1))
            if st.aborts > 0:
                out.append(("reduce_abort", -1))
        else:
            dead_member = any(
                not st.reps[_owner(m)].alive
                or st.reps[_owner(m)].wedged
                or _rid(_owner(m), st.reps[_owner(m)].inc) != m
                for m in cohort
            )
            if dead_member:
                out.append(("reduce_fail", -1))
    return sorted(out)


def _cohort_of(
    view: "Tuple[int, Tuple[Tuple[str, int], ...]]",
) -> "FrozenSet[str]":
    _, members = view
    max_step = max(s for _, s in members)
    return frozenset(rid for rid, s in members if s == max_step)


def _admissible(cfg: ModelConfig, st: State, i: int, r: Rep) -> bool:
    """Whether replica ``i`` can still be admitted to a quorum: while a
    live shrink_only requester exists and a previous quorum is on the
    books, only previous members pass the shrink filter — a filtered-out
    replica is a permanent straggler the bounded goal must not wait on."""
    if st.lh.prev is None or not cfg.shrink_only:
        return True
    shrink_active = any(
        st.reps[j].alive and not st.reps[j].wedged
        for j in cfg.shrink_only
        if j not in cfg.bystanders
    )
    if not shrink_active:
        return True
    return _rid(i, r.inc) in {rid for rid, _ in st.lh.prev}


def _expirable_rids(lh: LH, i: int, r: Rep) -> "FrozenSet[str]":
    """Heartbeat entries of replica ``i`` whose freshness window can run
    out: the current incarnation once its process died, and any prior
    incarnation whose process is gone (a wedged-but-alive zombie keeps
    heartbeating, so its entry stays until supersession evicts it)."""
    out = set()
    rid = _rid(i, r.inc)
    if not r.alive and rid in lh.hb:
        out.add(rid)
    if r.inc > 0:
        old = _rid(i, r.inc - 1)
        if old in lh.hb and old != r.zombie:
            out.add(old)
    return frozenset(out)


# ---------------------------------------------------------------------------
# transition application
# ---------------------------------------------------------------------------


def _mix(*parts: int) -> int:
    """Deterministic small-int state evolution (stands in for 'bitwise
    identical tensors': equal inputs -> equal output, any difference
    propagates)."""
    h = 0x811C9DC5
    for p in parts:
        h ^= (p + 0x9E3779B9) & 0xFFFFFFFF
        h = (h * 0x01000193) & 0xFFFFFFFF
    return h


def apply_transition(
    cfg: ModelConfig,
    st: State,
    t: Transition,
    mutations: "FrozenSet[str]" = frozenset(),
) -> State:
    op, i = t
    lh = st.lh
    reps = list(st.reps)
    ghost = st.ghost

    if op == "join":
        r = reps[i]
        rid = _rid(i, r.inc)
        member = (rid, (r.step, r.commit_failures, i in cfg.shrink_only))
        pending = tuple(p for p in lh.pending if p[0] != rid) + (member,)
        hb = lh.hb | {rid}
        evicted = lh.evicted
        # Fast-restart supersession: a new incarnation's join evicts any
        # other incarnation of the same logical replica, permanently.
        stale = {
            x
            for x in hb
            if x != rid and _logical(x) == _logical(rid)
        }
        if stale:
            hb = hb - stale
            pending = tuple(p for p in pending if p[0] not in stale)
            evicted = evicted | stale
        lh = lh._replace(pending=tuple(sorted(pending)), hb=hb, evicted=evicted)
        return st._replace(lh=lh)

    if op == "zombie_join":
        # only reachable under the zombie_rejoin mutation: the lighthouse
        # forgets the stamp and re-registers the superseded incarnation
        r = reps[i]
        assert r.zombie is not None
        member = (r.zombie, (0, 0, False))
        pending = tuple(p for p in lh.pending if p[0] != r.zombie) + (member,)
        lh = lh._replace(
            pending=tuple(sorted(pending)), hb=lh.hb | {r.zombie}
        )
        return st._replace(lh=lh)

    if op == "timeout":
        return st._replace(lh=lh._replace(join_fired=True))

    if op == "form":
        guard = _form_guard(cfg, lh, mutations)
        assert guard is not None
        candidates, fast = guard
        members = tuple((rid, m[0]) for rid, m in candidates)
        prev_ids = (
            None if lh.prev is None else tuple(rid for rid, _ in lh.prev)
        )
        membership_changed = prev_ids is None or prev_ids != tuple(
            rid for rid, _ in members
        )
        commit_failure = any(m[1] > 0 for _, m in candidates)
        new_qid = lh.quorum_id
        if membership_changed or (
            commit_failure and "skip_commit_failure_bump" not in mutations
        ):
            new_qid = lh.quorum_id + 1
        if "reuse_quorum_id" in mutations and lh.quorum_id > 0:
            # hand out an id from a previous configuration instead of a
            # fresh one (only expressible once an id has been minted)
            new_qid = lh.quorum_id - 1
        new_under_shrink = any(p[1][2] for p in candidates) and (
            lh.prev is not None
            and any(
                rid not in {pr for pr, _ in lh.prev} for rid, _ in members
            )
        )
        ghost = ghost._replace(
            last_form=(
                lh.quorum_id,
                new_qid,
                membership_changed,
                commit_failure,
                len(_participants(lh)),
                len(lh.hb),
                new_under_shrink,
                fast,
            )
        )
        view = (new_qid, members)
        for rid, _ in members:
            j = _owner(rid)
            r = reps[j]
            if r.alive and not r.wedged and _rid(j, r.inc) == rid:
                reps[j] = r._replace(
                    phase=RECONF, view=view, heal_src=None, vote=False
                )
        lh = lh._replace(
            quorum_id=new_qid, prev=members, pending=(), join_fired=False
        )
        return st._replace(
            lh=lh,
            reps=tuple(reps),
            ghost=ghost,
            forms=st.forms - 1 if st.forms > 0 else st.forms,
        )

    if op == "reconf":
        r = reps[i]
        assert r.view is not None
        _, members = r.view
        max_step = max(s for _, s in members)
        if r.step < max_step:
            sources = [rid for rid, s in members if s == max_step]
            if "heal_from_stale" in mutations:
                stale = [
                    rid
                    for rid, s in members
                    if s < max_step and _owner(rid) != i
                ]
                if stale:
                    sources = stale
            my_rank = [rid for rid, _ in members].index(_rid(i, r.inc))
            src = sources[my_rank % len(sources)]
            reps[i] = r._replace(phase=HEAL, heal_src=src)
        else:
            reps[i] = r._replace(phase=READY)
        return st._replace(reps=tuple(reps))

    if op == "heal":
        r = reps[i]
        assert r.view is not None and r.heal_src is not None
        _, members = r.view
        max_step = max(s for _, s in members)
        src_snapshot = dict(members)[r.heal_src]
        j = _owner(r.heal_src)
        src = reps[j]
        ghost = ghost._replace(
            last_heal=(_rid(i, r.inc), r.heal_src, src_snapshot, max_step)
        )
        if not src.alive or _rid(j, src.inc) != r.heal_src:
            # source gone: heal fails, go back and re-quorum
            reps[i] = r._replace(phase=IDLE, view=None, heal_src=None)
            return st._replace(reps=tuple(reps), ghost=ghost)
        # copy the source's CURRENT committed (step, state)
        reps[i] = r._replace(
            step=src.step,
            state=src.state,
            phase=IDLE,
            view=None,
            heal_src=None,
        )
        return st._replace(reps=tuple(reps), ghost=ghost)

    if op in ("reduce", "reduce_fail", "reduce_abort"):
        ready = [
            (j, r)
            for j, r in enumerate(reps)
            if r.phase == READY and r.alive and not r.wedged
        ]
        view = ready[0][1].view
        assert view is not None
        qid, members = view
        cohort = sorted(_cohort_of(view))
        step = max(s for _, s in members)
        if op == "reduce_abort":
            # transient wire failure with everyone alive: the whole
            # cohort latches the error and votes no (commit_failures will
            # be reported at the next quorum with UNCHANGED membership)
            for j, r in ready:
                reps[j] = r._replace(
                    phase=VOTED, vote=False, next_state=_mix(r.state, 0xDEAD, j)
                )
            return st._replace(reps=tuple(reps), aborts=st.aborts - 1)
        if op == "reduce":
            # gradient average over the live cohort: identical inputs on
            # every member, so every member stages the identical output
            value = _mix(qid, step, *(hash(m) & 0xFFFF for m in cohort))
            for j, r in ready:
                nxt = _mix(r.state, value)
                reps[j] = r._replace(phase=VOTED, vote=True, next_state=nxt)
        else:
            for j, r in ready:
                # collective failed: latch the error, vote no; the partial
                # buffer (modeled as a garbage value) must never commit
                reps[j] = r._replace(
                    phase=VOTED, vote=False, next_state=_mix(r.state, 0xDEAD, j)
                )
        return st._replace(reps=tuple(reps))

    if op == "commit":
        r = reps[i]
        vote = r.vote or "commit_despite_error" in mutations
        if vote:
            reps[i] = r._replace(
                step=r.step + 1,
                state=r.next_state,
                phase=IDLE,
                view=None,
                vote=False,
                commit_failures=0,
            )
        else:
            reps[i] = r._replace(
                phase=IDLE,
                view=None,
                vote=False,
                commit_failures=r.commit_failures + 1,
            )
        return st._replace(reps=tuple(reps), ghost=ghost)

    if op == "crash":
        r = reps[i]
        reps[i] = r._replace(alive=False, wedged=False)
        return st._replace(reps=tuple(reps), crashes=st.crashes - 1)

    if op == "wedge":
        r = reps[i]
        reps[i] = r._replace(wedged=True)
        return st._replace(reps=tuple(reps), wedges=st.wedges - 1)

    if op == "restart":
        r = reps[i]
        old_rid = _rid(i, r.inc)
        zombie = old_rid if r.wedged else None
        reps[i] = Rep(
            inc=r.inc + 1,
            alive=True,
            wedged=False,
            step=0,
            state=0,
            phase=IDLE,
            view=None,
            heal_src=None,
            vote=False,
            next_state=0,
            commit_failures=0,
            zombie=zombie,
        )
        return st._replace(reps=tuple(reps), restarts=st.restarts - 1)

    if op == "expire":
        stale = _expirable_rids(lh, i, reps[i])
        lh = lh._replace(
            hb=lh.hb - stale,
            pending=tuple(p for p in lh.pending if p[0] not in stale),
        )
        return st._replace(lh=lh)

    raise AssertionError(f"unknown transition {t}")


# ---------------------------------------------------------------------------
# invariants (the spec — never mutated)
# ---------------------------------------------------------------------------


def _inv_quorum_id_monotone(
    cfg: ModelConfig, st: State
) -> "Optional[Violation]":
    f = st.ghost.last_form
    if f is None:
        return None
    prev_qid, new_qid = f[0], f[1]
    if new_qid < prev_qid:
        return Violation(
            "quorum-id-monotone",
            f"quorum_id went backwards: {prev_qid} -> {new_qid}",
            "lighthouse",
            "form",
        )
    return None


def _inv_quorum_id_bump(cfg: ModelConfig, st: State) -> "Optional[Violation]":
    f = st.ghost.last_form
    if f is None:
        return None
    prev_qid, new_qid, membership_changed, commit_failure = f[0], f[1], f[2], f[3]
    if (membership_changed or commit_failure) and new_qid <= prev_qid:
        why = "membership changed" if membership_changed else "commit failure reported"
        return Violation(
            "quorum-id-bump",
            f"{why} but quorum_id did not advance ({prev_qid} -> {new_qid})",
            "lighthouse",
            "form",
        )
    return None


def _inv_majority_guard(cfg: ModelConfig, st: State) -> "Optional[Violation]":
    f = st.ghost.last_form
    if f is None:
        return None
    n_parts, n_healthy, fast = f[4], f[5], f[7]
    if fast:
        # The fast path (every previous member back) trusts membership
        # continuity and legitimately precedes the guard — the documented
        # design (docs/protocol.md step 1, native/lighthouse.cc).
        return None
    if n_parts <= n_healthy // 2:
        return Violation(
            "majority-guard",
            f"quorum formed with {n_parts} participants out of "
            f"{n_healthy} heartbeating replicas (minority side of a "
            f"partition admitted)",
            "lighthouse",
            "form",
        )
    return None


def _inv_shrink_only(cfg: ModelConfig, st: State) -> "Optional[Violation]":
    f = st.ghost.last_form
    if f is None:
        return None
    if f[6]:
        return Violation(
            "shrink-only",
            "shrink_only quorum admitted a member not in the previous "
            "quorum",
            "lighthouse",
            "form",
        )
    return None


def _inv_heal_source(cfg: ModelConfig, st: State) -> "Optional[Violation]":
    h = st.ghost.last_heal
    if h is None:
        return None
    dst, src, src_step, max_step = h
    if src_step < max_step:
        return Violation(
            "heal-source-max-step",
            f"{dst} healed from {src} at step {src_step}, but the quorum's "
            f"max_step is {max_step} (stale recovery source)",
            dst,
            "heal",
        )
    return None


def _inv_no_divergent_commit(
    cfg: ModelConfig, st: State
) -> "Optional[Violation]":
    """docs/protocol.md's single invariant, literally: replicas
    reporting the same step hold bitwise-identical state (live, unwedged
    replicas — a dead replica's unreplicated tail commits are lost by
    design, and its frozen state is not 'reported')."""
    by_step: "Dict[int, Tuple[str, int]]" = {}
    for i, r in enumerate(st.reps):
        if not r.alive or r.wedged:
            continue
        rid = _rid(i, r.inc)
        prior = by_step.get(r.step)
        if prior is not None and prior[1] != r.state:
            return Violation(
                "no-divergent-commit",
                f"{rid} holds state {r.state:#x} at step {r.step} but "
                f"{prior[0]} holds {prior[1]:#x} at the same step "
                f"(replicas at the same step must be bitwise identical)",
                rid,
                "commit",
            )
        by_step.setdefault(r.step, (rid, r.state))
    return None


def _inv_supersession(cfg: ModelConfig, st: State) -> "Optional[Violation]":
    lh = st.lh
    offenders = (lh.hb | _pending_ids(lh)) & lh.evicted
    if offenders:
        rid = sorted(offenders)[0]
        return Violation(
            "supersession",
            f"evicted incarnation {rid} re-registered at the lighthouse "
            f"(a zombie can evict its live successor)",
            rid,
            "join",
        )
    # at most one incarnation of a logical replica may be registered
    seen: "Dict[str, str]" = {}
    for rid in sorted(lh.hb | _pending_ids(lh)):
        log = _logical(rid)
        if log in seen:
            return Violation(
                "supersession",
                f"two incarnations of {log} registered at once: "
                f"{seen[log]} and {rid}",
                rid,
                "join",
            )
        seen[log] = rid
    return None


INVARIANTS: "Dict[str, Callable[[ModelConfig, State], Optional[Violation]]]" = {
    "quorum-id-monotone": _inv_quorum_id_monotone,
    "quorum-id-bump": _inv_quorum_id_bump,
    "majority-guard": _inv_majority_guard,
    "shrink-only": _inv_shrink_only,
    "heal-source-max-step": _inv_heal_source,
    "no-divergent-commit": _inv_no_divergent_commit,
    "supersession": _inv_supersession,
}


def check_invariants(cfg: ModelConfig, st: State) -> "List[Violation]":
    out = []
    for check in INVARIANTS.values():
        v = check(cfg, st)
        if v is not None:
            out.append(v)
    return out


def is_goal(cfg: ModelConfig, st: State) -> bool:
    """Every live, admissible, participating replica committed the
    target steps."""
    live = [
        r
        for i, r in enumerate(st.reps)
        if r.alive
        and not r.wedged
        and i not in cfg.bystanders
        and _admissible(cfg, st, i, r)
    ]
    return bool(live) and all(r.step >= cfg.target_steps for r in live)


# ---------------------------------------------------------------------------
# vote barrier sub-model (should_commit over one group's local ranks)
# ---------------------------------------------------------------------------
#
# The main model treats each replica group as one voter; this sub-model
# zooms into ONE group's Manager server barrier: world_size local ranks
# each send a should_commit vote per step over a pooled connection that
# can die after delivery but before the reply (the exact hazard
# coordination._RpcClient's idempotent=False exists for).


class VoteMsg(NamedTuple):
    rank: int
    step: int
    vote: bool
    resend: bool  # True when this is a blind client re-send


class VoteState(NamedTuple):
    step: int  # barrier's current round (the step being voted on)
    # votes tallied this round: ((rank, (step_voted, vote)), ...)
    tally: "Tuple[Tuple[int, Tuple[int, bool]], ...]"
    channel: "Tuple[VoteMsg, ...]"  # sent but undelivered messages
    # per rank: next step it will vote on (target+1 = done)
    at: "Tuple[int, ...]"
    # per rank: message awaiting a reply that the connection dropped on
    # (None = no outstanding drop)
    dropped: "Tuple[Optional[VoteMsg], ...]"
    decisions: "Tuple[Tuple[int, bool], ...]"  # (step, decision) history
    drops_left: int


def vote_initial(world: int = 2, steps: int = 2, drops: int = 1) -> VoteState:
    return VoteState(
        step=0,
        tally=(),
        channel=(),
        at=tuple(0 for _ in range(world)),
        dropped=tuple(None for _ in range(world)),
        decisions=(),
        drops_left=drops,
    )


VoteTransition = Tuple[str, int]


def vote_enabled(
    st: VoteState, steps: int, mutations: "FrozenSet[str]" = frozenset()
) -> "List[VoteTransition]":
    out: "List[VoteTransition]" = []
    world = len(st.at)
    for rank in range(world):
        if st.dropped[rank] is None and not any(
            m.rank == rank and not m.resend for m in st.channel
        ):
            tallied = any(r == rank for r, _ in st.tally)
            if st.at[rank] == st.step and st.step < steps and not tallied:
                out.append(("send", rank))
        if st.dropped[rank] is not None:
            if "resend_vote" in mutations:
                out.append(("resend", rank))
            out.append(("abstain", rank))
    for idx in range(len(st.channel)):
        out.append(("deliver", idx))
        if st.drops_left > 0 and not st.channel[idx].resend:
            out.append(("drop", idx))
    return sorted(out)


def vote_apply(st: VoteState, t: VoteTransition) -> VoteState:
    op, x = t
    if op == "send":
        msg = VoteMsg(rank=x, step=st.at[x], vote=True, resend=False)
        return st._replace(channel=st.channel + (msg,))
    if op == "resend":
        # mutated client behavior: blind re-send of the dropped vote
        msg = st.dropped[x]
        assert msg is not None
        dropped = list(st.dropped)
        dropped[x] = None
        return st._replace(
            channel=st.channel + (msg._replace(resend=True),),
            dropped=tuple(dropped),
        )
    if op == "abstain":
        # correct client behavior: surface the ConnectionError; the
        # Manager votes no for the NEXT round and moves on
        dropped = list(st.dropped)
        dropped[x] = None
        return st._replace(dropped=tuple(dropped))
    if op == "deliver":
        msg = st.channel[x]
        st = st._replace(channel=st.channel[:x] + st.channel[x + 1 :])
        return _vote_count(st, msg)
    if op == "drop":
        # connection died after the server took the request, before the
        # reply: the vote WAS delivered, the client only knows "broken"
        msg = st.channel[x]
        dropped = list(st.dropped)
        dropped[msg.rank] = msg
        st = st._replace(
            channel=st.channel[:x] + st.channel[x + 1 :],
            dropped=tuple(dropped),
            drops_left=st.drops_left - 1,
        )
        return _vote_count(st, msg)
    raise AssertionError(f"unknown vote transition {t}")


def _vote_count(st: VoteState, msg: VoteMsg) -> VoteState:
    """Server side of one delivered vote: fold it into the open tally and,
    on the world_size'th vote, complete the round (compute the decision,
    advance every rank that was at this step, open the next round)."""
    tally = dict(st.tally)
    tally[msg.rank] = (msg.step, msg.vote)
    st = st._replace(tally=tuple(sorted(tally.items())))
    if len(tally) < len(st.at):
        return st
    decision = all(v for _, (_, v) in sorted(tally.items()))
    at = tuple(a + 1 if a == st.step else a for a in st.at)
    return st._replace(
        step=st.step + 1,
        tally=(),
        at=at,
        decisions=st.decisions + ((st.step, decision),),
    )


# ---------------------------------------------------------------------------
# resize sub-model (online parallelism switching, parallel/layout.py)
# ---------------------------------------------------------------------------
#
# Models the two-phase layout-switch protocol over whole replica groups:
# a quorum whose live world no longer fits the active layout PLANS the
# next layout under a fresh monotone epoch; each group then STAGES the
# reshard transfers (which can fail, or the group can crash mid-stage);
# the next quorum COMMITS the switch iff every participant reports the
# staged epoch (min == max == E at the planned world) — otherwise the
# whole fleet rolls back and the epoch is BURNED, never reused.
#
# Layout identity is abstracted to the (world, generation) pair the plan
# was made for — equal inputs produce equal layouts in the runtime
# planner, so generation inequality stands in for "different (dp, shard,
# pp) / different resharded bytes".


class ResizeConfig(NamedTuple):
    """One bounded resize scenario."""

    n_replicas: int = 3
    target_switches: int = 2  # goal: this many committed layout switches
    crash_budget: int = 1  # group deaths (staged buffers die with them)
    join_budget: int = 1  # dead groups re-admitted fresh (epoch 0)
    stage_fail_budget: int = 1  # reshard transfer failures


class RRep(NamedTuple):
    alive: bool
    epoch: int  # active layout epoch
    gen: int  # active layout identity (0 = the implicit seed layout)
    world: int  # the world the ACTIVE layout was planned for
    # staged switch awaiting its commit round: (epoch, world, gen)
    staged: "Optional[Tuple[int, int, int]]"
    # planned this round, transfer not yet attempted: (epoch, world, gen)
    pending: "Optional[Tuple[int, int, int]]"


class RGhost(NamedTuple):
    """Spec-side bookkeeping; never read by the (mutable) behavior."""

    # epoch value -> generation it was first planned under (epoch reuse
    # across generations is the layout-epoch-monotone violation)
    epoch_gens: "Tuple[Tuple[int, int], ...]"
    # last quorum's (participant_count, activator_count, distinct (epoch,
    # gen) pairs activated) — the switch-atomicity record
    last_round: "Optional[Tuple[int, int, int]]"
    # last activation per replica: (replica, prev_epoch, new_epoch)
    last_activation: "Optional[Tuple[int, int, int]]"


class ResizeState(NamedTuple):
    reps: "Tuple[RRep, ...]"
    highest: int  # highest epoch ever planned (behavior-side)
    burned: "FrozenSet[int]"  # rolled-back epochs (behavior-side)
    gen_seq: int  # plan counter
    switches: int  # committed switch rounds so far
    ghost: RGhost
    crashes: int
    joins: int
    stage_fails: int


def resize_initial(cfg: ResizeConfig) -> ResizeState:
    # seed: every group runs the implicit pure-DP layout at epoch 0,
    # planned (by construction) for the full initial fleet
    reps = tuple(
        RRep(
            alive=True, epoch=0, gen=0, world=cfg.n_replicas,
            staged=None, pending=None,
        )
        for _ in range(cfg.n_replicas)
    )
    return ResizeState(
        reps=reps,
        highest=0,
        burned=frozenset(),
        gen_seq=0,
        switches=0,
        ghost=RGhost(epoch_gens=(), last_round=None, last_activation=None),
        crashes=cfg.crash_budget,
        joins=cfg.join_budget,
        stage_fails=cfg.stage_fail_budget,
    )


def _resize_live(st: ResizeState) -> "List[int]":
    return [i for i, r in enumerate(st.reps) if r.alive]


def resize_enabled(
    cfg: ResizeConfig,
    st: ResizeState,
    mutations: "FrozenSet[str]" = frozenset(),
) -> "List[Transition]":
    del mutations  # the mutated behaviors live in resize_apply
    out: "List[Transition]" = []
    for i, r in enumerate(st.reps):
        if r.alive and r.pending is not None:
            out.append(("stage", i))
            if st.stage_fails > 0:
                out.append(("stage_fail", i))
        if r.alive and st.crashes > 0:
            out.append(("crash", i))
        if not r.alive and st.joins > 0:
            out.append(("join", i))
    live = _resize_live(st)
    # the quorum barrier: everyone alive finished (or skipped) staging
    if live and all(st.reps[i].pending is None for i in live):
        if st.switches < cfg.target_switches:
            out.append(("quorum", -1))
    return sorted(out)


def resize_apply(
    cfg: ResizeConfig,
    st: ResizeState,
    t: Transition,
    mutations: "FrozenSet[str]" = frozenset(),
) -> ResizeState:
    op, i = t
    reps = list(st.reps)
    ghost = st.ghost

    if op == "stage":
        r = reps[i]
        assert r.pending is not None
        reps[i] = r._replace(staged=r.pending, pending=None)
        return st._replace(reps=tuple(reps))

    if op == "stage_fail":
        r = reps[i]
        # transfer failed: nothing staged; the commit round sees this
        # group still reporting its old epoch and rolls the fleet back
        reps[i] = r._replace(pending=None)
        return st._replace(reps=tuple(reps), stage_fails=st.stage_fails - 1)

    if op == "crash":
        reps[i] = reps[i]._replace(alive=False, staged=None, pending=None)
        return st._replace(reps=tuple(reps), crashes=st.crashes - 1)

    if op == "join":
        # a fresh incarnation: no layout history, no sharded data (its
        # world=0 can never equal a live world, forcing a fleet re-plan
        # that fetches its shard — exactly the runtime's joiner path)
        reps[i] = RRep(
            alive=True, epoch=0, gen=0, world=0, staged=None, pending=None
        )
        return st._replace(reps=tuple(reps), joins=st.joins - 1)

    if op == "quorum":
        live = _resize_live(st)
        world = len(live)
        reported = {
            j: (reps[j].staged[0] if reps[j].staged is not None else reps[j].epoch)
            for j in live
        }
        min_e, max_e = min(reported.values()), max(reported.values())
        staged_pairs = {
            reps[j].staged for j in live if reps[j].staged is not None
        }
        switches = st.switches
        burned = st.burned
        activators: "List[int]" = []
        activated_pairs: "set" = set()
        # --- commit / rollback of the previous round's stage ------------
        unanimous = (
            len(staged_pairs) == 1
            and all(reps[j].staged is not None for j in live)
            and min_e == max_e
            and next(iter(staged_pairs))[1] == world
        )
        for j in live:
            r = reps[j]
            if r.staged is None:
                continue
            if unanimous or "commit_mixed_epochs" in mutations:
                e, w, g = r.staged
                ghost = ghost._replace(last_activation=(j, r.epoch, e))
                activators.append(j)
                activated_pairs.add((e, g))
                reps[j] = r._replace(epoch=e, gen=g, world=w, staged=None)
            else:
                burned = burned | {r.staged[0]}
                reps[j] = r._replace(staged=None)
        ghost = ghost._replace(
            last_round=(len(live), len(activators), len(activated_pairs))
        )
        if activators and len(activators) == len(live):
            switches += 1
        # --- plan the next switch if the world no longer fits -----------
        live_reps = [reps[j] for j in live]
        uniform = len({(r.epoch, r.gen, r.world) for r in live_reps}) == 1
        needs_plan = (not uniform) or live_reps[0].world != world
        new_highest = st.highest
        gen_seq = st.gen_seq
        if needs_plan:
            if "reuse_epoch_after_rollback" in mutations and burned:
                epoch = max(burned)
            else:
                epoch = max(new_highest, max_e) + 1
            new_highest = max(new_highest, epoch)
            gen_seq += 1
            ghost = ghost._replace(
                epoch_gens=ghost.epoch_gens + ((epoch, gen_seq),)
            )
            for j in live:
                reps[j] = reps[j]._replace(
                    pending=(epoch, world, gen_seq)
                )
        return st._replace(
            reps=tuple(reps),
            highest=new_highest,
            burned=burned,
            gen_seq=gen_seq,
            switches=switches,
            ghost=ghost,
        )

    raise AssertionError(f"unknown resize transition {t}")


def resize_check(cfg: ResizeConfig, st: ResizeState) -> "List[Violation]":
    out: "List[Violation]" = []
    # layout-epoch-monotone: (a) an epoch value is bound to exactly one
    # generation — burned epochs are never reused; (b) activations
    # strictly advance the replica's epoch.
    seen: "Dict[int, int]" = {}
    for epoch, gen in st.ghost.epoch_gens:
        if epoch in seen and seen[epoch] != gen:
            out.append(
                Violation(
                    "layout-epoch-monotone",
                    f"layout epoch {epoch} planned twice (generations "
                    f"{seen[epoch]} and {gen}) — a rolled-back epoch was "
                    f"reused, so a straggler's stale stage could commit "
                    f"under the fresh plan",
                    "lighthouse",
                    "plan",
                )
            )
        seen.setdefault(epoch, gen)
    la = st.ghost.last_activation
    if la is not None and la[2] <= la[1]:
        out.append(
            Violation(
                "layout-epoch-monotone",
                f"replica r{la[0]} activated epoch {la[2]} over active "
                f"epoch {la[1]} — layout epochs must strictly advance",
                f"r{la[0]}:0",
                "commit_layout",
            )
        )
    # all-commit-same-epoch: a switch is fleet-atomic — either every
    # quorum participant activates (one identical layout) or none does.
    lr = st.ghost.last_round
    if lr is not None:
        participants, activators, distinct = lr
        if 0 < activators < participants or distinct > 1:
            out.append(
                Violation(
                    "all-commit-same-epoch",
                    f"layout commit split the fleet: {activators} of "
                    f"{participants} participants activated "
                    f"({distinct} distinct layouts) — every replica must "
                    f"switch at the same round or not at all",
                    "lighthouse",
                    "commit_layout",
                )
            )
    return out


def resize_is_goal(cfg: ResizeConfig, st: ResizeState) -> bool:
    return st.switches >= cfg.target_switches


def vote_check(st: VoteState) -> "List[Violation]":
    """vote-integrity: every tallied vote was cast for the round it is
    counted in — a duplicate delivery of an old vote must never satisfy a
    later round's barrier."""
    out = []
    for rank, (step_voted, _) in st.tally:
        if step_voted != st.step:
            out.append(
                Violation(
                    "vote-integrity",
                    f"rank {rank}'s should_commit vote for step "
                    f"{step_voted} was counted toward the step {st.step} "
                    f"barrier (double-delivered vote released a stale "
                    f"tally)",
                    f"rank{rank}",
                    "commit",
                )
            )
    return out


# ---------------------------------------------------------------------------
# coordination-plane HA sub-model: leased leader election
# ---------------------------------------------------------------------------
#
# N lighthouse peers over a static endpoint list elect a leader by
# majority lease acknowledgement (native/lighthouse.cc election_loop /
# rpc_lease).  Modeled faithfully where it matters for safety:
#
#   - each peer holds ONE promise (term, candidate) — monotone in term,
#     and a term granted to one candidate is never granted to another
#     (the at-most-one-leader-per-term rule);
#   - a fresh grant to ANOTHER peer shields the holder (the lease); a
#     peer's own failed-candidacy self-promise does not;
#   - promise freshness decays only by an explicit ``e_expire`` event
#     (renewals stopped: the promised leader is dead or deposed), which
#     is how takeover-on-expiry enters the model;
#   - lighthouse state is soft, so a takeover transfers nothing: the new
#     leader mints quorum ids as ``(term << 32) | seq`` with seq reset —
#     the ONLY mechanism keeping quorum_id monotone across failover, and
#     exactly what the reuse_quorum_seq_after_takeover mutation breaks.
#
# Ghost fields record every leadership and every minted quorum id in
# global order; the invariants read only the ghosts, so a mutated
# behavior cannot corrupt the judge.

_E_TERM_SHIFT = 32  # matches native lighthouse.h ha_epoch_id


class ElectionConfig(NamedTuple):
    """One bounded election scenario."""

    n_peers: int = 3
    target_quorums: int = 2  # goal: quorums formed across leaderships
    crash_budget: int = 1  # leader deaths
    expire_budget: int = 3  # promise-expiry (renewals-stopped) events


class EPeer(NamedTuple):
    alive: bool
    promised_term: int
    promised_to: int  # peer index; -1 = never granted
    promise_fresh: bool  # the lease shield (renewed by a live leader)
    leading_term: int  # term this peer leads under (0 = follower)
    # candidacy in flight: (term, frozenset of granting peer indices)
    candidacy: "Optional[Tuple[int, FrozenSet[int]]]"
    quorum_seq: int  # low word of ids minted under this leadership


class EGhost(NamedTuple):
    """Spec-side bookkeeping; never read by the (mutable) behavior."""

    # every leadership ever established, in establishment order
    leaderships: "Tuple[Tuple[int, int], ...]"  # (term, peer)
    # last grant: (peer, old_promised_term, new_promised_term)
    last_grant: "Optional[Tuple[int, int, int]]"
    # every quorum id minted, in formation order
    quorum_ids: "Tuple[int, ...]"


class ElectionState(NamedTuple):
    peers: "Tuple[EPeer, ...]"
    ghost: EGhost
    crashes: int
    expires: int


def election_initial(cfg: ElectionConfig) -> ElectionState:
    peers = tuple(
        EPeer(
            alive=True,
            promised_term=0,
            promised_to=-1,
            promise_fresh=False,
            leading_term=0,
            candidacy=None,
            quorum_seq=0,
        )
        for _ in range(cfg.n_peers)
    )
    return ElectionState(
        peers=peers,
        ghost=EGhost(leaderships=(), last_grant=None, quorum_ids=()),
        crashes=cfg.crash_budget,
        expires=cfg.expire_budget,
    )


def _e_pair(granter: int, candidate: int, n: int) -> int:
    """Encode a (granter, candidate) pair into the Transition int."""
    return granter * n + candidate


def e_unpair(code: int, n: int) -> "Tuple[int, int]":
    return code // n, code % n


def _e_can_campaign(p: EPeer, i: int) -> bool:
    """The elector's candidacy gate: free when never/self-promised or
    the granted promise lapsed (native election_loop 'stale')."""
    return (
        not p.promise_fresh or p.promised_to == i or p.promised_to == -1
    )


def election_enabled(
    cfg: ElectionConfig,
    st: ElectionState,
    mutations: "FrozenSet[str]" = frozenset(),
) -> "List[Transition]":
    del mutations  # mutated behaviors live in election_apply
    n = cfg.n_peers
    out: "List[Transition]" = []
    for i, p in enumerate(st.peers):
        if not p.alive:
            continue
        if (
            p.leading_term == 0
            and p.candidacy is None
            and _e_can_campaign(p, i)
        ):
            out.append(("e_candidate", i))
        if p.candidacy is not None:
            term, granted = p.candidacy
            for j, q in enumerate(st.peers):
                if j != i and q.alive and j not in granted:
                    out.append(("e_grant", _e_pair(j, i, n)))
            # the election post-check (native election_loop): the
            # candidate's own promise must still back THIS candidacy — a
            # higher-term grant it gave away meanwhile aborts the round
            if (
                2 * len(granted) > n
                and p.promised_to == i
                and p.promised_term == term
            ):
                out.append(("e_elect", i))
        if p.leading_term > 0:
            if len(st.ghost.quorum_ids) < cfg.target_quorums:
                out.append(("e_form", i))
            if st.crashes > 0:
                out.append(("e_crash", i))
    if st.expires > 0:
        for j, q in enumerate(st.peers):
            # renewals stop only when the promised leader cannot renew:
            # dead, deposed, or never a leader (a failed candidacy)
            if q.alive and q.promise_fresh and q.promised_to >= 0:
                holder = st.peers[q.promised_to]
                if not holder.alive or holder.leading_term == 0:
                    out.append(("e_expire", j))
    return sorted(out)


def election_apply(
    cfg: ElectionConfig,
    st: ElectionState,
    t: Transition,
    mutations: "FrozenSet[str]" = frozenset(),
) -> ElectionState:
    op, code = t
    n = cfg.n_peers
    peers = list(st.peers)
    ghost = st.ghost

    if op == "e_candidate":
        i = code
        p = peers[i]
        term = max(p.promised_term, p.leading_term) + 1
        # self-grant under the same rule rpc_lease applies locally
        peers[i] = p._replace(
            promised_term=term,
            promised_to=i,
            promise_fresh=True,
            candidacy=(term, frozenset({i})),
        )
        ghost = ghost._replace(last_grant=(i, p.promised_term, term))
        return st._replace(peers=tuple(peers), ghost=ghost)

    if op == "e_grant":
        j, i = e_unpair(code, n)
        granter = peers[j]
        cand = peers[i]
        assert cand.candidacy is not None
        term, granted = cand.candidacy
        # the grant rule (native rpc_lease): strictly higher term, and an
        # unshielded slot.  A fresh grant shields its holder — including
        # the granter's OWN record while it actually leads; only a
        # failed-candidacy self-promise (holder == granter, not leading)
        # does not shield.
        shielded = (
            granter.promise_fresh
            and granter.promised_to != -1
            and not (
                granter.promised_to == j and granter.leading_term == 0
            )
        )
        if "two_leaders_same_term" in mutations:
            ok = term >= granter.promised_term and not shielded
        else:
            ok = term > granter.promised_term and not shielded
        if ok:
            ghost = ghost._replace(
                last_grant=(j, granter.promised_term, term)
            )
            peers[j] = granter._replace(
                promised_term=term, promised_to=i, promise_fresh=True
            )
            peers[i] = cand._replace(candidacy=(term, granted | {j}))
        else:
            # a refusal teaches the candidate nothing in-model (max_seen
            # only accelerates convergence; safety is grant-side)
            peers[i] = cand._replace(candidacy=(term, granted))
        return st._replace(peers=tuple(peers), ghost=ghost)

    if op == "e_elect":
        i = code
        p = peers[i]
        assert p.candidacy is not None
        term, granted = p.candidacy
        assert 2 * len(granted) > n
        # winning refreshes the leader's own promise record (native
        # become_leader_locked): its slot now shields like any lease
        peers[i] = p._replace(
            leading_term=term,
            candidacy=None,
            quorum_seq=0,
            promised_term=term,
            promised_to=i,
            promise_fresh=True,
        )
        ghost = ghost._replace(leaderships=ghost.leaderships + ((term, i),))
        return st._replace(peers=tuple(peers), ghost=ghost)

    if op == "e_form":
        i = code
        p = peers[i]
        assert p.leading_term > 0
        seq = p.quorum_seq + 1
        if "reuse_quorum_seq_after_takeover" in mutations:
            qid = seq  # no term prefix: repeats the dead leader's values
        else:
            qid = (p.leading_term << _E_TERM_SHIFT) | seq
        peers[i] = p._replace(quorum_seq=seq)
        ghost = ghost._replace(quorum_ids=ghost.quorum_ids + (qid,))
        return st._replace(peers=tuple(peers), ghost=ghost)

    if op == "e_crash":
        i = code
        peers[i] = peers[i]._replace(
            alive=False, leading_term=0, candidacy=None
        )
        return st._replace(peers=tuple(peers), crashes=st.crashes - 1)

    if op == "e_expire":
        j = code
        holder = peers[j].promised_to
        peers[j] = peers[j]._replace(promise_fresh=False)
        # A lapsed promise withdraws its grant from any still-open
        # candidacy it backed — including the candidate's own self-grant:
        # the implementation bounds each candidacy round to the lease
        # window precisely so an election can never complete on expired
        # acknowledgements (election_loop's round-deadline check).
        if holder >= 0:
            h = peers[holder]
            if h.candidacy is not None:
                term, granted = h.candidacy
                if j in granted and term == peers[j].promised_term:
                    peers[holder] = h._replace(
                        candidacy=(term, granted - {j})
                    )
        return st._replace(peers=tuple(peers), expires=st.expires - 1)

    raise AssertionError(f"unknown election transition {t}")


def election_check(
    cfg: ElectionConfig, st: ElectionState
) -> "List[Violation]":
    out: "List[Violation]" = []
    # at-most-one-leader-per-term: no term ever establishes two leaders.
    by_term: "Dict[int, int]" = {}
    for term, peer in st.ghost.leaderships:
        if term in by_term and by_term[term] != peer:
            out.append(
                Violation(
                    "at-most-one-leader-per-term",
                    f"term {term} established two leaders (peer "
                    f"{by_term[term]} and peer {peer}) — a granter "
                    f"acknowledged the same term twice",
                    f"peer{peer}",
                    "e_elect",
                )
            )
        by_term.setdefault(term, peer)
    # term-monotone: (a) a grant never lowers a peer's promised term;
    # (b) successive leaderships carry strictly increasing terms.
    lg = st.ghost.last_grant
    if lg is not None and lg[2] < lg[1]:
        out.append(
            Violation(
                "term-monotone",
                f"peer {lg[0]}'s promised term regressed {lg[1]} -> "
                f"{lg[2]}",
                f"peer{lg[0]}",
                "e_grant",
            )
        )
    for k in range(1, len(st.ghost.leaderships)):
        prev_t, _ = st.ghost.leaderships[k - 1]
        cur_t, cur_p = st.ghost.leaderships[k]
        if cur_t < prev_t or (
            cur_t == prev_t and by_term.get(cur_t) == cur_p
        ):
            out.append(
                Violation(
                    "term-monotone",
                    f"leadership terms did not advance: term {prev_t} "
                    f"then term {cur_t}",
                    f"peer{cur_p}",
                    "e_elect",
                )
            )
    # quorum-id-monotone-across-failover: every minted id strictly
    # exceeds all earlier ones, INCLUDING across a leader change.
    ids = st.ghost.quorum_ids
    for k in range(1, len(ids)):
        if ids[k] <= ids[k - 1]:
            out.append(
                Violation(
                    "quorum-id-monotone-across-failover",
                    f"quorum_id regressed across formations: "
                    f"{ids[k - 1]} then {ids[k]} — a takeover minted ids "
                    f"a previous leader already served",
                    "lighthouse",
                    "e_form",
                )
            )
            break
    return out


def election_is_goal(cfg: ElectionConfig, st: ElectionState) -> bool:
    return len(st.ghost.quorum_ids) >= cfg.target_quorums


# ---------------------------------------------------------------------------
# Durable-store cold-restore sub-model (ISSUE 17, docs/architecture.md
# "Durable fragment store").
#
# Models the whole-fleet cold start: each disk spills versions fragment
# by fragment with the manifest written LAST (its presence asserts every
# referenced blob was durably written first), the fleet crashes at an
# arbitrary point (including mid-spill), blobs may additionally rot, and
# restore must pick the newest *complete, consistent* cut across the
# union of surviving disks — never serving a torn blob, never mixing
# fragment versions across an outer sync, and degrading to an older
# complete version (or a fresh init) instead of wedging.
#
# Blob cells are "ok" (durably written, digest-valid), "torn" (bytes on
# disk that fail digest verify — a torn write or bit rot), or "-"
# (absent).  The ghost records the spec-side answer (which versions were
# GENUINELY complete at restore) so mutated selection logic cannot
# corrupt the judge.
# ---------------------------------------------------------------------------


class RestoreConfig(NamedTuple):
    """One bounded cold-restore scenario."""

    n_disks: int = 2
    n_fragments: int = 2
    n_versions: int = 2
    rot_budget: int = 1  # blobs that may rot/tear before restore


class DiskRep(NamedTuple):
    # blobs[version][fragment] in {"ok", "torn", "-"}
    blobs: "Tuple[Tuple[str, ...], ...]"
    manifests: "Tuple[bool, ...]"  # manifest durably on disk, per version


class RestoreGhost(NamedTuple):
    """Spec-side restore record; never read by the (mutable) behavior."""

    # versions genuinely complete at restore time: some disk holds the
    # manifest and the union of digest-VALID blobs covers every fragment
    completes: "Tuple[int, ...]"
    chosen: int  # version the behavior restored (-1 = fresh init)
    # per-fragment provenance: (fragment, version served from, torn?)
    sources: "Tuple[Tuple[int, int, bool], ...]"


class RestoreState(NamedTuple):
    disks: "Tuple[DiskRep, ...]"
    crashed: bool
    restored: bool
    rot: int  # rot budget remaining
    ghost: "Optional[RestoreGhost]"


def restore_initial(cfg: RestoreConfig) -> RestoreState:
    empty = tuple(
        tuple("-" for _ in range(cfg.n_fragments))
        for _ in range(cfg.n_versions)
    )
    disks = tuple(
        DiskRep(blobs=empty, manifests=(False,) * cfg.n_versions)
        for _ in range(cfg.n_disks)
    )
    return RestoreState(
        disks=disks,
        crashed=False,
        restored=False,
        rot=cfg.rot_budget,
        ghost=None,
    )


def _disk_next_write(
    cfg: RestoreConfig, d: DiskRep
) -> "Optional[Tuple[int, int]]":
    """The disk's next spill write as (version, fragment), fragment == -1
    meaning the manifest: versions spill in order, blobs before the
    manifest (the durability contract store.py enforces)."""
    for v in range(cfg.n_versions):
        if d.manifests[v]:
            continue
        for f in range(cfg.n_fragments):
            if d.blobs[v][f] == "-":
                return (v, f)
        return (v, -1)
    return None


def _rot_target(d: DiskRep) -> "Optional[Tuple[int, int]]":
    """The blob rot flips: the first 'ok' blob of the NEWEST version
    holding any — deterministic, and exactly the blob whose loss makes
    'manifest present but cut torn' reachable."""
    for v in range(len(d.blobs) - 1, -1, -1):
        for f, cell in enumerate(d.blobs[v]):
            if cell == "ok":
                return (v, f)
    return None


def restore_enabled(
    cfg: RestoreConfig,
    st: RestoreState,
    mutations: "FrozenSet[str]" = frozenset(),
) -> "List[Transition]":
    del mutations  # the mutated behaviors live in restore_apply
    out: "List[Transition]" = []
    if st.restored:
        return out
    if not st.crashed:
        out.append(("crash", -1))
        for i, d in enumerate(st.disks):
            if _disk_next_write(cfg, d) is not None:
                out.append(("spill", i))
    else:
        out.append(("restore", -1))
    if st.rot > 0:
        for i, d in enumerate(st.disks):
            if _rot_target(d) is not None:
                out.append(("rot", i))
    return sorted(out)


def restore_apply(
    cfg: RestoreConfig,
    st: RestoreState,
    t: Transition,
    mutations: "FrozenSet[str]" = frozenset(),
) -> RestoreState:
    op, i = t
    disks = list(st.disks)

    if op == "spill":
        d = disks[i]
        nxt = _disk_next_write(cfg, d)
        assert nxt is not None
        v, f = nxt
        if f == -1:
            manifests = list(d.manifests)
            manifests[v] = True
            disks[i] = d._replace(manifests=tuple(manifests))
        else:
            blobs = [list(row) for row in d.blobs]
            blobs[v][f] = "ok"
            disks[i] = d._replace(blobs=tuple(tuple(r) for r in blobs))
        return st._replace(disks=tuple(disks))

    if op == "rot":
        d = disks[i]
        tgt = _rot_target(d)
        assert tgt is not None
        v, f = tgt
        blobs = [list(row) for row in d.blobs]
        blobs[v][f] = "torn"
        disks[i] = d._replace(blobs=tuple(tuple(r) for r in blobs))
        return st._replace(disks=tuple(disks), rot=st.rot - 1)

    if op == "crash":
        return st._replace(crashed=True)

    if op == "restore":
        frags_all = frozenset(range(cfg.n_fragments))

        def union(v: int, count_torn: bool) -> "FrozenSet[int]":
            got = set()
            for d in disks:
                if not d.manifests[v]:
                    continue
                for f in range(cfg.n_fragments):
                    cell = d.blobs[v][f]
                    if cell == "ok" or (count_torn and cell == "torn"):
                        got.add(f)
            return frozenset(got)

        # spec-side truth: genuinely complete versions (torn excluded)
        completes = tuple(
            v for v in range(cfg.n_versions) if union(v, False) == frags_all
        )

        chosen = -1
        sources: "List[Tuple[int, int, bool]]" = []
        if "serve_torn_blob" in mutations:
            # BUG: digest verify skipped — torn blobs count as servable,
            # so a torn cut can be chosen and torn bytes land in state.
            for v in range(cfg.n_versions - 1, -1, -1):
                if union(v, True) == frags_all:
                    chosen = v
                    valid = union(v, False)
                    sources = [
                        (f, v, f not in valid) for f in sorted(frags_all)
                    ]
                    break
        elif "mix_versions_in_cut" in mutations:
            # BUG: the newest manifested version is chosen even when
            # incomplete, its holes filled from OLDER versions' blobs —
            # the restored state splices fragments across outer syncs.
            newest = max(
                (
                    v
                    for v in range(cfg.n_versions)
                    if any(d.manifests[v] for d in disks)
                ),
                default=-1,
            )
            if newest >= 0:
                mixed_srcs: "Optional[List[Tuple[int, int, bool]]]" = []
                for f in sorted(frags_all):
                    src = next(
                        (
                            v
                            for v in range(newest, -1, -1)
                            if f in union(v, False)
                        ),
                        None,
                    )
                    if src is None:
                        # not even an older blob: this (buggy) selector
                        # still degrades to fresh init rather than a cut
                        # with holes — the modeled bug is the splice
                        mixed_srcs = None
                        break
                    mixed_srcs.append((f, src, False))
                if mixed_srcs is not None:
                    chosen = newest
                    sources = mixed_srcs
        else:
            # clean behavior (store.select_cut): newest version whose
            # digest-valid union covers every fragment; nothing -> fresh
            for v in range(cfg.n_versions - 1, -1, -1):
                if union(v, False) == frags_all:
                    chosen = v
                    sources = [(f, v, False) for f in sorted(frags_all)]
                    break

        ghost = RestoreGhost(
            completes=completes, chosen=chosen, sources=tuple(sources)
        )
        return st._replace(restored=True, ghost=ghost)

    raise AssertionError(f"unknown restore transition {t}")


def restore_check(cfg: RestoreConfig, st: RestoreState) -> "List[Violation]":
    out: "List[Violation]" = []
    g = st.ghost
    if not st.restored or g is None:
        return out
    # restore-cut-complete: a restored cut serves every fragment from
    # digest-VALID bytes — a torn blob is a missing fragment, and a cut
    # with holes must never be committed as restored state.
    torn_used = [s for s in g.sources if s[2]]
    if g.chosen >= 0 and (
        torn_used or len(g.sources) < cfg.n_fragments
    ):
        detail = (
            f"fragments {sorted(s[0] for s in torn_used)} served from "
            f"torn blobs"
            if torn_used
            else f"only {len(g.sources)} of {cfg.n_fragments} fragments "
            f"sourced"
        )
        out.append(
            Violation(
                "restore-cut-complete",
                f"cold restore committed v{g.chosen} with an incomplete "
                f"or corrupt cut: {detail} — torn blobs must read as "
                f"missing and incomplete cuts must degrade to an older "
                f"complete version",
                "fleet",
                "restore",
            )
        )
    # restore-cut-consistent: every fragment of the restored state comes
    # from the SAME version — mixing versions splices state across outer
    # syncs into a model that never existed.
    mixed = sorted({s[1] for s in g.sources})
    if g.chosen >= 0 and any(s[1] != g.chosen for s in g.sources):
        out.append(
            Violation(
                "restore-cut-consistent",
                f"cold restore of v{g.chosen} mixed fragment versions "
                f"{mixed} in one cut — fragments must never be filled "
                f"from older versions' blobs",
                "fleet",
                "restore",
            )
        )
    # restore-newest-complete: selection is canonical — the newest
    # genuinely complete version when one exists, fresh init otherwise
    # (degrade-never-wedge, and never a cut the spec says is incomplete).
    want = max(g.completes) if g.completes else -1
    if not out and g.chosen != want:
        out.append(
            Violation(
                "restore-newest-complete",
                f"cold restore chose v{g.chosen} but the newest complete "
                f"version on the surviving disks is "
                f"{'v%d' % want if want >= 0 else 'none (fresh init)'}",
                "fleet",
                "restore",
            )
        )
    return out


def restore_is_goal(cfg: RestoreConfig, st: RestoreState) -> bool:
    return st.restored
