"""tft-lint: project-invariant static analysis for torchft_tpu.

PRs 1-3 built a web of cross-cutting invariants — telemetry names and
docs tables in sync, one retry policy, non-blocking signal paths, every
fault site registered — that nothing enforced.  This package is the
enforcement: stdlib-``ast`` passes encoding *this project's* rules (not
generic style), run as ``python -m torchft_tpu.analysis torchft_tpu/``
or the ``tft-lint`` console script, and wired into tier-1 via
tests/test_lint.py so a violation fails CI.

Passes (each with an embedded ``--selftest`` and a checked-in baseline
file for grandfathered findings — all empty):

========================  ==================================================
``lock-discipline``       no blocking calls while holding a lock; no
                          blocking lock acquisition in signal handlers
``env-hygiene``           env reads only via utils/env.py helpers,
                          TORCHFT_*-named, documented
``metrics-sync``          metric names torchft_*, unique, documented;
                          event kinds in both _LOGGERS and _SEVERITY
``metrics-cardinality``   per-replica/per-peer label values bounded or
                          top-K-aggregated (fleet churn must not grow
                          the registry)
``retry-ban``             no time.sleep retry loops outside utils/retry.py
``fault-coverage``        fault sites registered/documented/wired; PG +
                          transport paths feed the flight recorder
``wire-drift``            framed-JSON wire schema in sync across Python
                          clients, native servers, docs/protocol.md, and
                          the committed protocol.lock
``span-vocab``            trace-span names from PROTOCOL_PHASES /
                          quant.* / heal.* / rpc.*; every span emitter
                          also feeds the flight recorder
``plan-discipline``       peer-communication structure (reduction
                          hierarchies, serving trees, stripe rosters)
                          built only via the plan layer's primitives in
                          bless-listed modules — plans stay verifiable
                          data (tft-verify --scenario plan)
========================  ==================================================

The runtime complement is ``utils/lockcheck.py`` (TORCHFT_LOCKCHECK=1
lock-order cycle detection) and the native TSan build
(``make -C native SANITIZE=thread``) — see docs/static_analysis.md.

The sibling subsystem ``tft-verify`` (``torchft_tpu.analysis.verify_cli``,
console script ``tft-verify``) is the *dynamic* half of the same
contract: an executable model of the quorum protocol
(:mod:`torchft_tpu.analysis.protocol_model`) exhaustively explored by
:mod:`torchft_tpu.analysis.model_checker`, plus the wire-schema lock
workflow (``--write-lock`` / ``--drift``).
"""

from torchft_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintPass,
    Project,
    SelftestError,
    run_passes,
)
from torchft_tpu.analysis.coverage import PASS as _coverage
from torchft_tpu.analysis.env_hygiene import PASS as _env_hygiene
from torchft_tpu.analysis.lock_discipline import PASS as _lock_discipline
from torchft_tpu.analysis.metrics_cardinality import PASS as _metrics_cardinality
from torchft_tpu.analysis.metrics_sync import PASS as _metrics_sync
from torchft_tpu.analysis.plan_discipline import PASS as _plan_discipline
from torchft_tpu.analysis.retry_ban import PASS as _retry_ban
from torchft_tpu.analysis.span_vocab import PASS as _span_vocab
from torchft_tpu.analysis.wire_schema import PASS as _wire_drift

#: Every registered pass, in documentation order.
PASSES = (
    _lock_discipline,
    _env_hygiene,
    _metrics_sync,
    _metrics_cardinality,
    _retry_ban,
    _coverage,
    _wire_drift,
    _span_vocab,
    _plan_discipline,
)

__all__ = [
    "PASSES",
    "Finding",
    "LintPass",
    "Project",
    "SelftestError",
    "run_passes",
]
