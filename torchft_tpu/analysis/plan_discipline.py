"""Pass ``plan-discipline``: peer-communication structure is built only
by the plan layer and its bless-listed executors (ISSUE 19).

The point of the Plan IR (:mod:`torchft_tpu.analysis.plan_ir`) is that
"who talks to whom" is *data* with checkable invariants — reduction
hierarchies, serving trees, stripe assignments.  That property dies the
day a fourth subsystem quietly derives its own peer list from a roster
slice or re-implements the round-robin fragment layout: the verifier
never sees that plan, and the next ROADMAP item 4 synthesizer can not
replace math it does not know exists.

This pass freezes the perimeter: calling a PLAN PRIMITIVE — the
constructors every communication structure flows through
(``synthesize_plan`` / ``parse_topology`` / ``resolve_topology``,
``serving_plan``, ``fragment_slots`` / ``split_chunks`` /
``fragment_into_map``, ``stripe_roster`` / ``stripe_source_cohort``,
``reference_serving_plan``) — is allowed only in the IR/adapter layer
and the bless-listed modules that execute or transport plans today.
Anything else is a new peer-structure author and must either go through
the plan layer or argue its way onto the bless list in review.  The
baseline ships empty: nothing is grandfathered.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    SelftestError,
    dotted,
)

PASS_ID = "plan-discipline"

#: Call names (last dotted segment) that build peer-communication
#: structure.  Definitions do not match — only calls.
PLAN_PRIMITIVES = frozenset(
    {
        "synthesize_plan",
        "parse_topology",
        "resolve_topology",
        "serving_plan",
        "fragment_slots",
        "split_chunks",
        "fragment_into_map",
        "stripe_roster",
        "stripe_source_cohort",
        "reference_serving_plan",
    }
)

#: Modules allowed to call plan primitives: the plan layer itself, the
#: planners' home modules, and the executors/transports that consume a
#: plan.  Growing this list is a review decision, not a default.
_BLESSED: "Tuple[str, ...]" = (
    "analysis/plan_ir.py",
    "analysis/plan_verify.py",
    "ops/topology.py",
    "ops/collectives.py",
    "parallel/process_group.py",
    "serving/client.py",
    "serving/replica.py",
    "checkpointing/fragments.py",
    "checkpointing/serialization.py",
    "checkpointing/http_transport.py",
    "manager.py",
)


def _blessed(relpath: str) -> bool:
    norm = relpath.replace("\\", "/")
    return any(norm.endswith(suffix) for suffix in _BLESSED)


class _Visitor(ast.NodeVisitor):
    def __init__(self, project: Project, path: str) -> None:
        self.project = project
        self.path = path
        self.findings: "List[Finding]" = []
        self._qual: "List[str]" = []

    def _visit_scoped(self, node: ast.AST) -> None:
        self._qual.append(node.name)  # type: ignore[attr-defined]
        self.generic_visit(node)
        self._qual.pop()

    visit_FunctionDef = _visit_scoped  # noqa: N815
    visit_AsyncFunctionDef = _visit_scoped  # noqa: N815
    visit_ClassDef = _visit_scoped  # noqa: N815

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        name = dotted(node.func)
        last = name.rsplit(".", 1)[-1] if name else ""
        if last in PLAN_PRIMITIVES:
            self.findings.append(
                Finding(
                    pass_id=PASS_ID,
                    code="plan-primitive-outside-plan-layer",
                    file=self.project.rel(self.path),
                    line=node.lineno,
                    symbol=".".join(self._qual),
                    message=(
                        f"{last}() builds peer-communication structure "
                        f"outside the plan layer — route it through "
                        f"analysis/plan_ir.py (so tft-verify sees the "
                        f"plan) or bless this module in plan_discipline "
                        f"with a review reason"
                    ),
                )
            )
        self.generic_visit(node)


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    for path in project.py_files:
        if _blessed(project.rel(path)):
            continue
        tree = project.tree(path)
        if tree is None:
            continue
        visitor = _Visitor(project, path)
        visitor.visit(tree)
        out.extend(visitor.findings)
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_BAD_SRC = """
from torchft_tpu.ops import topology

def my_private_schedule(world):
    topo = topology.parse_topology("hosts:2", world)
    return topology.synthesize_plan(topo, 0)
"""

_BAD_METHOD_SRC = """
def adopt(client):
    return client.serving_plan()
"""

_GOOD_SIMILAR_SRC = """
def make_plan(world):
    # not a plan primitive: local helper with an unrelated name
    return build_schedule(world)
"""

_GOOD_DEF_SRC = """
def synthesize_plan(topo, rank):
    # defining (e.g. stubbing) is not calling
    return None
"""


def _run_on(rel: str, src: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        return list(run(Project(td, [path])))


def selftest() -> None:
    if not _run_on("pkg/rogue.py", _BAD_SRC):
        raise SelftestError(
            f"{PASS_ID}: unblessed synthesize_plan call not flagged"
        )
    if not _run_on("pkg/rogue.py", _BAD_METHOD_SRC):
        raise SelftestError(
            f"{PASS_ID}: unblessed serving_plan() method call not flagged"
        )
    if _run_on("ops/collectives.py", _BAD_SRC):
        raise SelftestError(
            f"{PASS_ID}: bless-listed executor falsely flagged"
        )
    for name, src in (
        ("similar-name", _GOOD_SIMILAR_SRC),
        ("def-not-call", _GOOD_DEF_SRC),
    ):
        got = _run_on("pkg/ok.py", src)
        if got:
            raise SelftestError(
                f"{PASS_ID}: good snippet {name!r} falsely flagged: "
                f"{[f.render() for f in got]}"
            )


PASS = LintPass(
    id=PASS_ID,
    doc="plan primitives (synthesize_plan, serving_plan, fragment "
    "layout, stripe roster) called only from the plan layer and "
    "bless-listed executors — peer structure stays verifiable data",
    run=run,
    selftest=selftest,
)
