"""tft-verify command line: the quorum-protocol model checker + the
wire-schema lock workflow.

Exit codes: 0 clean, 1 violation/drift found, 2 usage or selftest
failure.  ``make verify`` runs ``tft-lint`` + ``tft-verify --selftest`` +
the full bounded exploration; tier-1 pins the same gates via
tests/test_verify.py and tests/test_wire_schema.py.

Typical invocations::

    tft-verify                      # explore every scenario + mutation gate
                                    # + liveness schedules + wire drift
    tft-verify --selftest           # fast internal-consistency gate
    tft-verify --scenario churn     # one scenario, verbose stats
    tft-verify --mutate heal_from_stale --dump /tmp/cex.jsonl
                                    # seeded-bug counterexample as a flight
                                    # dump torchft-diagnose can render
    tft-verify --write-lock         # regenerate analysis/protocol.lock
    tft-verify --drift              # wire-schema drift findings only
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional, Sequence

from torchft_tpu.analysis import model_checker as mc
from torchft_tpu.analysis import plan_verify as pv
from torchft_tpu.analysis import wire_schema as ws
from torchft_tpu.analysis.core import SelftestError
from torchft_tpu.analysis.protocol_model import MUTATIONS


def _detect_root(start: Optional[str] = None) -> str:
    """Walk up from ``start`` (default: cwd) to the tree that holds the
    native sources; fall back to the package's grandparent (the repo
    layout) and finally cwd."""
    candidates = [start or os.getcwd()]
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    candidates.append(pkg_root)
    for cand in candidates:
        d = os.path.abspath(cand)
        while True:
            if os.path.isfile(os.path.join(d, "native", "lighthouse.cc")):
                return d
            parent = os.path.dirname(d)
            if parent == d:
                break
            d = parent
    return os.path.abspath(start or os.getcwd())


def _print_result(name: str, r: mc.CheckResult, verbose: bool) -> None:
    status = "ok" if r.ok else "VIOLATION"
    line = (
        f"{name:12s} {status:9s} states={r.states} "
        f"transitions={r.transitions} goals={r.goal_states}"
    )
    print(line)
    if not r.ok and r.violation is not None:
        v = r.violation
        print(f"  invariant {v.invariant} violated by {v.replica_id} "
              f"in phase {v.phase}: {v.message}")
        if verbose:
            for op, _i, rid, step, qid in r.trace:
                print(f"    {rid:14s} {op:12s} step={step} quorum_id={qid}")


def run_explore_all(verbose: bool = False) -> int:
    bad = 0
    t0 = time.monotonic()
    for name, cfg in mc.SCENARIOS.items():
        r = mc.explore(cfg)
        _print_result(name, r, verbose)
        bad += 0 if r.ok else 1
    r = mc.explore_votes()
    _print_result("votes", r, verbose)
    bad += 0 if r.ok else 1
    for name, rcfg in mc.RESIZE_SCENARIOS.items():
        r = mc.explore_resize(rcfg)
        _print_result(name, r, verbose)
        bad += 0 if r.ok else 1
    for name, ecfg in mc.ELECTION_SCENARIOS.items():
        r = mc.explore_election(ecfg)
        _print_result(name, r, verbose)
        bad += 0 if r.ok else 1
    for name, scfg in mc.RESTORE_SCENARIOS.items():
        r = mc.explore_restore(scfg)
        _print_result(name, r, verbose)
        bad += 0 if r.ok else 1
    print(f"explored clean in {time.monotonic() - t0:.1f}s"
          if not bad else f"{bad} scenario(s) violated")
    return 1 if bad else 0


def run_mutation_gate(verbose: bool = False) -> int:
    """Every seeded protocol bug must be caught by its expected invariant."""
    missed = 0
    for m in MUTATIONS:
        r = mc.check_mutation(m.name)
        caught = (not r.ok) and r.violation is not None and (
            r.violation.invariant == m.catches
        )
        mark = "caught" if caught else "MISSED"
        print(f"mutation {m.name:26s} {mark} "
              f"(expect {m.catches}, "
              f"got {r.violation.invariant if r.violation else 'clean'})")
        if not caught:
            missed += 1
        elif verbose:
            _print_result(m.name, r, verbose=True)
    return 1 if missed else 0


def run_plan_gate(verbose: bool = False) -> int:
    """The tft-plan scenario (ISSUE 19): exhaustive small-world plan
    enumeration on all three planes must verify clean, and every seeded
    plan mutation must be caught by its named invariant."""
    bad = 0
    t0 = time.monotonic()
    r = pv.explore_plans()
    violations = r["violations"]
    print(f"{'plan':12s} {'ok' if not violations else 'VIOLATION':9s} "
          f"plans={r['plans']} invariants={len(pv.INVARIANTS)} "
          f"({time.monotonic() - t0:.1f}s)")
    if violations:
        bad += 1
        for v in violations[: 20 if verbose else 5]:
            print(f"  invariant {v.invariant} violated at {v.subject}: "
                  f"{v.message}")
    for m in pv.PLAN_MUTATIONS:
        vs = pv.check_plan_mutation(m.name)
        got = vs[0].invariant if vs else "clean"
        caught = got == m.catches
        print(f"plan mutation {m.name:18s} "
              f"{'caught' if caught else 'MISSED'} "
              f"(expect {m.catches}, got {got})")
        if not caught:
            bad += 1
        elif verbose:
            for v in vs[:3]:
                print(f"    {v.invariant}: {v.message}")
    return 1 if bad else 0


def run_liveness(verbose: bool = False) -> int:
    stuck = 0
    for name, scenario, rotation in mc.LIVENESS_SCHEDULES:
        ok, used, trace = mc.run_schedule(mc.SCENARIOS[scenario], rotation)
        print(f"schedule {name:12s} {'ok' if ok else 'LIVELOCK'} "
              f"({used} transitions)")
        if not ok:
            stuck += 1
            if verbose:
                for op, _i, rid, step, qid in trace[-20:]:
                    print(f"    {rid:14s} {op:12s} step={step} "
                          f"quorum_id={qid}")
    return 1 if stuck else 0


def run_drift(root: str) -> int:
    (
        py_source,
        native_sources,
        native_file_of,
        docs_text,
        lock,
        lock_file,
    ) = ws.gather_inputs(root)
    if not native_sources:
        print(f"tft-verify: no native sources under {root} "
              f"(pass --root)", file=sys.stderr)
        return 2
    found = list(
        ws.run_checks(
            py_source,
            native_sources,
            docs_text,
            lock,
            native_file_of=native_file_of,
            lock_file=lock_file,
        )
    )
    for f in found:
        print(f.render())
    print(f"wire drift: {len(found)} finding(s)")
    return 1 if found else 0


def write_lock(root: str) -> int:
    (
        py_source,
        native_sources,
        _nf,
        _docs,
        _lock,
        lock_file,
    ) = ws.gather_inputs(root)
    if not native_sources:
        print(f"tft-verify: no native sources under {root} "
              f"(pass --root)", file=sys.stderr)
        return 2
    fresh = ws.build_lock(py_source, native_sources)
    # write where gather_inputs read: the one canonical lock location
    path = os.path.join(root, lock_file)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(ws.dump_lock(fresh))
    print(f"wrote {path}")
    return 0


def run_selftest() -> int:
    """Fast internal-consistency gate: the checker catches every seeded
    mutation, the steady scenario is clean, and the wire extractor's own
    selftest passes."""
    rc = run_mutation_gate()
    r = mc.explore(mc.SCENARIOS["steady"])
    _print_result("steady", r, verbose=False)
    if not r.ok:
        rc = 2
    try:
        ws.selftest()
        print("selftest wire-drift: ok")
    except SelftestError as e:
        print(f"selftest wire-drift: FAIL — {e}", file=sys.stderr)
        rc = 2
    missed_plan = sum(
        1
        for m in pv.PLAN_MUTATIONS
        if (lambda vs: not vs or vs[0].invariant != m.catches)(
            pv.check_plan_mutation(m.name)
        )
    )
    print(f"selftest plan mutations: "
          f"{'ok' if not missed_plan else f'{missed_plan} MISSED'}")
    if missed_plan:
        rc = 2
    return 2 if rc else 0


def main(argv: "Optional[Sequence[str]]" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tft-verify",
        description=(
            "quorum-protocol model checker (bounded exhaustive exploration "
            "+ mutation gate + liveness schedules) and wire-schema lock "
            "workflow.  See docs/static_analysis.md."
        ),
    )
    parser.add_argument("--selftest", action="store_true",
                        help="fast internal-consistency gate and exit")
    parser.add_argument("--scenario", metavar="NAME",
                        help="explore one scenario (see --list)")
    parser.add_argument("--mutate", metavar="NAME",
                        help="run the checker over one seeded protocol bug")
    parser.add_argument("--dump", metavar="PATH",
                        help="with --mutate: write the counterexample as a "
                        "flight-recorder JSONL dump for torchft-diagnose")
    parser.add_argument("--list", action="store_true",
                        help="list scenarios, mutations and schedules")
    parser.add_argument("--drift", action="store_true",
                        help="run only the wire-schema drift checks")
    parser.add_argument("--write-lock", action="store_true",
                        help="regenerate torchft_tpu/analysis/protocol.lock")
    parser.add_argument("--root", default=None,
                        help="repo root for --drift/--write-lock "
                        "(default: auto-detect)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print counterexample traces")
    args = parser.parse_args(argv)

    if args.list:
        for name, cfg in mc.SCENARIOS.items():
            print(f"scenario {name:12s} {cfg}")
        for name, rcfg in mc.RESIZE_SCENARIOS.items():
            print(f"scenario {name:12s} {rcfg}")
        for name, ecfg in mc.ELECTION_SCENARIOS.items():
            print(f"scenario {name:12s} {ecfg}")
        for name, scfg in mc.RESTORE_SCENARIOS.items():
            print(f"scenario {name:12s} {scfg}")
        print(f"scenario {'plan':12s} topology-plan IR enumeration + "
              f"mutation gate (reduction/serving/stripe)")
        for m in MUTATIONS:
            print(f"mutation {m.name:26s} -> {m.catches}: {m.doc}")
        for pm in pv.PLAN_MUTATIONS:
            print(f"plan mutation {pm.name:21s} -> {pm.catches}: {pm.doc}")
        for name, scenario, rotation in mc.LIVENESS_SCHEDULES:
            print(f"schedule {name:12s} scenario={scenario} "
                  f"rotation={rotation}")
        return 0
    if args.selftest:
        return run_selftest()
    if args.write_lock:
        return write_lock(_detect_root(args.root))
    if args.drift:
        return run_drift(_detect_root(args.root))
    if args.mutate:
        if args.mutate not in {m.name for m in MUTATIONS}:
            print(f"tft-verify: unknown mutation {args.mutate!r}",
                  file=sys.stderr)
            return 2
        r = mc.check_mutation(args.mutate)
        _print_result(args.mutate, r, args.verbose)
        if args.dump and not r.ok:
            mc.write_flight_dump(r, args.dump)
            print(f"wrote counterexample dump to {args.dump} "
                  f"(render: torchft-diagnose {args.dump})")
        return 1 if not r.ok else 0
    if args.scenario:
        if args.scenario == "plan":
            return run_plan_gate(args.verbose)
        if args.scenario in mc.RESIZE_SCENARIOS:
            r = mc.explore_resize(mc.RESIZE_SCENARIOS[args.scenario])
            _print_result(args.scenario, r, args.verbose)
            return 0 if r.ok else 1
        if args.scenario in mc.ELECTION_SCENARIOS:
            r = mc.explore_election(mc.ELECTION_SCENARIOS[args.scenario])
            _print_result(args.scenario, r, args.verbose)
            return 0 if r.ok else 1
        if args.scenario in mc.RESTORE_SCENARIOS:
            r = mc.explore_restore(mc.RESTORE_SCENARIOS[args.scenario])
            _print_result(args.scenario, r, args.verbose)
            return 0 if r.ok else 1
        if args.scenario not in mc.SCENARIOS:
            print(f"tft-verify: unknown scenario {args.scenario!r} "
                  f"(see --list)", file=sys.stderr)
            return 2
        r = mc.explore(mc.SCENARIOS[args.scenario])
        _print_result(args.scenario, r, args.verbose)
        return 0 if r.ok else 1

    # the full gate: exploration + mutations + liveness + plans + drift
    rc = run_explore_all(args.verbose)
    rc = run_mutation_gate(args.verbose) or rc
    rc = run_liveness(args.verbose) or rc
    rc = run_plan_gate(args.verbose) or rc
    rc = run_drift(_detect_root(args.root)) or rc
    return rc


if __name__ == "__main__":
    sys.exit(main())
