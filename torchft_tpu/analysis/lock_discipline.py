"""Pass ``lock-discipline``: no blocking calls while a lock is held, and
no blocking lock acquisition inside signal handlers.

Why this is a *project* invariant and not a style preference: every lock
in this package sits on a path a failure can interrupt — the flight
recorder's signal-handler dump, the PG worker racing ``abort()``, the
metrics registry scraped mid-collective.  A blocking call under a lock
turns "one replica is slow" into "every thread that touches that lock is
wedged", which in a per-step FT protocol is indistinguishable from the
failure the protocol exists to survive.  The flight recorder's
non-blocking signal path (``blocking=False`` everywhere a handler runs)
is the founding example; this pass generalizes the rule.

What counts as *blocking* (deliberately conservative — the goal is zero
false positives on a disciplined tree, extended as new failure classes
appear):

- ``time.sleep``;
- process spawning: ``subprocess.run/call/check_call/check_output/Popen``;
- network ops: ``socket.create_connection``, ``urllib.request.urlopen``,
  ``post_otlp`` (the shared OTLP HTTP leg), ``connect_with_retry``, and
  socket-shaped method calls (``.connect``/``.accept``/``.sendall``);
- RPC round trips: ``.call(...)`` on a ``*client*``/``*rpc*`` receiver;
- collective/work waits: ``.wait(...)`` (except on a condition variable,
  whose ``wait`` *releases* the lock) and the collective submission
  entry points when invoked under a lock.

Lock-ish names: the final path segment ends in ``lock``/``mu``/
``mutex``/``cond`` (covers ``_lock``, ``send_lock``, ``_dump_lock``,
``_cond``, ``r_lock()/w_lock()`` context managers...).

Waivers: a ``# tft-lint: allow(lock-discipline)`` comment on the line
that takes the lock (the ``with`` or ``.acquire`` line) suppresses
findings inside that critical section — for locks whose *purpose* is to
serialize a blocking operation (e.g. the pooled-connection RPC lock,
where callers queueing on the round trip is the contract).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    SelftestError,
    dotted,
)

PASS_ID = "lock-discipline"

_LOCKISH = re.compile(r"(?:^|_)(?:lock|mu|mutex|cond)$")
_CONDISH = re.compile(r"(?:^|_)(?:cond|cv|condition)$")

_BLOCKING_DOTTED_SUFFIX: "Tuple[str, ...]" = (
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "socket.create_connection",
    "urllib.request.urlopen",
    "urlopen",
    "post_otlp",
    "connect_with_retry",
)
_BLOCKING_METHODS: "Tuple[str, ...]" = ("connect", "accept", "sendall", "wait")
_RPC_METHODS: "Tuple[str, ...]" = ("call",)
_COLLECTIVE_METHODS: "Tuple[str, ...]" = (
    "allreduce",
    "allgather",
    "broadcast",
    "reduce_scatter",
    "alltoall",
)


def _seg(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_lockish(name: str) -> bool:
    return bool(name) and bool(_LOCKISH.search(_seg(name)))


def _is_condish(name: str) -> bool:
    return bool(name) and bool(_CONDISH.search(_seg(name)))


def _blocking_reason(call: ast.Call) -> "str | None":
    """Why this call is considered blocking, or None."""
    name = dotted(call.func)
    if not name:
        return None
    for suffix in _BLOCKING_DOTTED_SUFFIX:
        if name == suffix or name.endswith("." + suffix):
            return f"blocking call {suffix}"
    if isinstance(call.func, ast.Attribute):
        meth = call.func.attr
        recv = dotted(call.func.value)
        if meth in _BLOCKING_METHODS:
            # cond.wait() RELEASES the lock — the one legitimate wait
            if meth == "wait" and _is_condish(recv):
                return None
            # thread.join-ish waits on executors are out of scope; sockets
            # and Work handles are the targets
            return f"blocking method .{meth}() on {recv or 'object'}"
        if meth in _RPC_METHODS and re.search(r"client|rpc", recv, re.I):
            return f"RPC round trip .{meth}() on {recv}"
        if meth in _COLLECTIVE_METHODS and recv not in ("", "self"):
            return f"collective .{meth}() submitted under a lock"
    return None


def _has_waiver(project: Project, path: str, lineno: int) -> bool:
    # the pass name is part of the syntax: a waiver written for a
    # different pass (or prose containing "tft-lint: allow") must not
    # silently disable this one
    lines = project.source(path).splitlines()
    if 0 < lineno <= len(lines):
        return f"tft-lint: allow({PASS_ID})" in lines[lineno - 1]
    return False


class _FuncScanner:
    """Scans one function body with a running set of held lock names."""

    def __init__(self, project: Project, path: str, qual: str) -> None:
        self.project = project
        self.path = path
        self.qual = qual
        self.findings: "List[Finding]" = []

    def scan(self, body: "List[ast.stmt]", held: "Set[str]") -> "Set[str]":
        held = set(held)
        for stmt in body:
            held = self._scan_stmt(stmt, held)
        return held

    def _scan_stmt(self, stmt: ast.stmt, held: "Set[str]") -> "Set[str]":
        # nested defs execute later, in their own lock context
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            _ModuleScanner(self.project, self.path, self).visit(stmt)
            return held
        # lock.acquire(...) / lock.release() statements
        call = (
            stmt.value
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)
            else None
        )
        if call is not None and isinstance(call.func, ast.Attribute):
            recv = dotted(call.func.value)
            if _is_lockish(recv):
                if call.func.attr == "acquire":
                    if not _has_waiver(self.project, self.path, stmt.lineno):
                        held.add(recv)
                    return held
                if call.func.attr == "release":
                    held.discard(recv)
                    return held
        if isinstance(stmt, ast.With):
            lock_names: "Set[str]" = set()
            for item in stmt.items:
                name = dotted(item.context_expr)
                if _is_lockish(name):
                    if not _has_waiver(self.project, self.path, stmt.lineno):
                        lock_names.add(name)
            inner = self.scan(stmt.body, held | lock_names)
            # locks from this with are released at exit; explicit
            # acquire()s made inside survive it
            return (inner - lock_names) | (held & lock_names)
        # Compound statements: each alternative branch scans from the
        # INCOMING held set (feeding one branch's exit into its sibling
        # would flag `else: sleep()` after `if c: lock.acquire()`); exits
        # union conservatively so a conditional acquire stays visible.
        if held:
            for expr in self._stmt_exprs(stmt):
                self._check_expr(expr, held)
        if isinstance(stmt, ast.If):
            body_out = self.scan(stmt.body, held)
            else_out = self.scan(stmt.orelse, held) if stmt.orelse else held
            return body_out | else_out
        if isinstance(stmt, (ast.While, ast.For)):
            body_out = self.scan(stmt.body, held)
            out = held | body_out  # body may run zero times
            if stmt.orelse:
                out |= self.scan(stmt.orelse, out)
            return out
        if isinstance(stmt, ast.Try):
            body_out = self.scan(stmt.body, held)
            out = body_out
            for handler in stmt.handlers:
                # an exception may fire mid-body: handlers see anything
                # from "nothing new acquired" to the body's full exit set
                out |= self.scan(handler.body, held | body_out)
            if stmt.orelse:
                out |= self.scan(stmt.orelse, body_out)
            if stmt.finalbody:
                return self.scan(stmt.finalbody, held | out)
            return out
        if held:
            self._check_expr(stmt, held)
        return held

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt) -> "List[ast.AST]":
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.For):
            return [stmt.iter]
        return []

    def _check_expr(self, node: ast.AST, held: "Set[str]") -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # deferred execution
            if isinstance(sub, ast.Call):
                reason = _blocking_reason(sub)
                if reason:
                    self.findings.append(
                        Finding(
                            pass_id=PASS_ID,
                            code="blocking-under-lock",
                            file=self.project.rel(self.path),
                            line=sub.lineno,
                            symbol=self.qual,
                            message=(
                                f"{reason} while holding "
                                f"{sorted(held)} — move the blocking work "
                                f"outside the critical section"
                            ),
                        )
                    )


class _ModuleScanner(ast.NodeVisitor):
    """Walks a module, running a :class:`_FuncScanner` per function and
    collecting ``signal.signal`` handler registrations."""

    def __init__(
        self, project: Project, path: str, parent: "_FuncScanner | None" = None
    ) -> None:
        self.project = project
        self.path = path
        self.findings: "List[Finding]" = (
            parent.findings if parent is not None else []
        )
        self.handler_names: "Set[str]" = set()
        self._stack: "List[str]" = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:  # noqa: N802
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node: ast.AST) -> None:
        qual = ".".join(self._stack + [node.name])  # type: ignore[attr-defined]
        scanner = _FuncScanner(self.project, self.path, qual)
        scanner.scan(node.body, set())  # type: ignore[attr-defined]
        self.findings.extend(scanner.findings)
        # still recurse for nested handler registrations / defs' own defs
        self._stack.append(node.name)  # type: ignore[attr-defined]
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                self.visit(child)
            else:
                self._collect_signal_calls(child)
        self._stack.pop()

    visit_FunctionDef = _visit_func  # noqa: N815
    visit_AsyncFunctionDef = _visit_func  # noqa: N815

    def visit_Module(self, node: ast.Module) -> None:  # noqa: N802
        scanner = _FuncScanner(self.project, self.path, "<module>")
        scanner.scan(
            [
                s
                for s in node.body
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ],
            set(),
        )
        self.findings.extend(scanner.findings)
        self.generic_visit(node)
        self._collect_signal_calls(node)

    def _collect_signal_calls(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and dotted(sub.func).endswith("signal.signal")
                and len(sub.args) >= 2
                and isinstance(sub.args[1], ast.Name)
            ):
                self.handler_names.add(sub.args[1].id)


def _check_signal_handlers(
    project: Project, path: str, tree: ast.Module, handler_names: "Set[str]"
) -> "Iterable[Finding]":
    """Inside a registered signal handler: no ``with <lock>`` and no
    ``.acquire`` without a timeout / ``blocking=False`` — the handler
    runs ON the interrupted thread, which may already hold that lock."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef) or node.name not in handler_names:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.With):
                for item in sub.items:
                    name = dotted(item.context_expr)
                    if _is_lockish(name):
                        yield Finding(
                            pass_id=PASS_ID,
                            code="blocking-lock-in-signal-handler",
                            file=project.rel(path),
                            line=sub.lineno,
                            symbol=node.name,
                            message=(
                                f"signal handler takes {name} with a "
                                f"blocking `with` — the interrupted thread "
                                f"may hold it (use acquire(timeout=...) and "
                                f"degrade, like flightrecorder's dump "
                                f"blocking=False path)"
                            ),
                        )
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
                and _is_lockish(dotted(sub.func.value))
            ):
                kw = {k.arg for k in sub.keywords}
                nonblocking = "timeout" in kw or any(
                    k.arg == "blocking"
                    and isinstance(k.value, ast.Constant)
                    and k.value.value is False
                    for k in sub.keywords
                ) or (
                    sub.args
                    and isinstance(sub.args[0], ast.Constant)
                    and sub.args[0].value is False
                )
                if not nonblocking:
                    yield Finding(
                        pass_id=PASS_ID,
                        code="blocking-lock-in-signal-handler",
                        file=project.rel(path),
                        line=sub.lineno,
                        symbol=node.name,
                        message=(
                            f"signal handler acquires "
                            f"{dotted(sub.func.value)} without a timeout — "
                            f"self-deadlocks when the interrupted thread "
                            f"holds it"
                        ),
                    )


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    for path in project.py_files:
        tree = project.tree(path)
        if tree is None:
            continue
        scanner = _ModuleScanner(project, path)
        scanner.visit(tree)
        out.extend(scanner.findings)
        if scanner.handler_names:
            out.extend(
                _check_signal_handlers(project, path, tree, scanner.handler_names)
            )
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_BAD = {
    "blocking-under-lock": """
import time, threading
_lock = threading.Lock()
def f():
    with _lock:
        time.sleep(1)
""",
    "blocking-under-lock-acquire": """
import time, threading
_mu = threading.Lock()
def f():
    _mu.acquire()
    try:
        time.sleep(1)
    finally:
        _mu.release()
""",
    "blocking-rpc": """
def f(self):
    with self._lock:
        self._client.call("quorum", {})
""",
    "blocking-lock-in-signal-handler": """
import signal, threading
_lock = threading.Lock()
def _handler(signum, frame):
    with _lock:
        pass
signal.signal(signal.SIGTERM, _handler)
""",
    # a waiver naming a DIFFERENT pass must not suppress this one
    "wrong-pass-waiver": """
def f(self):
    with self._lock:  # tft-lint: allow(env-hygiene)
        self._client.call("x", {})
""",
}

_GOOD = {
    "sleep-outside": """
import time, threading
_lock = threading.Lock()
def f():
    with _lock:
        x = 1
    time.sleep(x)
""",
    "cond-wait": """
import threading
_cond = threading.Condition()
def f():
    with _cond:
        _cond.wait(timeout=1)
""",
    "waiver": """
import threading
def f(self):
    with self._lock:  # tft-lint: allow(lock-discipline): pooled connection
        self._client.call("x", {})
""",
    "handler-timeout": """
import signal, threading
_lock = threading.Lock()
def _handler(signum, frame):
    if _lock.acquire(timeout=0.1):
        _lock.release()
signal.signal(signal.SIGTERM, _handler)
""",
    "deferred-closure": """
import time, threading
_lock = threading.Lock()
def f():
    with _lock:
        def later():
            time.sleep(1)
        cb = later
    cb()
""",
    "sibling-branch-not-poisoned": """
import time, threading
_lock = threading.Lock()
def f(cond):
    if cond:
        _lock.acquire()
    else:
        time.sleep(1)  # _lock is NOT held on this path
    if cond:
        _lock.release()
""",
    "handler-after-release": """
import time, threading
_lock = threading.Lock()
def f():
    _lock.acquire()
    try:
        pass
    finally:
        _lock.release()
    time.sleep(1)
""",
}


def _run_on_source(src: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "snippet.py")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        return list(run(Project(td, [path])))


def selftest() -> None:
    for name, src in _BAD.items():
        if not _run_on_source(src):
            raise SelftestError(f"{PASS_ID}: bad snippet {name!r} not flagged")
    for name, src in _GOOD.items():
        got = _run_on_source(src)
        if got:
            raise SelftestError(
                f"{PASS_ID}: good snippet {name!r} falsely flagged: "
                f"{[f.render() for f in got]}"
            )


PASS = LintPass(
    id=PASS_ID,
    doc="no blocking calls while holding a lock; no blocking lock "
    "acquisition inside signal handlers",
    run=run,
    selftest=selftest,
)
