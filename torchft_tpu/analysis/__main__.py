"""``python -m torchft_tpu.analysis`` — the tft-lint entry point."""

import sys

from torchft_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
