"""Shared machinery for the tft-lint passes: findings, the project
model, baselines, and the runner.

Design constraints (mirrors the rest of the package): stdlib only — the
passes are ``ast`` walkers, not plugins to an external linter, so the
suite runs anywhere the package imports, including CI images with no
dev-tooling layer.

A **pass** is an object with ``id``/``doc``, a ``run(project)`` returning
:class:`Finding` objects, and a ``selftest()`` that runs the pass over
embedded bad/good snippets — the suite distrusts itself first
(``tft-lint --selftest``; tier-1 runs it via tests/test_lint.py).

**Baselines** grandfather pre-existing findings: one fingerprint per
line in ``torchft_tpu/analysis/baselines/<pass>.txt``.  Fingerprints are
line-number-free (pass id, code, file, symbol, message hash) so an
unrelated edit above a grandfathered finding doesn't churn the file.
The shipped baselines are **empty** — every finding the passes surface
was fixed in the PR that introduced them — and the intent is they stay
that way: ``--write-baseline`` exists for incremental adoption of future
passes, not as an escape hatch.
"""

from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Project",
    "LintPass",
    "SelftestError",
    "load_baseline",
    "write_baseline",
    "run_passes",
]


@dataclass(frozen=True)
class Finding:
    """One violation of a project invariant."""

    pass_id: str
    code: str  # stable short slug, e.g. "sleep-under-lock"
    file: str  # path relative to the project root
    line: int
    message: str
    symbol: str = ""  # enclosing qualname / metric name / env knob

    def fingerprint(self) -> str:
        """Line-number-free identity used by baseline files."""
        digest = hashlib.sha256(self.message.encode()).hexdigest()[:8]
        return f"{self.pass_id}:{self.code}:{self.file}:{self.symbol}:{digest}"

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line}: {self.pass_id}/{self.code}{sym}: {self.message}"


class Project:
    """The analyzed tree: parsed sources plus the docs corpus.

    ``root`` is the directory that holds the docs (``README.md``,
    ``docs/*.md``); source files are the ``.py`` files under the target
    paths.  Parse failures surface as findings (code ``parse-error``)
    rather than exceptions so one broken file doesn't hide every other
    result.
    """

    def __init__(self, root: str, py_files: "Sequence[str]") -> None:
        self.root = os.path.abspath(root)
        self.py_files = sorted(os.path.abspath(f) for f in py_files)
        self._asts: "Dict[str, Optional[ast.Module]]" = {}
        self._sources: "Dict[str, str]" = {}
        self._docs: "Optional[str]" = None
        self.parse_errors: "List[Finding]" = []

    @classmethod
    def from_paths(cls, paths: "Sequence[str]", root: "Optional[str]" = None) -> "Project":
        """Build from files and/or directories (recursed for ``.py``).
        The root (docs anchor) is auto-detected by walking up from the
        first path to a directory containing ``docs`` or ``README.md``."""
        files: "List[str]" = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [
                        d for d in dirnames
                        if d not in ("__pycache__", ".git", "baselines")
                    ]
                    files.extend(
                        os.path.join(dirpath, f)
                        for f in filenames
                        if f.endswith(".py")
                    )
            elif p.endswith(".py"):
                files.append(p)
        if root is None:
            probe = os.path.abspath(paths[0] if paths else os.getcwd())
            if os.path.isfile(probe):
                probe = os.path.dirname(probe)
            root = probe
            while True:
                if os.path.isdir(os.path.join(root, "docs")) or os.path.isfile(
                    os.path.join(root, "README.md")
                ):
                    break
                parent = os.path.dirname(root)
                if parent == root:
                    root = probe  # no docs anywhere above: degrade quietly
                    break
                root = parent
        return cls(root, files)

    # -- accessors ---------------------------------------------------------

    def rel(self, path: str) -> str:
        try:
            return os.path.relpath(path, self.root)
        except ValueError:
            return path

    def source(self, path: str) -> str:
        if path not in self._sources:
            with open(path, encoding="utf-8") as fh:
                self._sources[path] = fh.read()
        return self._sources[path]

    def tree(self, path: str) -> "Optional[ast.Module]":
        """Parsed AST, or None (a ``parse-error`` finding is recorded)."""
        if path not in self._asts:
            try:
                self._asts[path] = ast.parse(self.source(path), filename=path)
            except (SyntaxError, OSError, UnicodeDecodeError) as e:
                self._asts[path] = None
                self.parse_errors.append(
                    Finding(
                        pass_id="core",
                        code="parse-error",
                        file=self.rel(path),
                        line=getattr(e, "lineno", 0) or 0,
                        message=f"could not parse: {e}",
                    )
                )
        return self._asts[path]

    def find_file(self, suffix: str) -> "Optional[str]":
        """The analyzed file whose normalized path ends with ``suffix``."""
        norm = suffix.replace("\\", "/")
        for f in self.py_files:
            if f.replace("\\", "/").endswith(norm):
                return f
        return None

    def docs_text(self) -> str:
        """README.md + docs/*.md concatenated (the knob/metric/fault-site
        tables live there); empty when the project has no docs."""
        if self._docs is None:
            chunks: "List[str]" = []
            for cand in [os.path.join(self.root, "README.md")]:
                if os.path.isfile(cand):
                    with open(cand, encoding="utf-8") as fh:
                        chunks.append(fh.read())
            docdir = os.path.join(self.root, "docs")
            if os.path.isdir(docdir):
                for name in sorted(os.listdir(docdir)):
                    if name.endswith(".md"):
                        with open(os.path.join(docdir, name), encoding="utf-8") as fh:
                            chunks.append(fh.read())
            self._docs = "\n".join(chunks)
        return self._docs

    def doc_text_for(self, relpath: str) -> str:
        """One specific doc file's text ('' when absent)."""
        cand = os.path.join(self.root, relpath)
        if os.path.isfile(cand):
            with open(cand, encoding="utf-8") as fh:
                return fh.read()
        return ""


class SelftestError(AssertionError):
    """A pass failed its own selftest — the suite's results are void."""


@dataclass
class LintPass:
    """One registered pass.  ``run`` yields findings over a Project;
    ``selftest`` raises :class:`SelftestError` on miss."""

    id: str
    doc: str
    run: "object" = None  # Callable[[Project], Iterable[Finding]]
    selftest: "object" = None  # Callable[[], None]


# ---------------------------------------------------------------------------
# AST helpers shared by the passes
# ---------------------------------------------------------------------------


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('' when not name-like):
    ``os.environ.get`` -> "os.environ.get", ``self._lock`` -> "self._lock"."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        return dotted(node.func)
    return ""


def const_str(node: "Optional[ast.AST]") -> "Optional[str]":
    """The value of a string-constant expression, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def module_str_constants(tree: ast.Module) -> "Dict[str, str]":
    """Module-level ``NAME = "literal"`` assignments (one level, no
    reassignment tracking) — lets passes resolve ``env_str(SOME_CONST)``."""
    out: "Dict[str, str]" = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            val = const_str(node.value)
            if isinstance(tgt, ast.Name) and val is not None:
                out[tgt.id] = val
    return out


class QualnameVisitor(ast.NodeVisitor):
    """Base visitor that tracks the enclosing class/function qualname."""

    def __init__(self) -> None:
        self._stack: "List[str]" = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:  # noqa: N802
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node: ast.AST) -> None:
        self._stack.append(node.name)  # type: ignore[attr-defined]
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func  # noqa: N815
    visit_AsyncFunctionDef = _visit_func  # noqa: N815


# ---------------------------------------------------------------------------
# baselines + runner
# ---------------------------------------------------------------------------


def default_baseline_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baselines")


def load_baseline(pass_id: str, baseline_dir: "Optional[str]" = None) -> "frozenset[str]":
    path = os.path.join(baseline_dir or default_baseline_dir(), f"{pass_id}.txt")
    if not os.path.isfile(path):
        return frozenset()
    with open(path, encoding="utf-8") as fh:
        return frozenset(
            line.strip()
            for line in fh
            if line.strip() and not line.lstrip().startswith("#")
        )


def write_baseline(
    pass_id: str, findings: "Iterable[Finding]", baseline_dir: "Optional[str]" = None
) -> str:
    bdir = baseline_dir or default_baseline_dir()
    os.makedirs(bdir, exist_ok=True)
    path = os.path.join(bdir, f"{pass_id}.txt")
    lines = sorted({f.fingerprint() for f in findings})
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(
            f"# Grandfathered findings for the {pass_id!r} pass.\n"
            f"# One fingerprint per line; regenerate with tft-lint --write-baseline.\n"
            f"# Target state: empty.\n"
        )
        for line in lines:
            fh.write(line + "\n")
    return path


@dataclass
class PassResult:
    lint_pass: LintPass
    findings: "List[Finding]" = field(default_factory=list)  # non-baselined
    baselined: int = 0


def run_passes(
    passes: "Sequence[LintPass]",
    project: Project,
    baseline_dir: "Optional[str]" = None,
) -> "List[PassResult]":
    results: "List[PassResult]" = []
    for lp in passes:
        found = list(lp.run(project))  # type: ignore[operator]
        base = load_baseline(lp.id, baseline_dir)
        fresh = [f for f in found if f.fingerprint() not in base]
        results.append(
            PassResult(lp, findings=fresh, baselined=len(found) - len(fresh))
        )
    if project.parse_errors:
        results.insert(
            0,
            PassResult(
                LintPass(id="core", doc="source files must parse"),
                findings=list(project.parse_errors),
            ),
        )
    return results
