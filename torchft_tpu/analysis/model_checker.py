"""Bounded exhaustive checker for the quorum-protocol model (tft-verify).

Explores every interleaving of the transition system in
:mod:`torchft_tpu.analysis.protocol_model` up to the scenario's bounds,
with two sound reductions that keep the clean configs inside the tier-1
time budget:

* **state deduplication** — the full state (including the spec's ghost
  fields) is hashable; a state reached twice is expanded once;
* **DPOR-style persistent sets** — transitions in ``INVISIBLE_OPS`` only
  rewrite the acting replica's private planning fields, are enabled
  deterministically, commute with every other actor's transitions, and
  cannot themselves violate an invariant; when any is enabled, only the
  first is expanded (the other interleavings reach the same states).

A safety violation returns a :class:`CheckResult` carrying the full
transition path; :func:`trace_to_flight_dump` rewrites that path into
the flight-recorder JSONL dialect so ``torchft-diagnose`` renders the
counterexample like any production post-mortem and names the violating
replica and phase.

Liveness is checked separately and *bounded*: :func:`run_schedule`
drives the model with deterministic fair schedules (rotating priority
over enabled transitions) through churn scenarios and requires the
fleet to reach the goal step within a transition budget — a livelock
shows up as budget exhaustion with the looping tail of the schedule in
hand.
"""

from __future__ import annotations

import json
from typing import Any, Dict, FrozenSet, List, NamedTuple, Optional, Tuple

from torchft_tpu.analysis.protocol_model import (
    INVISIBLE_OPS,
    MODEL_PHASE_OPS,
    ElectionConfig,
    ModelConfig,
    ResizeConfig,
    RestoreConfig,
    State,
    Transition,
    Violation,
    apply_transition,
    check_invariants,
    e_unpair,
    election_apply,
    election_check,
    election_enabled,
    election_initial,
    election_is_goal,
    enabled_transitions,
    initial_state,
    is_goal,
    resize_apply,
    resize_check,
    resize_enabled,
    resize_initial,
    resize_is_goal,
    restore_apply,
    restore_check,
    restore_enabled,
    restore_initial,
    restore_is_goal,
    vote_apply,
    vote_check,
    vote_enabled,
    vote_initial,
)

__all__ = [
    "CheckResult",
    "explore",
    "explore_votes",
    "explore_resize",
    "explore_election",
    "explore_restore",
    "run_schedule",
    "SCENARIOS",
    "RESIZE_SCENARIOS",
    "ELECTION_SCENARIOS",
    "RESTORE_SCENARIOS",
    "LIVENESS_SCHEDULES",
    "trace_to_flight_dump",
    "write_flight_dump",
]


class CheckResult(NamedTuple):
    ok: bool
    states: int  # distinct states visited
    transitions: int  # transitions applied
    goal_states: int  # states where every live replica hit the target
    violation: "Optional[Violation]"
    # the counterexample: ((op, actor_index, replica_id, step, quorum_id), ...)
    trace: "Tuple[Tuple[str, int, str, int, int], ...]"


def _trace_entry(
    st: State, t: Transition
) -> "Tuple[str, int, str, int, int]":
    op, i = t
    if i < 0:
        rid = "lighthouse"
        step = max((r.step for r in st.reps), default=0)
    else:
        r = st.reps[i]
        rid = f"r{i}:{r.inc}"
        step = r.step
    return (op, i, rid, step, st.lh.quorum_id)


def explore(
    cfg: ModelConfig,
    mutations: "FrozenSet[str]" = frozenset(),
    max_states: int = 400_000,
    max_depth: int = 250,
) -> CheckResult:
    """Exhaustive DFS over the bounded state space; stops at the first
    invariant violation (safety is per-state, so the first hit carries a
    minimal-enough path to read)."""
    init = initial_state(cfg)
    v0 = check_invariants(cfg, init)
    if v0:
        return CheckResult(False, 1, 0, 0, v0[0], ())
    seen = {init}
    goal_states = 0
    transitions = 0
    # DFS stack: (state, iterator-position over its transitions, path)
    stack: "List[Tuple[State, List[Transition], int]]" = []
    path: "List[Tuple[str, int, str, int, int]]" = []

    def expandable(st: State) -> "List[Transition]":
        ts = enabled_transitions(cfg, st, mutations)
        invisible = [t for t in ts if t[0] in INVISIBLE_OPS]
        if invisible:
            return invisible[:1]
        return ts

    stack.append((init, expandable(init), 0))
    while stack:
        st, ts, idx = stack[-1]
        if idx >= len(ts):
            stack.pop()
            if path:
                path.pop()
            continue
        stack[-1] = (st, ts, idx + 1)
        t = ts[idx]
        nxt = apply_transition(cfg, st, t, mutations)
        transitions += 1
        entry = _trace_entry(st, t)
        if nxt in seen:
            continue
        seen.add(nxt)
        path.append(entry)
        violations = check_invariants(cfg, nxt)
        if violations:
            return CheckResult(
                False,
                len(seen),
                transitions,
                goal_states,
                violations[0],
                tuple(path),
            )
        if is_goal(cfg, nxt):
            goal_states += 1
            path.pop()
            continue  # goal states are terminal for the bounded run
        if len(seen) >= max_states:
            raise RuntimeError(
                f"state-space bound exceeded ({max_states} states) — "
                f"shrink the scenario"
            )
        if len(stack) >= max_depth:
            path.pop()
            continue
        stack.append((nxt, expandable(nxt), 0))
    return CheckResult(True, len(seen), transitions, goal_states, None, ())


def explore_votes(
    world: int = 2,
    steps: int = 2,
    drops: int = 1,
    mutations: "FrozenSet[str]" = frozenset(),
    max_states: int = 200_000,
) -> CheckResult:
    """Exhaustive exploration of the should_commit vote-barrier sub-model
    (delivery orders x connection drops x client recovery behavior)."""
    init = vote_initial(world, steps, drops)
    seen = {init}
    transitions = 0
    goal = 0
    stack = [(init, vote_enabled(init, steps, mutations), 0)]
    path: "List[Tuple[str, int, str, int, int]]" = []
    while stack:
        st, ts, idx = stack[-1]
        if idx >= len(ts):
            stack.pop()
            if path:
                path.pop()
            continue
        stack[-1] = (st, ts, idx + 1)
        t = ts[idx]
        nxt = vote_apply(st, t)
        transitions += 1
        if nxt in seen:
            continue
        seen.add(nxt)
        path.append((t[0], t[1], f"rank{t[1]}", st.step, 0))
        violations = vote_check(nxt)
        if violations:
            return CheckResult(
                False, len(seen), transitions, goal, violations[0], tuple(path)
            )
        if len(nxt.decisions) >= steps:
            goal += 1
            path.pop()
            continue
        if len(seen) >= max_states:
            raise RuntimeError("vote state-space bound exceeded")
        stack.append((nxt, vote_enabled(nxt, steps, mutations), 0))
    return CheckResult(True, len(seen), transitions, goal, None, ())


def explore_resize(
    cfg: "ResizeConfig" = ResizeConfig(),
    mutations: "FrozenSet[str]" = frozenset(),
    max_states: int = 200_000,
) -> CheckResult:
    """Exhaustive exploration of the online-parallelism-switching
    (resize) sub-model: plan at quorum under a monotone layout epoch,
    stage (can fail, groups can crash mid-reshard), commit on unanimous
    epoch reports or roll back and burn the epoch."""
    init = resize_initial(cfg)
    seen = {init}
    transitions = 0
    goal = 0
    stack = [(init, resize_enabled(cfg, init, mutations), 0)]
    path: "List[Tuple[str, int, str, int, int]]" = []
    while stack:
        st, ts, idx = stack[-1]
        if idx >= len(ts):
            stack.pop()
            if path:
                path.pop()
            continue
        stack[-1] = (st, ts, idx + 1)
        t = ts[idx]
        nxt = resize_apply(cfg, st, t, mutations)
        transitions += 1
        if nxt in seen:
            continue
        seen.add(nxt)
        op, i = t
        rid = "lighthouse" if i < 0 else f"r{i}:0"
        epoch = max((r.epoch for r in st.reps), default=0)
        path.append((op, i, rid, st.switches, epoch))
        violations = resize_check(cfg, nxt)
        if violations:
            return CheckResult(
                False, len(seen), transitions, goal, violations[0], tuple(path)
            )
        if resize_is_goal(cfg, nxt):
            goal += 1
            path.pop()
            continue
        if len(seen) >= max_states:
            raise RuntimeError("resize state-space bound exceeded")
        stack.append((nxt, resize_enabled(cfg, nxt, mutations), 0))
    return CheckResult(True, len(seen), transitions, goal, None, ())


def explore_election(
    cfg: "ElectionConfig" = ElectionConfig(),
    mutations: "FrozenSet[str]" = frozenset(),
    max_states: int = 400_000,
) -> CheckResult:
    """Exhaustive exploration of the coordination-plane HA (leased
    leader election) sub-model: candidacies, per-peer lease grants,
    majority elections, leader crashes, promise expiry, and the
    term-prefixed quorum ids a takeover must keep monotone."""
    init = election_initial(cfg)
    seen = {init}
    transitions = 0
    goal = 0
    stack = [(init, election_enabled(cfg, init, mutations), 0)]
    path: "List[Tuple[str, int, str, int, int]]" = []
    while stack:
        st, ts, idx = stack[-1]
        if idx >= len(ts):
            stack.pop()
            if path:
                path.pop()
            continue
        stack[-1] = (st, ts, idx + 1)
        t = ts[idx]
        nxt = election_apply(cfg, st, t, mutations)
        transitions += 1
        if nxt in seen:
            continue
        seen.add(nxt)
        op, code = t
        if op == "e_grant":
            granter, _cand = e_unpair(code, cfg.n_peers)
            rid = f"peer{granter}"
        else:
            rid = f"peer{code}"
        term = max((p.leading_term for p in st.peers), default=0)
        qid = st.ghost.quorum_ids[-1] if st.ghost.quorum_ids else 0
        path.append((op, code, rid, term, qid))
        violations = election_check(cfg, nxt)
        if violations:
            return CheckResult(
                False, len(seen), transitions, goal, violations[0], tuple(path)
            )
        if election_is_goal(cfg, nxt):
            goal += 1
            path.pop()
            continue
        if len(seen) >= max_states:
            raise RuntimeError("election state-space bound exceeded")
        stack.append((nxt, election_enabled(cfg, nxt, mutations), 0))
    return CheckResult(True, len(seen), transitions, goal, None, ())


def explore_restore(
    cfg: "RestoreConfig" = RestoreConfig(),
    mutations: "FrozenSet[str]" = frozenset(),
    max_states: int = 400_000,
) -> CheckResult:
    """Exhaustive exploration of the durable-store cold-restore sub-model:
    per-disk spill orders (blobs before manifest), bounded bit-rot,
    whole-fleet crash, and the fleet-wide cut selection a cold start must
    keep complete, version-consistent, and newest-first."""
    init = restore_initial(cfg)
    seen = {init}
    transitions = 0
    goal = 0
    stack = [(init, restore_enabled(cfg, init, mutations), 0)]
    path: "List[Tuple[str, int, str, int, int]]" = []
    while stack:
        st, ts, idx = stack[-1]
        if idx >= len(ts):
            stack.pop()
            if path:
                path.pop()
            continue
        stack[-1] = (st, ts, idx + 1)
        t = ts[idx]
        nxt = restore_apply(cfg, st, t, mutations)
        transitions += 1
        if nxt in seen:
            continue
        seen.add(nxt)
        op, i = t
        rid = "fleet" if i < 0 else f"disk{i}"
        chosen = nxt.ghost.chosen if nxt.ghost is not None else -1
        path.append((op, i, rid, max(chosen, 0), 0))
        violations = restore_check(cfg, nxt)
        if violations:
            return CheckResult(
                False, len(seen), transitions, goal, violations[0], tuple(path)
            )
        if restore_is_goal(cfg, nxt):
            goal += 1
            path.pop()
            continue
        if len(seen) >= max_states:
            raise RuntimeError("restore state-space bound exceeded")
        stack.append((nxt, restore_enabled(cfg, nxt, mutations), 0))
    return CheckResult(True, len(seen), transitions, goal, None, ())


# ---------------------------------------------------------------------------
# scenarios (the bounded state spaces tier-1 proves clean)
# ---------------------------------------------------------------------------

#: name -> ModelConfig. Sized so the full set explores clean well inside
#: the 30 s tier-1 budget (tests/test_verify.py pins the wall time).
SCENARIOS: "Dict[str, ModelConfig]" = {
    # two replicas, two committed steps, no churn: the steady-state loop
    "steady": ModelConfig(n_replicas=2, min_replicas=1, target_steps=2),
    # a crash and a fresh incarnation rejoining mid-run (heal path,
    # supersession stamps, heartbeat expiry of the dead incarnation)
    "churn": ModelConfig(
        n_replicas=2,
        min_replicas=1,
        target_steps=1,
        crash_budget=1,
        restart_budget=1,
    ),
    # one transient collective abort with everyone alive: the whole
    # cohort votes no and the next quorum — UNCHANGED membership — must
    # bump quorum_id for the reported commit failures
    "abort": ModelConfig(
        n_replicas=2,
        min_replicas=1,
        target_steps=2,
        abort_budget=1,
    ),
    # start mid-run with two stragglers behind one up-to-date replica:
    # the heal-source round-robin with more than one possible source.
    # quorum_budget bounds the protocol rounds (the membership-overlap
    # constraint makes the unbounded space explode in re-join cycles).
    "skewed": ModelConfig(
        n_replicas=3,
        min_replicas=1,
        target_steps=1,
        initial_steps=(1, 0, 0),
        quorum_budget=3,
    ),
    # a wedged trainer whose manager keeps heartbeating, restarted as a
    # new incarnation: the zombie/supersession state space
    "zombie": ModelConfig(
        n_replicas=2,
        min_replicas=1,
        target_steps=1,
        wedge_budget=1,
        restart_budget=1,
    ),
    # one participant vs two partitioned-away heartbeaters: the majority
    # guard must hold the minority side at bay (no quorum ever forms)
    "partition": ModelConfig(
        n_replicas=3,
        min_replicas=1,
        target_steps=1,
        bystanders=frozenset({1, 2}),
    ),
    # a shrink_only joiner must never grow the quorum
    "shrink": ModelConfig(
        n_replicas=3,
        min_replicas=1,
        target_steps=1,
        shrink_only=frozenset({2}),
    ),
}

#: online-parallelism-switching sub-model scenarios (explore_resize):
#: membership churn + reshard-transfer failures around the two-phase
#: layout-epoch commit.
RESIZE_SCENARIOS: "Dict[str, ResizeConfig]" = {
    # a shrink (crash), a grow (rejoin) and one failed reshard transfer
    # around two committed switches — the full plan/stage/commit/rollback
    # space of ISSUE 11's switch protocol
    "resize": ResizeConfig(
        n_replicas=3,
        target_switches=2,
        crash_budget=1,
        join_budget=1,
        stage_fail_budget=1,
    ),
}

#: coordination-plane HA sub-model scenarios (explore_election): three
#: lighthouse peers, one leader crash, quorums formed across the
#: takeover — the full candidacy/grant/expiry interleaving space of the
#: leased election plus the term-prefixed id discipline.
ELECTION_SCENARIOS: "Dict[str, ElectionConfig]" = {
    "election": ElectionConfig(
        n_peers=3, target_quorums=2, crash_budget=1, expire_budget=3
    ),
}

#: durable-store cold-restore sub-model scenarios (explore_restore): two
#: disks spilling two versions of a two-fragment cut in every order, one
#: bit-rot, whole-fleet crash, then the cold-start cut selection.
RESTORE_SCENARIOS: "Dict[str, RestoreConfig]" = {
    "restore": RestoreConfig(
        n_disks=2, n_fragments=2, n_versions=2, rot_budget=1
    ),
}

#: scenario used to catch each mutation (the smallest space where the
#: mutated behavior is reachable)
MUTATION_SCENARIOS: "Dict[str, str]" = {
    "skip_commit_failure_bump": "abort",
    "reuse_quorum_id": "abort",
    "heal_from_stale": "skewed",
    "drop_majority_guard": "partition",
    "commit_despite_error": "abort",
    "zombie_rejoin": "zombie",
    "ignore_shrink_only": "shrink",
    "resend_vote": "votes",  # vote-barrier sub-model
    "commit_mixed_epochs": "resize",  # parallelism-switching sub-model
    "reuse_epoch_after_rollback": "resize",
    "two_leaders_same_term": "election",  # coordination-plane HA sub-model
    "reuse_quorum_seq_after_takeover": "election",
    "serve_torn_blob": "restore",  # durable-store cold-restore sub-model
    "mix_versions_in_cut": "restore",
}


def check_mutation(name: str) -> CheckResult:
    """Run the mutated model over its scenario; a correct checker returns
    ok=False with the expected invariant in the violation."""
    scenario = MUTATION_SCENARIOS[name]
    if scenario == "votes":
        return explore_votes(mutations=frozenset({name}))
    if scenario in RESIZE_SCENARIOS:
        return explore_resize(
            RESIZE_SCENARIOS[scenario], mutations=frozenset({name})
        )
    if scenario in ELECTION_SCENARIOS:
        return explore_election(
            ELECTION_SCENARIOS[scenario], mutations=frozenset({name})
        )
    if scenario in RESTORE_SCENARIOS:
        return explore_restore(
            RESTORE_SCENARIOS[scenario], mutations=frozenset({name})
        )
    return explore(SCENARIOS[scenario], mutations=frozenset({name}))


# ---------------------------------------------------------------------------
# bounded liveness (no livelock under churn schedules)
# ---------------------------------------------------------------------------

#: deterministic fair schedules: (name, scenario, rotation offset)
LIVENESS_SCHEDULES: "Tuple[Tuple[str, str, int], ...]" = (
    ("steady-rr0", "steady", 0),
    ("steady-rr1", "steady", 1),
    ("churn-rr0", "churn", 0),
    ("churn-rr2", "churn", 2),
    ("abort-rr0", "abort", 0),
    ("zombie-rr0", "zombie", 0),
    ("skewed-rr0", "skewed", 0),
    ("shrink-rr1", "shrink", 1),
)


def run_schedule(
    cfg: ModelConfig,
    rotation: int = 0,
    max_transitions: int = 400,
) -> "Tuple[bool, int, List[Tuple[str, int, str, int, int]]]":
    """Drive the model with a deterministic fair scheduler: at each state
    pick the enabled transition at the rotating priority index.  Returns
    (reached_goal, transitions_used, trace).  Fair because the rotation
    advances every pick, so no persistently-enabled transition is starved
    — a goal miss within the budget is a livelock (or a dead config)."""
    st = initial_state(cfg)
    trace: "List[Tuple[str, int, str, int, int]]" = []
    k = rotation
    for n in range(max_transitions):
        if is_goal(cfg, st):
            return True, n, trace
        ts = enabled_transitions(cfg, st)
        if not ts:
            return is_goal(cfg, st), n, trace
        t = ts[k % len(ts)]
        k += 1
        trace.append(_trace_entry(st, t))
        st = apply_transition(cfg, st, t)
        if check_invariants(cfg, st):
            return False, n, trace
    return is_goal(cfg, st), max_transitions, trace


# ---------------------------------------------------------------------------
# counterexample -> flight-recorder dialect (torchft-diagnose input)
# ---------------------------------------------------------------------------


def trace_to_flight_dump(
    result: CheckResult, t0_ns: int = 1_700_000_000_000_000_000
) -> "List[Dict[str, Any]]":
    """Rewrite a violation trace as flight-recorder JSONL records
    (utils/flightrecorder.py dump dialect) so ``torchft-diagnose`` can
    render the counterexample: the violating replica reports the failed
    phase, and — because its records stop at the violation while every
    other replica gets a later record — the silent-death culprit signal
    names it without bespoke tooling."""
    assert result.violation is not None and result.trace
    v = result.violation
    step_ms = 100_000_000  # 100 ms apart: diagnose's gap thresholds apply
    records: "List[Dict[str, Any]]" = [
        {
            "flight": "meta",
            "reason": f"tft-verify counterexample: {v.invariant}",
            "trigger": "model_checker",
            "ts": t0_ns / 1e9,
            "pid": 0,
            "records": len(result.trace) + 1,
        }
    ]
    t = t0_ns
    seen_rids = set()
    for op, _i, rid, step, qid in result.trace:
        t += step_ms
        seen_rids.add(rid)
        records.append(
            {
                "flight": "rec",
                "op": MODEL_PHASE_OPS.get(op, op),
                "model_op": op,
                "status": "ok",
                "start_ns": t,
                "end_ns": t + step_ms // 2,
                "replica_id": rid,
                "step": step,
                "quorum_id": qid,
                "kind": "phase",
            }
        )
    # the violation itself: an error record from the violating replica
    t += step_ms
    last = result.trace[-1]
    records.append(
        {
            "flight": "rec",
            "op": MODEL_PHASE_OPS.get(v.phase, v.phase),
            "model_op": v.phase,
            "status": "error",
            "start_ns": t,
            "end_ns": t + step_ms // 2,
            "replica_id": v.replica_id,
            "step": last[3],
            "quorum_id": last[4],
            "kind": "phase",
            "reason": f"invariant {v.invariant} violated: {v.message}",
            "invariant": v.invariant,
        }
    )
    # peers produce evidence after the violator stops: the survivors'
    # view diagnose uses to single out the replica whose records end
    for rid in sorted(seen_rids - {v.replica_id}):
        t += step_ms
        records.append(
            {
                "flight": "rec",
                "op": "quorum_rpc",
                "model_op": "post",
                "status": "ok",
                "start_ns": t,
                "end_ns": t + step_ms // 2,
                "replica_id": rid,
                "step": last[3],
                "quorum_id": last[4],
                "kind": "phase",
            }
        )
    return records


def write_flight_dump(result: CheckResult, path: str) -> str:
    with open(path, "w", encoding="utf-8") as fh:
        for rec in trace_to_flight_dump(result):
            fh.write(json.dumps(rec) + "\n")
    return path
