"""tft-lint command line: run the project-invariant passes.

Exit codes: 0 clean (or everything baselined), 1 findings, 2 usage /
selftest failure.  ``python -m torchft_tpu.analysis torchft_tpu/`` is
the CI form; the console script ``tft-lint`` is the same entry point.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from torchft_tpu.analysis import PASSES
from torchft_tpu.analysis.core import (
    Project,
    SelftestError,
    run_passes,
    write_baseline,
)


def _select_passes(names: "Optional[str]") -> "List":
    if not names:
        return list(PASSES)
    wanted = [n.strip() for n in names.split(",") if n.strip()]
    by_id = {p.id: p for p in PASSES}
    unknown = [n for n in wanted if n not in by_id]
    if unknown:
        raise SystemExit(
            f"tft-lint: unknown pass(es) {unknown}; available: {sorted(by_id)}"
        )
    return [by_id[n] for n in wanted]


def main(argv: "Optional[Sequence[str]]" = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tft-lint",
        description=(
            "torchft_tpu project-invariant static analysis: lock "
            "discipline, env-knob hygiene, metrics/event sync, retry-loop "
            "ban, fault-site + flight-recorder coverage.  See "
            "docs/static_analysis.md."
        ),
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["torchft_tpu"],
        help="files/directories to analyze (default: torchft_tpu)",
    )
    parser.add_argument(
        "--passes",
        help="comma-separated pass ids to run (default: all)",
    )
    parser.add_argument(
        "--list-passes", action="store_true", help="list passes and exit"
    )
    parser.add_argument(
        "--baseline-dir",
        default=None,
        help="directory of <pass>.txt fingerprint files "
        "(default: torchft_tpu/analysis/baselines/)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather current findings into the baseline files and exit 0",
    )
    parser.add_argument(
        "--selftest",
        action="store_true",
        help="run every pass's embedded selftest (bad snippets flagged, "
        "good snippets clean) and exit",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    args = parser.parse_args(argv)

    if args.list_passes:
        for p in PASSES:
            print(f"{p.id:18s} {p.doc}")
        return 0

    passes = _select_passes(args.passes)

    if args.selftest:
        failed = 0
        for p in passes:
            try:
                p.selftest()  # type: ignore[operator]
                print(f"selftest {p.id}: ok")
            except SelftestError as e:
                failed += 1
                print(f"selftest {p.id}: FAIL — {e}", file=sys.stderr)
        return 2 if failed else 0

    project = Project.from_paths(args.paths)
    if not project.py_files:
        print(f"tft-lint: no .py files under {args.paths}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # grandfather the FULL finding set, pre-filter — writing only the
        # fresh findings would erase previously grandfathered fingerprints
        # on a re-run
        for p in passes:
            found = list(p.run(project))  # type: ignore[operator]
            path = write_baseline(p.id, found, baseline_dir=args.baseline_dir)
            print(f"wrote {len(found)} fingerprint(s) to {path}")
        return 0

    results = run_passes(passes, project, baseline_dir=args.baseline_dir)

    total = 0
    if args.json:
        doc = {
            "files": len(project.py_files),
            "passes": {
                res.lint_pass.id: {
                    "findings": [
                        {
                            "code": f.code,
                            "file": f.file,
                            "line": f.line,
                            "symbol": f.symbol,
                            "message": f.message,
                            "fingerprint": f.fingerprint(),
                        }
                        for f in res.findings
                    ],
                    "baselined": res.baselined,
                }
                for res in results
            },
        }
        total = sum(len(r.findings) for r in results)
        print(json.dumps(doc, indent=2))
    else:
        for res in results:
            for f in sorted(res.findings, key=lambda f: (f.file, f.line)):
                print(f.render())
            total += len(res.findings)
        baselined = sum(r.baselined for r in results)
        summary = (
            f"tft-lint: {total} finding(s) across {len(results)} pass(es), "
            f"{len(project.py_files)} file(s)"
        )
        if baselined:
            summary += f" ({baselined} baselined)"
        print(summary)
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
