"""Pass ``metrics-cardinality``: per-replica/per-peer metric labels must
come from a bounded set.

Fleet scale broke the "one label child per replica" habit: a
``.labels(replica=<incarnation id>)`` call mints a new series per
restart and per fleet member, growing the registry (and every scrape)
without bound under churn — exactly the regime the lighthouse's worst-K
straggler tier exists for (docs/observability.md, "metric
cardinality").  The native lighthouse enforces its side by construction
(``straggler_topk``); this pass remembers the rule for the Python
registry:

- ``unbounded-entity-label``: a ``.labels(...)`` call whose label KEY is
  per-entity (``replica``, ``replica_id``, ``peer``, ``rank``, ...) and
  whose VALUE is not visibly bounded.  Bounded means: a string literal;
  the Manager's documented ``_metric_replica_id`` (the stable bare id —
  one value per process for the life of the job, restart-proof); or
  ``str()``/f-string-free wrapping of those.  Anything dynamic (a loop
  variable, an incarnation id, a peer address) must instead go through a
  top-K/aggregated summary tier — or carry an explicit
  ``# tft-lint: allow(metrics-cardinality)`` waiver arguing why the
  value set is bounded.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    QualnameVisitor,
    SelftestError,
    dotted,
)

PASS_ID = "metrics-cardinality"

# Label keys that name a fleet entity: values must be bounded.
PER_ENTITY_KEYS = frozenset(
    {"replica", "replica_id", "peer", "peer_rank", "rank", "host", "worker"}
)

# Dotted-name suffixes that ARE the bounded tier: the Manager's stable
# bare replica id (one value per process; the ":uuid" incarnation suffix
# is stripped precisely so restarts reuse the series).
_BOUNDED_NAME_SUFFIXES = ("_metric_replica_id",)

# Method-name suffixes whose RETURN VALUE is the bounded tier: the
# link-registry's ``peer_topk_label`` folds every peer beyond the
# worst-K into a literal "other", so the label set is K+1 values by
# construction (utils/linkstats.py) — the Python mirror of the native
# lighthouse's straggler_topk tier.
_TOPK_LABEL_SUFFIXES = ("topk_label",)


def _is_bounded_value(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    name = dotted(node)
    if name and any(
        name.split(".")[-1] == suffix or name.endswith(suffix)
        for suffix in _BOUNDED_NAME_SUFFIXES
    ):
        return True
    # the top-K folding tier: <registry>.peer_topk_label(<anything>) is
    # bounded regardless of its argument — folding is the whole point
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if any(
            node.func.attr.endswith(suffix) for suffix in _TOPK_LABEL_SUFFIXES
        ):
            return True
    # str(<bounded>) / int(<bounded>) wrappers
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("str", "int")
        and len(node.args) == 1
    ):
        return _is_bounded_value(node.args[0])
    return False


def _has_waiver(project: Project, path: str, lineno: int) -> bool:
    lines = project.source(path).splitlines()
    if 1 <= lineno <= len(lines):
        return f"tft-lint: allow({PASS_ID})" in lines[lineno - 1]
    return False


class _Visitor(QualnameVisitor):
    def __init__(self, project: Project, path: str) -> None:
        super().__init__()
        self.project = project
        self.path = path
        self.findings: "List[Finding]" = []

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "labels":
            in_test = any(
                part.startswith(("test_", "_selftest"))
                for part in self.qualname.split(".")
            )
            for kw in node.keywords:
                if kw.arg in PER_ENTITY_KEYS and not in_test:
                    if not _is_bounded_value(kw.value) and not _has_waiver(
                        self.project, self.path, node.lineno
                    ):
                        self.findings.append(
                            Finding(
                                pass_id=PASS_ID,
                                code="unbounded-entity-label",
                                file=self.project.rel(self.path),
                                line=node.lineno,
                                symbol=self.qualname,
                                message=(
                                    f"label {kw.arg}= fed from "
                                    f"{ast.dump(kw.value)[:60]}: per-entity "
                                    "metric labels must come from a bounded "
                                    "set (literal, _metric_replica_id, or a "
                                    "top-K summary tier) — unbounded series "
                                    "growth under fleet churn"
                                ),
                            )
                        )
        self.generic_visit(node)


def run(project: Project) -> "Iterable[Finding]":
    for path in project.py_files:
        rel = project.rel(path).replace("\\", "/")
        if rel.startswith("tests/") or "/tests/" in rel:
            continue  # fixture registries in tests are out of scope
        tree = project.tree(path)
        if tree is None:
            continue
        visitor = _Visitor(project, path)
        visitor.visit(tree)
        yield from visitor.findings


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_BAD = {
    # the fleet-churn failure mode: a per-incarnation id becomes a label
    "dynamic-replica-label": """
from torchft_tpu.utils.metrics import counter
M = counter("torchft_x_total", "d")
def observe(replica_id):
    M.labels(replica=replica_id).inc()
""",
    # per-peer labels in a loop: one series per fleet member
    "per-peer-loop": """
from torchft_tpu.utils.metrics import gauge
G = gauge("torchft_peer_lag", "d")
def export(peers):
    for p in peers:
        G.labels(peer=p.addr).set(p.lag)
""",
    # an incarnation id dressed as str() is still unbounded
    "str-wrapped-dynamic": """
from torchft_tpu.utils.metrics import counter
M = counter("torchft_y_total", "d")
def observe(self):
    M.labels(rank=str(self._group_rank_of_the_day())).inc()
""",
    # a lookalike method name is NOT the folding tier
    "fake-topk-method": """
from torchft_tpu.utils.metrics import gauge
G = gauge("torchft_peer_x", "d")
def export(reg, host):
    G.labels(peer=reg.peer_label(host)).set(1.0)
""",
}

_GOOD = {
    # the documented bounded tier: the stable bare replica id
    "metric-replica-id": """
from torchft_tpu.utils.metrics import counter
M = counter("torchft_x_total", "d")
class Manager:
    def observe(self):
        M.labels(replica_id=self._metric_replica_id).inc()
""",
    # literals are a bounded set by construction
    "literal-label": """
from torchft_tpu.utils.metrics import gauge
G = gauge("torchft_worst", "d")
def export():
    G.labels(replica="worst").set(1.0)
""",
    # non-entity keys (phase, transport, ...) are out of scope
    "non-entity-key": """
from torchft_tpu.utils.metrics import histogram
H = histogram("torchft_dur", "d")
def observe(phase):
    H.labels(phase=phase).observe(1.0)
""",
    # the top-K folding tier bounds its own output (K+1 label values)
    "topk-label-tier": """
from torchft_tpu.utils.metrics import counter
M = counter("torchft_peer_wait_total", "d")
def observe(reg, host):
    M.labels(peer=reg.peer_topk_label(host)).inc()
""",
    # an argued waiver is honored
    "waived": """
from torchft_tpu.utils.metrics import counter
M = counter("torchft_z_total", "d")
def observe(site):
    M.labels(rank=site).inc()  # tft-lint: allow(metrics-cardinality)
""",
}


def _run_on_source(src: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "snippet.py")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        return list(run(Project(td, [path])))


def selftest() -> None:
    for name, src in _BAD.items():
        if not _run_on_source(src):
            raise SelftestError(f"{PASS_ID}: bad snippet {name!r} not flagged")
    for name, src in _GOOD.items():
        got = _run_on_source(src)
        if got:
            raise SelftestError(
                f"{PASS_ID}: good snippet {name!r} falsely flagged: "
                f"{[f.render() for f in got]}"
            )


PASS = LintPass(
    id=PASS_ID,
    doc="per-replica/per-peer metric label values must come from a "
    "bounded or top-K-aggregated set (fleet churn must not grow the "
    "registry)",
    run=run,
    selftest=selftest,
)
