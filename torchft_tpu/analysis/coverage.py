"""Pass ``fault-coverage``: the chaos registry and the flight recorder
keep covering the failure surface as it grows.

Two halves:

**Fault sites.**  ``utils/faults.py`` owns ``KNOWN_SITES`` — the typo
guard for ``TORCHFT_FAULTS`` specs — and docs/robustness.md carries the
operator-facing site table.  Every ``faults.check("<site>")`` literal in
the production tree must be a known site (``unknown-fault-site``) and
documented (``undocumented-fault-site``); conversely every known site
must still be consulted somewhere (``unwired-fault-site``) — a site that
no longer fires turns every chaos schedule naming it into a vacuous
pass.  ``train.step`` is exempt from wiring: it is the *user* loop's
opt-in hook by design.

**Flight coverage.**  The flight recorder is only a blackbox if the
paths that wedge actually feed it.  The anchor functions below — the PG
worker loop that executes every collective, and both checkpoint
transports' send/recv entry points — must reference the flight recorder
(``record``/``start``/``track``/``dump`` or a ``FlightOp`` method)
directly or through a same-module helper (call graph followed two
levels).  Removing the instrumentation in a refactor yields
``missing-flight-op``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    QualnameVisitor,
    SelftestError,
    const_str,
    dotted,
)

PASS_ID = "fault-coverage"

_FAULTS_FILE = "utils/faults.py"
_ROBUSTNESS_DOC = "docs/robustness.md"

# Known sites that need no production check-call (user-facing hooks).
_WIRING_EXEMPT = ("train.step",)

# (file suffix, function name) anchors that must feed the recorder.
_FLIGHT_ANCHORS: "Tuple[Tuple[str, str], ...]" = (
    ("parallel/process_group.py", "_worker_loop"),
    ("checkpointing/http_transport.py", "send_checkpoint"),
    ("checkpointing/http_transport.py", "recv_checkpoint"),
    ("checkpointing/pg_transport.py", "send_checkpoint"),
    ("checkpointing/pg_transport.py", "recv_checkpoint"),
    # the shared fragment plane (ISSUE 15 promoted it out of serving/):
    # every raw fragment fetch — serving relay pulls AND striped-heal
    # stripes — plus the striped heal receive must stay
    # post-mortem-visible
    ("checkpointing/fragments.py", "fetch_raw"),
    # the native-vs-python dispatch point of the zero-copy data plane:
    # a fetch that falls back to Python must stay post-mortem-visible
    # (`fragment.native_fallback`)
    ("checkpointing/fragments.py", "_raw_data_plane"),
    ("checkpointing/fragments.py", "fetch_serialized"),
    ("checkpointing/http_transport.py", "recv_checkpoint_striped"),
    ("serving/replica.py", "_pull"),
)

_FLIGHT_CALLS = ("record", "start", "track", "dump", "update", "add_bytes", "finish")


def _known_sites(project: Project) -> "Optional[Set[str]]":
    """Parse KNOWN_SITES from utils/faults.py (None when absent)."""
    path = project.find_file(_FAULTS_FILE)
    if path is None:
        return None
    tree = project.tree(path)
    if tree is None:
        return None
    for node in tree.body:
        if (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "KNOWN_SITES"
        ):
            value = node.value
        elif (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "KNOWN_SITES"
        ):
            value = node.value
        else:
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            sites = {const_str(e) for e in value.elts}
            return {s for s in sites if s is not None}
    return None


class _CheckCollector(QualnameVisitor):
    """Collects ``*.check("<site>", ...)`` / ``check("<site>")`` calls."""

    def __init__(self, project: Project, path: str) -> None:
        super().__init__()
        self.project = project
        self.path = path
        self.calls: "List[Tuple[str, int, str]]" = []

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        name = dotted(node.func)
        leaf = name.rsplit(".", 1)[-1]
        if leaf == "check" and node.args:
            site = const_str(node.args[0])
            # only dotted site strings: filters unrelated .check() APIs
            if site is not None and "." in site:
                self.calls.append((site, node.lineno, self.qualname))
        # deferred wiring: a site handed to a client as its injection
        # hook (e.g. _RpcClient(addr, fault_site="lighthouse.rpc"))
        for kw in node.keywords:
            if kw.arg == "fault_site":
                site = const_str(kw.value)
                if site is not None and "." in site:
                    self.calls.append((site, node.lineno, self.qualname))
        self.generic_visit(node)


def _module_flight_reach(tree: ast.Module) -> "Set[str]":
    """Function names in this module that reference the flight recorder
    directly, or (transitively, two hops) call one that does."""
    direct: "Set[str]" = set()
    calls: "Dict[str, Set[str]]" = {}

    def touches_flight(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = dotted(sub.func)
                parts = name.split(".")
                if len(parts) >= 2 and parts[-1] in _FLIGHT_CALLS:
                    recv = ".".join(parts[:-1])
                    if "flightrec" in recv or "flight_op" in recv or recv.endswith(
                        "flightrecorder"
                    ):
                        return True
            if isinstance(sub, (ast.Attribute, ast.Name)):
                name = dotted(sub)
                if "flightrec" in name or "FlightOp" in name:
                    return True
        return False

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if touches_flight(node):
                direct.add(node.name)
            called: "Set[str]" = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    called.add(dotted(sub.func).rsplit(".", 1)[-1])
            calls[node.name] = called

    reach = set(direct)
    for _ in range(2):  # two hops of same-module indirection
        reach |= {fn for fn, cs in calls.items() if cs & reach}
    return reach


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    known = _known_sites(project)
    robustness = project.doc_text_for(_ROBUSTNESS_DOC)

    # --- fault-site checks ------------------------------------------------
    checked_sites: "Set[str]" = set()
    if known is not None:
        for path in project.py_files:
            tree = project.tree(path)
            if tree is None:
                continue
            col = _CheckCollector(project, path)
            col.visit(tree)
            for site, line, qual in col.calls:
                checked_sites.add(site)
                if site not in known:
                    out.append(
                        Finding(
                            pass_id=PASS_ID,
                            code="unknown-fault-site",
                            file=project.rel(path),
                            line=line,
                            symbol=site,
                            message=(
                                f"fault site {site!r} is not in "
                                f"faults.KNOWN_SITES — register it (and its "
                                f"docs row) or the TORCHFT_FAULTS grammar "
                                f"warns on every spec naming it"
                            ),
                        )
                    )
                elif robustness and site not in robustness:
                    out.append(
                        Finding(
                            pass_id=PASS_ID,
                            code="undocumented-fault-site",
                            file=project.rel(path),
                            line=line,
                            symbol=site,
                            message=(
                                f"fault site {site!r} is missing from the "
                                f"{_ROBUSTNESS_DOC} site table"
                            ),
                        )
                    )
        faults_path = project.find_file(_FAULTS_FILE)
        for site in sorted(known - checked_sites):
            if site in _WIRING_EXEMPT:
                continue
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code="unwired-fault-site",
                    file=project.rel(faults_path or ""),
                    line=1,
                    symbol=site,
                    message=(
                        f"KNOWN_SITES entry {site!r} has no faults.check() "
                        f"call site left in the tree — chaos schedules "
                        f"naming it silently never fire"
                    ),
                )
            )

    # --- flight-recorder anchors -----------------------------------------
    for suffix, func_name in _FLIGHT_ANCHORS:
        path = project.find_file(suffix)
        if path is None:
            continue  # module absent from the analyzed set
        tree = project.tree(path)
        if tree is None:
            continue
        defs = [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == func_name
        ]
        if not defs:
            continue  # anchor gone entirely: an API change, not a coverage gap
        reach = _module_flight_reach(tree)
        if func_name not in reach:
            out.append(
                Finding(
                    pass_id=PASS_ID,
                    code="missing-flight-op",
                    file=project.rel(path),
                    line=defs[0].lineno,
                    symbol=func_name,
                    message=(
                        f"{func_name} no longer feeds the flight recorder "
                        f"(no record/start/track reference within two "
                        f"same-module call hops) — the post-mortem loses "
                        f"this path's evidence"
                    ),
                )
            )
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------


def _run_on_project(files: "Dict[str, str]", robustness: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory(prefix="tftlint_selftest_") as td:
        os.makedirs(os.path.join(td, "docs"))
        with open(
            os.path.join(td, "docs", "robustness.md"), "w", encoding="utf-8"
        ) as fh:
            fh.write(robustness)
        paths = []
        for rel, src in files.items():
            path = os.path.join(td, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(src)
            paths.append(path)
        return list(run(Project(td, paths)))


_FAULTS_SRC = 'KNOWN_SITES = ("pg.allreduce", "manager.quorum", "train.step")\n'


def selftest() -> None:
    bad = _run_on_project(
        {
            "pkg/utils/faults.py": _FAULTS_SRC,
            "pkg/core.py": (
                "from torchft_tpu.utils import faults\n"
                "def step():\n"
                '    faults.check("pg.allreduce")\n'
                '    faults.check("pg.typo_site")\n'
            ),
            "pkg/parallel/process_group.py": (
                "def _worker_loop(self):\n"
                "    pass  # no flight recorder reference\n"
            ),
        },
        robustness="| `manager.quorum` | documented |\n",
    )
    codes = {f.code for f in bad}
    expect = {
        "unknown-fault-site",
        "undocumented-fault-site",  # pg.allreduce missing from the doc
        "unwired-fault-site",  # manager.quorum never checked
        "missing-flight-op",
    }
    missing = expect - codes
    if missing:
        raise SelftestError(f"{PASS_ID}: bad project missed codes {missing}")

    got = _run_on_project(
        {
            "pkg/utils/faults.py": _FAULTS_SRC,
            "pkg/core.py": (
                "from torchft_tpu.utils import faults\n"
                "def step():\n"
                '    faults.check("pg.allreduce")\n'
                '    faults.check("manager.quorum")\n'
            ),
            "pkg/parallel/process_group.py": (
                "from torchft_tpu.utils import flightrecorder as _flightrec\n"
                "def _finish(op):\n"
                '    _flightrec.record("op")\n'
                "def _worker_loop(self):\n"
                "    _finish(None)\n"
            ),
        },
        robustness="`pg.allreduce` `manager.quorum` `train.step`\n",
    )
    if got:
        raise SelftestError(
            f"{PASS_ID}: good project falsely flagged: "
            f"{[f.render() for f in got]}"
        )


PASS = LintPass(
    id=PASS_ID,
    doc="fault sites are registered+documented+wired; PG collectives and "
    "checkpoint transports feed the flight recorder",
    run=run,
    selftest=selftest,
)
