"""Pass ``retry-ban``: no ``time.sleep``-based retry loops outside
``utils/retry.py``.

PR 2 replaced three divergent ad-hoc backoff loops with one reviewable
:class:`RetryPolicy` (exponential backoff, full jitter, hard deadline
budgets, metrics) — on the argument that retry behavior must be one
object, not folklore.  Folklore regrows one `while True: ...sleep()` at
a time; this pass is the herbicide: any ``time.sleep`` lexically inside
a ``while``/``for`` body outside ``utils/retry.py`` is flagged unless
the (file, qualname) pair is on the structural allowlist.

The allowlist is for loops that *pace*, not *retry* — sleeping there is
the behavior, not a recovery policy:

- the launcher's child-poll / restart-backoff supervisor loop
  (``ReplicaGroupLauncher.run``): process supervision with its own
  restart budget semantics, deliberately simple;
- the timeout engine's watchdog heartbeat
  (``_TimeoutManager._run_watchdog``): a fixed-cadence liveness probe —
  routing the watchdog through the machinery it watches would be
  circular;
- the token-bucket rate limiter (``_TokenBucket.consume``): the sleep
  *is* the shaping.

Everything else retries and must say so: ``RetryPolicy.run`` gives the
loop jitter, budgets, ``torchft_retries_total`` accounting, and flight
records that let ``torchft-diagnose`` flag retry storms.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from torchft_tpu.analysis.core import (
    Finding,
    LintPass,
    Project,
    SelftestError,
    dotted,
)

PASS_ID = "retry-ban"

_EXEMPT_FILE_SUFFIX = "utils/retry.py"

# (file suffix, qualname) pairs allowed to sleep inside a loop.
_ALLOWLIST: "Tuple[Tuple[str, str], ...]" = (
    ("launcher.py", "ReplicaGroupLauncher.run"),
    ("utils/futures.py", "_TimeoutManager._run_watchdog"),
    ("parallel/process_group.py", "_TokenBucket.consume"),
)


def _allowed(relpath: str, qual: str) -> bool:
    norm = relpath.replace("\\", "/")
    return any(
        norm.endswith(suffix) and qual == q for suffix, q in _ALLOWLIST
    )


class _Visitor(ast.NodeVisitor):
    def __init__(self, project: Project, path: str) -> None:
        self.project = project
        self.path = path
        self.findings: "List[Finding]" = []
        self._qual: "List[str]" = []
        self._loop_depth = 0

    def _visit_scoped(self, node: ast.AST) -> None:
        self._qual.append(node.name)  # type: ignore[attr-defined]
        # a function defined inside a loop runs later: reset loop context
        saved, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = saved
        self._qual.pop()

    visit_FunctionDef = _visit_scoped  # noqa: N815
    visit_AsyncFunctionDef = _visit_scoped  # noqa: N815
    visit_ClassDef = _visit_scoped  # noqa: N815

    def _visit_loop(self, node: ast.AST) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = _visit_loop  # noqa: N815
    visit_For = _visit_loop  # noqa: N815

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        if self._loop_depth > 0 and dotted(node.func).endswith("time.sleep"):
            qual = ".".join(self._qual)
            rel = self.project.rel(self.path)
            if not _allowed(rel, qual):
                self.findings.append(
                    Finding(
                        pass_id=PASS_ID,
                        code="sleep-in-loop",
                        file=rel,
                        line=node.lineno,
                        symbol=qual,
                        message=(
                            "time.sleep inside a loop outside utils/retry.py "
                            "— use RetryPolicy.run (jitter, deadline budgets, "
                            "torchft_retries_total accounting) or add a "
                            "pacing-loop allowlist entry with a reason"
                        ),
                    )
                )
        self.generic_visit(node)


def run(project: Project) -> "Iterable[Finding]":
    out: "List[Finding]" = []
    for path in project.py_files:
        if path.replace("\\", "/").endswith(_EXEMPT_FILE_SUFFIX):
            continue
        tree = project.tree(path)
        if tree is None:
            continue
        visitor = _Visitor(project, path)
        visitor.visit(tree)
        out.extend(visitor.findings)
    return out


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

_BAD = {
    "while-retry": """
import time
def fetch():
    while True:
        try:
            return do()
        except ConnectionError:
            time.sleep(0.5)
""",
    "for-retry": """
import time
def fetch():
    for attempt in range(5):
        time.sleep(2 ** attempt)
""",
}

_GOOD = {
    "single-sleep": "import time\ndef pace():\n    time.sleep(0.1)\n",
    "policy": (
        "from torchft_tpu.utils.retry import RetryPolicy\n"
        "def fetch():\n"
        "    return RetryPolicy(name='x').run(lambda b: do())\n"
    ),
    "sleep-in-nested-def-outside-loop": """
import time
def outer():
    for i in range(3):
        def cb():
            time.sleep(1)  # runs later, not a loop retry
        register(cb)
""",
}


def _run_on_source(src: str) -> "List[Finding]":
    import os
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "snippet.py")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)
        return list(run(Project(td, [path])))


def selftest() -> None:
    for name, src in _BAD.items():
        if not _run_on_source(src):
            raise SelftestError(f"{PASS_ID}: bad snippet {name!r} not flagged")
    for name, src in _GOOD.items():
        got = _run_on_source(src)
        if got:
            raise SelftestError(
                f"{PASS_ID}: good snippet {name!r} falsely flagged: "
                f"{[f.render() for f in got]}"
            )


PASS = LintPass(
    id=PASS_ID,
    doc="no time.sleep retry loops outside utils/retry.py (pacing loops "
    "allowlisted: launcher supervisor, watchdog, rate limiter)",
    run=run,
    selftest=selftest,
)
