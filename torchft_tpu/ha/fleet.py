"""LighthouseFleet: N in-process lighthouse peers with leased leadership.

The test/bench/smoke harness for coordination-plane HA: picks N free
ports, starts N native ``LighthouseServer`` peers wired to each other,
and exposes the leader/term introspection plus targeted kills the chaos
tests and ``bench.py --ha-failover`` drive.  Production deployments run
one ``python -m torchft_tpu.lighthouse --peers ...`` process per node
instead — the wire behavior is identical.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional

from torchft_tpu.ha.endpoints import format_endpoints
from torchft_tpu.utils.retry import RetryPolicy

__all__ = ["LighthouseFleet", "pick_free_ports"]

# Leader-wait poll: a fixed-cadence probe under the unified retry layer
# (deadline budget, torchft_retries_total accounting) — elections settle
# within ~a lease, so the cadence is a fraction of the default lease.
_WAIT_LEADER_POLICY = RetryPolicy(
    name="ha.wait_leader",
    base_delay=0.02,
    multiplier=1.0,
    max_delay=0.02,
    jitter=False,
    retryable=(ConnectionError,),
)


def pick_free_ports(n: int) -> "List[int]":
    """``n`` distinct currently-free TCP ports.

    Bind-then-close: the usual (benign) race — something else could grab
    a port before the server binds it; callers that cannot tolerate that
    retry fleet construction.  All sockets are held open until every
    port is picked so the n ports are distinct.
    """
    socks: "List[socket.socket]" = []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class LighthouseFleet:
    """``n`` lighthouse peers in this process, leased leadership armed.

    Args mirror :class:`torchft_tpu.coordination.LighthouseServer`;
    ``lease_timeout_ms`` is kept deliberately small by default (300 ms)
    so tests exercise real takeovers quickly.  ``addresses()`` is the
    comma list to hand to clients/``TORCHFT_LIGHTHOUSE``.
    """

    def __init__(
        self,
        n: int = 3,
        min_replicas: int = 1,
        join_timeout_ms: int = 100,
        quorum_tick_ms: int = 50,
        heartbeat_timeout_ms: int = 5000,
        lease_timeout_ms: int = 300,
        host: str = "127.0.0.1",
    ) -> None:
        from torchft_tpu.coordination import LighthouseServer

        if n < 1:
            raise ValueError("fleet needs at least one peer")
        self._host = host
        self._ports = pick_free_ports(n)
        self._endpoints = [f"{host}:{p}" for p in self._ports]
        self._servers: "List[Optional[LighthouseServer]]" = []
        for i in range(n):
            others = [ep for j, ep in enumerate(self._endpoints) if j != i]
            self._servers.append(
                LighthouseServer(
                    bind=f"{host}:{self._ports[i]}",
                    min_replicas=min_replicas,
                    join_timeout_ms=join_timeout_ms,
                    quorum_tick_ms=quorum_tick_ms,
                    heartbeat_timeout_ms=heartbeat_timeout_ms,
                    peers=others,
                    lease_timeout_ms=lease_timeout_ms,
                )
            )
        self._lease_timeout_ms = lease_timeout_ms

    # -- introspection -----------------------------------------------------

    def endpoints(self) -> "List[str]":
        return list(self._endpoints)

    def addresses(self) -> str:
        """The ``TORCHFT_LIGHTHOUSE`` comma-list value for this fleet."""
        return format_endpoints(self._endpoints)

    def ha_info(self, i: int) -> "Dict[str, Any]":
        server = self._servers[i]
        if server is None:
            raise RuntimeError(f"peer {i} was killed")
        return server.ha_info()

    def alive(self) -> "List[int]":
        return [i for i, s in enumerate(self._servers) if s is not None]

    def leader_index(self) -> "Optional[int]":
        """The peer currently leading, or None mid-election."""
        for i in self.alive():
            try:
                if self.ha_info(i)["is_leader"]:
                    return i
            except RuntimeError:
                continue
        return None

    def leader_address(self) -> "Optional[str]":
        i = self.leader_index()
        return None if i is None else self._endpoints[i]

    def wait_for_leader(self, timeout: float = 10.0) -> int:
        """Block until some peer leads; returns its index."""

        def attempt(_budget: "Optional[float]") -> int:
            i = self.leader_index()
            if i is None:
                raise ConnectionError("no lighthouse leader yet")
            return i

        try:
            return _WAIT_LEADER_POLICY.run(
                attempt, timeout=timeout, op="ha.wait_leader"
            )
        except TimeoutError as e:
            raise TimeoutError(
                f"no lighthouse leader elected within {timeout}s "
                f"(alive: {self.alive()})"
            ) from e

    def term(self) -> int:
        """The current leader's term (0 when no leader)."""
        i = self.leader_index()
        return 0 if i is None else int(self.ha_info(i)["term"])

    # -- chaos -------------------------------------------------------------

    def kill(self, i: int) -> None:
        """Hard-stop peer ``i`` (its socket closes; clients see a dead
        endpoint, exactly like a SIGKILL'd process)."""
        server = self._servers[i]
        if server is not None:
            self._servers[i] = None
            server.shutdown()

    def kill_leader(self, timeout: float = 10.0) -> int:
        """Kill the current leader; returns its index."""
        i = self.wait_for_leader(timeout)
        self.kill(i)
        return i

    # -- lifecycle ---------------------------------------------------------

    def shutdown(self) -> None:
        for i in list(range(len(self._servers))):
            self.kill(i)

    def __enter__(self) -> "LighthouseFleet":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()
