"""Endpoint-list plumbing for the replicated lighthouse.

The one parser (`coordination.parse_endpoints`) is re-exported here so
HA tooling has a single import home; `exclude_self` implements the
"same config file on every node" convention — each peer is handed the
FULL ``TORCHFT_LIGHTHOUSE`` list and removes its own entry by port.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

from torchft_tpu.coordination import parse_endpoints, parse_host_port
from torchft_tpu.utils.hostident import local_host_identities

__all__ = ["parse_endpoints", "format_endpoints", "exclude_self"]


def format_endpoints(endpoints: "Sequence[str]") -> str:
    """The inverse of :func:`parse_endpoints`: a ``TORCHFT_LIGHTHOUSE``
    comma-list value."""
    return ",".join(endpoints)


def exclude_self(
    endpoints: "Sequence[str]",
    bind_port: int,
    local_hosts: "Optional[Iterable[str]]" = None,
) -> "List[str]":
    """Drop this peer's own entry from a full endpoint list.

    Operators hand every lighthouse the SAME ``--peers`` list.  A unique
    entry on this peer's bind port is unambiguously "me".  The standard
    multi-host deployment puts EVERY peer on the same port, so among
    several same-port entries the one whose host is a local identity
    (hostname, short hostname, loopback, the hostname's resolved IP,
    plus any ``local_hosts`` the caller adds — the CLI passes its bind
    host) is removed.  If none can be
    identified the list is ambiguous and this RAISES: a silently wrong
    exclusion would leave the peer in its own peer list, double-counting
    its self-vote toward lease majorities — exactly the split-brain HA
    exists to prevent.  A list that never contained this peer's port
    comes back unchanged (the caller is then a pure witness peer, which
    also works); ``bind_port`` 0 (ephemeral) never matches — an
    ephemeral-port peer cannot appear in a static list.
    """
    eps = list(endpoints)
    if bind_port == 0:
        return eps

    def _port(ep: str) -> "Optional[int]":
        try:
            return parse_host_port(ep)[1]
        except ValueError:
            return None

    candidates = [i for i, ep in enumerate(eps) if _port(ep) == bind_port]
    if not candidates:
        return eps
    if len(candidates) > 1:
        local = local_host_identities() | (
            frozenset(local_hosts) if local_hosts is not None else frozenset()
        )
        candidates = [
            i for i in candidates if parse_host_port(eps[i])[0] in local
        ]
    if len(candidates) != 1:
        raise ValueError(
            f"cannot identify this peer (port {bind_port}) in the peer "
            f"list {eps}: {len(candidates)} entries match by port+host — "
            f"use distinct hostnames (or distinct ports) per peer so the "
            f"self-entry is unambiguous"
        )
    return eps[: candidates[0]] + eps[candidates[0] + 1 :]
