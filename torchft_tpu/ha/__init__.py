"""Coordination-plane HA: a replicated lighthouse with leased leadership.

Run N lighthouse peers (static endpoint list), let them elect a leader by
majority lease acknowledgement (monotone term, heartbeat-renewed lease,
takeover on expiry), and point every client at the full list —
``TORCHFT_LIGHTHOUSE=host1:p,host2:p,host3:p``.  Followers answer
leader-only RPCs with a ``NOT_LEADER`` redirect naming the current
holder; ``LighthouseClient`` and the native manager's lighthouse client
walk the list and follow redirects transparently, so ``Manager``,
serving replicas/clients and ``torchft-diagnose`` need no changes to
survive a lighthouse death.

Because lighthouse state is soft (heartbeats and serving registrations
rebuild through client re-registration), failover transfers nothing —
only monotonicity is preserved: the leader's term prefixes every id the
lighthouse mints (``(term << 32) | seq`` for ``quorum_id`` and the
serving plan epoch), so a new leader's ids strictly dominate its
predecessor's.  See docs/architecture.md "Coordination-plane HA".
"""

from torchft_tpu.ha.endpoints import (
    exclude_self,
    format_endpoints,
    parse_endpoints,
)
from torchft_tpu.ha.fleet import LighthouseFleet, pick_free_ports

__all__ = [
    "LighthouseFleet",
    "exclude_self",
    "format_endpoints",
    "parse_endpoints",
    "pick_free_ports",
]
