"""LocalSGD and (Streaming) DiLoCo: communication-reducing semi-sync DP.

TPU-native rebuild of the reference algorithms
(reference: torchft/local_sgd.py:46-797).  Functional JAX adaptation: model
parameters are a flat ``{name: array}`` pytree owned by the trainer and
accessed through get/set callables; fragments are key subsets; backup
("global") parameters live on host (numpy) — the CPU-backup analog of
reference :237-254; outer optimizers are optax transforms.

Semantics parity:
- LocalSGD (:46-173): every ``sync_every`` inner steps, average parameters
  across the quorum and commit.
- DiLoCo / Streaming DiLoCo (:176-797): the model is split into fragments,
  each with its own outer optimizer and host backup.  Per fragment cycle of
  ``sync_every // n_fragments`` inner steps: at ``cycle - fragment_sync_delay``
  start quorum + kick off an async allreduce of the fragment's pseudogradients
  (backup - local, optionally quantized); at ``cycle`` wait, restore backup
  params, vote commit, and on success outer-step + merge local/global by
  ``fragment_update_alpha``.  Fragment order is driven by
  ``manager.current_step() % n_fragments`` so all replicas reduce the same
  fragment — avoiding the cross-replica deadlock described in reference
  :746-792.  Requires a synchronous quorum (reference :618-643).
"""

from __future__ import annotations

import logging
import time
from types import TracebackType
from typing import Any, Callable, Dict, List, Optional, Type

import jax
import numpy as np
import optax

from torchft_tpu.manager import Manager
from torchft_tpu.parallel.work import Work
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics

logger = logging.getLogger(__name__)

Params = Dict[str, Any]
GetParams = Callable[[], Params]
SetParams = Callable[[Params], None]


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(np.asarray, tree)


class LocalSGD:
    """Synchronize by averaging parameters every ``sync_every`` steps.

    Usage::

        with LocalSGD(manager, get_params, set_params, sync_every=32) as lsgd:
            for batch in data:
                params = inner_step(params, batch)   # local-only update
                set_params(params)
                lsgd.step()                          # counts; syncs on schedule
    """

    def __init__(
        self,
        manager: Manager,
        get_params: GetParams,
        set_params: SetParams,
        sync_every: int,
    ) -> None:
        if sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self._manager = manager
        self._get_params = get_params
        self._set_params = set_params
        self._sync_every = sync_every
        self._local_step = 0
        manager.register_state_dict_fn(
            "LocalSGD", self._load_state_dict, lambda: _to_host(self._get_params())
        )
        # Online parallelism switching (parallel/layout.py): a committed
        # layout switch changes the averaging cohort mid-cycle, so restart
        # the inner cycle — the first post-switch sync then bounds local
        # divergence by at most sync_every fresh steps, not a straddled
        # pre-switch remainder.
        controller = manager.layout_controller()
        if controller is not None:
            controller.add_listener(self._on_layout_commit)

    def _on_layout_commit(self, layout: Any, info: "Dict[str, Any]") -> None:
        self._local_step = 0

    def _load_state_dict(self, state_dict: Params) -> None:
        self._set_params(state_dict)

    def __enter__(self) -> "LocalSGD":
        return self

    def __exit__(
        self,
        exc_type: "Optional[Type[BaseException]]",
        exc_value: "Optional[BaseException]",
        traceback: "Optional[TracebackType]",
    ) -> bool:
        return False

    def step(self) -> None:
        """Count one inner optimizer step; sync when the schedule fires."""
        self._local_step += 1
        if self._local_step >= self._sync_every:
            self.sync()

    def sync(self) -> None:
        """Average parameters across the quorum (reference :112-173)."""
        # chaos site: a raise here is a replica crash at the semi-sync
        # boundary — the worst moment, mid-divergence from the backup
        _faults.check(
            "local_sgd.sync",
            replica=self._manager.replica_id(),
            step=self._manager.current_step(),
        )
        with _flightrec.track(
            "local_sgd.sync",
            replica_id=self._manager.replica_id(),
            step=self._manager.current_step(),
        ) as flight:
            self._sync(flight)

    def _sync(self, flight: "_flightrec.FlightOp") -> None:
        self._local_step = 0
        self._manager.start_quorum()
        params = self._get_params()
        avg = self._manager.allreduce(params).wait(timeout=self._manager._timeout)
        committed = self._manager.should_commit()
        flight.update(committed=committed)
        if committed:
            # Guard the mutation: an async quorum thread may be snapshotting
            # the state dict for a healing peer (reference :112-124).
            self._manager.disallow_state_dict_read()
            try:
                self._set_params(avg)
            finally:
                self._manager.allow_state_dict_read()


class _Fragment:
    """One DiLoCo fragment: key subset + host backup + outer optimizer.

    Reference: _StreamingDiLoCoFragment (local_sgd.py:176-567).
    """

    def __init__(
        self,
        manager: Manager,
        fragment_id: int,
        keys: "List[str]",
        get_params: GetParams,
        set_params: SetParams,
        outer_optimizer: optax.GradientTransformation,
        should_quantize: bool,
        fragment_update_alpha: float,
        device_quantize: "Optional[bool]" = None,
    ) -> None:
        self._manager = manager
        self._fragment_id = fragment_id
        self._keys = keys
        self._get_params = get_params
        self._set_params = set_params
        self._outer = outer_optimizer
        self._should_quantize = should_quantize
        self._device_quantize = device_quantize
        self._alpha = fragment_update_alpha

        # host ("global") backup of this fragment's params
        self.original_parameters: Params = {}
        self._outer_state: Any = None
        self._allreduce_work: "List[Work]" = []
        self._local_parameters: "Optional[Params]" = None
        self.save_parameters()
        self._outer_state = self._outer.init(self.original_parameters)
        self.register_state_dict_fn()

    def _fragment_params(self) -> Params:
        params = self._get_params()
        return {k: params[k] for k in self._keys}

    def _write_fragment(self, frag: Params) -> None:
        params = dict(self._get_params())
        params.update(frag)
        self._set_params(params)

    def save_parameters(self) -> None:
        self.original_parameters = _to_host(self._fragment_params())

    def restore_parameters(self) -> None:
        self._write_fragment(
            jax.tree_util.tree_map(np.array, self.original_parameters)
        )

    def register_state_dict_fn(self) -> None:
        # per-fragment healing slice (reference :256-287)
        key = f"StreamingDiLoCoFragment_{self._fragment_id}"

        def load_fn(sd: "Dict[str, Any]") -> None:
            self.original_parameters = jax.tree_util.tree_map(
                np.array, sd["original_parameters"]
            )
            self._outer_state = sd["outer_optimizer"]

        def save_fn() -> "Dict[str, Any]":
            return {
                "original_parameters": jax.tree_util.tree_map(
                    np.array, self.original_parameters
                ),
                "outer_optimizer": self._outer_state,
            }

        self._manager.register_state_dict_fn(key, load_fn, save_fn)

    def _device_pseudograds(self) -> bool:
        """True when this fragment's pseudogradients should stay on
        device for the quantized sync: explicit ``device_quantize``
        wins, else auto — quantized leg + TPU backend (ROADMAP item 1:
        the Pallas int8 kernel quantizes on-chip and only the int8
        payload + row scales cross the device→host boundary, the D2H
        copies riding the chunk queue of the wire pipeline)."""
        if not self._should_quantize:
            return False
        if self._device_quantize is not None:
            return self._device_quantize
        return jax.default_backend() == "tpu"

    def prepare_sync(self) -> None:
        """Pseudograds = backup - local; kick off the async allreduce
        (reference :402-421)."""
        if self._device_pseudograds():
            # compute backup - local ON DEVICE (one H2D of the host
            # backup) so the quantized collective takes the Pallas
            # device-quantize path: the f32 pseudograds never cross PCIe
            import jax.numpy as jnp

            local = self._fragment_params()
            pseudograds = jax.tree_util.tree_map(
                lambda g, l: jnp.asarray(g, dtype=jnp.float32)
                - jnp.asarray(l, dtype=jnp.float32),
                self.original_parameters,
                local,
            )
        else:
            local = _to_host(self._fragment_params())
            pseudograds = jax.tree_util.tree_map(
                lambda g, l: g.astype(np.float32) - l.astype(np.float32),
                self.original_parameters,
                local,
            )
        # payload-byte fallback for the wire gauge: both the quantized
        # pipeline AND the unquantized TCP ring now report measured
        # wire_bytes on the Work (f32 vs int8 traffic compares honestly in
        # bench/diagnose), so this only covers PG backends without ring
        # accounting (e.g. test fakes).  Computed from size*itemsize, not
        # np.asarray — device leaves must not be pulled to host here.
        self._payload_bytes = sum(
            int(v.size) * np.dtype(v.dtype).itemsize
            for v in jax.tree_util.tree_leaves(pseudograds)
        )
        assert not self._allreduce_work
        self._allreduce_work.append(
            self._manager.allreduce(
                pseudograds,
                should_quantize=self._should_quantize,
                device_quantize=self._device_quantize,
            )
        )

    def discard_pending_work(self) -> None:
        """Drop any queued allreduce work (error-path cleanup so the next
        prepare_sync's not-already-pending assert holds)."""
        self._allreduce_work.clear()
        self._local_parameters = None

    def perform_sync(self) -> bool:
        """Wait for the allreduce, vote, and outer-step on success
        (reference :423-476)."""
        assert self._allreduce_work, "perform_sync before prepare_sync"
        t_sync = time.perf_counter()
        with _flightrec.track(
            "local_sgd.fragment_sync",
            fragment=self._fragment_id,
            replica_id=self._manager.replica_id(),
            step=self._manager.current_step(),
        ) as flight:
            result = self._perform_sync()
            flight.update(committed=result)
        _metrics.DILOCO_SYNC_SECONDS.labels(fragment=str(self._fragment_id)).set(
            time.perf_counter() - t_sync
        )
        return result

    def _perform_sync(self) -> bool:
        work = self._allreduce_work.pop()
        avg_pseudograds = work.wait(timeout=self._manager._timeout)
        wire_bytes = getattr(work, "wire_bytes", None)
        if wire_bytes is None:
            # explicit None check: wire_bytes == 0 is a real measurement
            # (world size 1 sends nothing) and must not fall back to the
            # full payload size
            wire_bytes = getattr(self, "_payload_bytes", 0)
        _metrics.DILOCO_WIRE_BYTES.labels(fragment=str(self._fragment_id)).set(
            wire_bytes
        )

        # save local then roll back to the global backup: a failed commit
        # must leave us on consistent (pre-divergence) state
        self._local_parameters = _to_host(self._fragment_params())
        self.restore_parameters()

        should_commit = self._manager.should_commit()
        if should_commit:
            # outer update on the backup params; optax's sgd(+momentum,
            # nesterov) is the reference's default outer optimizer
            tm = jax.tree_util.tree_map
            grads = tm(lambda v: np.asarray(v, dtype=np.float32), avg_pseudograds)
            updates, self._outer_state = self._outer.update(
                grads, self._outer_state, self.original_parameters
            )
            new_global = optax.apply_updates(
                tm(lambda v: v.astype(np.float32), self.original_parameters),
                updates,
            )
            new_global = tm(
                lambda v, o: np.asarray(v, dtype=o.dtype),
                new_global,
                self.original_parameters,
            )
            self.original_parameters = new_global
            # merge: params = (1-alpha) * global + alpha * local
            merged = tm(
                lambda g, l: np.asarray(
                    (1.0 - self._alpha) * g.astype(np.float32)
                    + self._alpha * l.astype(np.float32),
                    dtype=g.dtype,
                ),
                new_global,
                self._local_parameters,
            )
            self._write_fragment(merged)
        self._local_parameters = None
        return should_commit


class DiLoCo:
    """(Streaming) DiLoCo over fragment key subsets.

    Args:
        manager: must use a synchronous quorum (use_async_quorum=False).
        fragments: list of key lists partitioning the flat param dict; one
            entry behaves as classic DiLoCo, several as Streaming DiLoCo.
        outer_optimizer: optax transform (or list, one per fragment);
            the paper (and reference) default is SGD + nesterov momentum.
        sync_every: inner steps per full round; must be divisible by the
            fragment count.
        fragment_sync_delay: inner steps between kicking off a fragment's
            allreduce and blocking on it ("tau" in Streaming DiLoCo).
        fragment_update_alpha: local/global mixing factor.
        device_quantize: quantized leg only — compute pseudogradients on
            device and quantize with the Pallas kernel before the D2H
            copy.  ``None`` = auto (on for TPU backends); ``False``
            forces the host codec; ``True`` forces the device path (used
            by the CPU interpret-mode parity test).
    """

    def __init__(
        self,
        manager: Manager,
        fragments: "List[List[str]]",
        get_params: GetParams,
        set_params: SetParams,
        outer_optimizer: "optax.GradientTransformation | List[optax.GradientTransformation]",
        sync_every: int,
        should_quantize: bool = False,
        fragment_sync_delay: int = 0,
        fragment_update_alpha: float = 0.0,
        device_quantize: "Optional[bool]" = None,
    ) -> None:
        if manager._use_async_quorum:
            raise ValueError(
                "DiLoCo requires synchronous quorum: construct the Manager "
                "with use_async_quorum=False"
            )
        if not fragments or not all(fragments):
            raise ValueError("fragments must be non-empty key lists")
        if sync_every < len(fragments):
            raise ValueError("only 1 fragment can be synchronized at a time")
        if sync_every % len(fragments) != 0:
            raise ValueError("sync_every must be divisible by the number of fragments")
        self._cycle = sync_every // len(fragments)
        if fragment_sync_delay >= self._cycle:
            raise ValueError("fragment must be synced before it is reduced again")
        if not (0.0 <= fragment_update_alpha <= 1.0):
            raise ValueError("fragment_update_alpha must be within [0, 1]")

        if isinstance(outer_optimizer, list):
            if len(outer_optimizer) != len(fragments):
                raise ValueError("need one outer optimizer per fragment")
            outers = outer_optimizer
        else:
            outers = [outer_optimizer] * len(fragments)

        self._manager = manager
        self._local_step = 0
        self._fragment_sync_delay = fragment_sync_delay
        self._fragments = [
            _Fragment(
                manager,
                i,
                keys,
                get_params,
                set_params,
                outers[i],
                should_quantize,
                fragment_update_alpha,
                device_quantize=device_quantize,
            )
            for i, keys in enumerate(fragments)
        ]
        # Online parallelism switching: a committed switch must not be
        # straddled by fragment state — discard any in-flight fragment
        # allreduce (its cohort is gone) and re-snapshot the outer
        # backups so no pseudogradient ever spans a layout generation.
        # DiLoCo managers are sync-quorum, so the listener runs on the
        # training-loop thread — no race with inner steps.
        controller = manager.layout_controller()
        if controller is not None:
            controller.add_listener(self._on_layout_commit)

    def _on_layout_commit(self, layout: Any, info: "Dict[str, Any]") -> None:
        for frag in self._fragments:
            frag.discard_pending_work()
            frag.save_parameters()

    def __enter__(self) -> "DiLoCo":
        return self

    def __exit__(
        self,
        exc_type: "Optional[Type[BaseException]]",
        exc_value: "Optional[BaseException]",
        traceback: "Optional[TracebackType]",
    ) -> bool:
        return False

    def _current_fragment(self) -> int:
        # driven by the committed step so every replica reduces the same
        # fragment (reference :735-741)
        return self._manager.current_step() % len(self._fragments)

    def step(self) -> None:
        """Call after each inner optimizer step (the post-hook analog,
        reference :746-792)."""
        self._local_step += 1

        if self._local_step == self._cycle - self._fragment_sync_delay:
            # chaos site: replica crash at the fragment-sync boundary (the
            # DiLoCo analog of LocalSGD.sync's injection point)
            _faults.check(
                "local_sgd.sync",
                replica=self._manager.replica_id(),
                step=self._manager.current_step(),
            )
            self._manager.start_quorum()
            fragment = self._current_fragment()
            logger.info("preparing fragment=%d step=%d", fragment, self._local_step)
            self._fragments[fragment].prepare_sync()

        if self._local_step < self._cycle:
            return
        if self._local_step == self._cycle:
            fragment = self._current_fragment()
            logger.info(
                "syncing fragment=%d step=%d manager_step=%d",
                fragment,
                self._local_step,
                self._manager.current_step(),
            )
            # Reset before the fallible sync (like LocalSGD.sync): if
            # perform_sync raises (e.g. allreduce wait timeout), a caller
            # that catches per-step errors and keeps stepping must start a
            # fresh cycle, not hit the exceeded-cycle assert below forever.
            self._local_step = 0
            try:
                self._fragments[fragment].perform_sync()
            except Exception:
                self._fragments[fragment].discard_pending_work()
                raise
            return
        raise AssertionError(
            f"local_step {self._local_step} exceeded cycle {self._cycle}"
        )
