"""Manager: the per-worker fault-tolerance state machine.

TPU-native rebuild of the reference Manager (reference: torchft/manager.py).
Orchestrates the per-step protocol: quorum (async, overlapped with forward),
process-group reconfiguration on quorum change, live healing (send/recv of
the composite state dict), error capture, and the commit vote.

JAX-first adaptations:
- state dicts are pytrees (params/opt-state/step), not torch module dicts;
- no CUDA streams: JAX dispatch is async on its own, and the DCN collective
  layer runs host-side with Work handles; ``should_commit`` blocks on any
  outstanding recovery future instead of stream events;
- the allreduce hot path zero-fills non-participants and divides by the live
  participant count (reference manager.py:416-417,447-454) so membership
  changes never change compiled shapes — no re-jit on fail/join.

Env knobs (parity with reference manager.py:76-89):
``TORCHFT_LIGHTHOUSE`` (a single ``host:port`` or the coordination-plane
HA comma list ``h1:p,h2:p,h3:p`` — the native manager's lighthouse
client walks dead peers and follows ``NOT_LEADER`` redirects to the
current lease holder, so a replicated lighthouse needs no Manager-side
changes; docs/architecture.md "Coordination-plane HA"),
``TORCHFT_MANAGER_PORT``, ``TORCHFT_TIMEOUT_SEC``,
``TORCHFT_QUORUM_TIMEOUT_SEC``, ``TORCHFT_CONNECT_TIMEOUT_SEC``,
``TORCHFT_QUORUM_RETRIES`` (quorum RPC attempts on connection failure,
with exponential backoff + full jitter via ``utils.retry.RetryPolicy``
inside the quorum timeout budget — no longer a bare loop count).
Chaos: ``TORCHFT_FAULTS`` / ``TORCHFT_FAULTS_SEED`` (utils/faults.py)
inject failures at ``manager.quorum`` / ``manager.heal`` /
``pg.allreduce`` (docs/robustness.md).
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, TypeVar, cast

import jax
import numpy as np

from torchft_tpu.checkpointing import provenance as provenance
from torchft_tpu.checkpointing import store as fragment_store
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.coordination import ManagerClient, ManagerServer, StoreClient, StoreServer
from torchft_tpu.parallel.process_group import ProcessGroup, REDUCE_AVG, REDUCE_SUM
from torchft_tpu.parallel.work import Work, completed_work
from torchft_tpu.utils import faults as faults
from torchft_tpu.utils import flightrecorder as flightrec
from torchft_tpu.utils import linkstats as linkstats
from torchft_tpu.utils import metrics as metrics
from torchft_tpu.utils import tracing as tracing
from torchft_tpu.utils.env import env_bool, env_float, env_int, env_str
from torchft_tpu.utils.logging import ReplicaLogger, log_event
from torchft_tpu.utils.retry import RetryPolicy
from torchft_tpu.utils.rwlock import RWLock

logger = logging.getLogger(__name__)

T = TypeVar("T")

MANAGER_ADDR_KEY = "manager_addr"
REPLICA_ID_KEY = "replica_id"

#: Canonical per-step phase vocabulary recorded by ``_record_phase`` (the
#: quorum_duration histogram labels, flight-recorder phase records, and
#: per-phase trace spans all use these names).  The tft-verify protocol
#: model renders its counterexample traces in the same vocabulary
#: (analysis/protocol_model.MODEL_PHASE_OPS), pinned by a tier-1 test —
#: add here BEFORE recording a new phase name.
PROTOCOL_PHASES = (
    "quorum_wait",
    "quorum_rpc",
    "pg_configure",
    "heal_send",
    "heal_recv",
    # striped-heal receive split (ISSUE 15): manifest fetch from the
    # primary / local digest diff / striped fragment wire / decode into
    # retained buffers — heal_recv stays the umbrella total.
    "heal_manifest",
    "heal_diff",
    "heal_wire",
    "heal_decode",
    "reshard",
    "layout_commit",
    "host_sync",
    "ring",
    "commit",
)

TIMEOUT_SEC = env_float("TORCHFT_TIMEOUT_SEC", 60.0)
QUORUM_TIMEOUT_SEC = env_float("TORCHFT_QUORUM_TIMEOUT_SEC", 60.0)
CONNECT_TIMEOUT_SEC = env_float("TORCHFT_CONNECT_TIMEOUT_SEC", 10.0)
QUORUM_RETRIES = env_int("TORCHFT_QUORUM_RETRIES", 0, minimum=0)


def _to_sec(t: "float | timedelta | None", default: float) -> float:
    if t is None:
        return default
    if isinstance(t, timedelta):
        return t.total_seconds()
    return float(t)


def _is_floating(dtype: Any) -> bool:
    """True for float dtypes incl. ml_dtypes (bfloat16/fp8 — the TPU training
    dtypes), which np.issubdtype does not classify as np.floating."""
    return jax.numpy.issubdtype(dtype, jax.numpy.floating)


class WorldSizeMode(Enum):
    """How the quorum world size behaves (reference manager.py:112-127).

    DYNAMIC: the world grows/shrinks with membership; gradients are averaged
    over the live participant count.
    FIXED_WITH_SPARES: the world is capped at min_replica_size; extra healthy
    replicas are warm spares that compute but do not contribute.
    """

    DYNAMIC = 0
    FIXED_WITH_SPARES = 1


class Manager:
    """Fault-tolerance manager for one worker of one replica group.

    Args:
        pg: the replica-dimension process group (reconfigured per quorum).
        min_replica_size: minimum replicas for a commit to count.
        load_state_dict / state_dict: callables for the user training state
            (pytree); more can be registered via register_state_dict_fn.
        use_async_quorum: overlap quorum with the forward pass.
        checkpoint_transport: transport for live healing (HTTPTransport by
            default).
        store_addr: address of this replica group's rendezvous store; if
            None and group_rank == 0, an in-process StoreServer is started.
        replica_id: stable id of this replica group; a ``:uuid`` suffix is
            appended for fast-restart disambiguation (reference :300-306).
    """

    def __init__(
        self,
        pg: ProcessGroup,
        min_replica_size: int,
        load_state_dict: "Optional[Callable[[Any], None]]" = None,
        state_dict: "Optional[Callable[[], Any]]" = None,
        use_async_quorum: bool = True,
        timeout: "float | timedelta" = TIMEOUT_SEC,
        quorum_timeout: "float | timedelta" = QUORUM_TIMEOUT_SEC,
        connect_timeout: "float | timedelta" = CONNECT_TIMEOUT_SEC,
        group_rank: "Optional[int]" = None,
        group_world_size: "Optional[int]" = None,
        world_size_mode: WorldSizeMode = WorldSizeMode.DYNAMIC,
        store_addr: "Optional[str]" = None,
        lighthouse_addr: "Optional[str]" = None,
        replica_id: "Optional[str]" = None,
        port: "Optional[int]" = None,
        checkpoint_transport: "Optional[CheckpointTransport[Any]]" = None,
        init_sync: bool = True,
        max_retries: "Optional[int]" = None,
        quorum_retries: int = QUORUM_RETRIES,
        heartbeat_interval: float = 0.1,
    ) -> None:
        self._pg = pg
        self._min_replica_size = min_replica_size
        self._use_async_quorum = use_async_quorum
        self._timeout = _to_sec(timeout, TIMEOUT_SEC)
        self._quorum_timeout = _to_sec(quorum_timeout, QUORUM_TIMEOUT_SEC)
        self._connect_timeout = _to_sec(connect_timeout, CONNECT_TIMEOUT_SEC)
        self._replica_world_size_mode = world_size_mode
        self._init_sync = init_sync
        self._max_retries = max_retries
        # Real backoff semantics for quorum_retries (previously only a bare
        # loop count inside the native server): connection-level failures of
        # the quorum RPC retry with exponential backoff + full jitter, all
        # inside the quorum timeout budget.  TimeoutError is NOT retried —
        # the budget expiring IS the failure — and RpcError is not either
        # (the server already applied its own lighthouse retries).
        self._quorum_policy = RetryPolicy(
            name="manager.quorum",
            max_attempts=max(quorum_retries, 0) + 1,
            base_delay=0.25,
            multiplier=2.0,
            max_delay=5.0,
            retryable=(ConnectionError,),
        )

        self._group_rank = (
            group_rank if group_rank is not None else env_int("RANK", 0, minimum=0)
        )
        self._group_world_size = (
            group_world_size
            if group_world_size is not None
            else env_int("WORLD_SIZE", 1)
        )

        self._load_state_dict_fns: Dict[str, Callable[[Any], None]] = {}
        self._user_state_dicts: Dict[str, Callable[[], Any]] = {}
        if load_state_dict is not None and state_dict is not None:
            self.register_state_dict_fn("default", load_state_dict, state_dict)

        if checkpoint_transport is None:
            from torchft_tpu.checkpointing.http_transport import HTTPTransport

            checkpoint_transport = HTTPTransport(timeout=self._timeout)
        self._checkpoint_transport: CheckpointTransport[Any] = checkpoint_transport

        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="torchft_quorum"
        )
        self._quorum_future: "Optional[concurrent.futures.Future[None]]" = None

        self._state_dict_lock = RWLock(timeout=self._timeout)
        self._pending_state_dict: "Optional[Dict[str, Any]]" = None
        self._errored: "Optional[Exception]" = None
        self._healing = False
        self._recovery_future: "Optional[concurrent.futures.Future[None]]" = None
        self._participating_replica_rank: "Optional[int]" = None
        self._participating_replica_world_size: int = 0

        self._step = 0
        self._batches_committed = 0
        self._commit_failures = 0
        self._quorum_id = -1

        # Wall-clock accumulated per protocol phase — the FT-overhead
        # observability surface (the reference only exposes these as
        # profiler spans, torchft/manager.py:385,591,790); consumers read
        # the non-destructive ``phase_times`` snapshot.  ``_record_phase``
        # additionally feeds the torchft_quorum_duration_seconds histogram
        # and, when a tracer is installed, one child span per phase under
        # the round's root span.
        self._phase_acc: Dict[str, float] = {}
        self._phase_lock = threading.Lock()
        # Trace context of the in-flight quorum round (None when tracing
        # is off or the step is unsampled).  The trace id is DERIVED FROM
        # THE STEP (tracing.step_trace_id), so every replica group, the
        # lighthouse, and both heal endpoints of one training step share
        # one trace with zero coordination.
        self._round_ctx: "Optional[tracing.TraceContext]" = None
        self._round_start_ns = 0
        self._round_step = 0

        # --- coordination wiring (reference manager.py:277-325) -----------
        lighthouse_addr = lighthouse_addr or env_str("TORCHFT_LIGHTHOUSE") or None
        if lighthouse_addr is None:
            raise ValueError(
                "lighthouse_addr (or TORCHFT_LIGHTHOUSE) is required"
            )

        self._owned_store: "Optional[StoreServer]" = None
        if store_addr is None:
            if self._group_world_size != 1:
                raise ValueError(
                    "store_addr is required when group_world_size > 1"
                )
            self._owned_store = StoreServer()
            store_addr = self._owned_store.address()
        self._store_addr = store_addr
        store = StoreClient(store_addr, connect_timeout=self._connect_timeout)

        self._manager_server: "Optional[ManagerServer]" = None
        if self._group_rank == 0:
            if replica_id is None:
                replica_id = ""
            # uuid suffix: a fast-restarted replica must not be confused with
            # its dead predecessor in lighthouse state.
            new_replica_id = replica_id + ":" + str(uuid.uuid4())
            bind_port = port or env_int("TORCHFT_MANAGER_PORT", 0, minimum=0)
            self._manager_server = ManagerServer(
                replica_id=new_replica_id,
                lighthouse_addr=lighthouse_addr,
                store_address=store_addr,
                world_size=self._group_world_size,
                bind=f":{bind_port}",
                heartbeat_interval=heartbeat_interval,
                connect_timeout=self._connect_timeout,
                quorum_retries=quorum_retries,
            )
            # replica_id BEFORE manager_addr: readers probe the addr and
            # then read the id, so publishing in this order guarantees a
            # live addr is never paired with the previous incarnation's id
            store.set(REPLICA_ID_KEY, new_replica_id)
            store.set(MANAGER_ADDR_KEY, self._manager_server.address())

        # Non-zero ranks discover the group's ManagerServer through the
        # store.  After a whole-group fast restart the store still holds
        # the DEAD incarnation's address until the new rank 0 republishes
        # — probe the endpoint and re-read until a live server answers
        # (bounded by connect_timeout), instead of wiring this Manager to
        # a corpse for its whole lifetime.
        def _probe(budget: "Optional[float]") -> str:
            probe_timeout = (
                self._connect_timeout if budget is None else max(budget, 0.001)
            )
            addr = store.get(MANAGER_ADDR_KEY, timeout=probe_timeout)
            if self._manager_server is None and not self._endpoint_alive(addr):
                raise ConnectionError(
                    f"manager server at {addr} (from store) not accepting "
                    f"connections yet"
                )
            return addr

        try:
            addr = RetryPolicy(
                name="manager.store_probe",
                base_delay=0.25,
                multiplier=1.0,
                max_delay=0.25,
                jitter=False,
                retryable=(ConnectionError,),
            ).run(_probe, timeout=self._connect_timeout)
        except TimeoutError as e:
            raise TimeoutError(
                f"manager server (from store) unreachable within "
                f"connect_timeout={self._connect_timeout}s: {e.__cause__ or e}"
            ) from e
        # read the id AFTER the probe succeeds: rank 0 publishes replica_id
        # before manager_addr, so a live addr implies the matching
        # incarnation's id is already visible
        self._replica_id = store.get(REPLICA_ID_KEY, timeout=self._connect_timeout)
        self._client = ManagerClient(addr, connect_timeout=self._connect_timeout)
        store.close()

        self._logger = ReplicaLogger(self, self._replica_id, self._group_rank)
        # Opt-in per-manager scrape endpoint (TORCHFT_METRICS_PORT);
        # process-wide singleton, so multi-manager tests don't fight.
        metrics.maybe_serve_from_env()
        # Metric labels use the STABLE replica id (the prefix before the
        # ':<uuid>' incarnation suffix): every restart would otherwise mint
        # a fresh label value, growing the process-wide registry without
        # bound across crash-and-heal cycles and resetting each series'
        # counters (breaking rate() continuity).  Events/logs keep the full
        # incarnation id — they are records, not series.
        self._metric_replica_id = (
            self._replica_id.split(":", 1)[0] or self._replica_id
        )
        # Bound metric children cached per replica: the labels() lookup is
        # ~9 us and _record_phase sits on the step hot path — caching keeps
        # the telemetry cost per phase at the observe() itself (~1 us).
        self._phase_hist: Dict[str, Any] = {}
        self._m_allreduces = metrics.ALLREDUCES.labels(
            replica_id=self._metric_replica_id
        )
        self._m_commits = {
            result: metrics.COMMITS.labels(
                replica_id=self._metric_replica_id, result=result
            )
            for result in ("success", "failure")
        }
        self._m_step = metrics.STEP.labels(replica_id=self._metric_replica_id)
        self._m_participants = metrics.PARTICIPANTS.labels(
            replica_id=self._metric_replica_id
        )
        # Cluster step-timeline digest state (guarded by _phase_lock):
        # phase_times() snapshot at the last digest, plus codec/wire busy
        # seconds accumulated from quantized collectives since then.  The
        # per-step deltas ride the native manager's lighthouse heartbeat
        # (report_summary) into /timeline.json.
        self._summary_phase_snapshot: Dict[str, float] = {}
        self._summary_codec_s = 0.0
        self._summary_wire_s = 0.0
        # Online parallelism switching (parallel/layout.py): optional
        # LayoutController attached via attach_layout().  When present,
        # every quorum entry carries this group's layout epoch + shard
        # manifest, and the async-quorum thread runs the two-phase
        # switch protocol (commit round first, then plan+stage).
        self._layout: "Optional[Any]" = None
        self._weight_publisher: "Optional[Any]" = None
        self._publish_pending: "Optional[int]" = None
        self._publish_executor: (
            "Optional[concurrent.futures.ThreadPoolExecutor]"
        ) = None
        # Durable fragment store (checkpointing/store.py, ISSUE 17):
        # opt-in via TORCHFT_STORE_DIR.  Committed steps spill to disk
        # off the hot path (single-worker spiller) and the store is
        # attached to the checkpoint transport so peers' cold-start
        # restores can stripe-fetch spilled fragments from this rank's
        # disk exactly like a live heal.
        self._frag_store = fragment_store.store_from_env(
            self._metric_replica_id, self._group_rank
        )
        self._spiller: "Optional[Any]" = None
        self._spill_pending: "Optional[int]" = None
        self._last_spill = 0.0
        if self._frag_store is not None:
            attach = getattr(self._checkpoint_transport, "attach_store", None)
            if attach is not None:
                attach(self._frag_store)
            self._spiller = fragment_store.StoreSpiller(self._frag_store)

    @staticmethod
    def _endpoint_alive(addr: str, probe_timeout: float = 1.0) -> bool:
        """True if a TCP listener answers at ``addr`` ("host:port")."""
        from torchft_tpu.coordination import parse_host_port

        try:
            with socket.create_connection(parse_host_port(addr), probe_timeout):
                return True
        except OSError:
            return False

    # ------------------------------------------------------------------
    # state dict registry
    # ------------------------------------------------------------------

    def register_state_dict_fn(
        self,
        key: str,
        load_state_dict_fn: "Callable[[Any], None]",
        state_dict_fn: "Callable[[], Any]",
    ) -> None:
        """Register a named slice of user state for healing
        (reference manager.py:355-366)."""
        self._load_state_dict_fns[key] = load_state_dict_fn
        self._user_state_dicts[key] = state_dict_fn

    def attach_layout(self, controller: Any) -> Any:
        """Attach a :class:`~torchft_tpu.parallel.layout.LayoutController`
        enabling online parallelism switching: on membership change the
        fleet re-plans its (dp, shard, pp) layout under a monotone layout
        epoch, re-shards registered state live over the checkpoint
        transport, and commits the switch at the same quorum round on
        every group or rolls back (docs/architecture.md "Online
        parallelism switching").  Returns the controller for chaining."""
        self._layout = controller
        if hasattr(controller, "bind"):
            controller.bind(self)
        return controller

    def layout_controller(self) -> "Optional[Any]":
        return self._layout

    def attach_weight_publisher(self, publisher: Any) -> Any:
        """Attach a :class:`~torchft_tpu.serving.WeightPublisher`: every
        COMMITTED step's user state is published as weight version
        ``step`` into the serving tier (docs/architecture.md
        "Weight-serving tier").  Timing: the user applies the optimizer
        update AFTER ``should_commit`` returns, so the snapshot is taken
        at the start of the NEXT round (the same point layout updates
        settle) — and flushed at :meth:`shutdown` for the final step.
        Attach to ONE rank per job — typically group 0's rank 0; the
        publisher's versions fan out through the lighthouse-synthesized
        distribution tree.  Publish failures are logged, never allowed
        to fail training.  Returns the publisher for chaining."""
        self._weight_publisher = publisher
        return publisher

    def _flush_pending_publish(self, wait: bool = False) -> None:
        """Publish the last committed step's user state, if one is
        pending (called from the next round's start and from shutdown —
        both points where the user's post-commit optimizer update has
        fully materialized).

        Only the SNAPSHOT runs on the caller (under the state-dict read
        lock, the heal consistency point); the encode + staging + HTTP
        advertise run on a single-worker executor so a multi-GB publish
        never turns the publishing rank into the fleet's straggler at
        every ``start_quorum``.  One worker keeps versions ordered;
        ``wait`` (shutdown) drains the queue so the final version is
        staged before the transports die."""
        version, self._publish_pending = self._publish_pending, None
        pub = self._weight_publisher
        if pub is None:
            return
        if version is not None:
            try:
                with self._state_dict_lock.r_lock():
                    state = {
                        k: fn() for k, fn in self._user_state_dicts.items()
                    }
            except Exception:  # noqa: BLE001 - serving never fails training
                self._logger.exception("weight-publish snapshot failed")
                return
            if self._publish_executor is None:
                self._publish_executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="tft_weight_publish"
                )

            def _do_publish() -> None:
                try:
                    pub.publish(state, version=version)
                except Exception:  # noqa: BLE001 - never fails training
                    self._logger.exception(
                        "weight publish failed (serving tier degraded "
                        "this step)"
                    )

            self._publish_executor.submit(_do_publish)
        if wait and self._publish_executor is not None:
            self._publish_executor.shutdown(wait=True)
            self._publish_executor = None

    def _flush_pending_spill(self, wait: bool = False) -> None:
        """Spill the last committed step to the durable fragment store,
        if one is pending and the ``TORCHFT_STORE_SPILL_S`` cadence has
        elapsed (0 = every commit).  Only the snapshot runs on the
        caller (under the state-dict read lock — the exact bytes a live
        replica at this step holds); encode + blob writes + manifest
        publish run on the single spill worker, and a failed spill skips
        the version (counted), never failing or stalling training."""
        version, self._spill_pending = self._spill_pending, None
        spiller = self._spiller
        if spiller is None:
            return
        if wait:
            # shutdown path: drain the in-flight spill FIRST so the final
            # committed version is accepted instead of skipped (the
            # no-backlog rule exists to protect the training loop, which
            # is over by now)
            spiller.flush()
        if version is not None:
            interval = env_float("TORCHFT_STORE_SPILL_S", 0.0, minimum=0.0)
            if time.monotonic() - self._last_spill >= interval:
                try:
                    state = self._manager_state_dict()
                except Exception:  # noqa: BLE001 - spill never fails training
                    self._logger.exception("store spill snapshot failed")
                    state = None
                if state is not None and spiller.submit(version, state):
                    self._last_spill = time.monotonic()
        if wait:
            spiller.flush()

    def _manager_state_dict(self) -> "Dict[str, Any]":
        with self._state_dict_lock.r_lock():
            assert self._user_state_dicts, "user state_dict is not initialized"
            return {
                "user": {k: fn() for k, fn in self._user_state_dicts.items()},
                "torchft": self.state_dict(),
            }

    def state_dict(self) -> "Dict[str, int]":
        return {"step": self._step, "batches_committed": self._batches_committed}

    def load_state_dict(self, state_dict: "Dict[str, int]") -> None:
        self._step = state_dict["step"]
        self._batches_committed = state_dict["batches_committed"]

    # Hooks for callers that mutate user state outside the step protocol
    # (reference local_sgd.py:112-124 toggles these around optimizer
    # mutation): disallow takes the state-dict write lock so a concurrent
    # checkpoint send cannot snapshot mid-mutation.
    def disallow_state_dict_read(self) -> None:
        self._state_dict_lock.acquire_write()

    def allow_state_dict_read(self) -> None:
        self._state_dict_lock.release_write()

    # ------------------------------------------------------------------
    # quorum
    # ------------------------------------------------------------------

    def start_quorum(
        self,
        allow_heal: bool = True,
        shrink_only: bool = False,
        timeout: "float | timedelta | None" = None,
    ) -> None:
        """Begin a new step: compute quorum (possibly async) and ready the PG.

        Reference: torchft/manager.py:534-589.
        """
        if self._quorum_future is not None:
            self._quorum_future.result()

        # Serving tier: the previous round's committed weights are fully
        # materialized by now (the user's optimizer update ran between
        # should_commit and this call) — publish them as that step's
        # weight version before the new round begins.
        self._flush_pending_publish()
        self._flush_pending_spill()

        self._errored = None
        self._healing = False
        # Straggler telemetry: piggyback (step, in-flight op) on the native
        # manager's lighthouse heartbeats, so the lighthouse can compute
        # per-replica step lag and straggler scores while this replica is
        # inside the quorum protocol.
        self._report_progress("quorum")

        tracer = tracing.get_tracer()
        ctx: "Optional[tracing.TraceContext]" = None
        if tracer is not None and tracer.sample_step(self._step):
            # Deterministic per-step trace id: every replica at this step
            # derives the same one (and the same sampling decision), so a
            # sampled step's trace is complete across the whole fleet.
            ctx = tracing.TraceContext(
                tracing.step_trace_id(self._step), tracing.new_span_id()
            )
        self._round_ctx = ctx
        self._round_start_ns = time.time_ns() if ctx is not None else 0
        self._round_step = self._step
        # Bind on the caller thread too: the allreduce submit and the
        # should_commit RPC run here and must inject the same context.
        tracing.set_current(ctx)

        self._quorum_future = self._executor.submit(
            self._async_quorum,
            allow_heal=allow_heal,
            shrink_only=shrink_only,
            quorum_timeout=_to_sec(timeout, self._quorum_timeout),
        )
        if not self._use_async_quorum:
            self.wait_quorum()
            if self._healing:
                # eagerly apply the healed state so the forward pass runs on
                # recovered weights
                self._apply_pending_state_dict()
                self._healing = False

    def wait_quorum(self) -> None:
        assert (
            self._quorum_future is not None
        ), "must call start_quorum before wait_quorum"
        t0 = time.perf_counter()
        self._quorum_future.result()
        self._record_phase("quorum_wait", time.perf_counter() - t0)

    def _async_quorum(
        self, allow_heal: bool, shrink_only: bool, quorum_timeout: float
    ) -> None:
        # The executor thread is where the quorum RPC, pg configure, and
        # the heal transfers run: bind the round's trace context so every
        # outbound RPC (manager quorum, store barriers) and the heal
        # transports carry it.
        tracing.set_current(self._round_ctx)
        try:
            t_rpc = time.perf_counter()
            with jax.profiler.TraceAnnotation("torchft::manager::_client::_quorum"):

                def _quorum_rpc(budget: "Optional[float]") -> Any:
                    # chaos site INSIDE the retry policy: an injected drop
                    # (ConnectionError) exercises the quorum_retries backoff
                    # path; an injected raise escapes to report_error
                    faults.check(
                        "manager.quorum", replica=self._replica_id, step=self._step
                    )
                    return self._client._quorum(
                        group_rank=self._group_rank,
                        step=self._step,
                        checkpoint_metadata=self._checkpoint_transport.metadata(),
                        shrink_only=shrink_only,
                        timeout=budget if budget is not None else quorum_timeout,
                        init_sync=self._init_sync,
                        commit_failures=self._commit_failures,
                        layout_epoch=(
                            0 if self._layout is None else self._layout.wire_epoch()
                        ),
                        layout_data=(
                            "" if self._layout is None else self._layout.wire_data()
                        ),
                    )

                quorum = self._quorum_policy.run(
                    _quorum_rpc, timeout=quorum_timeout, op="manager.quorum"
                )
            self._record_phase("quorum_rpc", time.perf_counter() - t_rpc)
        except Exception as e:  # noqa: BLE001 - captured into the protocol
            # Graceful capture (the reference leaves this as a TODO,
            # manager.py:566-567): the replica sits out this step and votes
            # False rather than crashing the training loop.
            self._logger.exception(f"got exception in quorum: {e}")
            self._participating_replica_rank = None
            self._participating_replica_world_size = 0
            self.report_error(e if isinstance(e, Exception) else RuntimeError(str(e)))
            return

        # Async quorum participates with the max-step cohort (healing
        # replicas contribute zeros this step); sync quorum heals eagerly so
        # everyone participates (reference manager.py:641-657).
        self._participating_replica_rank, self._participating_replica_world_size = (
            (quorum.max_replica_rank, quorum.max_world_size)
            if self._use_async_quorum or not allow_heal
            else (quorum.replica_rank, quorum.replica_world_size)
        )

        if self._replica_world_size_mode == WorldSizeMode.FIXED_WITH_SPARES:
            self._participating_replica_world_size = min(
                self._participating_replica_world_size, self._min_replica_size
            )
            if (
                self._participating_replica_rank is not None
                and self._participating_replica_rank >= self._min_replica_size
            ):
                self._participating_replica_rank = None

        # Online parallelism switching, two-phase (parallel/layout.py):
        # FIRST resolve the previous round's staged switch (commit when
        # the whole quorum reports the staged epoch, else roll back and
        # burn it), THEN — if the live world no longer fits the active
        # layout — plan the next layout and run the reshard transfers on
        # this thread, where heal runs.  Neither phase may fail the
        # training step: a broken switch degrades to the old layout.
        # Runs BEFORE pg configure and the allow_heal gate: this round's
        # quorum entry already advertised our epoch report, so skipping
        # the commit round here (configure error, heal-less round) would
        # let the rest of the fleet activate without us — the exact
        # mixed-generation split the all-commit-same-epoch invariant
        # forbids.  The transfers ride the checkpoint transport, not the
        # PG, so ordering before configure is safe.
        if self._layout is not None:
            t_lc = time.perf_counter()
            outcome = ""
            try:
                faults.check(
                    "manager.layout_commit",
                    replica=self._replica_id,
                    step=quorum.max_step,
                )
                outcome = self._layout.maybe_commit(quorum)
            except Exception as e:  # noqa: BLE001 - degrade, never wedge
                self._logger.exception(f"layout commit failed: {e}")
                self._layout.abort_staged(f"layout commit failed: {e}")
                outcome = "rolled_back"
            if outcome:
                self._record_phase("layout_commit", time.perf_counter() - t_lc)
                metrics.LAYOUT_SWITCHES.labels(
                    replica_id=self._metric_replica_id, result=outcome
                ).inc()
                active = self._layout.active_layout()
                metrics.LAYOUT_EPOCH.labels(
                    replica_id=self._metric_replica_id
                ).set(active.epoch if active is not None else 0)
                log_event(
                    "layout",
                    f"layout switch {outcome}",
                    job_id=env_str("JOB_ID", "unknown"),
                    replica_id=self._replica_id,
                    rank=self._group_rank,
                    quorum_id=quorum.quorum_id,
                    step=quorum.max_step,
                    outcome=outcome,
                    layout=str(active.key() if active is not None else None),
                )
            t_rs = time.perf_counter()
            try:
                staged = self._layout.maybe_stage(self, quorum)
            except Exception as e:  # noqa: BLE001 - degrade, never wedge
                self._logger.exception(f"layout staging failed: {e}")
                self._layout.abort_staged(f"layout staging failed: {e}")
                staged = True
            if staged:
                self._record_phase("reshard", time.perf_counter() - t_rs)

        if quorum.quorum_id != self._quorum_id:
            metrics.QUORUM_CHANGES.labels(replica_id=self._metric_replica_id).inc()
            log_event(
                "quorum",
                "quorum changed",
                job_id=env_str("JOB_ID", "unknown"),
                replica_id=self._replica_id,
                rank=self._group_rank,
                quorum_id=quorum.quorum_id,
                step=quorum.max_step,
            )
            store_prefixed_addr = (
                f"{quorum.store_address}/torchft/{quorum.quorum_id}/{self._group_rank}"
            )
            self._logger.info(
                f"reconfiguring for quorum_id={quorum.quorum_id} store={store_prefixed_addr}"
            )
            try:
                t_cfg = time.perf_counter()
                with jax.profiler.TraceAnnotation("torchft::manager::_pg::configure"):
                    self._pg.configure(
                        store_prefixed_addr,
                        self._replica_id,
                        quorum.replica_rank,
                        quorum.replica_world_size,
                    )
                self._record_phase("pg_configure", time.perf_counter() - t_cfg)
                self._quorum_id = quorum.quorum_id
                log_event(
                    "reconfigure",
                    "pg reconfigured",
                    job_id=env_str("JOB_ID", "unknown"),
                    replica_id=self._replica_id,
                    rank=self._group_rank,
                    quorum_id=quorum.quorum_id,
                    step=quorum.max_step,
                    replica_world_size=quorum.replica_world_size,
                )
            except Exception as e:  # noqa: BLE001 - captured into the protocol
                self._logger.exception(f"got exception in pg configure: {e}")
                self.report_error(e)
                return

        if not allow_heal:
            return

        # Striped heal (ISSUE 15): stream-stage fragments + stripe the
        # receive across every max-step peer when the transport carries
        # the fragment protocol (the flag must be literally True so
        # duck-typed test doubles keep the legacy path).
        streamed_heal = (
            env_bool("TORCHFT_HEAL_STREAM", True)
            and getattr(
                self._checkpoint_transport, "supports_striped_heal", False
            )
            is True
        )

        # Whole-fleet cold start (ISSUE 17): nobody in the quorum holds
        # live state (max_step == 0) but disks might — restore the newest
        # complete, consistent spilled cut through the striped heal path
        # with files as stripe sources.  Every replica computes the same
        # deterministic cut from the same fleet catalogs, so a
        # successful restore replaces this round's live init-sync
        # branches entirely; a failed one degrades to fresh init (and a
        # replica whose restore failed alone re-heals live next round
        # once its peers commit) — never a wedge.
        if (
            self._frag_store is not None
            and streamed_heal
            and self._step == 0
            and quorum.max_step == 0
        ):
            if self._maybe_cold_restore(quorum):
                return

        # Proactive stripe-source staging: a max-step participant can
        # tell healers exist this round (the max-step cohort is smaller
        # than the quorum) and stages its own fragment stream so healers
        # aggregate up-to-date uplinks beyond the assigned primary's.
        # Bounded by the SAME pure quorum math the healer's source
        # resolution applies: every healer stripes over the first
        # TORCHFT_HEAL_SOURCES max-step roster entries (minus its
        # primary), so only those participants stage — a 64-replica
        # fleet must not burn 60 full encodes for slots nobody fetches.
        # Degrade-only: a failed proactive stage merely shrinks the
        # healer's stripe back toward the primary.
        if (
            streamed_heal
            and not quorum.recover_dst_replica_ranks
            and not quorum.heal
            and quorum.max_replica_rank is not None
            and quorum.max_world_size < quorum.replica_world_size
            and self._in_stripe_source_set(quorum)
        ):
            t_send = time.perf_counter()
            try:
                self._checkpoint_transport.send_checkpoint_streamed(
                    dst_ranks=[],
                    step=quorum.max_step,
                    state_dict=self._manager_state_dict(),
                    timeout=self._timeout,
                )
                self._record_phase("heal_send", time.perf_counter() - t_send)
                log_event(
                    "heal",
                    "staged stripe-source checkpoint for healing peers",
                    job_id=env_str("JOB_ID", "unknown"),
                    replica_id=self._replica_id,
                    rank=self._group_rank,
                    quorum_id=quorum.quorum_id,
                    step=quorum.max_step,
                    direction="send",
                    proactive=True,
                )
            except Exception as e:  # noqa: BLE001 - degrade, never wedge
                self._logger.warning(
                    f"proactive stripe-source staging failed "
                    f"(healers fall back to fewer sources): {e}"
                )

        try:
            if quorum.recover_dst_replica_ranks:
                faults.check(
                    "manager.heal", replica=self._replica_id, step=quorum.max_step
                )
                self._logger.info(
                    f"peers need recovery from us {quorum.recover_dst_replica_ranks}"
                )
                t_send = time.perf_counter()
                with jax.profiler.TraceAnnotation(
                    "torchft::manager::_checkpoint_transport::send_checkpoint"
                ):
                    if streamed_heal:
                        self._checkpoint_transport.send_checkpoint_streamed(
                            dst_ranks=quorum.recover_dst_replica_ranks,
                            step=quorum.max_step,
                            state_dict=self._manager_state_dict(),
                            timeout=self._timeout,
                        )
                    else:
                        self._checkpoint_transport.send_checkpoint(
                            dst_ranks=quorum.recover_dst_replica_ranks,
                            step=quorum.max_step,
                            state_dict=self._manager_state_dict(),
                            timeout=self._timeout,
                        )
                self._record_phase("heal_send", time.perf_counter() - t_send)
                metrics.HEALS.labels(
                    replica_id=self._metric_replica_id, direction="send"
                ).inc()
                log_event(
                    "heal",
                    "sent checkpoint to healing peers",
                    job_id=env_str("JOB_ID", "unknown"),
                    replica_id=self._replica_id,
                    rank=self._group_rank,
                    quorum_id=quorum.quorum_id,
                    step=quorum.max_step,
                    direction="send",
                    dst_ranks=quorum.recover_dst_replica_ranks,
                )

            if quorum.heal:
                faults.check(
                    "manager.heal", replica=self._replica_id, step=quorum.max_step
                )
                self._healing = True
                t_recv = time.perf_counter()
                self._logger.info(
                    f"healing required, fetching checkpoint metadata from "
                    f"{quorum.recover_src_manager_address} max_step={quorum.max_step}"
                )
                primary_client = ManagerClient(
                    quorum.recover_src_manager_address,
                    connect_timeout=self._connect_timeout,
                )
                checkpoint_metadata = primary_client._checkpoint_metadata(
                    self._group_rank, timeout=self._timeout
                )
                primary_client.close()
                assert (
                    quorum.recover_src_replica_rank is not None
                ), "must have a recover rank when healing"
                with jax.profiler.TraceAnnotation(
                    "torchft::manager::_checkpoint_transport::recv_checkpoint"
                ):
                    heal_info: "Dict[str, Any]" = {}
                    if streamed_heal:
                        sources = [checkpoint_metadata]
                        # Stripe only when genuinely BEHIND the cohort:
                        # an init-sync force-recover round has every
                        # replica at max_step with unsynchronized state —
                        # only the primary's copy is truth there.
                        if quorum.max_replica_rank is None:
                            sources += self._resolve_stripe_sources(
                                quorum, checkpoint_metadata
                            )
                        (
                            self._pending_state_dict,
                            heal_info,
                        ) = self._checkpoint_transport.recv_checkpoint_striped(
                            sources,
                            step=quorum.max_step,
                            timeout=self._timeout,
                            local_state_fn=self._manager_state_dict,
                        )
                    else:
                        self._pending_state_dict = (
                            self._checkpoint_transport.recv_checkpoint(
                                src_rank=quorum.recover_src_replica_rank,
                                metadata=checkpoint_metadata,
                                step=quorum.max_step,
                                timeout=self._timeout,
                            )
                        )
                self.load_state_dict(self._pending_state_dict["torchft"])
                # loading the torchft dict restores the step; set it anyway
                # to make reasoning (and tests) simpler
                self._step = quorum.max_step
                # Phase split (ISSUE 15): the striped path records its
                # four sub-phases plus the residue (metadata RPC, source
                # resolution, reassembly) under the legacy heal_recv
                # name, so ledger sums stay exact and never double-count
                # a split phase against its umbrella.
                heal_phases = heal_info.get("phases") or {}
                if "heal_manifest" in heal_phases:
                    self._record_phase(
                        "heal_manifest", heal_phases["heal_manifest"]
                    )
                if "heal_diff" in heal_phases:
                    self._record_phase("heal_diff", heal_phases["heal_diff"])
                if "heal_wire" in heal_phases:
                    self._record_phase("heal_wire", heal_phases["heal_wire"])
                if "heal_decode" in heal_phases:
                    self._record_phase(
                        "heal_decode", heal_phases["heal_decode"]
                    )
                self._record_phase(
                    "heal_recv",
                    max(
                        time.perf_counter()
                        - t_recv
                        - sum(heal_phases.values()),
                        0.0,
                    ),
                )
                metrics.HEALS.labels(
                    replica_id=self._metric_replica_id, direction="recv"
                ).inc()
                log_event(
                    "heal",
                    "received checkpoint from peer",
                    job_id=env_str("JOB_ID", "unknown"),
                    replica_id=self._replica_id,
                    rank=self._group_rank,
                    quorum_id=quorum.quorum_id,
                    step=quorum.max_step,
                    direction="recv",
                    src_rank=quorum.recover_src_replica_rank,
                    mode=heal_info.get("mode", "legacy"),
                    stripe_sources=heal_info.get("sources", 1),
                    changed_fragments=heal_info.get("changed"),
                )
        except Exception as e:  # noqa: BLE001 - captured into the protocol
            self._logger.exception(f"got exception in recovery: {e}")
            self.report_error(e)

    def _in_stripe_source_set(self, quorum: Any) -> bool:
        """True when this replica is among the first
        ``TORCHFT_HEAL_SOURCES`` max-step participants in roster order —
        the superset every healer's ``_resolve_stripe_sources`` pick
        (first ``max_sources - 1`` entries after excluding its primary)
        can reach, computed from the same roster on every peer — via
        the plan layer's one copy of the first-K math (ISSUE 19:
        ``tft-verify --scenario plan`` checks the structure this
        produces)."""
        from torchft_tpu.analysis.plan_ir import stripe_source_cohort

        max_sources = env_int("TORCHFT_HEAL_SOURCES", 4, minimum=1)
        return self._replica_id in stripe_source_cohort(
            quorum.participants, quorum.max_step, max_sources
        )

    def _resolve_stripe_sources(
        self, quorum: Any, primary_metadata: str
    ) -> "List[str]":
        """Transport addresses of the max-step quorum peers beyond the
        assigned primary — the striped heal's extra sources.

        The participants roster (replica-rank order) carries each peer's
        manager address and step; every peer at ``max_step`` holds
        bitwise-replicated state, so its fragments must hash to the
        primary's manifest digests.  Each candidate's checkpoint
        transport address resolves through its manager's
        ``checkpoint_metadata`` RPC (the same discovery heal and reshard
        use), in parallel and best-effort: an unreachable peer just
        shrinks the stripe.  Bounded by ``TORCHFT_HEAL_SOURCES``
        (total sources including the primary).  The candidate pick is
        the plan layer's :func:`~torchft_tpu.analysis.plan_ir.
        stripe_roster` — the same math the tft-plan verifier and the
        source-side cohort test consume."""
        from torchft_tpu.analysis.plan_ir import stripe_roster

        max_sources = env_int("TORCHFT_HEAL_SOURCES", 4, minimum=1)
        candidates = stripe_roster(
            quorum.participants,
            quorum.max_step,
            quorum.recover_src_replica_rank,
            max_sources,
        )
        if not candidates:
            return []

        def _resolve(addr: str) -> "Optional[str]":
            client = ManagerClient(
                addr, connect_timeout=self._connect_timeout
            )
            try:
                return client._checkpoint_metadata(
                    self._group_rank, timeout=self._connect_timeout
                )
            except Exception as e:  # noqa: BLE001 - best-effort stripe
                self._logger.info(
                    f"stripe source {addr} unresolvable ({e}); striping "
                    f"without it"
                )
                return None
            finally:
                client.close()

        with ThreadPoolExecutor(
            max_workers=min(len(candidates), 4),
            thread_name_prefix="tft_stripe_resolve",
        ) as pool:
            resolved = list(pool.map(_resolve, candidates))
        return [
            m for m in resolved if m and m != primary_metadata
        ]

    def _resolve_store_bases(self, quorum: Any, own: str) -> "List[str]":
        """Checkpoint-transport addresses of every reachable quorum
        participant plus our own — cold restore canvasses ALL disks
        (everyone is at step 0, so there is no max-step cohort to
        prefer).  Sorted + deduped so every replica that resolves the
        same roster derives the same base list, which keeps cut
        selection deterministic fleet-wide."""
        addrs: "List[str]" = []
        for p in quorum.participants:
            if isinstance(p, dict) and p.get("address"):
                addrs.append(p["address"])

        def _resolve(addr: str) -> "Optional[str]":
            client = ManagerClient(
                addr, connect_timeout=self._connect_timeout
            )
            try:
                return client._checkpoint_metadata(
                    self._group_rank, timeout=self._connect_timeout
                )
            except Exception as e:  # noqa: BLE001 - best-effort discovery
                self._logger.info(
                    f"store base {addr} unresolvable ({e}); restoring "
                    f"without its disk"
                )
                return None
            finally:
                client.close()

        resolved: "List[Optional[str]]" = []
        if addrs:
            with ThreadPoolExecutor(
                max_workers=min(len(addrs), 4),
                thread_name_prefix="tft_store_resolve",
            ) as pool:
                resolved = list(pool.map(_resolve, addrs))
        return sorted({m for m in resolved if m} | {own})

    def _maybe_cold_restore(self, quorum: Any) -> bool:
        """Whole-fleet cold-start restore (ISSUE 17, docs/architecture.md
        "Durable fragment store").

        Discovers spilled catalogs across every reachable disk (own +
        peers' via ``/store/versions``), picks the newest complete,
        consistent cut (:func:`~torchft_tpu.checkpointing.store.
        select_cut` — deterministic, never mixes fragment versions), and
        reassembles it via ``recv_checkpoint_striped`` with disks as
        stripe sources: per-fragment failover across disks, delta reuse
        of surviving local state.  Returns True when restored (state is
        pending; the standard healing application path applies it).
        Any failure returns False — fresh init, never a wedge."""
        t0 = time.perf_counter()
        try:
            faults.check("store.restore", replica=self._replica_id, step=0)
            own = self._checkpoint_transport.metadata()
            bases = self._resolve_store_bases(quorum, own)
            catalogs: "Dict[str, Any]" = {}
            for base in bases:
                cat = fragment_store.fetch_catalog(
                    base, timeout=self._connect_timeout
                )
                if cat:
                    catalogs[base] = cat
            plan = fragment_store.select_cut(catalogs)
            if plan is None:
                return False
            version, sources = plan
            self._logger.info(
                f"cold restore: selected spilled v{version} across "
                f"{len(sources)} disk(s)"
            )
            self._healing = True
            (
                self._pending_state_dict,
                info,
            ) = self._checkpoint_transport.recv_checkpoint_striped(
                sources,
                step=version,
                timeout=self._timeout,
                local_state_fn=self._manager_state_dict,
                plane="restore",
            )
            metrics.STORE_RESTORE_BYTES.labels(
                mode=info.get("mode", "full")
            ).inc(int(info.get("wire_bytes") or 0))
            self.load_state_dict(
                cast(Dict[str, int], self._pending_state_dict["torchft"])
            )
            self._record_phase("heal_recv", time.perf_counter() - t0)
            metrics.HEALS.labels(
                replica_id=self._metric_replica_id, direction="recv"
            ).inc()
            log_event(
                "heal",
                "cold-restored from durable store",
                job_id=env_str("JOB_ID", "unknown"),
                replica_id=self._replica_id,
                rank=self._group_rank,
                quorum_id=quorum.quorum_id,
                step=version,
                direction="recv",
                mode=info.get("mode", "full"),
                stripe_sources=info.get("sources", 1),
                changed_fragments=info.get("changed"),
            )
            self._logger.info(
                f"cold-restored to step {version} from {len(sources)} "
                f"store source(s) mode={info.get('mode')}"
            )
            return True
        except Exception as e:  # noqa: BLE001 - degrade to fresh init
            self._logger.warning(f"cold restore failed (starting fresh): {e}")
            self._healing = False
            self._pending_state_dict = None
            return False

    def _apply_pending_state_dict(self) -> None:
        assert self._healing, "must be in healing state"
        assert self._quorum_future is not None, "must call start_quorum first"
        self._quorum_future.result()

        pending = self._pending_state_dict
        if pending is None:
            assert self.errored() is not None, (
                "checkpoint was not staged and no error occurred"
            )
            return
        self._logger.info("applying pending state dict")
        assert self._load_state_dict_fns, "user load_state_dict is not initialized"
        user_state = cast(Dict[str, Any], pending["user"])
        for key, load_fn in self._load_state_dict_fns.items():
            load_fn(user_state[key])
        self._pending_state_dict = None

    # ------------------------------------------------------------------
    # collectives
    # ------------------------------------------------------------------

    def allreduce(
        self,
        value: Any,
        should_quantize: bool = False,
        reduce_op: str = REDUCE_AVG,
        device_quantize: "Optional[bool]" = None,
    ) -> Work:
        """Fault-tolerant allreduce of an array or pytree of arrays.

        Averages over the live participant count; non-participants (healing
        replicas) contribute zeros.  On error the Work completes *cleanly*
        with the input (zeroed) value and the error is tracked for
        ``should_commit`` (reference manager.py:385-467).

        ``device_quantize`` (quantized path only): quantize on-chip with
        the Pallas kernel before the device→host copy; ``None`` = auto
        (on when every leaf is a jax array on a TPU backend) — forwarded
        to :func:`~torchft_tpu.ops.collectives.allreduce_quantized`.
        """
        if self.errored():
            return completed_work(value)

        self.wait_quorum()
        num_participants = self.num_participants()

        t_host = time.perf_counter()
        leaves, treedef = jax.tree_util.tree_flatten(value)
        if should_quantize and self.is_participating():
            # Leave device arrays on device: the quantized collective runs
            # the Pallas quantize kernel on-chip (when on TPU) so only the
            # int8 payload + row scales cross the device→host boundary
            # (reference wires its Triton kernels the same way,
            # torchft/collectives.py:297-415).  The device→host hop is then
            # inside the collective and counted in the ``ring`` phase.
            # Non-array leaves (Python scalars) still need numpy wrapping
            # for the dtype checks below.
            send_leaves: "List[Any]" = [
                x if isinstance(x, (np.ndarray, jax.Array)) else np.asarray(x)
                for x in leaves
            ]
        elif not self.is_participating():
            send_leaves = [np.zeros_like(np.asarray(x)) for x in leaves]
        else:
            # Leaves pass through unmaterialized: the PG converts on its
            # worker thread, so the device→host sync overlaps whatever the
            # caller does next instead of blocking this thread (counted in
            # the ``ring`` phase; the DiLoCo fragment-overlap pattern
            # depends on this submit being non-blocking).  Non-array leaves
            # (Python scalars) still need numpy wrapping for the dtype
            # checks below.
            send_leaves = [
                x if isinstance(x, (np.ndarray, jax.Array)) else np.asarray(x)
                for x in leaves
            ]
        self._record_phase("host_sync", time.perf_counter() - t_host)

        if reduce_op == REDUCE_AVG:
            if not all(_is_floating(x.dtype) for x in send_leaves):
                raise ValueError(
                    "average reduce op is only supported for floating point arrays"
                )
            pg_reduce_op = REDUCE_SUM
        else:
            pg_reduce_op = reduce_op

        self._m_allreduces.inc()
        try:
            faults.check(
                "pg.allreduce", replica=self._replica_id, step=self._step
            )
            t_submit = time.perf_counter()
            if should_quantize:
                from torchft_tpu.ops.collectives import allreduce_quantized

                work = allreduce_quantized(
                    send_leaves, pg_reduce_op, self._pg,
                    device_quantize=device_quantize,
                )
            else:
                work = self._pg.allreduce(send_leaves, pg_reduce_op)

            def _postprocess(reduced: "List[np.ndarray]") -> Any:
                if reduce_op == REDUCE_AVG:
                    reduced = [x / num_participants for x in reduced]
                return jax.tree_util.tree_unflatten(treedef, reduced)

            chained = work.then(_postprocess)

            # Track errors out-of-band: the returned Work must complete
            # cleanly so the training loop proceeds to should_commit.
            out: concurrent.futures.Future = concurrent.futures.Future()

            def _done(f: "concurrent.futures.Future[Any]") -> None:
                self._record_phase("ring", time.perf_counter() - t_submit)
                # quantized-pipeline accounting for the step digest: the
                # stats dict is complete once the pipeline finished, i.e.
                # before this callback fires
                qs = getattr(work, "quant_stats", None)
                if isinstance(qs, dict):
                    with self._phase_lock:
                        self._summary_codec_s += float(qs.get("codec_s") or 0.0)
                        self._summary_wire_s += float(qs.get("wire_s") or 0.0)
                exc = f.exception()
                if exc is not None:
                    self.report_error(
                        exc if isinstance(exc, Exception) else RuntimeError(str(exc))
                    )
                    out.set_result(
                        jax.tree_util.tree_unflatten(treedef, send_leaves)
                    )
                else:
                    out.set_result(f.result())

            chained.get_future().add_done_callback(_done)
            managed = Work(out)
            # surface the collective's wire/codec accounting on the
            # returned handle: the quantized pipeline's (wire_bytes set
            # synchronously; codec_s_box/quant_stats written at pipeline
            # completion — read after wait) and the TCP ring's measured
            # wire_bytes on the unquantized path
            for attr in (
                "wire_bytes",
                "unquantized_wire_bytes",
                "device_quantized",
                "wire_dtype",
                "codec_s_box",
                "quant_stats",
            ):
                if hasattr(work, attr):
                    setattr(managed, attr, getattr(work, attr))
            return managed
        except Exception as e:  # noqa: BLE001 - captured into the protocol
            self._logger.exception(f"got exception in allreduce -- skipping: {e}")
            self.report_error(e)
            return completed_work(value)

    # ------------------------------------------------------------------
    # errors & commit
    # ------------------------------------------------------------------

    def report_error(self, e: Exception) -> None:
        """Latch an async error; the current step will not be committed
        (reference manager.py:469-482)."""
        self._errored = e
        metrics.ERRORS.labels(replica_id=self._metric_replica_id).inc()
        log_event(
            "error",
            str(e),
            job_id=env_str("JOB_ID", "unknown"),
            replica_id=self._replica_id,
            rank=self._group_rank,
            quorum_id=self._quorum_id,
            step=self._step,
        )
        # Flight recorder: the latched error plus a crash-durable dump of
        # the ring around it — an unhandled manager error is a dump
        # trigger (utils/flightrecorder.py); no-op without
        # TORCHFT_FLIGHT_FILE.
        flightrec.record(
            "manager.error",
            status="error",
            error=str(e),
            replica_id=self._replica_id,
            rank=self._group_rank,
            quorum_id=self._quorum_id,
            step=self._step,
        )
        flightrec.dump(f"manager error: {e!r}", trigger="manager_error")

    def errored(self) -> "Optional[Exception]":
        return self._errored

    def should_commit(self, timeout: "float | timedelta | None" = None) -> bool:
        """Vote on committing this step; all group workers return the same
        value (reference manager.py:790-878)."""
        # recovery (send/recv checkpoint) must be complete before committing
        if self._quorum_future is not None:
            t_q = time.perf_counter()
            try:
                self._quorum_future.result()
            except Exception as e:  # noqa: BLE001
                self.report_error(
                    e if isinstance(e, Exception) else RuntimeError(str(e))
                )
            finally:
                self._record_phase("quorum_wait", time.perf_counter() - t_q)

        if (err := self._pg.errored()) is not None:
            self.report_error(err)

        if self._healing:
            self._apply_pending_state_dict()

        enough_replicas = self.num_participants() >= self._min_replica_size
        local_should_commit = enough_replicas and self._errored is None
        t_commit = time.perf_counter()
        try:
            should_commit = self._client.should_commit(
                self._group_rank,
                self._step,
                local_should_commit,
                timeout=_to_sec(timeout, self._timeout),
            )
        except ConnectionError as e:
            # The vote RPC is non-idempotent (no blind resend — a double-
            # delivered vote could release the barrier with a stale tally),
            # so a broken connection surfaces here.  Abstain: latch the
            # error and treat the step as uncommitted — if the group did
            # commit without us, our step falls behind and the next quorum
            # heals us, the same path as any other failed step.
            self._logger.exception(f"should_commit rpc failed, abstaining: {e}")
            self.report_error(e)
            should_commit = False
        self._record_phase("commit", time.perf_counter() - t_commit)
        self._m_commits["success" if should_commit else "failure"].inc()
        self._m_participants.set(self.num_participants())
        self._logger.info(
            f"should_commit={should_commit} enough_replicas={enough_replicas}, "
            f"errored={self._errored}"
        )
        log_event(
            "commit",
            "commit vote",
            job_id=env_str("JOB_ID", "unknown"),
            replica_id=self._replica_id,
            rank=self._group_rank,
            quorum_id=self._quorum_id,
            step=self._step,
            commit_result=should_commit,
        )

        # Layout two-phase hook: the barrier outcome decides whether a
        # staged reshard survives into the next quorum's commit round —
        # every local rank observes the same vote, so the whole group
        # either carries the staged epoch or burns it together.
        if self._layout is not None:
            self._layout.on_step_commit(should_commit)

        self._checkpoint_transport.disallow_checkpoint()

        # Raised AFTER the round's root span closes below: the terminally
        # failed round is exactly the one a post-mortem trace needs, and
        # the thread-local context must not leak past the raise.
        retries_exhausted: "Optional[RuntimeError]" = None
        if should_commit:
            self._step += 1
            self._batches_committed += self.num_participants()
            self._commit_failures = 0
            # Serving tier: committed weights become weight version
            # `step` — published at the NEXT round's start / shutdown,
            # after the user's post-commit optimizer update lands
            # (attach_weight_publisher; no-op when unattached).
            self._publish_pending = self._step
            # Durable store: the committed step spills to disk at the
            # NEXT round's start (same timing as publish — the user's
            # post-commit optimizer update must land first so the
            # spilled bytes equal what a live replica at this step
            # holds), off the hot path on the single spill worker.
            self._spill_pending = self._step
        else:
            self._commit_failures += 1
            if (
                self._max_retries is not None
                and self._commit_failures > self._max_retries
            ):
                msg = (
                    f"should_commit failed {self._commit_failures} times "
                    f"consecutively, exceeding max_retries={self._max_retries}"
                )
                self._logger.exception(msg)
                retries_exhausted = RuntimeError(msg)
        self._m_step.set(self._step)
        # step (possibly) advanced: refresh the heartbeat-piggybacked
        # progress so lighthouse step-lag tracking follows commits, not
        # just quorum entries — and ship the step digest (phase deltas +
        # codec/wire busy) for the cluster timeline
        self._report_progress("")
        self._report_step_summary()

        # Close the quorum round's root span (children were emitted per
        # phase from _record_phase, native rpc.* server spans joined via
        # the shared trace id); the ``step`` attribute is the step the
        # round RAN, matching the trace-id derivation, so the diagnose
        # ledger joins spans, flight dumps, and the lighthouse timeline
        # on one key.
        tracer = tracing.get_tracer()
        ctx, self._round_ctx = self._round_ctx, None
        if tracer is not None and ctx is not None:
            tracer.export_span(
                name="quorum_round",
                trace_id=ctx.trace_id,
                span_id=ctx.span_id,
                start_ns=self._round_start_ns,
                end_ns=time.time_ns(),
                attributes={
                    "replica_id": self._replica_id,
                    "rank": self._group_rank,
                    "quorum_id": self._quorum_id,
                    "step": self._round_step,
                    "commit_result": should_commit,
                },
                ok=self._errored is None and retries_exhausted is None,
            )
        tracing.set_current(None)
        if retries_exhausted is not None:
            raise retries_exhausted
        return should_commit

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------

    def _record_phase(self, name: str, dt: float) -> None:
        """Record one phase timing into every observability surface: the
        destructive accumulator (bench), the non-destructive
        torchft_quorum_duration_seconds histogram (scrapers), and — when a
        tracer is installed — a child span under the round's root span.
        Called from the caller thread AND the async quorum thread."""
        with self._phase_lock:
            self._phase_acc[name] = self._phase_acc.get(name, 0.0) + dt
        # flight record per phase: the quorum protocol's footprint in the
        # postmortem timeline (~6 records/step; record() is ~1 us)
        end_ns = time.time_ns()
        flightrec.record(
            name,
            kind="phase",
            start_ns=end_ns - int(dt * 1e9),
            end_ns=end_ns,
            replica_id=self._replica_id,
            quorum_id=self._quorum_id,
            step=self._step,
        )
        child = self._phase_hist.get(name)
        if child is None:
            # benign race: concurrent creators both resolve to the same
            # underlying child (labels() is keyed), last write wins
            child = metrics.QUORUM_DURATION.labels(
                replica_id=self._metric_replica_id, phase=name
            )
            self._phase_hist[name] = child
        child.observe(dt)
        tracer = tracing.get_tracer()
        ctx = self._round_ctx
        if tracer is not None and ctx is not None:
            end_ns = time.time_ns()
            # Phase names come from PROTOCOL_PHASES (pinned by tier-1;
            # span-vocab lint checks the literal call sites).
            tracer.export_span(
                name=name,
                trace_id=ctx.trace_id,
                parent_span_id=ctx.span_id,
                start_ns=end_ns - int(dt * 1e9),
                end_ns=end_ns,
                attributes={
                    "replica_id": self._replica_id,
                    "quorum_id": self._quorum_id,
                    "step": self._step,
                },
            )

    def phase_times(self) -> "Dict[str, float]":
        """Non-destructive snapshot of the cumulative wall-clock seconds
        spent per protocol phase.  Safe for any number of concurrent
        consumers (bench takes deltas between snapshots); scrapers should
        prefer the ``torchft_quorum_duration_seconds`` histogram, which
        this same data also feeds.

        Caller-thread keys: ``quorum_wait`` (blocked waiting for the async
        quorum work — the part NOT hidden behind the forward pass; includes
        the wait in ``should_commit``), ``host_sync`` (caller-thread
        flatten + zero-fill; the device→host materialisation itself runs on
        the PG worker and lands in ``ring``), ``ring`` (collective
        submit→completion: device sync, queueing, the wire, and the
        host-side AVG division chained after the raw collective),
        ``commit`` (should_commit RPC barrier).

        Async-quorum-thread keys (run inside the executor, so they OVERLAP
        ``quorum_wait`` rather than adding to it — they break down what the
        caller was waiting FOR): ``quorum_rpc`` (the lighthouse-mediated
        quorum round trip), ``pg_configure`` (collective reconfigure on
        quorum change), ``heal_send`` / ``heal_recv`` (live checkpoint
        transfer to/from a recovering peer, incl. the metadata fetch),
        ``reshard`` (online-parallelism-switch staging: plan + slice-diff
        transfers into the staged buffer) and ``layout_commit`` (the
        fleet-wide activate/rollback of a staged layout at the commit
        round) — both only with a LayoutController attached.

        (``pop_phase_times``, the destructive single-consumer drain this
        replaced, was deprecated in PR 3 and removed in PR 9.)
        """
        with self._phase_lock:
            return dict(self._phase_acc)

    def _report_progress(self, inflight_op: str) -> None:
        """Push (step, in-flight op) to the group's native ManagerServer so
        its lighthouse heartbeats carry per-replica progress (rank 0 only —
        the heartbeat is per replica group).  Best-effort: progress
        telemetry never fails a step."""
        server = self._manager_server
        if server is None:
            return
        try:
            server.report_progress(self._step, inflight_op)
        except Exception:  # noqa: BLE001 - telemetry must not fail the step
            logger.debug("progress report failed", exc_info=True)

    def _report_step_summary(self) -> None:
        """Ship the per-step digest (phase-time deltas since the last
        digest, codec/wire busy seconds from quantized collectives) to the
        native ManagerServer; its next lighthouse heartbeat carries it
        once into the rolling cluster timeline (``/timeline.json``).
        Best-effort like :meth:`_report_progress`."""
        server = self._manager_server
        if server is None:
            return
        with self._phase_lock:
            phases = {
                k: round((v - self._summary_phase_snapshot.get(k, 0.0)) * 1e3, 3)
                for k, v in self._phase_acc.items()
                if v - self._summary_phase_snapshot.get(k, 0.0) > 0.0
            }
            self._summary_phase_snapshot = dict(self._phase_acc)
            codec_s, self._summary_codec_s = self._summary_codec_s, 0.0
            wire_s, self._summary_wire_s = self._summary_wire_s, 0.0
        try:
            server.report_summary(
                {
                    "step": self._step,
                    "phase_ms": phases,
                    "codec_busy_s": round(codec_s, 6),
                    "wire_busy_s": round(wire_s, 6),
                }
            )
        except Exception:  # noqa: BLE001 - telemetry must not fail the step
            logger.debug("step summary report failed", exc_info=True)
        # Piggyback the fleet link-state digest on the same heartbeat
        # channel (consumed-on-send, like the summary).  maybe_digest
        # rate-limits itself (TORCHFT_LINK_REPORT_S), so this is a no-op
        # on most steps; a faulted or failing report never touches the
        # step path.
        try:
            digest = linkstats.LINKS.maybe_digest(socket.gethostname())
            if digest is not None:
                server.report_links(digest)
        except Exception:  # noqa: BLE001 - telemetry must not fail the step
            logger.debug("link digest report failed", exc_info=True)
        # Same piggyback channel for the fragment provenance digest
        # (ISSUE 18): hand the bounded version-vector digest to the
        # native heartbeat loop, which owns consumed-on-send/restore.
        fdigest = None
        try:
            fdigest = provenance.PROV.maybe_digest(socket.gethostname())
            if fdigest is not None:
                server.report_fragments(fdigest)
        except Exception:  # noqa: BLE001 - telemetry must not fail the step
            provenance.PROV.restore_digest(fdigest)
            logger.debug("fragment digest report failed", exc_info=True)

    def current_step(self) -> int:
        return self._step

    def batches_committed(self) -> int:
        return self._batches_committed

    def participating_rank(self) -> "Optional[int]":
        if self._quorum_future is None:
            return None
        self.wait_quorum()
        return self._participating_replica_rank

    def num_participants(self) -> int:
        if self._quorum_future is None:
            return 0
        self.wait_quorum()
        assert self._participating_replica_world_size >= 0, "internal error"
        return self._participating_replica_world_size

    def is_participating(self) -> bool:
        if self._participating_replica_rank is None:
            return False
        if self._healing:
            assert self._use_async_quorum
            return False
        return True

    def replica_id(self) -> str:
        return self._replica_id

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def shutdown(self, wait: bool = True) -> None:
        """Tear down transport, servers, client and executor.

        The four legs are independent (separate sockets/threads), so they
        shut down CONCURRENTLY: during recovery the replacement replica's
        time-to-healthy includes the dying incarnation's teardown, and the
        serial version's ~40 ms (r4 recovery_phases teardown leg) was the
        second-largest addressable recovery phase.  Reference semantics
        preserved (manager.rs shutdown aborts in one Drop).
        """
        # Final committed step's weight version, if a publisher is
        # attached and the loop ended right after its commit; wait=True
        # drains the publish queue before the transports die.
        self._flush_pending_publish(wait=True)
        # Final committed step spills too (wait=True drains the worker),
        # so a clean shutdown leaves the newest step restorable on disk.
        self._flush_pending_spill(wait=True)
        if self._spiller is not None:
            self._spiller.shutdown()
            self._spiller = None
        legs = [
            lambda: self._checkpoint_transport.shutdown(wait=wait),
            self._client.close,
        ]
        if self._manager_server is not None:
            legs.append(self._manager_server.shutdown)
        if self._owned_store is not None:
            legs.append(self._owned_store.shutdown)
        threads = [
            threading.Thread(target=leg, daemon=True) for leg in legs[1:]
        ]
        for t in threads:
            t.start()
        legs[0]()  # checkpoint transport on the caller thread
        if wait:
            for t in threads:
                t.join(timeout=5.0)
        self._executor.shutdown(wait=wait)

    def __enter__(self) -> "Manager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()
