"""Streaming pytree (de)serialization for checkpoint transports.

Analog of the reference's streaming state-dict serialization
(reference: torchft/checkpointing/_serialization.py:1-33 and the
pytree-flatten logic in http_transport.py:220-242).  A state dict (arbitrary
pytree of jax/numpy arrays and plain Python leaves) is split into:

- a picklable **skeleton** (the tree with integer leaf slots),
- per-leaf **metadata** (shape/dtype for arrays, inline pickle otherwise),
- the raw array buffers, streamed in order without copies.

Wire layout: ``[8-byte meta length][pickled meta][buffer 0][buffer 1]...``.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

import jax
import numpy as np

_HEADER = struct.Struct(">Q")


def _flatten(state_dict: Any) -> Tuple[Any, List[Any]]:
    leaves, treedef = jax.tree_util.tree_flatten(state_dict)
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    return skeleton, leaves


def _leaf_meta(leaf: Any) -> Tuple[Dict[str, Any], Optional[np.ndarray]]:
    if isinstance(leaf, (np.ndarray, jax.Array)) or np.isscalar(leaf) is False and hasattr(leaf, "__array__"):
        arr = np.asarray(leaf)
        # Record shape BEFORE ascontiguousarray: it promotes 0-d to (1,),
        # which would corrupt pytree leaf shapes on the receiving side.
        shape = arr.shape
        return (
            {"kind": "array", "shape": shape, "dtype": str(arr.dtype)},
            np.ascontiguousarray(arr),
        )
    return {"kind": "object", "value": leaf}, None


def prepare(
    state_dict: Any, chunk_indices: "Optional[List[int]]" = None
) -> "Tuple[int, Any]":
    """Build a streamable serialization of ``state_dict``.

    Returns ``(total_bytes, writer)`` where ``writer(out)`` streams the
    payload without materializing it (buffers are written directly) — the
    zero-copy path for serving multi-GB checkpoints.

    ``chunk_indices`` restricts to a subset of leaf slots (for round-robin
    chunked transport, reference http_transport.py:288-299); the skeleton is
    still complete so any chunk can be merged by slot index.
    """
    skeleton, leaves = _flatten(state_dict)
    indices = chunk_indices if chunk_indices is not None else list(range(len(leaves)))
    metas: List[Dict[str, Any]] = []
    buffers: List[Optional[np.ndarray]] = []
    for i in indices:
        meta, buf = _leaf_meta(leaves[i])
        meta["slot"] = i
        metas.append(meta)
        buffers.append(buf)
    header = pickle.dumps(
        {"skeleton": skeleton, "num_leaves": len(leaves), "leaves": metas}
    )
    total = _HEADER.size + len(header) + sum(b.nbytes for b in buffers if b is not None)

    def writer(out: BinaryIO) -> None:
        out.write(_HEADER.pack(len(header)))
        out.write(header)
        for buf in buffers:
            if buf is not None:
                # uint8 view, not memoryview.cast: ml_dtypes (bfloat16, fp8 —
                # the TPU training dtypes) have no buffer-protocol format
                # char and would raise in cast("B").
                out.write(buf.reshape(-1).view(np.uint8))

    return total, writer


def serialize_to(state_dict: Any, out: BinaryIO, chunk_indices: "Optional[List[int]]" = None) -> None:
    _, writer = prepare(state_dict, chunk_indices)
    writer(out)


def serialize(state_dict: Any, chunk_indices: "Optional[List[int]]" = None) -> bytes:
    bio = io.BytesIO()
    serialize_to(state_dict, bio, chunk_indices)
    return bio.getvalue()


def num_leaves(state_dict: Any) -> int:
    return len(jax.tree_util.tree_flatten(state_dict)[0])


def raw_view(value: Any) -> "Optional[memoryview]":
    """Memoryview of a value that is ALREADY serialized wire bytes
    (``bytes``/``bytearray``/contiguous ``uint8`` ndarray — the serving
    tier's zero-decode passthrough forms), ``None`` otherwise."""
    if isinstance(value, (bytes, bytearray)):
        return memoryview(value)
    if isinstance(value, memoryview):
        return value
    if (
        isinstance(value, np.ndarray)
        and value.dtype == np.uint8
        and value.ndim == 1
        and value.flags.c_contiguous
    ):
        return memoryview(value)
    return None


def _read_exact(src: BinaryIO, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = src.read(n - len(buf))
        if not chunk:
            raise EOFError(f"stream ended with {n - len(buf)} bytes missing")
        buf.extend(chunk)
    return bytes(buf)


def _read_exact_into(src: BinaryIO, view: memoryview) -> None:
    """Fill ``view`` from the stream — no intermediate byte assembly, so
    multi-GB array payloads land straight in their final buffer."""
    off, n = 0, len(view)
    readinto = getattr(src, "readinto", None)
    while off < n:
        if readinto is not None:
            got = readinto(view[off:])
            if not got:
                raise EOFError(f"stream ended with {n - off} bytes missing")
            off += got
        else:
            chunk = src.read(n - off)
            if not chunk:
                raise EOFError(f"stream ended with {n - off} bytes missing")
            view[off : off + len(chunk)] = chunk
            off += len(chunk)


def deserialize_from(
    src: BinaryIO, into: "Optional[Dict[int, np.ndarray]]" = None
) -> Tuple[Any, Dict[int, Any], int]:
    """Read one serialized stream.

    Returns ``(skeleton, {slot: leaf}, num_leaves)`` so chunked fetches can
    be merged before reassembly via :func:`reassemble`.

    ``into`` maps leaf slots to existing arrays to receive **in place**
    (matching shape/dtype/contiguity required) — the warm-buffer fast path:
    cold ``np.empty`` targets page-fault during the socket reads, roughly
    halving effective recv bandwidth for multi-GB checkpoints.
    """
    (hlen,) = _HEADER.unpack(_read_exact(src, _HEADER.size))
    header = pickle.loads(_read_exact(src, hlen))
    leaves: Dict[int, Any] = {}
    for meta in header["leaves"]:
        if meta["kind"] == "array":
            dtype = np.dtype(meta["dtype"])
            out = None
            if into is not None:
                target = into.get(meta["slot"])
                if (
                    isinstance(target, np.ndarray)
                    and target.dtype == dtype
                    and target.shape == tuple(meta["shape"])
                    and target.flags.c_contiguous
                ):
                    out = target
            if out is None:
                out = np.empty(meta["shape"], dtype=dtype)
            if out.nbytes:
                # uint8 view (not memoryview.cast): ml_dtypes leaves have no
                # buffer-protocol format char
                _read_exact_into(
                    src, memoryview(out.reshape(-1).view(np.uint8))
                )
            leaves[meta["slot"]] = out
        else:
            leaves[meta["slot"]] = meta["value"]
    return header["skeleton"], leaves, header["num_leaves"]


def reassemble(skeleton: Any, leaves: Dict[int, Any], num_leaves: int) -> Any:
    if len(leaves) != num_leaves:
        missing = sorted(set(range(num_leaves)) - set(leaves))
        raise ValueError(f"missing leaf slots {missing[:8]}... in checkpoint")
    treedef = jax.tree_util.tree_structure(skeleton)
    ordered = [leaves[i] for i in range(num_leaves)]
    return jax.tree_util.tree_unflatten(treedef, ordered)


def deserialize(data: bytes) -> Any:
    skeleton, leaves, n = deserialize_from(io.BytesIO(data))
    return reassemble(skeleton, leaves, n)


def split_chunks(num_leaves: int, num_chunks: int) -> "List[List[int]]":
    """Round-robin leaf-slot assignment (reference http_transport.py:288-299)."""
    return [list(range(i, num_leaves, num_chunks)) for i in range(num_chunks)]
