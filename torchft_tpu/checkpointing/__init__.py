from torchft_tpu.checkpointing.durable import (
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    save_checkpoint,
)
from torchft_tpu.checkpointing.http_transport import HTTPTransport
from torchft_tpu.checkpointing.pg_transport import PGTransport
from torchft_tpu.checkpointing.store import (
    FragmentStore,
    StoreSpiller,
    select_cut,
    store_from_env,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

__all__ = [
    "CheckpointTransport",
    "FragmentStore",
    "HTTPTransport",
    "PGTransport",
    "StoreSpiller",
    "latest_checkpoint",
    "list_checkpoints",
    "load_checkpoint",
    "save_checkpoint",
    "select_cut",
    "store_from_env",
]
