"""Durable (on-disk) checkpoints for cold-start resume.

Live healing (HTTP/PG transports) covers the *partial* failure case — some
replicas die, peers hold the state.  Durable checkpoints cover the total
one: every replica died (preemption, maintenance), so on restart there is
no healthy peer to heal from and the job must resume from disk.  The
reference demonstrates this in its trainer: periodic ``torch.save`` of
``{model, optim}`` alongside ``manager.state_dict()``
(reference: train_ddp.py:201-208).

Since ISSUE 17 the save path is a thin wrapper over the content-addressed
:class:`~torchft_tpu.checkpointing.store.FragmentStore`: the state dict is
split into heal fragments whose wire bytes land in ``<dir>/blobs/<sha256>``
(deduped across steps — an unchanged fragment costs zero extra disk) and
``ckpt_step<N>.tft`` holds only the digest-bearing manifest, written
atomically (tmp + fsync + ``os.replace``) AFTER every blob it references,
so a kill mid-save can never corrupt the latest checkpoint.  Loads verify
every blob against its manifest sha256 and raise ``ValueError`` loudly on
a missing/corrupt blob — silently wrong weights are never returned.

Legacy format: a pre-ISSUE-17 ``ckpt_step<N>.tft`` holding the whole
serialized state dict (no manifest marker) still loads — the single-file
format is supported **read-only**; new saves always use the store layout.
"""

from __future__ import annotations

import os
import re
from typing import Any, List, Optional, Tuple

from torchft_tpu.checkpointing import store as _store
from torchft_tpu.checkpointing.serialization import (
    deserialize_from,
    reassemble,
)

_CKPT_RE = re.compile(r"^ckpt_step(\d+)\.tft$")


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_step{step}.tft")


def save_checkpoint(
    directory: str, step: int, state_dict: Any, keep_last: int = 2
) -> str:
    """Write ``state_dict`` for ``step`` onto the fragment store; prune
    to ``keep_last``.

    Returns the manifest path (``ckpt_step<N>.tft``).  The composite
    Manager layout (``{"user": ..., "torchft": {"step": ..., ...}}``) is
    conventional but not required — any pytree serializes.  A failure at
    any point before the final manifest replace leaves the previous
    checkpoint for ``step`` intact (blobs are content-addressed, so
    half-spilled new blobs are garbage-collected, never referenced).
    """
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    # max_versions=0: pruning follows keep_last below, not the store's
    # own TORCHFT_STORE_VERSIONS window.
    store = _store.FragmentStore(directory, max_versions=0)
    store.put_state(step, state_dict, manifest_path=path)

    if keep_last > 0:
        for old_step, old_path in list_checkpoints(directory)[:-keep_last]:
            if old_step != step:
                try:
                    os.remove(old_path)
                except OSError:
                    pass
        store.gc_blobs()
    return path


def load_checkpoint(path: str) -> Any:
    """Load one checkpoint by manifest path, digest-verifying every
    fragment blob (raises ``ValueError`` on a missing or corrupt blob).
    Legacy single-file ``.tft`` checkpoints load as-is (read-only
    fallback, no integrity metadata to verify)."""
    with open(path, "rb") as f:
        obj = reassemble(*deserialize_from(f))
    if (
        isinstance(obj, dict)
        and obj.get(_store.STORE_MARKER) == _store.STORE_FORMAT
        and "fragments" in obj
        and "digests" in obj
    ):
        store = _store.FragmentStore(
            os.path.dirname(os.path.abspath(path)), max_versions=0
        )
        return store.load_state(obj)
    return obj


def list_checkpoints(directory: str) -> "List[Tuple[int, str]]":
    """All checkpoints in ``directory`` as (step, path), step-ascending."""
    if not os.path.isdir(directory):
        return []
    found = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(found)


def latest_checkpoint(directory: str) -> "Optional[str]":
    """Path of the highest-step checkpoint, or None."""
    ckpts = list_checkpoints(directory)
    return ckpts[-1][1] if ckpts else None
