"""Fragment provenance plane: per-fragment version vectors + hop audit.

The fragment is the system's universal wire unit — serving relay
(ISSUE 14), striped heal (ISSUE 15), durable spill/restore (ISSUE 17)
all move digest-manifested fragments — yet until this module every
observability surface spoke at node or whole-model granularity.
ROADMAP item 1 (continuous multi-publisher serving) needs the fleet to
answer: *which version of fragment f is held where, how stale is it,
and which hops did these exact bytes traverse?*  This registry is the
process-local half of that answer:

- **Stable fragment identity.**  ``frag_id(payload, index)`` =
  ``"<payload>/<index>"`` — the payload family (``weights`` for serving
  documents, ``heal`` for heal streams) plus the round-robin layout
  index that names the fragment everywhere in the plane
  (``fragments.fragment_slots``).  The id is version-free on purpose:
  the vector tracks *which version of that slot* a holder has.

- **Per-fragment version vector.**  Every holder — publisher, serving
  relay, serving client, heal destination, durable store — calls
  :func:`note_hold` at stage/verify/spill time; the vector entry keeps
  ``(version, digest8, held_since_ms, version_ms)`` where ``version_ms``
  is the manifest's publish stamp (``created_ns`` // 1e6, the
  publisher's clock) carried unmodified — so fleet-side staleness is a
  difference of two stamps from ONE clock, skew-free (the PR 16 ledger
  generalized down to the fragment).

- **Hop-level audit.**  Every fragment transfer on any plane appends a
  ``fragment.hop`` record (source host, plane ∈ {serving, heal,
  restore}, digest verdict ok/mismatch/torn, bytes, first-byte ms) to a
  bounded private :class:`~torchft_tpu.utils.flightrecorder.
  FlightRecorder` ring (``TORCHFT_FRAG_RING``, default 1024) — same
  ~1 us/record budget discipline, same JSONL dump format, dumped
  crash-durably *alongside* ``TORCHFT_FLIGHT_FILE`` (``<path>.prov``)
  via the flight recorder's companion hook.  ``torchft-diagnose
  --fragment <id>`` replays a fragment's whole journey from these dumps
  alone and names the hop where a mismatch first entered
  (``poisoned_hop``).

- **Fleet aggregation.**  :meth:`ProvenanceRegistry.maybe_digest` emits
  a bounded digest (worst-K stalest + changed-since-last-report,
  ``TORCHFT_FRAG_TOPK`` / ``TORCHFT_FRAG_REPORT_S``) that manager and
  serving heartbeats piggyback — consumed-on-send, restored on RPC
  failure via :meth:`ProvenanceRegistry.restore_digest`, exactly the
  PR 16 links-digest contract.  The lighthouse folds reports into the
  per-(host, frag_id) version matrix served at ``/fragments.json``.

Failure policy matches every telemetry surface: provenance must never
fail a transfer — all public entry points swallow their own errors.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils.env import env_float, env_int

logger = logging.getLogger(__name__)

__all__ = [
    "PLANES",
    "VERDICTS",
    "frag_id",
    "ProvenanceRegistry",
    "PROV",
    "note_hold",
    "note_hop",
]

#: transfer planes a fragment hop can ride
PLANES = ("serving", "heal", "restore")

#: digest verdicts a hop can carry: ``ok`` (verified), ``mismatch``
#: (wire bytes hash differently than the manifest), ``torn`` (a durable
#: blob failed its content-address check at read time)
VERDICTS = ("ok", "mismatch", "torn")

_DEFAULT_RING = 1024


def frag_id(payload: str, index: Any) -> str:
    """The stable fragment identity: payload family + layout index."""
    return f"{payload}/{index}"


class _Held:
    """One vector entry.  Mutated only under the registry lock."""

    __slots__ = ("version", "digest8", "held_since_ms", "version_ms", "pub")

    def __init__(self) -> None:
        self.version = 0
        self.digest8 = ""
        self.held_since_ms = 0
        self.version_ms = 0
        self.pub = False

    def to_row(self, fid: str) -> "Dict[str, Any]":
        row: "Dict[str, Any]" = {
            "frag": fid,
            "version": self.version,
            "digest8": self.digest8,
            "held_ms": self.held_since_ms,
            "version_ms": self.version_ms,
        }
        if self.pub:
            row["pub"] = True
        return row


class ProvenanceRegistry:
    """The process-wide fragment provenance table (module global
    ``PROV``): version vector + hop ring + heartbeat digest."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._vector: "Dict[str, _Held]" = {}
        # changed-since-last-report set (consumed by maybe_digest)
        self._dirty: "set[str]" = set()
        self._topk = env_int("TORCHFT_FRAG_TOPK", 16, minimum=1)
        self._report_s = env_float("TORCHFT_FRAG_REPORT_S", 2.0, minimum=0.0)
        self._last_report_mono = 0.0
        # first-K distinct frag ids keep their name as a metric label;
        # later ones fold into "other" (the worst-K cardinality tier)
        self._label_frags: "Dict[str, str]" = {}
        self._flightrec_ring = _flightrec.FlightRecorder(
            capacity=env_int("TORCHFT_FRAG_RING", _DEFAULT_RING, minimum=16)
        )
        self._holder = f"{socket.gethostname()}:{os.getpid()}"

    # -- configuration ----------------------------------------------------

    def reset(self) -> None:
        """Drop the vector + ring and re-read env knobs (tests flip
        them)."""
        with self._lock:
            self._vector.clear()
            self._dirty.clear()
            self._label_frags.clear()
            self._last_report_mono = 0.0
            self._topk = env_int("TORCHFT_FRAG_TOPK", 16, minimum=1)
            self._report_s = env_float(
                "TORCHFT_FRAG_REPORT_S", 2.0, minimum=0.0
            )
            self._flightrec_ring = _flightrec.FlightRecorder(
                capacity=env_int(
                    "TORCHFT_FRAG_RING", _DEFAULT_RING, minimum=16
                )
            )
            self._holder = f"{socket.gethostname()}:{os.getpid()}"

    def set_holder(self, holder: str) -> None:
        """Override the holder identity stamped on ring records (defaults
        to ``host:pid``; tests and multi-role processes disambiguate)."""
        with self._lock:
            self._holder = holder

    # -- hot path ---------------------------------------------------------

    def note_hold(
        self,
        fid: str,
        version: int,
        digest: str = "",
        version_ms: int = 0,
        role: str = "holder",
        publisher: bool = False,
    ) -> None:
        """A holder staged/verified/spilled fragment ``fid`` at
        ``version``.  Updates the local version vector (newest version
        wins; an equal re-hold refreshes nothing) and appends a
        ``fragment.hold`` ring record so dumps carry the journey's
        endpoints too.  ``version_ms`` is the manifest publish stamp
        (publisher's clock), carried unmodified."""
        try:
            now_ms = int(time.time() * 1e3)
            d8 = str(digest)[:8]
            with self._lock:
                e = self._vector.get(fid)
                if e is None:
                    e = self._vector[fid] = _Held()
                if version < e.version:
                    return  # stale re-hold never regresses the vector
                changed = version > e.version or d8 != e.digest8
                e.version = int(version)
                e.digest8 = d8
                e.version_ms = int(version_ms)
                e.pub = e.pub or publisher
                if changed or e.held_since_ms == 0:
                    e.held_since_ms = now_ms
                    self._dirty.add(fid)
                holder = self._holder
            self._flightrec_ring.record(
                "fragment.hold",
                frag=fid,
                version=int(version),
                digest8=d8,
                version_ms=int(version_ms),
                holder=holder,
                role=role,
            )
        except Exception:  # noqa: BLE001 - provenance never fails a hold
            logger.debug("note_hold failed", exc_info=True)

    def note_hop(
        self,
        fid: str,
        version: int,
        source: str,
        plane: str,
        verdict: str = "ok",
        nbytes: int = 0,
        first_byte_ms: float = 0.0,
        start_ns: "Optional[int]" = None,
    ) -> None:
        """One fragment transfer completed (or was rejected): append the
        provenance record.  ~1 us on the ok path — one ring record + one
        bounded counter; the span joins the per-step trace only when a
        sampled trace context is live."""
        try:
            holder = self._holder
            self._flightrec_ring.record(
                "fragment.hop",
                status="ok" if verdict == "ok" else "error",
                start_ns=start_ns,
                frag=fid,
                version=int(version),
                source=source,
                plane=plane,
                verdict=verdict,
                bytes=int(nbytes),
                first_byte_ms=round(float(first_byte_ms), 3),
                holder=holder,
            )
            from torchft_tpu.utils import metrics as _metrics

            _metrics.FRAG_HOPS.labels(plane=plane, verdict=verdict).inc()
            from torchft_tpu.utils import tracing as _tracing

            tracer = _tracing.get_tracer()
            ctx = _tracing.get_current()
            if tracer is not None and ctx is not None and ctx.sampled:
                end_ns = time.time_ns()
                tracer.export_span(
                    name="fragment.hop",
                    trace_id=ctx.trace_id,
                    parent_span_id=ctx.span_id,
                    start_ns=start_ns if start_ns is not None else end_ns,
                    end_ns=end_ns,
                    attributes={
                        "frag": fid,
                        "version": int(version),
                        "source": source,
                        "plane": plane,
                        "verdict": verdict,
                        "bytes": int(nbytes),
                    },
                )
        except Exception:  # noqa: BLE001 - provenance never fails a hop
            logger.debug("note_hop failed", exc_info=True)

    # -- bounded metric labels (worst-K tier) -----------------------------

    def frag_topk_label(self, fid: str) -> str:
        """Bounded per-fragment metric label: the first
        ``TORCHFT_FRAG_TOPK`` distinct frag ids keep their name, later
        ones fold into ``other`` — at most K+1 values ever (frag ids are
        layout coordinates, restart-stable).  The ``metrics-cardinality``
        lint recognizes ``*topk_label`` accessors as this bounded tier."""
        with self._lock:
            label = self._label_frags.get(fid)
            if label is None:
                label = (
                    fid if len(self._label_frags) < self._topk else "other"
                )
                self._label_frags[fid] = label
            return label

    # -- snapshots / digest ------------------------------------------------

    def snapshot(self) -> "Dict[str, Dict[str, Any]]":
        """Copy of the local version vector, keyed by frag id."""
        with self._lock:
            return {fid: e.to_row(fid) for fid, e in self._vector.items()}

    def hop_records(self) -> "List[Dict[str, Any]]":
        """Completed hop/hold ring records, oldest first (tests/bench)."""
        return self._flightrec_ring.snapshot()

    def maybe_digest(self, host: str) -> "Optional[Dict[str, Any]]":
        """The heartbeat-piggyback digest, rate-limited to one per
        ``TORCHFT_FRAG_REPORT_S``: ``None`` when not due or empty.  Rows
        are bounded: the worst-K stalest stamped fragments (oldest
        ``version_ms`` first — the rows worth aggregating fleet-wide)
        plus everything that changed since the last report, hard-capped
        at 8*K.  The dirty set is CONSUMED here; on RPC failure the
        sender hands the digest back via :meth:`restore_digest`."""
        now = time.monotonic()
        with self._lock:
            if not self._vector:
                return None
            if (
                self._report_s > 0.0
                and now - self._last_report_mono < self._report_s
            ):
                return None
            self._last_report_mono = now
            entries = sorted(self._vector.items())
            dirty = set(self._dirty)
            self._dirty.clear()
            topk = self._topk
        stamped = [(fid, e) for fid, e in entries if e.version_ms > 0]
        stale = sorted(stamped, key=lambda kv: kv[1].version_ms)[:topk]
        chosen = {fid for fid, _ in stale} | dirty
        rows = [e.to_row(fid) for fid, e in entries if fid in chosen]
        rows = rows[: 8 * topk]
        self._export_metrics(entries, topk)
        if not rows:
            return None
        return {"host": host, "frags": rows}

    def restore_digest(self, digest: "Optional[Dict[str, Any]]") -> None:
        """A piggybacked digest failed to send: re-mark its rows dirty
        and lift the rate limit so the next beat re-reports (the
        consumed-on-send contract's failure leg)."""
        if not digest:
            return
        with self._lock:
            for row in digest.get("frags") or []:
                fid = row.get("frag")
                if fid in self._vector:
                    self._dirty.add(str(fid))
            self._last_report_mono = 0.0

    def _export_metrics(
        self, entries: "List[Any]", topk: int
    ) -> None:
        """Refresh the worst-K-bounded ``torchft_frag_*`` gauges plus the
        unlabeled aggregates (cardinality contract: docs/observability.md
        "metric cardinality")."""
        try:
            from torchft_tpu.utils import metrics as _metrics

            _metrics.FRAG_HELD.set(len(entries))
            now_ms = int(time.time() * 1e3)
            stamped = [
                (fid, e) for fid, e in entries if e.version_ms > 0
            ]
            _metrics.FRAG_STAMP_AGE_MAX.set(
                max(
                    (now_ms - e.version_ms for _, e in stamped),
                    default=0,
                )
                / 1e3
            )
            for fid, e in sorted(
                stamped, key=lambda kv: kv[1].version_ms
            )[:topk]:
                _metrics.FRAG_STAMP_AGE.labels(
                    frag=self.frag_topk_label(fid)
                ).set((now_ms - e.version_ms) / 1e3)
        except Exception:  # noqa: BLE001 - telemetry refresh never raises
            logger.debug("frag metric export failed", exc_info=True)

    # -- crash-durable dump ------------------------------------------------

    def dump(
        self,
        reason: str,
        trigger: str = "manual",
        path: "Optional[str]" = None,
        blocking: bool = True,
    ) -> "Optional[str]":
        """Dump the hop ring as JSONL — same format as the flight
        recorder, default sink ``TORCHFT_FLIGHT_FILE + ".prov"`` (the
        provenance evidence lands alongside the flight evidence)."""
        if path is None:
            base = _flightrec.dump_path()
            if base is None:
                return None
            path = base + ".prov"
        return self._flightrec_ring.dump(
            reason, trigger=trigger, path=path, blocking=blocking
        )


#: the process-wide registry every fragment plane feeds
PROV = ProvenanceRegistry()

# module-level shorthands (the form the production call sites use)
note_hold = PROV.note_hold
note_hop = PROV.note_hop


def _companion_dump(
    reason: str, trigger: str, blocking: bool, target: str
) -> None:
    # Ride every process-recorder dump: the same trigger (signal, abort,
    # manager error) that freezes the flight ring freezes the hop ring,
    # into <same path>.prov.
    PROV.dump(reason, trigger=trigger, path=target + ".prov",
              blocking=blocking)


_flightrec.register_companion_dump(_companion_dump)
