"""Shared fragment plane: digest-manifested payloads + pipelined fetches.

One fragment data path used by BOTH consumers of bulk weight movement
(ISSUE 15 promoted it out of ``serving/`` so live healing could ride it
too; ``serving/payload.py`` and ``serving/fetcher.py`` remain as thin
aliases):

- the **weight-serving tier** (``serving/``): versioned payload docs,
  cut-through relays, delta client fetches;
- the **heal path** (``checkpointing/http_transport.py`` +
  ``manager.py``): a stale replica stripes disjoint fragment ranges
  across every max-step quorum peer in parallel, verifies each fragment
  against the primary source's manifest digest, and — on a transient
  rejoin — fetches only the fragments whose digest differs from its own
  state (docs/architecture.md "Striped heal").

A payload/heal document is one staged checkpoint-transport document:

.. code-block:: text

    {
      "frag:header":   {version, wire, fragments, skeleton, num_leaves}   (heal only; staged FIRST)
      "frag:manifest": {header fields + digests, created_ns}              (staged last on the heal path)
      "frag:0": <serialized fragment wire bytes>,
      ...
    }

Every fragment is independently fetchable via the transport's
``frag_<name>`` resource.  Fragments are stored (and staged, and
relayed) as the **serialized wire stream itself**
(``checkpointing/serialization.py`` format), and the digest is the
sha256 of exactly those bytes: any node can verify a fragment on receipt
and re-serve it **verbatim** — zero decode passes — and replicas holding
bitwise-identical state produce bitwise-identical fragments by
construction, which is what makes cross-peer striped fetches safe.  A
fragment may appear as ``bytes`` (encoder output), a bufpool-backed
``uint8`` ndarray (fetch/relay passthrough), or a decoded
``{slot: leaf}`` dict (tests/legacy); :func:`fragment_wire` normalizes
the raw forms.

The fetch plane (persistent per-``(thread, netloc)`` HTTP/1.1
connections, bufpool ``readinto`` receive, 503-poll retry, WAN
wire-model charging, flight/span/fault instrumentation) is shared
verbatim; callers select the telemetry identity — the serving tier uses
the ``serving.frag`` site/record/span, heal uses ``transport.heal.frag``
+ ``heal.frag``.

Leaves are optionally int8-quantized through the same per-row absmax
codec the quantized collectives use (``ops/quantization.py``): a float32
leaf becomes ``{"q8": int8 payload, "scale": f32 row scales,
"shape": [...]}``.  The heal path never quantizes — heal is bitwise.
"""

from __future__ import annotations

import hashlib
import http.client
import io
import threading
import time
import urllib.error
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
)
from urllib.parse import urlparse

import numpy as np

from torchft_tpu.checkpointing import fragdata as _fragdata
from torchft_tpu.checkpointing import provenance as _prov
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import linkstats as _linkstats
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.bufpool import POOL
from torchft_tpu.utils.env import env_int
from torchft_tpu.utils.retry import RetryPolicy

__all__ = [
    # payload codec
    "WIRE_F32",
    "WIRE_INT8",
    "MANIFEST_FRAG",
    "HEADER_FRAG",
    "encode_payload",
    "decode_fragment",
    "decode_manifest",
    "decode_payload",
    "assemble",
    "changed_fragments",
    "fragment_wire",
    "fragment_slots",
    "fragment_into_map",
    "verify_fragment",
    # heal-side helpers
    "heal_fragment_names",
    "iter_heal_fragments",
    "stage_heal_checkpoint",
    "local_fragment_digests",
    "maybe_decode_heal_doc",
    # fetch plane
    "FragmentFetcher",
    "fetch_raw",
    "fetch_serialized",
    "close_connections",
    "striped_fetch",
    "StripeError",
]

WIRE_F32 = "f32"
WIRE_INT8 = "int8"

#: the manifest travels as a fragment itself so the delta path is
#: uniform: fetch ``frag_manifest``, diff digests, fetch what moved.
MANIFEST_FRAG = "manifest"

#: heal-only: the digest-less manifest prefix staged BEFORE any fragment
#: encodes, so the healer's striped fetch can start while the source is
#: still snapshotting — the full manifest (with digests) lands last.
HEADER_FRAG = "header"

_Q8_KEY = "q8"


# ---------------------------------------------------------------------------
# payload codec (digest-manifested fragment documents)
# ---------------------------------------------------------------------------


def _encode_leaf(leaf: Any, wire: str) -> Any:
    if wire != WIRE_INT8:
        return leaf
    if not isinstance(leaf, np.ndarray) and hasattr(leaf, "__array__"):
        leaf = np.asarray(leaf)
    if (
        not isinstance(leaf, np.ndarray)
        or leaf.dtype != np.float32
        or leaf.size == 0
    ):
        return leaf
    from torchft_tpu.ops import quantization as q

    # The codec's own row view (``_as_rows``: leading dim = rows, rest
    # flattened) — passing the leaf straight through keeps serving
    # payload bytes in lockstep with the collective wire bytes by
    # construction, not by a mirrored re-implementation.
    scales, payload = q.quantize(np.ascontiguousarray(leaf), q.WIRE_INT8)
    return {
        _Q8_KEY: payload,
        "scale": scales,
        "shape": np.asarray(leaf.shape, dtype=np.int64),
    }


def _decode_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and _Q8_KEY in leaf:
        from torchft_tpu.ops import quantization as q

        shape = tuple(int(d) for d in np.asarray(leaf["shape"]).tolist())
        return q.dequantize(
            np.asarray(leaf["scale"]),
            np.asarray(leaf[_Q8_KEY]),
            shape,
            np.dtype(np.float32),
        )
    return leaf


def fragment_wire(frag: Any) -> "Optional[memoryview]":
    """Raw wire view of a fragment in passthrough form (``bytes`` from
    the encoder, a bufpool-backed ``uint8`` ndarray on a relay/fetch);
    ``None`` for decoded/pytree fragments."""
    return ser.raw_view(frag)


class _ViewReader(io.RawIOBase):
    """Zero-copy BinaryIO over a memoryview: ``deserialize_from`` reads
    straight out of the received buffer into the final leaf arrays —
    ``io.BytesIO(raw)`` would copy the whole fragment first."""

    def __init__(self, view: memoryview) -> None:
        self._view = view
        self._off = 0

    def readable(self) -> bool:
        return True

    def readinto(self, b: Any) -> int:
        n = min(len(b), len(self._view) - self._off)
        b[:n] = self._view[self._off:self._off + n]
        self._off += n
        return n


def verify_fragment(name: str, frag: Any, manifest: "Dict[str, Any]") -> None:
    """Check a raw fragment against the publisher-computed sha256 in the
    manifest; raises ``ValueError`` on mismatch.  Decoded fragments (no
    raw view) and fragments the manifest carries no digest for pass —
    integrity is a property of the wire form."""
    raw = fragment_wire(frag)
    if raw is None:
        return
    want = (manifest.get("digests") or {}).get(name)
    if want is None:
        return
    # wire_digest (not hashlib directly): when the native data plane
    # landed this buffer it already digested it GIL-free — re-hashing
    # every fragment on every hop would throw that work away
    got = wire_digest(frag)
    if got != want:
        raise ValueError(
            f"serving fragment {name!r} v{manifest.get('version')}: digest "
            f"mismatch ({got[:12]} != {want[:12]}) — corrupted or torn "
            f"fragment must never be staged or served"
        )


def encode_payload(
    state_dict: Any,
    version: int,
    wire: str = WIRE_F32,
    fragments: int = 1,
) -> "Dict[str, Any]":
    """Build the staged document for one published weight version.

    ``fragments``: leaf slots are split round-robin into this many
    independently fetchable fragments (the delta unit); pass the DiLoCo
    fragment count to align delta fetches with training's sync unit.
    Fragment values are the serialized wire bytes; ``digests`` is the
    sha256 of those bytes, so relays verify and re-serve them verbatim.
    """
    import jax

    if wire not in (WIRE_F32, WIRE_INT8):
        raise ValueError(f"serving wire must be f32|int8, got {wire!r}")
    fragments = max(int(fragments), 1)
    leaves, treedef = jax.tree_util.tree_flatten(state_dict)
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    frag_names = [str(i) for i in range(min(fragments, max(len(leaves), 1)))]
    doc: "Dict[str, Any]" = {}
    digests: "Dict[str, str]" = {}
    for name in frag_names:
        frag: "Dict[str, Any]" = {}
        for slot in fragment_slots(name, len(leaves), len(frag_names)):
            frag[str(slot)] = _encode_leaf(leaves[slot], wire)
        raw = ser.serialize(frag)
        doc[f"frag:{name}"] = raw
        digests[name] = hashlib.sha256(raw).hexdigest()
    doc[f"frag:{MANIFEST_FRAG}"] = {
        "version": int(version),
        "wire": wire,
        "fragments": frag_names,
        "digests": digests,
        "skeleton": skeleton,
        "num_leaves": len(leaves),
        "created_ns": time.time_ns(),
    }
    return doc


def decode_fragment(
    frag: Any, into: "Optional[Dict[int, np.ndarray]]" = None
) -> "Dict[int, Any]":
    """Decode one fragment (raw wire bytes or an already-deserialized
    sub-dict) into ``{GLOBAL leaf slot: decoded leaf}``.

    ``into`` maps the fragment's LOCAL leaf slots (its own flatten
    order — build it with :func:`fragment_into_map`) to arrays received
    **in place** (the heal path's warm retained buffers,
    ``serialization.deserialize_from`` semantics); inapplicable slots
    fall back to fresh arrays."""
    raw = fragment_wire(frag)
    if raw is not None:
        skeleton, leaves, n = ser.deserialize_from(
            _ViewReader(raw), into=into
        )
        frag = ser.reassemble(skeleton, leaves, n)
    return {int(slot): _decode_leaf(leaf) for slot, leaf in frag.items()}


def fragment_slots(
    name: str, num_leaves: int, num_fragments: int
) -> "List[int]":
    """GLOBAL leaf slots belonging to fragment ``name`` — the one
    round-robin layout rule (``serialization.split_chunks``) every
    producer/consumer of the fragment plane shares."""
    return ser.split_chunks(num_leaves, num_fragments)[int(name)]


def fragment_into_map(
    name: str,
    num_leaves: int,
    num_fragments: int,
    into: "Dict[int, np.ndarray]",
) -> "Dict[int, np.ndarray]":
    """Remap a GLOBAL-slot ``into`` buffer map onto fragment ``name``'s
    LOCAL leaf slots, for :func:`decode_fragment`'s in-place receive.

    A fragment serializes as the sub-dict ``{str(global_slot): leaf}``;
    jax's dict flatten orders keys LEXICOGRAPHICALLY, so the fragment's
    local slot *i* is the *i*-th key in sorted-string order — not the
    numeric order the round-robin assignment suggests."""
    keys = sorted(
        str(s) for s in fragment_slots(name, num_leaves, num_fragments)
    )
    return {
        i: into[int(k)] for i, k in enumerate(keys) if int(k) in into
    }


def decode_manifest(raw: Any) -> "Dict[str, Any]":
    """Decode a raw ``frag_manifest`` (or ``frag_header``) fetch into
    the manifest dict."""
    view = fragment_wire(raw)
    skeleton, leaves, n = ser.deserialize_from(
        _ViewReader(view) if view is not None else io.BytesIO(raw)
    )
    manifest = ser.reassemble(skeleton, leaves, n)
    if not isinstance(manifest, dict) or "fragments" not in manifest:
        raise ValueError("serving fetch: frag_manifest is not a manifest")
    return manifest


def changed_fragments(
    manifest: "Dict[str, Any]", prev_manifest: "Optional[Dict[str, Any]]"
) -> "List[str]":
    """Fragment names whose digest differs from ``prev_manifest`` (all of
    them when there is no previous version or the shape changed)."""
    names = list(manifest["fragments"])
    if prev_manifest is None or prev_manifest.get("num_leaves") != manifest.get(
        "num_leaves"
    ):
        return names
    prev = prev_manifest.get("digests") or {}
    return [n for n in names if manifest["digests"].get(n) != prev.get(n)]


def assemble(
    manifest: "Dict[str, Any]", leaves: "Dict[int, Any]"
) -> Any:
    """Rebuild the state dict from a complete ``{slot: decoded leaf}``
    map and the manifest skeleton (the tail of :func:`decode_payload`,
    split out so pipelined fetchers can merge leaves incrementally)."""
    import jax

    n = int(manifest["num_leaves"])
    missing = [i for i in range(n) if i not in leaves]
    if missing:
        raise ValueError(
            f"serving payload v{manifest.get('version')}: missing leaf "
            f"slots {missing[:5]}{'...' if len(missing) > 5 else ''} "
            f"(delta fetch without a complete previous version?)"
        )
    return jax.tree_util.tree_map(
        lambda slot: leaves[slot], manifest["skeleton"]
    )


def decode_payload(
    doc: "Dict[str, Any]",
    prev: "Optional[Tuple[Dict[str, Any], Dict[int, Any]]]" = None,
) -> "Tuple[Any, Dict[str, Any], Dict[int, Any]]":
    """Decode a full fetched document (or a manifest + changed-fragment
    subset merged over ``prev = (prev_manifest, prev_leaves)``).

    Returns ``(state_dict, manifest, leaves)`` — keep ``(manifest,
    leaves)`` around to decode the next delta fetch.
    """
    manifest = doc[f"frag:{MANIFEST_FRAG}"]
    leaves: "Dict[int, Any]" = dict(prev[1]) if prev is not None else {}
    for name in manifest["fragments"]:
        frag = doc.get(f"frag:{name}")
        if frag is not None:
            verify_fragment(name, frag, manifest)
            leaves.update(decode_fragment(frag))
    state = assemble(manifest, leaves)
    return state, manifest, leaves


# ---------------------------------------------------------------------------
# heal-side encode: streamed staging + local digests
# ---------------------------------------------------------------------------

#: Fragments a heal checkpoint is split into (the stripe/delta unit).
#: More fragments = finer striping + finer deltas but more per-fragment
#: message overhead; both heal endpoints read the count from the header,
#: so the knob only needs to be set on the sources.
DEFAULT_HEAL_FRAGMENTS = 8


def heal_fragment_names(num_leaves: int, fragments: int) -> "List[str]":
    return [str(i) for i in range(min(max(fragments, 1), max(num_leaves, 1)))]


def iter_heal_fragments(
    state_dict: Any, fragments: "Optional[int]" = None
) -> "Tuple[Dict[str, Any], Iterator[Tuple[str, bytes, str]]]":
    """Split ``state_dict`` into heal fragments.

    Returns ``(header, iterator)`` where ``header`` is the digest-less
    manifest prefix (available BEFORE any encoding work) and the
    iterator lazily yields ``(name, wire_bytes, sha256)`` — each
    ``next()`` performs that fragment's host snapshot + serialize +
    hash, which is what lets the streamed staging overlap a healer's
    fetch of fragment *i* with the encode of fragment *i+1*.

    Heal fragments are always ``f32`` wire (bitwise — a healed replica
    must converge exactly), leaf slots split round-robin like
    :func:`encode_payload`.
    """
    import jax

    if fragments is None:
        fragments = env_int(
            "TORCHFT_HEAL_FRAGMENTS", DEFAULT_HEAL_FRAGMENTS, minimum=1
        )
    leaves, treedef = jax.tree_util.tree_flatten(state_dict)
    skeleton = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    names = heal_fragment_names(len(leaves), fragments)
    header: "Dict[str, Any]" = {
        "wire": WIRE_F32,
        "fragments": names,
        "skeleton": skeleton,
        "num_leaves": len(leaves),
    }

    def gen() -> "Iterator[Tuple[str, bytes, str]]":
        for name in names:
            frag = {
                str(slot): leaves[slot]
                for slot in fragment_slots(name, len(leaves), len(names))
            }
            raw = ser.serialize(frag)
            yield name, raw, hashlib.sha256(raw).hexdigest()

    return header, gen()


def stage_heal_checkpoint(
    transport: Any,
    step: int,
    state_dict: Any,
    fragments: "Optional[int]" = None,
    timeout: "Optional[float]" = None,
) -> "Dict[str, Any]":
    """Stage ``state_dict`` for heal as a CUT-THROUGH fragment stream.

    The digest-less header is staged first (healers fetch it and start
    striping immediately), each fragment is staged the moment it
    encodes (healer wire overlaps source snapshot/encode — the
    transport's fragment long-poll hands each one out one round trip
    after it lands), and the full manifest (with every digest) lands
    LAST, which is also what flips the slot complete.  Returns the
    manifest so the source can keep its own digests for delta
    bookkeeping."""
    header, frag_iter = iter_heal_fragments(state_dict, fragments)
    header = dict(header, version=int(step))
    transport.begin_streamed_checkpoint(
        step, {f"frag:{HEADER_FRAG}": header}, timeout=timeout
    )
    digests: "Dict[str, str]" = {}
    try:
        for name, raw, digest in frag_iter:
            transport.stage_streamed_part(
                step, f"frag:{name}", raw, timeout=timeout
            )
            digests[name] = digest
    except BaseException:
        # a torn stage must never linger half-served: retire the slot so
        # healers fail over to another source instead of polling forever
        try:
            transport.retire_checkpoint(step)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        raise
    manifest = dict(header, digests=digests, created_ns=time.time_ns())
    transport.stage_streamed_part(
        step, f"frag:{MANIFEST_FRAG}", manifest, timeout=timeout
    )
    transport.finish_streamed_checkpoint(step, timeout=timeout)
    # provenance: the heal source is these fragments' publisher — its
    # manifest stamp is the reference clock fleet staleness compares on
    v_ms = int(manifest["created_ns"] // 1_000_000)
    for name, digest in digests.items():
        _prov.note_hold(
            _prov.frag_id("heal", name), step, digest,
            version_ms=v_ms, role="source", publisher=True,
        )
    return manifest


def local_fragment_digests(
    state_dict: Any, fragments: int
) -> "Tuple[int, Dict[str, str]]":
    """Encode ``state_dict`` locally (no staging, no wire) into the heal
    fragment layout and return ``(num_leaves, {name: sha256})`` — the
    delta-heal diff base: a rejoiner whose fragment hashes to the same
    digest as the source's already holds those bytes bitwise and skips
    their wire entirely."""
    _header, frag_iter = iter_heal_fragments(state_dict, fragments)
    digests = {name: digest for name, _raw, digest in frag_iter}
    return int(_header["num_leaves"]), digests


def maybe_decode_heal_doc(doc: Any) -> Any:
    """Decode a whole-document fetch that turned out to be a fragment
    doc (a legacy ``full`` fetch against a source that staged the
    streamed form); any other value passes through unchanged."""
    if isinstance(doc, dict) and f"frag:{MANIFEST_FRAG}" in doc:
        state, _manifest, _leaves = decode_payload(doc)
        return state
    return doc


# ---------------------------------------------------------------------------
# fetch plane (persistent connections, bufpool receive, 503-poll retry)
# ---------------------------------------------------------------------------

# Fragment fetch retry: 503 = the version/fragment exists fleet-wide but
# this node has not staged it yet (publisher encoding, parent relay
# still streaming it — the cut-through poll) — poll within the source's
# budget.  Connection errors (server killed mid-fetch, stale keep-alive
# connection) retry here too; budget expiry surfaces so the caller fails
# over to the next source.  The backoff ceiling is deliberately LOW:
# cut-through fragments land every few ms–tens of ms, so a 0.5 s ceiling
# would add more cascade latency per hop than the fragment wire itself
# (the polls ride a kept-alive connection, so each one is cheap).


def _frag_retry_if(e: BaseException) -> bool:
    return (
        e.code == 503
        if isinstance(e, urllib.error.HTTPError)
        else isinstance(e, (urllib.error.URLError, ConnectionError, OSError))
    )


_FRAG_POLICY = RetryPolicy(
    name="serving.frag",
    base_delay=0.01,
    multiplier=1.6,
    max_delay=0.1,
    retry_if=_frag_retry_if,
)

#: the heal stripe's identity on the shared policy shape — separate so
#: ``torchft_retries_total{op}`` tells serving churn from heal churn
_HEAL_FRAG_POLICY = RetryPolicy(
    name="transport.heal.frag",
    base_delay=0.01,
    multiplier=1.6,
    max_delay=0.1,
    retry_if=_frag_retry_if,
)

def _role_identity(
    fault_site: str, record: str, policy: RetryPolicy
) -> "Tuple[str, str, RetryPolicy]":
    """One fetch role's telemetry identity; the ``fault_site=`` keyword
    is the fault-coverage pass's deferred-wiring idiom — the literal
    site names here ARE the registered injection points fetch_raw/
    fetch_serialized consult per attempt."""
    return fault_site, record, policy


#: telemetry identities per fetch role: (fault site, flight/span name,
#: retry policy).  The serving tier keeps the ISSUE-14 vocabulary; heal
#: fetches are their own site so chaos schedules can kill a stripe
#: source without touching serving traffic.
_ROLE_TELEMETRY: "Dict[str, Tuple[str, str, RetryPolicy]]" = {
    "client": _role_identity(
        fault_site="serving.frag", record="serving.frag",
        policy=_FRAG_POLICY,
    ),
    "relay": _role_identity(
        fault_site="serving.frag", record="serving.frag",
        policy=_FRAG_POLICY,
    ),
    "heal": _role_identity(
        fault_site="transport.heal.frag", record="heal.frag",
        policy=_HEAL_FRAG_POLICY,
    ),
}


def _role_telemetry(role: str) -> "Tuple[str, str, RetryPolicy]":
    return _ROLE_TELEMETRY.get(role, _ROLE_TELEMETRY["client"])


def _count_fetch_bytes(role: str, nbytes: int) -> None:
    if role == "heal":
        _metrics.CHECKPOINT_BYTES.labels(
            transport="http", direction="recv"
        ).inc(nbytes)
    else:
        _metrics.SERVING_FETCH_BYTES.labels(role=role).inc(nbytes)


_wire_mod: "Optional[Any]" = None


def _charge_wire(base: str, nbytes: int) -> float:
    # WAN wire model (serving/wire.py): one RTT + bytes/rate of source-
    # uplink bucket debt per fetch message crossing the topology
    # boundary.  Lazily bound: checkpointing must stay importable
    # without dragging the serving package in at module-import time
    # (serving's own modules alias THIS module).  Returns the seconds
    # charged so the link-state plane can fold the modeled WAN cost into
    # its passive goodput estimate.
    global _wire_mod
    if _wire_mod is None:
        from torchft_tpu.serving import wire as _w

        _wire_mod = _w
    return _wire_mod.get_shaper().charge(base, nbytes)


#: per-thread first-byte latency of the most recent _request_once (the
#: fetch planes are thread-confined, like the keep-alive connections)
_fb_local = threading.local()


def _record_link(base: str, nbytes: int, seconds: float) -> None:
    """Feed the fragment plane's passive link estimator
    (utils/linkstats.py): bytes + whole-message wall (shaper charge
    included — the modeled WAN cost IS the link cost) + first-byte
    latency (connection RTT + the shaper's modeled first-byte leg)."""
    shaper = _wire_mod.get_shaper()
    host = _wire_mod.source_host(base) or "unknown"
    fb = getattr(_fb_local, "seconds", 0.0) + shaper.first_byte_s(base)
    _linkstats.record(
        host,
        "fragments",
        nbytes,
        seconds,
        first_byte_s=fb,
        local=not shaper.crosses_boundary(base),
    )


_conns = threading.local()


def _conn_cache() -> "Dict[str, http.client.HTTPConnection]":
    cache = getattr(_conns, "cache", None)
    if cache is None:
        cache = _conns.cache = {}
    return cache


def _conn_for(base: str, timeout: float) -> http.client.HTTPConnection:
    cache = _conn_cache()
    conn = cache.get(base)
    if conn is None:
        p = urlparse(base)
        conn = http.client.HTTPConnection(
            p.hostname or "127.0.0.1", p.port, timeout=timeout
        )
        cache[base] = conn
    conn.timeout = timeout
    if conn.sock is not None:
        conn.sock.settimeout(timeout)
    return conn


def _drop_conn(base: str) -> None:
    conn = _conn_cache().pop(base, None)
    if conn is not None:
        try:
            conn.close()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass


def close_connections() -> None:
    """Close THIS thread's cached keep-alive connections (tests; worker
    threads drop theirs when their executor shuts down)."""
    for base in list(_conn_cache()):
        _drop_conn(base)


def _request_once(
    base: str, path: str, timeout: float,
    extra_headers: "Optional[Dict[str, str]]" = None,
) -> http.client.HTTPResponse:
    """One GET over the cached keep-alive connection; returns the live
    200 response (the caller consumes the body).  Raises
    ``urllib.error.HTTPError`` on non-200 (503 = retryable
    not-yet-staged, drained so the connection stays reusable) and
    ``ConnectionError`` / ``OSError`` on transport failure."""
    conn = _conn_for(base, timeout)
    headers = dict(extra_headers) if extra_headers else {}
    traceparent = _tracing.current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent
    try:
        t0 = time.perf_counter()
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        # observed first-byte latency of this request (headers arrived);
        # the link-state plane adds the shaper's modeled RTT on top
        _fb_local.seconds = time.perf_counter() - t0
        if resp.status != 200:
            body = resp.read()  # drain so the connection could be reused
            if resp.will_close:
                _drop_conn(base)
            raise urllib.error.HTTPError(
                f"{base}{path}",
                resp.status,
                body[:200].decode("utf-8", "replace") or resp.reason,
                resp.headers,
                None,
            )
        return resp
    except (OSError, http.client.HTTPException) as e:
        if isinstance(e, urllib.error.HTTPError):
            raise
        _drop_conn(base)
        if isinstance(e, OSError):
            raise
        raise ConnectionError(f"http fetch {base}{path}: {e}") from e


def _get_raw_once(
    base: str, path: str, timeout: float,
    extra_headers: "Optional[Dict[str, str]]" = None,
) -> np.ndarray:
    """One GET returning a POOLED uint8 buffer the caller owns."""
    resp = _request_once(base, path, timeout, extra_headers)
    try:
        n = int(resp.headers.get("Content-Length") or 0)
        buf = POOL.take(n, np.uint8)
        try:
            view = memoryview(buf)
            off = 0
            while off < n:
                got = resp.readinto(view[off:])
                if not got:
                    raise ConnectionError(
                        f"http fetch {base}{path}: body ended {n - off} "
                        f"bytes short"
                    )
                off += got
        except BaseException:
            POOL.give(buf)
            raise
        if resp.will_close:
            _drop_conn(base)
        return buf
    except (OSError, http.client.HTTPException) as e:
        _drop_conn(base)
        if isinstance(e, OSError):
            raise
        raise ConnectionError(f"http fetch {base}{path}: {e}") from e


_digest_local = threading.local()


def _note_native_digest(buf: np.ndarray, sha_hex: str) -> None:
    """Remember the digest the native receive path already computed
    GIL-free over this exact buffer (one-shot, consumed by
    :func:`wire_digest` on the same thread)."""
    _digest_local.entry = (id(buf), sha_hex)


def _consume_native_digest(buf) -> "Optional[str]":
    """Pop this thread's native-computed digest for ``buf`` (or None) —
    used to HAND the digest across a thread boundary: the pipelined
    fetcher's worker consumes it here and re-notes it on the consumer
    thread so verify still skips the re-hash."""
    entry = getattr(_digest_local, "entry", None)
    if entry is not None and entry[0] == id(buf):
        _digest_local.entry = None
        return entry[1]
    return None


def wire_digest(buf) -> str:
    """sha256 hex of one wire buffer.  Reuses the digest the native
    data plane computed over this buffer as it landed (same thread, same
    object — consumed one-shot so a pool-recycled buffer can never
    inherit a stale digest); otherwise hashes here."""
    entry = getattr(_digest_local, "entry", None)
    if entry is not None and entry[0] == id(buf):
        _digest_local.entry = None
        return entry[1]
    return hashlib.sha256(memoryview(buf)).hexdigest()


def _raw_data_plane(
    base: str, path: str, version: int, resource: str, timeout: float
) -> np.ndarray:
    """Route one raw fragment GET: native data plane when armed
    (``TORCHFT_FRAG_NATIVE``), Python HTTP otherwise and on any native
    miss.  The miss fallback is what keeps Mock transports, gated-off
    peers, and non-mirrored resources (manifests, legacy docs) working
    unchanged — and it is recorded so a fleet silently running the slow
    path shows up in the flight recorder."""
    headers: "Optional[Dict[str, str]]" = None
    if resource.startswith("frag_"):
        # Client-driven cut-through park (X-TFT-Poll-Ms): ask the server
        # to hold a not-yet-staged fragment as long as our own budget
        # allows (bounded) — parking on the server's staging wake beats
        # a 503 + retry-ladder cycle that duplicates request load.  The
        # margin keeps the park ending before our socket deadline.
        poll_ms = int(min(max(timeout * 1000 - 150, 0), 5000))
        if poll_ms > 0:
            headers = {"X-TFT-Poll-Ms": str(poll_ms)}
        if _fragdata.enabled():
            got = _fragdata.fetch_native(base, version, resource, timeout)
            if got is not None:
                buf, sha_hex, first_byte_s = got
                _fb_local.seconds = first_byte_s
                _note_native_digest(buf, sha_hex)
                return buf
            _flightrec.record(
                "fragment.native_fallback",
                step=version,
                resource=resource,
                source=base,
            )
    return _get_raw_once(base, path, timeout, headers)


def fetch_raw(
    base: str,
    version: int,
    resource: str,
    timeout: float,
    role: str = "client",
    frag_index: "Optional[int]" = None,
) -> np.ndarray:
    """Fetch one staged resource as raw wire bytes (POOLED uint8 buffer —
    the caller owns giving it back or staging it), with the 503-poll
    retry, the WAN wire-model charge, and per-fragment telemetry.

    ``role`` selects the telemetry identity: serving roles consult the
    ``serving.frag`` chaos site and record ``serving.frag``; ``"heal"``
    consults ``transport.heal.frag`` and records/spans ``heal.frag``
    (the striped-heal vocabulary, docs/robustness.md)."""
    site, record, policy = _role_telemetry(role)
    path = f"/checkpoint/{version}/{resource}"
    t0_ns = time.time_ns()

    def attempt(budget: "Optional[float]") -> np.ndarray:
        # Chaos INSIDE the attempt: an injected drop takes exactly the
        # broken-connection path a real one would — absorbed by this
        # policy's in-budget retries (docs/robustness.md serving.frag),
        # while raise surfaces to the caller's source-failover walk.
        _faults.check(
            site,
            step=frag_index if frag_index is not None else version,
        )
        t = max(budget if budget is not None else 0.001, 0.001)
        return _raw_data_plane(base, path, version, resource, t)

    t0p = time.perf_counter()
    buf = policy.run(attempt, timeout=max(timeout, 0.001), op=site)
    wall_s = time.perf_counter() - t0p
    wall_s += _charge_wire(base, buf.nbytes)
    _record_link(base, buf.nbytes, wall_s)
    _count_fetch_bytes(role, buf.nbytes)
    _flightrec.record(
        record, start_ns=t0_ns, step=version, resource=resource,
        bytes=buf.nbytes, source=base, role=role,
    )
    tracer = _tracing.get_tracer()
    ctx = _tracing.get_current()
    if tracer is not None and ctx is not None and ctx.sampled:
        # the per-role span identity resolves via _ROLE_TELEMETRY; both
        # values ("serving.frag" / "heal.frag") live in allowed families
        tracer.export_span(  # tft-lint: allow(span-vocab)
            name=record,
            trace_id=ctx.trace_id,
            parent_span_id=ctx.span_id,
            start_ns=t0_ns,
            end_ns=time.time_ns(),
            attributes={
                "version": version, "resource": resource,
                "bytes": buf.nbytes, "role": role,
            },
        )
    return buf


def fetch_serialized(
    base: str,
    version: int,
    resource: str,
    timeout: float,
    role: str = "client",
) -> "Tuple[Any, Dict[int, Any], int]":
    """Fetch one resource and deserialize it STRAIGHT OFF the socket —
    the whole-payload (``full``) path: a multi-GB document lands
    directly in its final leaf buffers (serialization.py's streaming
    contract) instead of being buffered raw and copied again.  Returns
    ``(skeleton, leaves, num_leaves)``; same retry/wire/telemetry
    envelope as :func:`fetch_raw`."""
    site, record, policy = _role_telemetry(role)
    path = f"/checkpoint/{version}/{resource}"
    t0_ns = time.time_ns()

    def attempt(budget: "Optional[float]") -> "Tuple[Any, Dict[int, Any], int, int]":
        _faults.check(site, step=version)
        t = max(budget if budget is not None else 0.001, 0.001)
        resp = _request_once(base, path, t)
        nbytes = int(resp.headers.get("Content-Length") or 0)
        try:
            out = ser.deserialize_from(resp)
            resp.read()  # drain any trailer so the connection is reusable
        except BaseException as e:
            # mid-body failure: unknown remainder, the conn can't be kept
            _drop_conn(base)
            if isinstance(e, EOFError):
                # truncated stream = broken connection: retryable
                raise ConnectionError(
                    f"http fetch {base}{path}: truncated stream: {e}"
                ) from e
            raise
        if resp.will_close:
            _drop_conn(base)
        return out + (nbytes,)

    t0p = time.perf_counter()
    skeleton, leaves, n, nbytes = policy.run(
        attempt, timeout=max(timeout, 0.001), op=site
    )
    wall_s = time.perf_counter() - t0p
    wall_s += _charge_wire(base, nbytes)
    _record_link(base, nbytes, wall_s)
    _count_fetch_bytes(role, nbytes)
    _flightrec.record(
        record, start_ns=t0_ns, step=version, resource=resource,
        bytes=nbytes, source=base, role=role,
    )
    return skeleton, leaves, n


class FragmentFetcher:
    """Bounded-parallel pipelined fragment fetcher.

    ``parallel`` (default ``TORCHFT_SERVING_PARALLEL``) raw fetches ride
    persistent per-thread connections concurrently; results come back in
    SUBMISSION order so the consumer's verify/decode/stage of fragment
    *i* overlaps the wire of fragments *i+1..i+K*.
    """

    def __init__(
        self, parallel: "Optional[int]" = None, role: str = "client"
    ) -> None:
        self._parallel = (
            parallel
            if parallel is not None
            else env_int("TORCHFT_SERVING_PARALLEL", 4, minimum=1)
        )
        self._role = role
        self._pool: "Optional[ThreadPoolExecutor]" = None
        self._lock = threading.Lock()

    def _executor(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._parallel,
                    thread_name_prefix="tft_serving_fetch",
                )
            return self._pool

    def fetch_raw(
        self, base: str, version: int, resource: str, timeout: float
    ) -> np.ndarray:
        return fetch_raw(base, version, resource, timeout, role=self._role)

    def fetch_stream(
        self,
        base: str,
        version: int,
        resources: "List[str]",
        deadline: float,
    ) -> "Iterator[Tuple[str, np.ndarray, Tuple[float, float]]]":
        """Pipelined raw fetches of ``resources`` from one source; yields
        ``(resource, pooled_buffer, (wire_start, wire_end))`` in
        submission order — the perf-counter interval each fetch occupied
        the wire, so the consumer can compute true (union) wire busy
        time across the concurrent in-flight window.  On failure,
        buffers still in flight are drained back to the pool and the
        error re-raised (the caller fails over to another source;
        already-yielded items stay valid and staged)."""
        if not resources:
            return
        ex = self._executor()
        pending: "deque[Tuple[str, Future]]" = deque()
        it = iter(enumerate(resources))

        def _timed(
            res: str, idx: int
        ) -> "Tuple[np.ndarray, Tuple[float, float], Optional[str]]":
            t0 = time.perf_counter()
            buf = fetch_raw(
                base, version, res,
                timeout=max(deadline - time.monotonic(), 0.001),
                role=self._role, frag_index=idx,
            )
            # the native digest is noted thread-locally on THIS worker;
            # carry it to the consumer thread so verify can reuse it
            sha = _consume_native_digest(buf)
            return buf, (t0, time.perf_counter()), sha

        def _submit_next() -> bool:
            try:
                idx, res = next(it)
            except StopIteration:
                return False
            pending.append((res, ex.submit(_timed, res, idx)))
            return True

        def _drain_pending() -> None:
            while pending:
                _res, fut = pending.popleft()
                try:
                    buf, _, _ = fut.result()
                except BaseException:  # noqa: BLE001 - already failing
                    continue
                POOL.give(buf)

        for _ in range(self._parallel):
            if not _submit_next():
                break
        try:
            while pending:
                res, fut = pending.popleft()
                try:
                    buf, span, sha = fut.result()
                except BaseException:
                    _drain_pending()
                    raise
                _submit_next()
                if sha is not None:
                    _note_native_digest(buf, sha)
                yield res, buf, span
        except GeneratorExit:
            # consumer abandoned the stream mid-flight (failover after a
            # verify failure): nothing may leak out of the pool
            _drain_pending()
            raise

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# striped multi-source fetch (the heal wire plane)
# ---------------------------------------------------------------------------


class StripeError(ConnectionError):
    """Every stripe source died/failed before the fragment set
    completed (the heal falls back to report_error like any other
    recovery failure)."""


class _Stripe:
    """One source's live state inside a striped fetch."""

    __slots__ = ("base", "alive", "is_primary")

    def __init__(self, base: str, is_primary: bool) -> None:
        self.base = base
        self.alive = True
        self.is_primary = is_primary


def striped_fetch(
    sources: "List[str]",
    step: int,
    names: "List[str]",
    deadline: float,
    digests: "Optional[Dict[str, str]]" = None,
    parallel: "Optional[int]" = None,
    source_budget: "Optional[float]" = None,
    role: str = "heal",
    on_buf: "Optional[Callable[[str, np.ndarray, str], None]]" = None,
    plane: str = "heal",
) -> "Dict[str, Any]":
    """Fetch ``names`` striped across ``sources`` in parallel with
    per-fragment failover.

    ``plane`` is the provenance-plane identity of these transfers
    (``heal`` for live heals, ``restore`` when the stripe sources are
    durable-store disks) — every fragment that lands (or is rejected on
    digest mismatch) appends a ``fragment.hop`` audit record.

    ``sources[0]`` is the PRIMARY (the quorum-assigned heal source —
    the one whose manifest defines truth); the rest are max-step quorum
    peers whose state is bitwise-replicated, so any fragment they serve
    must hash to the primary's digest.  Work assignment is dynamic (a
    shared work queue, ``parallel`` concurrent fetches per source):
    faster uplinks finish more fragments, a dead/slow/poisoned source's
    fragments fail over to the survivors, and the fetch only fails when
    EVERY source has been exhausted for some fragment.

    With ``digests``, each fragment is verified the moment it lands
    (mismatch = dead source, fragment requeued — delta-heal mode);
    without, the caller verifies later against the sha256 handed to
    ``on_buf`` (full-heal mode: the manifest lands after the stream).

    ``on_buf(name, pooled_buffer, sha256)`` is invoked on the CALLER
    thread for each completed fragment, in arrival order — decode of
    fragment *i* overlaps the wire of every in-flight stripe.  Buffer
    ownership transfers to the callback.

    Returns stats: ``{"wire_bytes", "failovers", "spans", "hashes",
    "sources_used"}`` — ``sources_used`` is the set of source addresses
    that actually delivered at least one fragment (a degraded stripe is
    visible as fewer used sources than configured).
    """
    if not sources:
        raise StripeError("striped fetch: no sources")
    if parallel is None:
        parallel = env_int("TORCHFT_HEAL_PARALLEL", 2, minimum=1)
    stripes = [_Stripe(s, i == 0) for i, s in enumerate(sources)]
    frag_index = {name: i for i, name in enumerate(names)}

    # Shared state, all guarded by ``cv``: the dynamic work queue (a
    # requeued fragment lands at the FRONT — it is the oldest debt), the
    # completed set, completed results awaiting the consumer, and the
    # last per-source error (the failure chain when everything dies).
    cv = threading.Condition()
    work: "deque[str]" = deque(names)
    done: "Set[str]" = set()
    out_q: "deque[Tuple[str, np.ndarray, str, Tuple[float, float]]]" = deque()
    last_err: "List[BaseException]" = []
    stopped = False
    failovers = 0
    wire_bytes = 0
    inflight = 0
    spans: "List[Tuple[float, float]]" = []
    hashes: "Dict[str, str]" = {}
    sources_used: "Set[str]" = set()

    def _alive_locked() -> int:
        return sum(1 for s in stripes if s.alive)

    def _fail_locked(stripe: "_Stripe", name: str, e: BaseException) -> None:
        nonlocal failovers, inflight
        stripe.alive = False
        inflight -= 1
        work.appendleft(name)
        last_err.append(e)
        if _alive_locked() > 0:
            failovers += 1
            _metrics.HEAL_FRAG_FAILOVERS.inc()
        cv.notify_all()

    # the caller's per-step trace context rides into the worker threads
    # so every heal.frag span (and the traceparent header the source's
    # heal.send span joins on) lands in the healer's round trace
    caller_ctx = _tracing.get_current()

    def _worker(stripe: "_Stripe") -> None:
        nonlocal wire_bytes, inflight
        _tracing.set_current(caller_ctx)
        while True:
            with cv:
                while True:
                    if stopped or not stripe.alive or len(done) >= len(names):
                        return
                    if work:
                        name = work.popleft()
                        inflight += 1
                        break
                    # idle but not finished: a failing peer may requeue
                    cv.wait(0.02)
                remaining = deadline - time.monotonic()
                # Non-primary sources are capped so a dead one costs the
                # failover bound, not the whole heal; the primary (and
                # the last stripe standing) gets the full remaining
                # deadline — striping must never make the heal LESS
                # available than the single-source path it replaced.
                budget = remaining
                if (
                    source_budget is not None
                    and not stripe.is_primary
                    and _alive_locked() > 1
                ):
                    budget = min(source_budget, remaining)
            if budget <= 0:
                with cv:
                    _fail_locked(
                        stripe, name,
                        TimeoutError("striped fetch: deadline expired"),
                    )
                return
            t0 = time.perf_counter()
            try:
                buf = fetch_raw(
                    stripe.base, step, f"frag_{name}",
                    timeout=budget, role=role,
                    frag_index=frag_index[name],
                )
            except Exception as e:  # noqa: BLE001 - per-fragment failover
                with cv:
                    _fail_locked(stripe, name, e)
                return
            sha = wire_digest(buf)
            fb_ms = getattr(_fb_local, "seconds", 0.0) * 1e3
            if digests is not None and digests.get(name, sha) != sha:
                # poisoned/diverged source: its bytes must never land in
                # the healed state — treat exactly like a dead source
                _prov.note_hop(
                    _prov.frag_id("heal", name), step, stripe.base, plane,
                    verdict="mismatch", nbytes=buf.nbytes,
                    first_byte_ms=fb_ms,
                )
                POOL.give(buf)
                with cv:
                    _fail_locked(
                        stripe, name,
                        ValueError(
                            f"heal fragment {name!r} from {stripe.base}: "
                            f"digest mismatch ({sha[:12]} != "
                            f"{digests.get(name, '')[:12]})"
                        ),
                    )
                return
            _prov.note_hop(
                _prov.frag_id("heal", name), step, stripe.base, plane,
                verdict="ok", nbytes=buf.nbytes, first_byte_ms=fb_ms,
            )
            with cv:
                inflight -= 1
                if stopped or name in done:
                    POOL.give(buf)
                    cv.notify_all()
                    if stopped:
                        return
                    continue
                done.add(name)
                wire_bytes += buf.nbytes
                sources_used.add(stripe.base)
                spans.append((t0, time.perf_counter()))
                hashes[name] = sha
                out_q.append((name, buf, sha, spans[-1]))
                cv.notify_all()

    threads: "List[threading.Thread]" = []
    for si, stripe in enumerate(stripes):
        for w in range(max(min(parallel, len(names)), 1)):
            t = threading.Thread(
                target=_worker, args=(stripe,),
                name=f"tft_heal_stripe{si}_{w}", daemon=True,
            )
            threads.append(t)
            t.start()

    delivered = 0
    try:
        while delivered < len(names):
            with cv:
                while not out_q:
                    # "every source failed" only once nothing is still in
                    # flight: a final fetch racing its stripe's death may
                    # yet deliver the missing fragment
                    if _alive_locked() == 0 and inflight == 0:
                        raise StripeError(
                            f"striped fetch: every source failed with "
                            f"{len(names) - delivered} fragment(s) missing"
                        ) from (last_err[-1] if last_err else None)
                    if time.monotonic() > deadline:
                        raise StripeError(
                            f"striped fetch: deadline expired with "
                            f"{len(names) - delivered} fragment(s) missing"
                        )
                    cv.wait(0.05)
                name, buf, sha, _span = out_q.popleft()
            delivered += 1
            if on_buf is not None:
                on_buf(name, buf, sha)
            else:
                POOL.give(buf)
    finally:
        with cv:
            stopped = True
            cv.notify_all()
        for t in threads:
            t.join(timeout=5.0)
        # drain anything that landed after the consumer stopped
        with cv:
            while out_q:
                _name, buf, _sha, _span = out_q.popleft()
                POOL.give(buf)
    return {
        "wire_bytes": wire_bytes,
        "failovers": failovers,
        "spans": spans,
        "hashes": hashes,
        "sources_used": sources_used,
    }
