"""ProcessGroup checkpoint transport: push weights over collectives.

Analog of the reference PG transport
(reference: torchft/checkpointing/pg_transport.py:27-300): the sender ships a
pickled metadata frame (skeleton + per-leaf shape/dtype) followed by each
array as a raw buffer over tagged point-to-point sends; the receiver
reconstructs, optionally **in place** into an existing same-structure state
dict (no reallocation — the fast path for healing into live training state).
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.parallel.process_group import ProcessGroup
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.futures import context_timeout

logger = logging.getLogger(__name__)

_META_TAG = 3000
_TENSOR_TAG = 3001


class PGTransport(CheckpointTransport[Any]):
    """Checkpoint transport over a ProcessGroup's send/recv.

    Args:
        pg: the (replica-dimension) process group; src/dst ranks are replica
            ranks within the current quorum.
        timeout: per-transfer deadline.  Both directions ARM it: the whole
            send/recv runs under a ``utils.futures.context_timeout`` whose
            expiry callback is ``pg.abort`` — a dead peer mid-stream cannot
            wedge healing past the deadline, because the abort closes the
            sockets out from under every queued op.
        state_dict_fn: optional callable returning a same-structure state
            dict whose buffers are received into (in-place fast path).
    """

    def __init__(
        self,
        pg: ProcessGroup,
        timeout: float = 60.0,
        state_dict_fn: "Optional[Callable[[], Any]]" = None,
    ) -> None:
        self._pg = pg
        self._timeout = timeout
        self._state_dict_fn = state_dict_fn

    def metadata(self) -> str:
        return "<n/a>"  # rendezvous rides the quorum PG; nothing to publish

    def send_checkpoint(
        self, dst_ranks: "List[int]", step: int, state_dict: Any, timeout: float
    ) -> None:
        from torchft_tpu.checkpointing.serialization import _flatten, _leaf_meta

        _faults.check("transport.send", step=step)
        skeleton, leaves = _flatten(state_dict)
        metas = []
        arrays: List[Optional[np.ndarray]] = []
        for leaf in leaves:
            meta, arr = _leaf_meta(leaf)
            metas.append(meta)
            arrays.append(arr)
        # Trace propagation: the source's round context rides the metadata
        # frame, so the destination's receive span joins the SOURCE's
        # per-step trace — both endpoints of one heal in one trace (the
        # HTTP transport does the same with a traceparent header).
        header_doc = {"step": step, "skeleton": skeleton, "leaves": metas}
        traceparent = _tracing.current_traceparent()
        if traceparent is not None:
            header_doc["traceparent"] = traceparent
        header = np.frombuffer(
            pickle.dumps(header_doc),
            dtype=np.uint8,
        )
        t0 = time.perf_counter()
        nbytes = header.nbytes + sum(a.nbytes for a in arrays if a is not None)
        # Armed per-transfer deadline: a receiver that dies mid-stream
        # leaves sends wedged on full socket buffers; expiry aborts the
        # PG, failing every queued op fast instead of wedging healing.
        with _flightrec.track(
            "checkpoint.pg.send", step=step, dst_ranks=list(dst_ranks),
            bytes=nbytes,
        ), context_timeout(self._pg.abort, timeout):
            for dst in dst_ranks:
                # submit the whole stream, then reap: the PG worker
                # executes in submission order, and keeping its queue
                # non-empty lets it drain the socket continuously instead
                # of idling one thread-wakeup round trip per leaf
                works = [self._pg.send(header, dst, tag=_META_TAG)]
                for i, arr in enumerate(arrays):
                    if arr is not None:
                        works.append(
                            self._pg.send(
                                arr.reshape(-1).view(np.uint8), dst, tag=_TENSOR_TAG + i
                            )
                        )
                for w in works:
                    w.wait(timeout=timeout)
                _metrics.CHECKPOINT_BYTES.labels(
                    transport="pg", direction="send"
                ).inc(nbytes)
        _metrics.CHECKPOINT_DURATION.labels(
            transport="pg", direction="send"
        ).observe(time.perf_counter() - t0)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> Any:
        _faults.check("transport.recv", step=step)
        t0 = time.perf_counter()
        # Armed per-transfer deadline (see send_checkpoint): expiry aborts
        # the PG so a dead/stalled sender cannot wedge healing — the
        # receiving replica latches the error and re-heals next quorum.
        with _flightrec.track(
            "checkpoint.pg.recv", step=step, src_rank=src_rank,
        ), context_timeout(self._pg.abort, timeout):
            return self._recv_checkpoint(src_rank, step, timeout, t0)

    def _recv_checkpoint(
        self, src_rank: int, step: int, timeout: float, t0: float
    ) -> Any:
        header_bytes = self._pg.recv(src_rank, tag=_META_TAG).wait(timeout=timeout)
        header = pickle.loads(header_bytes.tobytes())
        if header["step"] != step:
            raise RuntimeError(
                f"checkpoint step mismatch: expected {step}, got {header['step']}"
            )
        # In-place fast path: receive into the live state dict's buffers.
        inplace_leaves: "Optional[List[Any]]" = None
        if self._state_dict_fn is not None:
            try:
                existing = self._state_dict_fn()
                inplace_leaves = jax.tree_util.tree_flatten(existing)[0]
                if len(inplace_leaves) != len(header["leaves"]):
                    inplace_leaves = None
            except Exception:  # noqa: BLE001 - fall back to fresh alloc
                inplace_leaves = None

        leaves: List[Any] = []
        try:
            # Submit every tensor recv up front (the PG worker runs them in
            # order, streaming the socket without per-leaf wakeup gaps);
            # in-place targets go straight to the wire reader as
            # recv(out=...) (uint8 view: the wire carries flat bytes).
            works: "List[Optional[Any]]" = []
            for i, meta in enumerate(header["leaves"]):
                if meta["kind"] == "object":
                    works.append(None)
                    continue
                out = None
                if inplace_leaves is not None:
                    target = inplace_leaves[i]
                    if (
                        isinstance(target, np.ndarray)
                        and target.shape == tuple(meta["shape"])
                        and str(target.dtype) == meta["dtype"]
                        and target.flags.c_contiguous
                    ):
                        out = target
                works.append(
                    (
                        self._pg.recv(
                            src_rank,
                            tag=_TENSOR_TAG + i,
                            out=None
                            if out is None
                            else out.reshape(-1).view(np.uint8),
                        ),
                        out,
                    )
                )

            for meta, w in zip(header["leaves"], works):
                if w is None:
                    leaves.append(meta["value"])
                    continue
                work, out = w
                raw = work.wait(timeout=timeout)
                if out is not None:
                    leaves.append(out)
                else:
                    # raw is a fresh private buffer; the reshaped view owns it
                    leaves.append(
                        raw.view(np.dtype(meta["dtype"])).reshape(meta["shape"])
                    )
        except Exception:
            # Abandoning mid-stream (including a failure while still
            # SUBMITTING — e.g. a malformed leaf meta) leaves the tag
            # stream desynced AND queued in-place recvs that would keep
            # writing into LIVE training buffers as bytes arrive.  Abort
            # tears the PG down so no queued op ever executes; the Manager
            # latches the error and reconfigures at the next quorum.
            self._pg.abort()
            raise
        nbytes = header_bytes.nbytes + sum(
            l.nbytes for l in leaves if isinstance(l, np.ndarray)
        )
        _metrics.CHECKPOINT_BYTES.labels(transport="pg", direction="recv").inc(
            nbytes
        )
        _metrics.CHECKPOINT_DURATION.labels(
            transport="pg", direction="recv"
        ).observe(time.perf_counter() - t0)
        # Distributed tracing: continue the source's context from the
        # metadata frame — this receive lands as a heal.recv span in the
        # SOURCE's per-step trace, next to its heal_send phase.  The
        # mirrored flight record keeps the traced phase visible in
        # post-mortem dumps too (span-vocab lint's 2-hop flight rule).
        tracer = _tracing.get_tracer()
        if tracer is not None:
            ctx = _tracing.TraceContext.from_traceparent(
                header.get("traceparent")
            )
            if ctx is not None and ctx.sampled:
                end_ns = time.time_ns()
                start_ns = end_ns - int((time.perf_counter() - t0) * 1e9)
                _flightrec.record(
                    "heal.recv", start_ns=start_ns, step=step,
                    src_rank=src_rank, bytes=nbytes,
                )
                tracer.export_span(
                    name="heal.recv",
                    trace_id=ctx.trace_id,
                    parent_span_id=ctx.span_id,
                    start_ns=start_ns,
                    end_ns=end_ns,
                    attributes={
                        "transport": "pg",
                        "step": step,
                        "src_rank": src_rank,
                        "bytes": nbytes,
                    },
                )
        treedef = jax.tree_util.tree_structure(header["skeleton"])
        return jax.tree_util.tree_unflatten(treedef, leaves)
