"""ProcessGroup checkpoint transport: push weights over collectives.

Analog of the reference PG transport
(reference: torchft/checkpointing/pg_transport.py:27-300): the sender ships a
pickled metadata frame (skeleton + per-leaf shape/dtype) followed by each
array as a raw buffer over tagged point-to-point sends; the receiver
reconstructs, optionally **in place** into an existing same-structure state
dict (no reallocation — the fast path for healing into live training state).
"""

from __future__ import annotations

import logging
import pickle
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.parallel.process_group import ProcessGroup

logger = logging.getLogger(__name__)

_META_TAG = 3000
_TENSOR_TAG = 3001


class PGTransport(CheckpointTransport[Any]):
    """Checkpoint transport over a ProcessGroup's send/recv.

    Args:
        pg: the (replica-dimension) process group; src/dst ranks are replica
            ranks within the current quorum.
        timeout: per-transfer deadline.
        state_dict_fn: optional callable returning a same-structure state
            dict whose buffers are received into (in-place fast path).
    """

    def __init__(
        self,
        pg: ProcessGroup,
        timeout: float = 60.0,
        state_dict_fn: "Optional[Callable[[], Any]]" = None,
    ) -> None:
        self._pg = pg
        self._timeout = timeout
        self._state_dict_fn = state_dict_fn

    def metadata(self) -> str:
        return "<n/a>"  # rendezvous rides the quorum PG; nothing to publish

    def send_checkpoint(
        self, dst_ranks: "List[int]", step: int, state_dict: Any, timeout: float
    ) -> None:
        from torchft_tpu.checkpointing.serialization import _flatten, _leaf_meta

        skeleton, leaves = _flatten(state_dict)
        metas = []
        arrays: List[Optional[np.ndarray]] = []
        for leaf in leaves:
            meta, arr = _leaf_meta(leaf)
            metas.append(meta)
            arrays.append(arr)
        header = np.frombuffer(
            pickle.dumps({"step": step, "skeleton": skeleton, "leaves": metas}),
            dtype=np.uint8,
        )
        for dst in dst_ranks:
            self._pg.send(header, dst, tag=_META_TAG).wait(timeout=timeout)
            for i, arr in enumerate(arrays):
                if arr is not None:
                    self._pg.send(
                        arr.reshape(-1).view(np.uint8), dst, tag=_TENSOR_TAG + i
                    ).wait(timeout=timeout)

    def recv_checkpoint(
        self, src_rank: int, metadata: str, step: int, timeout: float
    ) -> Any:
        header_bytes = self._pg.recv(src_rank, tag=_META_TAG).wait(timeout=timeout)
        header = pickle.loads(header_bytes.tobytes())
        if header["step"] != step:
            raise RuntimeError(
                f"checkpoint step mismatch: expected {step}, got {header['step']}"
            )
        # In-place fast path: receive into the live state dict's buffers.
        inplace_leaves: "Optional[List[Any]]" = None
        if self._state_dict_fn is not None:
            try:
                existing = self._state_dict_fn()
                inplace_leaves = jax.tree_util.tree_flatten(existing)[0]
                if len(inplace_leaves) != len(header["leaves"]):
                    inplace_leaves = None
            except Exception:  # noqa: BLE001 - fall back to fresh alloc
                inplace_leaves = None

        leaves: List[Any] = []
        for i, meta in enumerate(header["leaves"]):
            if meta["kind"] == "object":
                leaves.append(meta["value"])
                continue
            raw = self._pg.recv(src_rank, tag=_TENSOR_TAG + i).wait(timeout=timeout)
            arr = raw.view(np.dtype(meta["dtype"])).reshape(meta["shape"])
            if (
                inplace_leaves is not None
                and isinstance(inplace_leaves[i], np.ndarray)
                and inplace_leaves[i].shape == arr.shape
                and inplace_leaves[i].dtype == arr.dtype
            ):
                inplace_leaves[i][...] = arr
                leaves.append(inplace_leaves[i])
            else:
                leaves.append(arr.copy())
        treedef = jax.tree_util.tree_structure(header["skeleton"])
        return jax.tree_util.tree_unflatten(treedef, leaves)
