"""HTTP checkpoint transport: pull-based live weight streaming.

Analog of the reference HTTP transport
(reference: torchft/checkpointing/http_transport.py:73-299): each worker runs
a daemon HTTP server; ``send_checkpoint`` stages the state dict (host copies)
under an RWLock and serves ``GET /checkpoint/{step}/{full|metadata|chunk_i}``;
receivers fetch the full stream or parallel-fetch round-robin chunks with a
thread pool.  The RWLock guarantees the staged snapshot cannot be replaced
mid-serve; ``disallow_checkpoint`` retires it before the optimizer mutates
parameters.

Striped heal (ISSUE 15, docs/architecture.md "Striped heal"): heal
snapshots can instead stage as a cut-through fragment stream
(``send_checkpoint_streamed`` — header first, digest manifest last) and
a healer stripes disjoint fragment ranges across every max-step quorum
peer (``recv_checkpoint_striped`` — per-fragment failover, delta diffs,
decode overlapping wire into retained ``into=`` buffers), all over the
shared fragment plane (``checkpointing/fragments.py``).
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, List, Optional

from torchft_tpu.checkpointing import fragdata as _fragdata
from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.retry import RetryPolicy
from torchft_tpu.utils.rwlock import RWLock

logger = logging.getLogger(__name__)

# Checkpoint fetch retry: the healer and the sender learn the quorum
# simultaneously, so the sender may still be device->host staging the
# snapshot — poll through retryable 503s (and connection errors during a
# sender restart) with jittered backoff until the receiver's deadline.
# Permanent failures (404 bad path / chunk range) fail immediately.
#: Staged-snapshot slots kept live at once (heal steps + reshard epochs);
#: oldest-inserted evicts first.  4 covers a heal and a reshard in flight
#: plus one superseded generation of each.
_MAX_STAGED = 4

_FETCH_POLICY = RetryPolicy(
    name="transport.http.fetch",
    base_delay=0.05,
    multiplier=2.0,
    max_delay=1.0,
    retry_if=lambda e: (
        e.code == 503
        if isinstance(e, urllib.error.HTTPError)
        else isinstance(e, (urllib.error.URLError, ConnectionError, OSError))
    ),
)


class _Staged:
    """One staged snapshot slot.

    ``complete=False`` is the serving tier's CUT-THROUGH state: the
    document is still streaming in fragment by fragment
    (``stage_streamed_part``).  While incomplete, a missing ``frag_*``
    resource is a retryable 503 (the child/client polls until the relay
    stages it — that IS the cut-through overlap) and whole-document
    resources (``full``/``metadata``/``chunk_*``) 503 too: a torn
    version must never serve.  ``pooled`` tracks bufpool-backed buffers
    this slot owns; they return to the pool when the slot is retired.

    ``grace``: streamed HEAL slots hold serialized BYTES — immutable
    copies, unlike the legacy host-array snapshot that aliases the live
    optimizer state — so they may legally outlive the step commit.  A
    positive grace survives that many ``disallow_checkpoint`` rounds
    before retiring, which keeps a striped healer's multi-request fetch
    window open across the sources' commit instead of tearing it at the
    first fast peer's ``should_commit``.
    """

    __slots__ = ("sd", "num_chunks", "complete", "pooled", "grace")

    def __init__(
        self,
        sd: Any,
        num_chunks: int = 1,
        complete: bool = True,
        grace: int = 0,
    ):
        self.sd = sd
        self.num_chunks = num_chunks
        self.complete = complete
        self.pooled: "List[Any]" = []
        self.grace = grace

    def release(self) -> None:
        from torchft_tpu.utils.bufpool import POOL

        for buf in self.pooled:
            POOL.give(buf)
        self.pooled = []


class _HTTPServerIPv6(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    daemon_threads = True


def _make_server() -> ThreadingHTTPServer:
    # IPv6 dual-stack when available (reference: torchft/http.py:5-7).
    try:
        return _HTTPServerIPv6(("::", 0), _Handler)
    except OSError:
        return ThreadingHTTPServer(("0.0.0.0", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Idle keep-alive reap: persistent fetcher connections (serving tier,
    # serving/fetcher.py) would otherwise pin one server thread each for
    # the life of the client; a timed-out WAIT for the next request
    # closes the connection.  Scoped to the between-requests wait only
    # (re-armed below, disarmed before serving): an in-flight response
    # body — a multi-GB heal stream stalling on a congested link — must
    # block like it always did, not die at the idle timeout.
    timeout = 30.0
    transport: "HTTPTransport"  # injected per-server subclass attr

    def handle_one_request(self) -> None:
        self.connection.settimeout(self.timeout)
        super().handle_one_request()

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet
        logger.debug("http: " + fmt, *args)

    def _retry_later(self, message: str) -> None:
        # Retryable 503 WITHOUT closing the connection (``send_error``
        # sends ``Connection: close``): the cut-through pollers re-ask
        # the same keep-alive connection every few ms — a reconnect per
        # poll would dominate the poll itself at WAN RTTs.
        body = message.encode("utf-8", "replace")
        self.send_response(503, "retry later")
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except BrokenPipeError:
            pass

    def _send_bytes(self, body: bytes, content_type: str) -> None:
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except BrokenPipeError:
            pass

    def _serve_store_catalog(self, transport: "HTTPTransport") -> None:
        """``/store/versions``: this rank's durable-store restore
        inventory (version -> cut id, fragment list, digest-valid
        fragments) for fleet-wide cold-start cut selection."""
        import json

        store = transport._store
        if store is None:
            self.send_error(404, "no durable store attached")
            return
        try:
            body = json.dumps(store.catalog()).encode()
        except Exception as e:
            self.send_error(503, f"store catalog unavailable: {e}")
            return
        self._send_bytes(body, "application/json")

    def _serve_from_store(
        self, transport: "HTTPTransport", step: int, what: str
    ) -> bool:
        """Serve a ``frag_*`` resource for a version that is NOT
        RAM-staged from the attached durable store.  Returns True when a
        response (200 or permanent 404) was written; False falls through
        to the retryable 503 (the version may simply be staging late).

        Called under the staged read lock — disk reads are local and
        bounded, and the lock is writer-priority so stagers stay live.
        """
        from torchft_tpu.checkpointing import fragments as frags

        store = transport._store
        if store is None or not what.startswith("frag_"):
            return False
        name = what[len("frag_"):]
        t0_ns = time.time_ns()
        if name == frags.MANIFEST_FRAG:
            body = store.manifest_bytes(step)
            if body is None:
                return False
        elif name == frags.HEADER_FRAG:
            manifest = store.manifest(step)
            if manifest is None:
                return False
            body = ser.serialize(
                {k: v for k, v in manifest.items() if k != "digests"}
            )
        else:
            if store.manifest(step) is None:
                return False
            frag = store.fragment(step, name)
            if frag is None:
                # Version known but this blob is torn/missing: permanent
                # 404 so the striped restorer fails over to another disk
                # immediately instead of polling a hole.
                self.send_error(404, "fragment missing or torn on disk")
                return True
            body = frag
        self._send_bytes(body, "application/octet-stream")
        _metrics.CHECKPOINT_BYTES.labels(
            transport="http", direction="send"
        ).inc(len(body))
        _flightrec.record(
            "checkpoint.http.send", start_ns=t0_ns, step=step,
            bytes=len(body), resource=what, source="store",
        )
        return True

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        # request received: the idle-reap timeout must not bound the
        # serve itself (see class docstring; re-armed per request above)
        self.connection.settimeout(None)
        transport = self.server.transport  # type: ignore[attr-defined]
        parts = self.path.strip("/").split("/")
        # /store/versions — the durable store's restore catalog (plain
        # JSON, not a framed RPC: the wire-schema lock is untouched).
        if parts == ["store", "versions"]:
            self._serve_store_catalog(transport)
            return
        # /nativeport — native fragment data-plane discovery: 200 + port
        # when this node mirrors frag_* payloads into the C++ server,
        # 404 = python-only node.  Clients cache either definitive
        # answer (checkpointing/fragdata.py _resolve_port).
        if parts == ["nativeport"]:
            native = transport._frag_native
            if native is None:
                self.send_error(404, "no native data plane")
            else:
                self._send_bytes(str(native.port).encode(), "text/plain")
            return
        # /checkpoint/{step}/{what}
        if len(parts) != 3 or parts[0] != "checkpoint":
            self.send_error(404, "unknown path")
            return
        try:
            step = int(parts[1])
        except ValueError:
            self.send_error(400, "bad step")
            return
        what = parts[2]
        if what.startswith("frag_"):
            # Cut-through long-poll: when the step is STREAMING in and
            # this fragment hasn't landed yet, block briefly server-side
            # until the relay stages it — a child's fragment request
            # then costs one round trip, not a client poll loop whose
            # backoff would add dead time between fragment arrivals.
            # Returns immediately for complete/absent steps (those take
            # the plain 404/503 paths below).  Its read-lock timeout
            # maps to the same retryable busy-503 every other lock
            # timeout in this request takes, never an unhandled raise.
            # Client-driven park window (X-TFT-Poll-Ms): a cut-through
            # chain's child would rather wait here — woken the moment
            # the fragment stages — than eat a 503 + retry-ladder cycle
            # that duplicates request load exactly when the parent is
            # busiest.  Absent/garbage header keeps the 250 ms default.
            try:
                poll_ms = float(
                    self.headers.get("X-TFT-Poll-Ms") or 250.0
                )
            except (TypeError, ValueError):
                poll_ms = 250.0
            max_wait = min(max(poll_ms, 0.0), 5000.0) / 1e3
            try:
                transport.await_streamed_part(
                    step, f"frag:{what[len('frag_'):]}", max_wait=max_wait
                )
            except TimeoutError:
                self.send_error(503, "checkpoint busy")
                return
        try:
            # Hold the read lock for the whole serve so the snapshot can't be
            # retired mid-stream (reference http_transport.py:77-131).
            with transport._staged_lock.r_lock(timeout=transport._lock_timeout):
                staged = transport._staged.get(step)
                if staged is None:
                    # Not in RAM: a cold-start restorer may still be able
                    # to serve this version from the attached durable
                    # fragment store (blobs digest-verified at read; a
                    # torn blob 404s so the striped fetch fails over).
                    if self._serve_from_store(transport, step, what):
                        return
                    # Healer raced the sender's staging: retryable 503 (the
                    # receiver polls until its deadline). Permanent problems
                    # (bad path, chunk out of range) stay 404 and fail fast.
                    self._retry_later(
                        f"no checkpoint staged for step {step}"
                    )
                    return
                state_dict, num_chunks = staged.sd, staged.num_chunks
                raw: "Optional[memoryview]" = None
                if not staged.complete and not what.startswith("frag_"):
                    # A streaming (cut-through) slot serves ONLY its
                    # staged fragments: a whole-document read of a torn
                    # version must never complete — poll until finished.
                    self._retry_later(
                        f"step {step} is still streaming in"
                    )
                    return
                if what == "full":
                    indices = None
                elif what == "metadata":
                    indices = []
                elif what.startswith("chunk_"):
                    idx = int(what[len("chunk_"):])
                    chunks = ser.split_chunks(ser.num_leaves(state_dict), num_chunks)
                    if idx >= len(chunks):
                        self.send_error(404, "chunk out of range")
                        return
                    indices = chunks[idx]
                elif what.startswith("frag_"):
                    # Version-keyed fragment serving (serving/ tier): the
                    # staged doc maps "frag:<name>" to one fragment.  A
                    # fragment staged as raw wire bytes (publisher encode
                    # or relay cut-through passthrough) is served
                    # VERBATIM — no serialize pass, Content-Length is the
                    # buffer length; a decoded sub-dict takes the pytree
                    # path.  A missing name on a COMPLETE document is a
                    # permanent 404 (the staged manifest names every
                    # fragment); on a streaming document it is the
                    # retryable not-yet-relayed 503 — that poll IS the
                    # cut-through overlap.
                    frag = state_dict.get(f"frag:{what[len('frag_'):]}")
                    if frag is None:
                        if not staged.complete:
                            self._retry_later(
                                f"fragment {what} of step {step} not "
                                f"relayed yet"
                            )
                        else:
                            self.send_error(404, "unknown fragment")
                        return
                    raw = ser.raw_view(frag)
                    state_dict = frag
                    indices = None
                elif what.startswith("part_"):
                    # Reshard slice-diff serving (parallel/layout.py): the
                    # staged doc maps "for:<rank>" to the slices planned
                    # for that destination; serve exactly that sub-dict so
                    # the wire carries only the destination's missing
                    # intervals.  An empty sub-dict (nothing routed through
                    # this source) is a valid, tiny payload — NOT a 404 —
                    # so a racing fetcher can distinguish "staged, nothing
                    # for you" from "not staged yet" (503 above).
                    try:
                        part = int(what[len("part_"):])
                    except ValueError:
                        self.send_error(400, "bad part rank")
                        return
                    state_dict = state_dict.get(f"for:{part}", {})
                    indices = None
                else:
                    self.send_error(404, "unknown resource")
                    return
                # Stream straight to the socket: no materialized copy per
                # fetcher (multi-GB state dicts, N concurrent healers).
                # Raw passthrough fragments skip the serialize pass
                # entirely — the relay's verified bytes go out verbatim.
                if raw is not None:
                    total = len(raw)

                    def writer(out: Any, _raw: memoryview = raw) -> None:
                        out.write(_raw)

                else:
                    total, writer = ser.prepare(
                        state_dict, chunk_indices=indices
                    )
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(total))
                self.end_headers()
                t0 = time.perf_counter()
                t0_ns = time.time_ns()
                writer(self.wfile)
                _metrics.CHECKPOINT_BYTES.labels(
                    transport="http", direction="send"
                ).inc(total)
                _metrics.CHECKPOINT_DURATION.labels(
                    transport="http", direction="send"
                ).observe(time.perf_counter() - t0)
                _flightrec.record(
                    "checkpoint.http.send", start_ns=t0_ns, step=step,
                    bytes=total, resource=what,
                )
                # Distributed tracing: the healing destination sends its
                # round context as a ``traceparent`` header; the source's
                # serve lands as a heal.send span IN THE DESTINATION'S
                # TRACE — source and destination of one heal share a
                # trace (docs/observability.md "Distributed tracing").
                tracer = _tracing.get_tracer()
                if tracer is not None:
                    ctx = _tracing.TraceContext.from_traceparent(
                        self.headers.get("traceparent")
                    )
                    if ctx is not None and ctx.sampled:
                        tracer.export_span(
                            name="heal.send",
                            trace_id=ctx.trace_id,
                            parent_span_id=ctx.span_id,
                            start_ns=t0_ns,
                            end_ns=time.time_ns(),
                            attributes={
                                "transport": "http",
                                "step": step,
                                "bytes": total,
                                "resource": what,
                            },
                        )
        except TimeoutError:
            self.send_error(503, "checkpoint busy")
        except BrokenPipeError:
            pass


class HTTPTransport(CheckpointTransport[Any]):
    """Pull-based checkpoint transport over HTTP.

    Args:
        timeout: default lock/serve timeout.
        num_chunks: if > 0, receivers parallel-fetch this many round-robin
            leaf chunks; 0 fetches one full stream.
        state_dict_fn: optional callable returning a same-structure state
            dict whose numpy buffers are received into — the in-place
            warm-page fast path (PGTransport parity; cold allocations
            page-fault during recv and halve effective bandwidth).
    """

    #: This transport can serve the live-reshard slice-diff protocol
    #: (multi-slot staging + ``part_<rank>`` resources + ``resource=``
    #: fetches); parallel/layout.py gates data-moving switches on it.
    supports_reshard = True

    #: This transport can stage/receive the striped fragment heal
    #: protocol (ISSUE 15: ``send_checkpoint_streamed`` +
    #: ``recv_checkpoint_striped``); the Manager gates the streamed heal
    #: path on this being literally ``True`` so duck-typed test doubles
    #: keep the legacy whole-document path.
    supports_striped_heal = True

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        state_dict_fn: "Optional[Callable[[], Any]]" = None,
        max_staged: int = _MAX_STAGED,
        native: "Optional[bool]" = None,
    ) -> None:
        self._lock_timeout = timeout
        self._num_chunks = num_chunks
        self._state_dict_fn = state_dict_fn
        # Durable fragment store (checkpointing/store.py): when attached,
        # versions absent from RAM serve their fragments from disk —
        # cold-start restore rides the exact same frag_* resources and
        # striped fetch path as live heal.
        self._store: "Optional[Any]" = None
        # Staged-slot budget: heal/reshard transports keep the default;
        # the weight-serving tier sizes it to its version window so a
        # burst of publishes cannot retire a version clients still fetch.
        self._max_staged = max(int(max_staged), 1)
        # Staged snapshots keyed by step.  Heal staging uses the real
        # (>= 0) step and is retired per step by disallow_checkpoint();
        # live-reshard staging (parallel/layout.py) uses NEGATIVE keys
        # derived from the layout epoch so it survives the per-step heal
        # retirement until the switch commits or rolls back.  Bounded:
        # oldest slots are evicted past _MAX_STAGED.
        self._staged: "dict[int, _Staged]" = {}
        # writer_priority: staging/retirement must acquire in bounded
        # time even under a dense fetch storm (the serving tier's
        # 503-polling clients keep the read side continuously occupied —
        # a reader-preferring lock starves the stager forever).
        self._staged_lock = RWLock(timeout=timeout, writer_priority=True)
        # wakes fragment long-pollers (await_streamed_part) whenever the
        # staged set changes — never held together with _staged_lock
        self._stream_cond = threading.Condition()
        self._server = _make_server()
        self._server.transport = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            # small poll interval: shutdown() blocks until the serve loop
            # polls, and transport teardown sits on the recovery-latency
            # critical path (default 0.5s poll = up to 0.5s per shutdown)
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            name="torchft_http",
            daemon=True,
        )
        self._thread.start()
        host = socket.gethostname()
        self._address = f"http://{host}:{self._server.server_address[1]}"
        # Native zero-copy fragment DATA plane: raw ``frag:*`` staging is
        # mirrored into a C++ sidecar server (native/fragserver.cc) that
        # serves payload bytes via writev out of pooled buffers, GIL-free.
        # Python keeps every control decision — plans, manifests, staging
        # lifecycle, telemetry — and advertises the data port at
        # ``/nativeport``.  ``native=None`` follows the
        # TORCHFT_FRAG_NATIVE gate; any create failure degrades this node
        # to python-only serving (the mirror is an accelerator, never a
        # correctness dependency).
        self._frag_native: "Optional[_fragdata.FragDataServer]" = None
        if _fragdata.enabled() if native is None else bool(native):
            try:
                self._frag_native = _fragdata.FragDataServer()
            except Exception:
                logger.warning(
                    "native fragment data plane unavailable; "
                    "serving fragments from Python",
                    exc_info=True,
                )

    def metadata(self) -> str:
        return self._address

    def attach_store(self, store: Any) -> None:
        """Expose a durable :class:`~torchft_tpu.checkpointing.store.
        FragmentStore` through this server: peers' cold-start restores
        fetch ``frag_*`` resources of spilled versions (and the
        ``/store/versions`` catalog) exactly like a live heal."""
        self._store = store

    def send_checkpoint(
        self, dst_ranks: "List[int]", step: int, state_dict: Any, timeout: float
    ) -> None:
        _faults.check("transport.send", step=step)
        # Pull transport: stage a host snapshot; receivers fetch within their
        # own timeout. Device arrays are copied to host once here.
        import numpy as np
        import jax

        t0_ns = time.time_ns()
        host_sd = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "__array__") else x, state_dict
        )
        with self._staged_lock.w_lock(timeout=timeout):
            self._put_locked(step, _Staged(host_sd, max(self._num_chunks, 1)))
        self._native_mirror_complete(step, host_sd)
        self._wake_stream_waiters()
        _flightrec.record(
            "checkpoint.http.stage", start_ns=t0_ns, step=step,
            dst_ranks=list(dst_ranks),
        )

    def _put_locked(self, step: int, staged: _Staged) -> None:
        old = self._staged.pop(step, None)
        if old is not None:
            old.release()
            self._native_retire(step)
        self._staged[step] = staged
        while len(self._staged) > self._max_staged:
            evicted = next(iter(self._staged))
            self._staged.pop(evicted).release()
            self._native_retire(evicted)

    # -- native data-plane mirror -------------------------------------
    #
    # Every mirror call is best-effort: the native server accelerates
    # raw frag_* serves, but the Python slot remains the source of truth
    # — on any mirror failure peers transparently fall back to the
    # Python data path (fragments._raw_data_plane), so these helpers
    # swallow rather than surface errors.  ``retire`` is non-blocking
    # native-side (in-flight serves recycle their buffer on last deref),
    # so calling it under the staged write lock is safe.

    def _native_retire(self, step: int) -> None:
        if self._frag_native is not None:
            try:
                self._frag_native.retire(step)
            except Exception:
                logger.debug("native frag retire failed", exc_info=True)

    def _native_begin(self, step: int) -> None:
        if self._frag_native is not None:
            try:
                self._frag_native.begin(step)
            except Exception:
                logger.debug("native frag begin failed", exc_info=True)

    def _native_stage(self, step: int, key: Any, value: Any) -> None:
        srv = self._frag_native
        if (
            srv is None
            or not isinstance(key, str)
            or not key.startswith("frag:")
        ):
            return
        raw = ser.raw_view(value)
        if raw is None:
            return  # control parts (header/manifest dicts) stay Python
        try:
            srv.stage(step, "frag_" + key[len("frag:"):], raw)
        except Exception:
            logger.debug("native frag stage failed", exc_info=True)

    def _native_finish(self, step: int) -> None:
        if self._frag_native is not None:
            try:
                self._frag_native.finish(step)
            except Exception:
                logger.debug("native frag finish failed", exc_info=True)

    def _native_mirror_complete(self, step: int, sd: Any) -> None:
        """Mirror the raw ``frag:*`` parts of a COMPLETE document in one
        begin/stage*/finish stroke (the ``send_checkpoint`` path — e.g. a
        pre-serialized fragment document staged whole)."""
        if self._frag_native is None or not isinstance(sd, dict):
            return
        raws = [
            (k, ser.raw_view(v))
            for k, v in sd.items()
            if isinstance(k, str) and k.startswith("frag:")
        ]
        raws = [(k, r) for k, r in raws if r is not None]
        if not raws:
            return
        self._native_begin(step)
        for k, raw in raws:
            self._native_stage(step, k, raw)
        self._native_finish(step)

    # -- per-fragment (cut-through) staging ---------------------------------
    #
    # The serving tier's streaming relay (serving/replica.py, ISSUE 14)
    # stages one version FRAGMENT BY FRAGMENT: children and clients poll
    # ``frag_<name>`` and get each fragment the moment it lands (503
    # while missing), while whole-document reads 503 until the version
    # is finished — cut-through can never serve a torn version.

    def _wake_stream_waiters(self) -> None:
        with self._stream_cond:
            self._stream_cond.notify_all()

    def await_streamed_part(
        self, step: int, key: str, max_wait: float
    ) -> None:
        """Server-side fragment long-poll: block up to ``max_wait``
        while the slot for ``step`` is STREAMING and ``key`` has not
        landed.  Returns immediately for absent/complete slots and when
        the part arrives — the caller re-reads state under the lock and
        takes the normal serve/503/404 path."""
        deadline = time.monotonic() + max_wait
        while True:
            with self._staged_lock.r_lock(timeout=self._lock_timeout):
                staged = self._staged.get(step)
                if staged is None or staged.complete or key in staged.sd:
                    return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            with self._stream_cond:
                self._stream_cond.wait(min(remaining, 0.05))

    def begin_streamed_checkpoint(
        self,
        step: int,
        state_dict: Any,
        timeout: "Optional[float]" = None,
        grace: int = 1,
    ) -> None:
        """Stage an INCOMPLETE document (normally just the manifest);
        fragments arrive via :meth:`stage_streamed_part`.  ``grace``:
        ``disallow_checkpoint`` rounds the finished slot survives (see
        ``_Staged`` — streamed slots hold immutable bytes, so one round
        of grace keeps a striped healer's window open across the
        sources' commit)."""
        with self._staged_lock.w_lock(timeout=timeout or self._lock_timeout):
            self._put_locked(
                step, _Staged(dict(state_dict), 1, complete=False, grace=grace)
            )
        self._native_begin(step)
        for k, v in dict(state_dict).items():
            self._native_stage(step, k, v)
        self._wake_stream_waiters()

    def stage_streamed_part(
        self,
        step: int,
        key: str,
        value: Any,
        pooled: bool = False,
        timeout: "Optional[float]" = None,
    ) -> None:
        """Add one part (``frag:<name>`` -> raw wire bytes) to a
        streaming slot.  ``pooled=True`` transfers ownership of a
        bufpool-backed buffer to the slot (returned to the pool on
        retirement).  Raises ``KeyError`` when the slot was evicted
        mid-stream (version window overrun by newer publishes)."""
        with self._staged_lock.w_lock(timeout=timeout or self._lock_timeout):
            staged = self._staged.get(step)
            if staged is None:
                raise KeyError(
                    f"streamed staging slot for step {step} was evicted"
                )
            staged.sd[key] = value
            if pooled:
                staged.pooled.append(value)
        self._native_stage(step, key, value)
        self._wake_stream_waiters()

    def finish_streamed_checkpoint(
        self, step: int, timeout: "Optional[float]" = None
    ) -> None:
        """Mark a streaming slot complete: whole-document reads serve."""
        with self._staged_lock.w_lock(timeout=timeout or self._lock_timeout):
            staged = self._staged.get(step)
            if staged is None:
                raise KeyError(
                    f"streamed staging slot for step {step} was evicted"
                )
            staged.complete = True
        self._native_finish(step)
        self._wake_stream_waiters()

    def streamed_parts(self, step: int) -> "Optional[set]":
        """Part keys of a still-streaming slot (``None`` when absent or
        already complete) — lets an interrupted relay pull RESUME from
        the fragments it already verified instead of refetching."""
        with self._staged_lock.r_lock(timeout=self._lock_timeout):
            staged = self._staged.get(step)
            if staged is None or staged.complete:
                return None
            return set(staged.sd)

    def copy_staged_part(
        self, step: int, key: str, timeout: "Optional[float]" = None
    ) -> "Optional[Any]":
        """Pooled copy of one raw part of a COMPLETE staged document
        (``None`` when absent or not raw wire bytes) — the delta relay
        pull reuses unchanged fragments from version v-1 without wire.
        A copy, not a shared reference: the source slot may retire (and
        return ITS buffer to the pool) while the new slot still serves.
        """
        import numpy as np

        from torchft_tpu.utils.bufpool import POOL

        with self._staged_lock.r_lock(timeout=timeout or self._lock_timeout):
            staged = self._staged.get(step)
            if staged is None or not staged.complete:
                return None
            raw = ser.raw_view(staged.sd.get(key))
            if raw is None:
                return None
            buf = POOL.take(len(raw), np.uint8)
            buf[:] = np.frombuffer(raw, dtype=np.uint8)
            return buf

    def send_checkpoint_streamed(
        self,
        dst_ranks: "List[int]",
        step: int,
        state_dict: Any,
        timeout: float,
        fragments: "Optional[int]" = None,
    ) -> "dict":
        """Stage a heal snapshot as a CUT-THROUGH fragment stream
        (docs/architecture.md "Striped heal"): the digest-less header
        serves immediately, each fragment serves the moment it encodes
        (a healer's striped fetch overlaps this host's snapshot/encode),
        and the digest manifest lands last.  Returns the manifest.

        The step protocol calls this instead of :meth:`send_checkpoint`
        when streamed heal is enabled (``TORCHFT_HEAL_STREAM``); the
        staged document serves the same ``frag_*`` resources the serving
        tier uses, so the whole fragment fetch plane applies."""
        from torchft_tpu.checkpointing import fragments as frags

        _faults.check("transport.send", step=step)
        t0_ns = time.time_ns()
        manifest = frags.stage_heal_checkpoint(
            self, step, state_dict, fragments=fragments, timeout=timeout
        )
        _flightrec.record(
            "checkpoint.http.stage", start_ns=t0_ns, step=step,
            dst_ranks=list(dst_ranks),
            fragments=len(manifest.get("fragments", ())),
        )
        return manifest

    def recv_checkpoint_striped(
        self,
        sources: "List[str]",
        step: int,
        timeout: float,
        local_state_fn: "Optional[Callable[[], Any]]" = None,
        delta: "Optional[bool]" = None,
        plane: str = "heal",
    ) -> "tuple[Any, dict]":
        """Striped multi-source heal receive (ISSUE 15).

        ``plane`` names the provenance plane these transfers audit
        under: ``heal`` for live heals, ``restore`` when the sources
        are durable-store disks (the cold-start path).

        ``sources`` are transport base addresses in trust order —
        ``sources[0]`` is the quorum-assigned PRIMARY whose manifest
        defines truth; the rest are max-step peers whose bitwise-
        replicated state lets the healer stripe disjoint fragment
        ranges across every uplink at once.  Per-fragment failover: a
        dead/slow/poisoned stripe source's fragments move to the
        survivors (ultimately the primary).

        Two modes:

        - **delta** (``TORCHFT_HEAL_DELTA``, on, and a local state
          snapshot is available): fetch the primary's digest manifest,
          hash the local state into the same fragment layout, and fetch
          ONLY the fragments whose digest moved — rejoin wire scales
          with the update delta, not model size.  Every fetched
          fragment verifies against the primary digest on receipt.
        - **full**: fetch the digest-less header first (served before
          the source has encoded anything), stripe ALL fragments while
          the source is still encoding, then verify the recorded
          hashes against the primary's manifest (staged last) and
          refetch any mismatch from the primary alone.

        Decode of fragment *i* (straight into the retained ``into=``
        leaf buffers) overlaps the wire of every in-flight stripe.

        Returns ``(state_dict, info)`` where ``info`` carries the phase
        split (``heal_manifest``/``heal_diff``/``heal_wire``/
        ``heal_decode``), mode, fragment counts and wire bytes.  Falls
        back to the legacy single-source whole-document fetch when the
        primary's staged document has no fragments (mixed-config
        fleet)."""
        import urllib.error as _uerr

        from torchft_tpu.checkpointing import fragments as frags
        from torchft_tpu.ops.codec_pool import merged_seconds
        from torchft_tpu.utils.bufpool import POOL
        from torchft_tpu.utils.env import env_bool, env_float

        _faults.check("transport.recv", step=step)
        if not sources:
            raise ValueError("striped heal: no sources")
        primary = sources[0]
        deadline = time.monotonic() + timeout
        phases: "dict[str, float]" = {}
        info: "dict[str, Any]" = {"sources": len(sources)}
        with _flightrec.track(
            "checkpoint.http.recv", step=step, src_rank=0,
            sources=len(sources),
        ) as op:
            local_state, into = self._build_into_map(local_state_fn)
            use_delta = (
                delta
                if delta is not None
                else env_bool("TORCHFT_HEAL_DELTA", True)
            ) and local_state is not None

            # -- manifest phase: the primary defines truth.  Delta needs
            # the digests (staged last — waits out the source's encode);
            # full mode starts from the digest-less header (staged
            # first) so the stripe overlaps the source's encode.
            t0 = time.perf_counter()
            want = frags.MANIFEST_FRAG if use_delta else frags.HEADER_FRAG
            try:
                mbuf = frags.fetch_raw(
                    primary, step, f"frag_{want}",
                    timeout=max(deadline - time.monotonic(), 0.001),
                    role="heal",
                )
            except _uerr.HTTPError as e:
                if e.code != 404:
                    raise
                # Source staged a legacy whole-document snapshot (mixed
                # config): take the classic path against the primary.
                result = self._recv_checkpoint(
                    0, primary, step,
                    max(deadline - time.monotonic(), 0.001),
                )
                op.update(mode="legacy")
                info.update(mode="legacy", phases=phases)
                return frags.maybe_decode_heal_doc(result), info
            try:
                manifest = frags.decode_manifest(mbuf)
            finally:
                POOL.give(mbuf)
            phases["heal_manifest"] = time.perf_counter() - t0

            names = [str(n) for n in manifest["fragments"]]
            num_leaves = int(manifest["num_leaves"])

            # TORCHFT_PLAN_VERIFY: the stripe assignment is a plan —
            # validate its coverage (disjoint, exhaustive round-robin
            # leaf ranges across the resolved sources) before any
            # fragment goes on the wire.
            from torchft_tpu.analysis import plan_verify as _pv

            if _pv.enabled():
                from torchft_tpu.analysis import plan_ir as _pir

                _pv.check_live(
                    _pir.stripe_ir(sources, len(names), num_leaves,
                                   step=step)
                )

            # -- diff phase: hash the local state into the source's
            # fragment layout; identical digests need no wire at all.
            t0 = time.perf_counter()
            changed = list(names)
            leaves: "dict[int, Any]" = {}
            if use_delta:
                import jax

                local_leaves = jax.tree_util.tree_flatten(local_state)[0]
                if len(local_leaves) == num_leaves:
                    _n, mine = frags.local_fragment_digests(
                        local_state, len(names)
                    )
                    src_digests = manifest.get("digests") or {}
                    changed = [
                        n for n in names
                        if src_digests.get(n) != mine.get(n)
                    ]
                    for name in names:
                        if name not in changed:
                            for slot in frags.fragment_slots(
                                name, num_leaves, len(names)
                            ):
                                leaves[slot] = local_leaves[slot]
            phases["heal_diff"] = time.perf_counter() - t0
            mode = "delta" if use_delta else "full"

            # -- wire + decode: striped fetch across every source,
            # decode of fragment i overlapping the wire of the rest.
            decode_busy = [0.0]
            decode_failed: "List[str]" = []

            def _decode(name: str, buf: Any, _sha: str) -> None:
                t_d = time.perf_counter()
                try:
                    sub_into = (
                        frags.fragment_into_map(
                            name, num_leaves, len(names), into
                        )
                        if into
                        else None
                    )
                    decoded = frags.decode_fragment(buf, into=sub_into)
                    # Trust boundary: the slot keys come from the (in
                    # full mode, not-yet-verified) fragment bytes — a
                    # corrupt fragment claiming FOREIGN slots could
                    # otherwise overwrite other fragments' leaves with
                    # garbage the per-fragment repair pass would never
                    # restore.  Anything but exactly this fragment's
                    # round-robin slot set is a decode failure.
                    expected = set(
                        frags.fragment_slots(name, num_leaves, len(names))
                    )
                    if set(decoded) != expected:
                        raise ValueError(
                            f"fragment {name}: slots {sorted(decoded)[:4]}"
                            f"... do not match its layout"
                        )
                    leaves.update(decoded)
                except Exception:  # noqa: BLE001 - repaired below
                    # Garbage that happened to land before verification
                    # (full mode verifies AFTER the stripe): remember
                    # the fragment for the digest-verified repair pass.
                    decode_failed.append(name)
                finally:
                    POOL.give(buf)
                decode_busy[0] += time.perf_counter() - t_d

            t0 = time.perf_counter()
            failover_s = env_float(
                "TORCHFT_HEAL_FAILOVER_S", 2.0, minimum=0.05
            )
            stats = frags.striped_fetch(
                sources, step, changed, deadline,
                digests=manifest.get("digests") if use_delta else None,
                source_budget=failover_s,
                on_buf=_decode,
                plane=plane,
            )
            wire_bytes = stats["wire_bytes"]
            failovers = stats["failovers"]
            sources_used = set(stats["sources_used"])

            if not use_delta and changed:
                # Deferred verify: the digest manifest (staged last —
                # the source has finished encoding by the time the
                # stripe drains) checks every recorded hash.
                mfull = frags.fetch_raw(
                    primary, step, f"frag_{frags.MANIFEST_FRAG}",
                    timeout=max(deadline - time.monotonic(), 0.001),
                    role="heal",
                )
                try:
                    manifest = frags.decode_manifest(mfull)
                finally:
                    POOL.give(mfull)
            digests = manifest.get("digests") or {}
            bad = sorted(
                set(decode_failed)
                | {
                    n for n in changed
                    if n in stats["hashes"]
                    and digests.get(n, stats["hashes"][n])
                    != stats["hashes"][n]
                }
            )
            if bad:
                # Repair pass: mismatched/undecodable fragments refetch
                # from the PRIMARY alone, digest-verified on receipt; a
                # decode failure here is terminal (the primary's own
                # bytes are truth — there is nothing left to fail over
                # to).
                _metrics.HEAL_FRAG_FAILOVERS.inc(len(bad))
                failovers += len(bad)
                decode_failed.clear()
                restats = frags.striped_fetch(
                    [primary], step, bad, deadline,
                    digests=digests, on_buf=_decode,
                    plane=plane,
                )
                wire_bytes += restats["wire_bytes"]
                sources_used |= set(restats["sources_used"])
                if decode_failed:
                    raise ValueError(
                        f"striped heal: fragments {decode_failed} from "
                        f"the primary verified but failed to decode"
                    )
            loop_wall = time.perf_counter() - t0
            wire_busy = merged_seconds(stats["spans"])
            phases["heal_decode"] = decode_busy[0]
            phases["heal_wire"] = max(
                wire_busy, loop_wall - decode_busy[0], 0.0
            )

            _metrics.HEAL_WIRE_BYTES.labels(mode=mode).inc(wire_bytes)
            # the gauge reports sources that DELIVERED fragments, not
            # the configured list — a degraded stripe (dead peers, all
            # bytes from the primary) must read as 1, not len(sources);
            # a delta heal that fetched nothing still talked to the
            # primary for the manifest, hence the floor of 1
            _metrics.HEAL_STRIPE_SOURCES.set(max(len(sources_used), 1))
            _metrics.HEAL_CHANGED_FRAGMENTS.set(len(changed))
            _metrics.CHECKPOINT_DURATION.labels(
                transport="http", direction="recv"
            ).observe(sum(phases.values()))
            state = frags.assemble(manifest, leaves)
            # provenance: the heal destination now holds every fragment
            # of this version (fetched AND delta-reused — reuse means
            # the local bytes already hash to the source digest)
            from torchft_tpu.checkpointing import provenance as _prov

            h_ms = int(manifest.get("created_ns", 0) // 1_000_000)
            for name in names:
                _prov.note_hold(
                    _prov.frag_id("heal", name), step,
                    digests.get(name, ""), version_ms=h_ms, role="heal",
                )
            info.update(
                mode=mode,
                fragments=len(names),
                changed=len(changed),
                wire_bytes=wire_bytes,
                failovers=failovers,
                sources_used=len(sources_used),
                phases=phases,
            )
            op.update(
                mode=mode, fragments=len(names), changed=len(changed),
                bytes=wire_bytes, failovers=failovers,
            )
        return state, info

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        resource: "Optional[str]" = None,
    ) -> Any:
        """Fetch a staged snapshot from ``metadata``'s server.  With
        ``resource`` (e.g. ``part_<rank>``, the reshard slice-diff
        payload) that single resource is fetched instead of the
        full/chunked stream."""
        _faults.check("transport.recv", step=step)
        # in-flight op for the whole heal fetch: a healer wedged mid-fetch
        # shows up in the flight dump with src/step context
        with _flightrec.track(
            "checkpoint.http.recv", step=step, src_rank=src_rank,
        ):
            return self._recv_checkpoint(
                src_rank, metadata, step, timeout, resource
            )

    def _build_into_map(
        self, state_fn: "Optional[Callable[[], Any]]" = None
    ) -> "tuple[Optional[Any], Optional[dict]]":
        """Snapshot the local state and build the ``{global leaf slot:
        ndarray}`` in-place receive map for ``serialization.deserialize_from``
        (the warm-buffer fast path — cold allocations page-fault during
        the socket reads and roughly halve effective recv bandwidth).

        Only the user-supplied state callable may fail (it is arbitrary
        training code); that fallback is LOUD — logged and counted in
        ``torchft_heal_into_fallbacks_total`` — because silently decoding
        into fresh arrays every heal is a decode-path perf regression,
        not a benign default.  Returns ``(state, into)``, both ``None``
        when no state callable is available."""
        import jax
        import numpy as np

        fn = state_fn if state_fn is not None else self._state_dict_fn
        if fn is None:
            return None, None
        try:
            state = fn()
        except Exception as e:  # noqa: BLE001 - user state fn, but LOUD
            logger.warning(
                "heal recv: state_dict_fn failed (%s: %s); decoding into "
                "freshly allocated arrays this heal",
                type(e).__name__, e,
            )
            _metrics.HEAL_INTO_FALLBACKS.inc()
            return None, None
        existing = jax.tree_util.tree_flatten(state)[0]
        into = {
            i: leaf
            for i, leaf in enumerate(existing)
            if isinstance(leaf, np.ndarray)
        }
        return state, into

    def _recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        resource: "Optional[str]" = None,
    ) -> Any:
        base = f"{metadata}/checkpoint/{step}"
        deadline = time.monotonic() + timeout
        t_recv = time.perf_counter()

        _state, into = self._build_into_map()

        # Trace propagation: the destination's round context rides a
        # ``traceparent`` header so the SOURCE's serve spans join this
        # replica's per-step trace (None when tracing is off/unsampled).
        traceparent = _tracing.current_traceparent()

        def fetch(path: str):
            # Retry/backoff policy: _FETCH_POLICY (module top) — retryable
            # 503s and connection errors poll until the receiver's deadline.
            def attempt(budget: "Optional[float]"):
                t = max(budget if budget is not None else 0.001, 0.001)
                req = urllib.request.Request(
                    f"{base}/{path}",
                    headers=(
                        {"traceparent": traceparent} if traceparent else {}
                    ),
                )
                with urllib.request.urlopen(req, timeout=t) as resp:
                    _metrics.CHECKPOINT_BYTES.labels(
                        transport="http", direction="recv"
                    ).inc(int(resp.headers.get("Content-Length") or 0))
                    return ser.deserialize_from(resp, into=into)

            return _FETCH_POLICY.run(
                attempt,
                timeout=max(deadline - time.monotonic(), 0.001),
                op="transport.http.fetch",
                on_retry=lambda e, n, d: _metrics.CHECKPOINT_RETRIES.labels(
                    transport="http"
                ).inc(),
            )

        def _done() -> None:
            _metrics.CHECKPOINT_DURATION.labels(
                transport="http", direction="recv"
            ).observe(time.perf_counter() - t_recv)

        if resource is not None:
            skeleton, leaves, n = fetch(resource)
            _done()
            return ser.reassemble(skeleton, leaves, n)

        if self._num_chunks <= 0:
            skeleton, leaves, n = fetch("full")
            _done()
            return ser.reassemble(skeleton, leaves, n)

        # Parallel chunk fetch (reference http_transport.py:244-267).
        with ThreadPoolExecutor(max_workers=self._num_chunks) as pool:
            results = list(pool.map(fetch, [f"chunk_{i}" for i in range(self._num_chunks)]))
        _done()
        skeleton, _, n = results[0]
        merged: dict = {}
        for _, leaves, _ in results:
            merged.update(leaves)
        return ser.reassemble(skeleton, merged, n)

    def disallow_checkpoint(self) -> None:
        """Retire heal snapshots (real, >= 0 step keys) before the
        optimizer mutates parameters.  Reshard staging (negative keys)
        stays until its switch commits/rolls back — peers may still be
        mid-fetch when this group's step commits.  Streamed heal slots
        with remaining ``grace`` survive (they hold immutable serialized
        bytes, not aliases of the live state — see ``_Staged``); each
        call burns one grace round so nothing lingers unbounded."""
        retired: "List[int]" = []
        with self._staged_lock.w_lock(timeout=self._lock_timeout):
            for k in [k for k in self._staged if k >= 0]:
                staged = self._staged[k]
                if staged.grace > 0:
                    staged.grace -= 1
                    continue
                self._staged.pop(k).release()
                retired.append(k)
        for k in retired:
            self._native_retire(k)
        self._wake_stream_waiters()

    def retire_checkpoint(self, step: int) -> None:
        """Drop one staged snapshot (the reshard slots' explicit
        retirement path); no-op when absent."""
        with self._staged_lock.w_lock(timeout=self._lock_timeout):
            staged = self._staged.pop(step, None)
            if staged is not None:
                staged.release()
        self._native_retire(step)
        self._wake_stream_waiters()

    def staged_steps(self) -> "List[int]":
        """Step/version keys currently staged (insertion order — the
        eviction order).  The serving tier uses this as "which versions
        do I still hold"; tests assert retention windows with it."""
        with self._staged_lock.r_lock(timeout=self._lock_timeout):
            return list(self._staged)

    def shutdown(self, wait: bool = True) -> None:
        if self._frag_native is not None:
            try:
                self._frag_native.shutdown()
            except Exception:
                pass
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)
