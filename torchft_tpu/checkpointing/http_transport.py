"""HTTP checkpoint transport: pull-based live weight streaming.

Analog of the reference HTTP transport
(reference: torchft/checkpointing/http_transport.py:73-299): each worker runs
a daemon HTTP server; ``send_checkpoint`` stages the state dict (host copies)
under an RWLock and serves ``GET /checkpoint/{step}/{full|metadata|chunk_i}``;
receivers fetch the full stream or parallel-fetch round-robin chunks with a
thread pool.  The RWLock guarantees the staged snapshot cannot be replaced
mid-serve; ``disallow_checkpoint`` retires it before the optimizer mutates
parameters.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, List, Optional

from torchft_tpu.checkpointing import serialization as ser
from torchft_tpu.checkpointing.transport import CheckpointTransport
from torchft_tpu.utils import faults as _faults
from torchft_tpu.utils import flightrecorder as _flightrec
from torchft_tpu.utils import metrics as _metrics
from torchft_tpu.utils import tracing as _tracing
from torchft_tpu.utils.retry import RetryPolicy
from torchft_tpu.utils.rwlock import RWLock

logger = logging.getLogger(__name__)

# Checkpoint fetch retry: the healer and the sender learn the quorum
# simultaneously, so the sender may still be device->host staging the
# snapshot — poll through retryable 503s (and connection errors during a
# sender restart) with jittered backoff until the receiver's deadline.
# Permanent failures (404 bad path / chunk range) fail immediately.
#: Staged-snapshot slots kept live at once (heal steps + reshard epochs);
#: oldest-inserted evicts first.  4 covers a heal and a reshard in flight
#: plus one superseded generation of each.
_MAX_STAGED = 4

_FETCH_POLICY = RetryPolicy(
    name="transport.http.fetch",
    base_delay=0.05,
    multiplier=2.0,
    max_delay=1.0,
    retry_if=lambda e: (
        e.code == 503
        if isinstance(e, urllib.error.HTTPError)
        else isinstance(e, (urllib.error.URLError, ConnectionError, OSError))
    ),
)


class _HTTPServerIPv6(ThreadingHTTPServer):
    address_family = socket.AF_INET6
    daemon_threads = True


def _make_server() -> ThreadingHTTPServer:
    # IPv6 dual-stack when available (reference: torchft/http.py:5-7).
    try:
        return _HTTPServerIPv6(("::", 0), _Handler)
    except OSError:
        return ThreadingHTTPServer(("0.0.0.0", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    transport: "HTTPTransport"  # injected per-server subclass attr

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet
        logger.debug("http: " + fmt, *args)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        transport = self.server.transport  # type: ignore[attr-defined]
        parts = self.path.strip("/").split("/")
        # /checkpoint/{step}/{what}
        if len(parts) != 3 or parts[0] != "checkpoint":
            self.send_error(404, "unknown path")
            return
        try:
            step = int(parts[1])
        except ValueError:
            self.send_error(400, "bad step")
            return
        what = parts[2]
        try:
            # Hold the read lock for the whole serve so the snapshot can't be
            # retired mid-stream (reference http_transport.py:77-131).
            with transport._staged_lock.r_lock(timeout=transport._lock_timeout):
                staged = transport._staged.get(step)
                if staged is None:
                    # Healer raced the sender's staging: retryable 503 (the
                    # receiver polls until its deadline). Permanent problems
                    # (bad path, chunk out of range) stay 404 and fail fast.
                    self.send_error(
                        503,
                        f"no checkpoint staged for step {step}",
                    )
                    return
                state_dict, num_chunks = staged
                if what == "full":
                    indices = None
                elif what == "metadata":
                    indices = []
                elif what.startswith("chunk_"):
                    idx = int(what[len("chunk_"):])
                    chunks = ser.split_chunks(ser.num_leaves(state_dict), num_chunks)
                    if idx >= len(chunks):
                        self.send_error(404, "chunk out of range")
                        return
                    indices = chunks[idx]
                elif what.startswith("frag_"):
                    # Version-keyed fragment serving (serving/ tier): the
                    # staged doc maps "frag:<name>" to one fragment's
                    # sub-dict; serve exactly that fragment so delta
                    # updates move one fragment, not the checkpoint.  A
                    # missing fragment name is a permanent 404 (the
                    # staged manifest names every fragment), distinct
                    # from the retryable not-yet-staged 503 above.
                    frag = state_dict.get(f"frag:{what[len('frag_'):]}")
                    if frag is None:
                        self.send_error(404, "unknown fragment")
                        return
                    state_dict = frag
                    indices = None
                elif what.startswith("part_"):
                    # Reshard slice-diff serving (parallel/layout.py): the
                    # staged doc maps "for:<rank>" to the slices planned
                    # for that destination; serve exactly that sub-dict so
                    # the wire carries only the destination's missing
                    # intervals.  An empty sub-dict (nothing routed through
                    # this source) is a valid, tiny payload — NOT a 404 —
                    # so a racing fetcher can distinguish "staged, nothing
                    # for you" from "not staged yet" (503 above).
                    try:
                        part = int(what[len("part_"):])
                    except ValueError:
                        self.send_error(400, "bad part rank")
                        return
                    state_dict = state_dict.get(f"for:{part}", {})
                    indices = None
                else:
                    self.send_error(404, "unknown resource")
                    return
                # Stream straight to the socket: no materialized copy per
                # fetcher (multi-GB state dicts, N concurrent healers).
                total, writer = ser.prepare(state_dict, chunk_indices=indices)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(total))
                self.end_headers()
                t0 = time.perf_counter()
                t0_ns = time.time_ns()
                writer(self.wfile)
                _metrics.CHECKPOINT_BYTES.labels(
                    transport="http", direction="send"
                ).inc(total)
                _metrics.CHECKPOINT_DURATION.labels(
                    transport="http", direction="send"
                ).observe(time.perf_counter() - t0)
                _flightrec.record(
                    "checkpoint.http.send", start_ns=t0_ns, step=step,
                    bytes=total, resource=what,
                )
                # Distributed tracing: the healing destination sends its
                # round context as a ``traceparent`` header; the source's
                # serve lands as a heal.send span IN THE DESTINATION'S
                # TRACE — source and destination of one heal share a
                # trace (docs/observability.md "Distributed tracing").
                tracer = _tracing.get_tracer()
                if tracer is not None:
                    ctx = _tracing.TraceContext.from_traceparent(
                        self.headers.get("traceparent")
                    )
                    if ctx is not None and ctx.sampled:
                        tracer.export_span(
                            name="heal.send",
                            trace_id=ctx.trace_id,
                            parent_span_id=ctx.span_id,
                            start_ns=t0_ns,
                            end_ns=time.time_ns(),
                            attributes={
                                "transport": "http",
                                "step": step,
                                "bytes": total,
                                "resource": what,
                            },
                        )
        except TimeoutError:
            self.send_error(503, "checkpoint busy")
        except BrokenPipeError:
            pass


class HTTPTransport(CheckpointTransport[Any]):
    """Pull-based checkpoint transport over HTTP.

    Args:
        timeout: default lock/serve timeout.
        num_chunks: if > 0, receivers parallel-fetch this many round-robin
            leaf chunks; 0 fetches one full stream.
        state_dict_fn: optional callable returning a same-structure state
            dict whose numpy buffers are received into — the in-place
            warm-page fast path (PGTransport parity; cold allocations
            page-fault during recv and halve effective bandwidth).
    """

    #: This transport can serve the live-reshard slice-diff protocol
    #: (multi-slot staging + ``part_<rank>`` resources + ``resource=``
    #: fetches); parallel/layout.py gates data-moving switches on it.
    supports_reshard = True

    def __init__(
        self,
        timeout: float = 60.0,
        num_chunks: int = 0,
        state_dict_fn: "Optional[Callable[[], Any]]" = None,
        max_staged: int = _MAX_STAGED,
    ) -> None:
        self._lock_timeout = timeout
        self._num_chunks = num_chunks
        self._state_dict_fn = state_dict_fn
        # Staged-slot budget: heal/reshard transports keep the default;
        # the weight-serving tier sizes it to its version window so a
        # burst of publishes cannot retire a version clients still fetch.
        self._max_staged = max(int(max_staged), 1)
        # Staged snapshots keyed by step.  Heal staging uses the real
        # (>= 0) step and is retired per step by disallow_checkpoint();
        # live-reshard staging (parallel/layout.py) uses NEGATIVE keys
        # derived from the layout epoch so it survives the per-step heal
        # retirement until the switch commits or rolls back.  Bounded:
        # oldest slots are evicted past _MAX_STAGED.
        self._staged: "dict[int, tuple[Any, int]]" = {}
        # writer_priority: staging/retirement must acquire in bounded
        # time even under a dense fetch storm (the serving tier's
        # 503-polling clients keep the read side continuously occupied —
        # a reader-preferring lock starves the stager forever).
        self._staged_lock = RWLock(timeout=timeout, writer_priority=True)
        self._server = _make_server()
        self._server.transport = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            # small poll interval: shutdown() blocks until the serve loop
            # polls, and transport teardown sits on the recovery-latency
            # critical path (default 0.5s poll = up to 0.5s per shutdown)
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            name="torchft_http",
            daemon=True,
        )
        self._thread.start()
        host = socket.gethostname()
        self._address = f"http://{host}:{self._server.server_address[1]}"

    def metadata(self) -> str:
        return self._address

    def send_checkpoint(
        self, dst_ranks: "List[int]", step: int, state_dict: Any, timeout: float
    ) -> None:
        _faults.check("transport.send", step=step)
        # Pull transport: stage a host snapshot; receivers fetch within their
        # own timeout. Device arrays are copied to host once here.
        import numpy as np
        import jax

        t0_ns = time.time_ns()
        host_sd = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "__array__") else x, state_dict
        )
        with self._staged_lock.w_lock(timeout=timeout):
            self._staged[step] = (host_sd, max(self._num_chunks, 1))
            while len(self._staged) > self._max_staged:
                self._staged.pop(next(iter(self._staged)))
        _flightrec.record(
            "checkpoint.http.stage", start_ns=t0_ns, step=step,
            dst_ranks=list(dst_ranks),
        )

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        resource: "Optional[str]" = None,
    ) -> Any:
        """Fetch a staged snapshot from ``metadata``'s server.  With
        ``resource`` (e.g. ``part_<rank>``, the reshard slice-diff
        payload) that single resource is fetched instead of the
        full/chunked stream."""
        _faults.check("transport.recv", step=step)
        # in-flight op for the whole heal fetch: a healer wedged mid-fetch
        # shows up in the flight dump with src/step context
        with _flightrec.track(
            "checkpoint.http.recv", step=step, src_rank=src_rank,
        ):
            return self._recv_checkpoint(
                src_rank, metadata, step, timeout, resource
            )

    def _recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        resource: "Optional[str]" = None,
    ) -> Any:
        base = f"{metadata}/checkpoint/{step}"
        deadline = time.monotonic() + timeout
        t_recv = time.perf_counter()

        into = None
        if self._state_dict_fn is not None:
            try:
                import jax
                import numpy as np

                existing = jax.tree_util.tree_flatten(self._state_dict_fn())[0]
                into = {
                    i: leaf
                    for i, leaf in enumerate(existing)
                    if isinstance(leaf, np.ndarray)
                }
            except Exception:  # noqa: BLE001 - fall back to fresh alloc
                into = None

        # Trace propagation: the destination's round context rides a
        # ``traceparent`` header so the SOURCE's serve spans join this
        # replica's per-step trace (None when tracing is off/unsampled).
        traceparent = _tracing.current_traceparent()

        def fetch(path: str):
            # Retry/backoff policy: _FETCH_POLICY (module top) — retryable
            # 503s and connection errors poll until the receiver's deadline.
            def attempt(budget: "Optional[float]"):
                t = max(budget if budget is not None else 0.001, 0.001)
                req = urllib.request.Request(
                    f"{base}/{path}",
                    headers=(
                        {"traceparent": traceparent} if traceparent else {}
                    ),
                )
                with urllib.request.urlopen(req, timeout=t) as resp:
                    _metrics.CHECKPOINT_BYTES.labels(
                        transport="http", direction="recv"
                    ).inc(int(resp.headers.get("Content-Length") or 0))
                    return ser.deserialize_from(resp, into=into)

            return _FETCH_POLICY.run(
                attempt,
                timeout=max(deadline - time.monotonic(), 0.001),
                op="transport.http.fetch",
                on_retry=lambda e, n, d: _metrics.CHECKPOINT_RETRIES.labels(
                    transport="http"
                ).inc(),
            )

        def _done() -> None:
            _metrics.CHECKPOINT_DURATION.labels(
                transport="http", direction="recv"
            ).observe(time.perf_counter() - t_recv)

        if resource is not None:
            skeleton, leaves, n = fetch(resource)
            _done()
            return ser.reassemble(skeleton, leaves, n)

        if self._num_chunks <= 0:
            skeleton, leaves, n = fetch("full")
            _done()
            return ser.reassemble(skeleton, leaves, n)

        # Parallel chunk fetch (reference http_transport.py:244-267).
        with ThreadPoolExecutor(max_workers=self._num_chunks) as pool:
            results = list(pool.map(fetch, [f"chunk_{i}" for i in range(self._num_chunks)]))
        _done()
        skeleton, _, n = results[0]
        merged: dict = {}
        for _, leaves, _ in results:
            merged.update(leaves)
        return ser.reassemble(skeleton, merged, n)

    def disallow_checkpoint(self) -> None:
        """Retire heal snapshots (real, >= 0 step keys) before the
        optimizer mutates parameters.  Reshard staging (negative keys)
        stays until its switch commits/rolls back — peers may still be
        mid-fetch when this group's step commits."""
        with self._staged_lock.w_lock(timeout=self._lock_timeout):
            self._staged = {k: v for k, v in self._staged.items() if k < 0}

    def retire_checkpoint(self, step: int) -> None:
        """Drop one staged snapshot (the reshard slots' explicit
        retirement path); no-op when absent."""
        with self._staged_lock.w_lock(timeout=self._lock_timeout):
            self._staged.pop(step, None)

    def staged_steps(self) -> "List[int]":
        """Step/version keys currently staged (insertion order — the
        eviction order).  The serving tier uses this as "which versions
        do I still hold"; tests assert retention windows with it."""
        with self._staged_lock.r_lock(timeout=self._lock_timeout):
            return list(self._staged)

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
        if wait:
            self._thread.join(timeout=5)
