"""Durable content-addressed fragment store (ISSUE 17).

Every byte of fleet state used to be RAM: live heal (PR 15) and serving
(PR 12/14) survive *partial* failures, but a whole-fleet outage lost the
job.  This module adds the spill tier: each rank persists its heal
fragments + manifests to local disk under ``TORCHFT_STORE_DIR``, keyed
by content so steady-state write amplification scales with the update
delta, and on cold start the fleet reassembles from whichever disks
survived via the PR 15 striped multi-source fetch path — restore is
just a heal whose sources are files.

Layout (one directory per rank)::

    <dir>/blobs/<sha256>        # fragment wire bytes, deduped across versions
    <dir>/manifest_v<N>.tft     # serialized manifest: digests + skeleton

Durability contract:

- Blobs and manifests are written tmp + flush + fsync + ``os.replace``
  (the ``durable.py`` idiom), so a crash mid-spill leaves either the
  previous version intact or a fully-written new one — never a torn
  manifest.  The manifest is written LAST: its presence asserts every
  blob it references was durably written first.
- A torn or bit-rotted blob is detected at read time by digest verify
  and treated as a *missing* fragment (counted in
  ``torchft_store_torn_blobs_total``), never served — the striped
  restore path then fails over to another disk holding the same digest.
- Old versions are retired under a ``TORCHFT_STORE_VERSIONS`` window;
  blobs are garbage-collected by scanning the digests still referenced
  by surviving manifests (refcount-by-scan — crash-safe because a
  half-finished GC only ever deletes *unreferenced* blobs).

Cut selection (:func:`select_cut`) is deterministic across replicas:
given the per-disk catalogs the fleet exposes over ``/store/versions``,
every replica picks the same newest version whose fragment set is
covered by the union of digest-valid blobs within one consistent cut
(same manifest content hash), and the same failover-ordered source
list.  Versions are never mixed inside a cut, and an incomplete newer
version degrades to the newest complete older one — degrade, never
wedge.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ..utils import faults as _faults
from ..utils import metrics as _metrics
from ..utils.env import env_int, env_str
from . import fragments as frags
from . import provenance as _prov
from . import serialization as ser

logger: logging.Logger = logging.getLogger(__name__)

_MANIFEST_RE = re.compile(r"^manifest_v(\d+)\.tft$")
_DURABLE_RE = re.compile(r"^ckpt_step(\d+)\.tft$")

# Marker key stamped into store-format manifests so load paths can
# distinguish them from legacy whole-model ``.tft`` payloads (which are
# arbitrary user state dicts).
STORE_MARKER = "store"
STORE_FORMAT = "blobs"

DEFAULT_STORE_VERSIONS = 4


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory so renames inside it are durable
    (not available on all platforms; durability degrades gracefully)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + flush + fsync + ``os.replace`` — a reader never observes a
    half-written file under the final name."""
    tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(path) or ".")


def cut_id(manifest: Dict[str, Any]) -> str:
    """Content hash of a manifest's (fragment name, digest) pairs: two
    disks hold the *same cut* of a version iff their manifests agree on
    every fragment's bytes.  Mixing blobs across different cut ids would
    splice state from different outer syncs — forbidden."""
    h = hashlib.sha256()
    digests = manifest.get("digests") or {}
    for name in sorted(manifest.get("fragments") or []):
        h.update(name.encode())
        h.update(b"\0")
        h.update(str(digests.get(name, "")).encode())
        h.update(b"\0")
    return h.hexdigest()


class FragmentStore:
    """Content-addressed on-disk fragment store for one rank.

    Thread-safety: writes are serialized by callers (the single-worker
    :class:`StoreSpiller`); reads are lock-free because blobs are
    immutable once named (content-addressed) and manifests are replaced
    atomically.
    """

    def __init__(
        self, directory: str, max_versions: Optional[int] = None
    ) -> None:
        self._dir = directory
        self._blob_dir = os.path.join(directory, "blobs")
        if max_versions is None:
            max_versions = env_int(
                "TORCHFT_STORE_VERSIONS", DEFAULT_STORE_VERSIONS, minimum=1
            )
        # max_versions == 0 disables automatic retirement (the durable.py
        # wrapper prunes by its own keep_last policy instead).
        self._max_versions = max_versions
        os.makedirs(self._blob_dir, exist_ok=True)

    @property
    def directory(self) -> str:
        return self._dir

    # ------------------------------------------------------------- blobs

    def blob_path(self, digest: str) -> str:
        return os.path.join(self._blob_dir, digest)

    def write_blob(self, digest: str, raw: Any) -> int:
        """Persist one fragment's wire bytes under its digest.  Returns
        the byte count actually written — 0 when the digest already
        exists (dedup: unchanged fragments cost no disk writes)."""
        path = self.blob_path(digest)
        if os.path.exists(path):
            return 0
        data = bytes(memoryview(raw))
        _atomic_write(path, data)
        return len(data)

    def read_blob(self, digest: str) -> Optional[bytes]:
        """Read one blob, verifying its bytes still hash to the digest
        that names it.  Torn/bit-rotted blobs return ``None`` (treated
        as missing — the caller fails over), never bad bytes."""
        try:
            with open(self.blob_path(digest), "rb") as f:
                data = f.read()
        except OSError:
            return None
        if hashlib.sha256(data).hexdigest() != digest:
            _metrics.STORE_TORN_BLOBS.inc()
            logger.warning(
                f"store blob {digest[:12]} failed digest verify "
                f"(torn or bit-rotted) — treating as missing"
            )
            return None
        return data

    # --------------------------------------------------------- manifests

    def _manifest_path(self, version: int) -> str:
        return os.path.join(self._dir, f"manifest_v{version}.tft")

    def _manifest_files(self) -> List[Tuple[int, str]]:
        """All store + durable-wrapper manifests in the directory, as
        sorted ``(version, path)``.  Durable checkpoints share the blob
        namespace, so GC must see both."""
        out = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        for n in names:
            m = _MANIFEST_RE.match(n) or _DURABLE_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self._dir, n)))
        out.sort()
        return out

    def _read_manifest_file(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path, "rb") as f:
                obj = ser.reassemble(*ser.deserialize_from(f))
        except Exception:
            return None
        if not isinstance(obj, dict) or "fragments" not in obj:
            return None
        return obj

    def versions(self) -> List[int]:
        return [v for v, p in self._manifest_files() if _MANIFEST_RE.match(os.path.basename(p))]

    def manifest(self, version: int) -> Optional[Dict[str, Any]]:
        """Decode one version's manifest, or ``None`` if absent/torn
        (atomic writes make torn manifests near-impossible; a corrupt
        one is simply not a restorable version)."""
        path = self._manifest_path(version)
        if not os.path.exists(path):
            return None
        return self._read_manifest_file(path)

    def manifest_bytes(self, version: int) -> Optional[bytes]:
        """Raw serialized manifest for wire passthrough (the HTTP
        ``frag_manifest`` resource serves these bytes verbatim)."""
        try:
            with open(self._manifest_path(version), "rb") as f:
                data = f.read()
        except OSError:
            return None
        # Validate decodability so a torn manifest is never served.
        if self._read_manifest_file(self._manifest_path(version)) is None:
            return None
        return data

    def fragment(self, version: int, name: str) -> Optional[bytes]:
        """One fragment's verified wire bytes, or ``None`` when the
        version/fragment is unknown or its blob is torn."""
        manifest = self.manifest(version)
        if manifest is None:
            return None
        digest = (manifest.get("digests") or {}).get(name)
        if digest is None:
            return None
        data = self.read_blob(str(digest))
        if data is None and os.path.exists(self.blob_path(str(digest))):
            # the blob exists but failed its content-address check:
            # a torn/bit-rotted disk read IS a provenance hop verdict —
            # diagnose --fragment names this disk as the poisoned source
            _prov.note_hop(
                _prov.frag_id(self._payload_family(manifest), name),
                version, f"disk:{self._dir}", "restore", verdict="torn",
            )
        return data

    @staticmethod
    def _payload_family(manifest: Dict[str, Any]) -> str:
        """Provenance payload family of a stored manifest: ``weights``
        for serving documents spilled via :meth:`put_doc`, ``heal``
        (the heal fragment layout) otherwise."""
        return str(manifest.get("payload") or "heal")

    # ------------------------------------------------------------- spill

    def put_state(
        self,
        version: int,
        state_dict: Any,
        fragments: Optional[int] = None,
        manifest_path: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Spill one version: encode ``state_dict`` into heal fragments,
        persist each blob (deduped by digest), then atomically publish
        the manifest.  The fault site ``store.spill`` fires here (chaos:
        a failed spill skips the version, it never corrupts an earlier
        one — the manifest is written last).

        ``manifest_path`` overrides the manifest location (the
        ``durable.py`` wrapper points it at ``ckpt_step<N>.tft``)."""
        _faults.check("store.spill", step=version)
        header, frag_iter = frags.iter_heal_fragments(state_dict, fragments)
        digests: Dict[str, str] = {}
        written = 0
        for name, raw, digest in frag_iter:
            written += self.write_blob(digest, raw)
            digests[name] = digest
        manifest = dict(header)
        manifest["version"] = int(version)
        manifest["digests"] = digests
        manifest["created_ns"] = time.time_ns()
        manifest[STORE_MARKER] = STORE_FORMAT
        _atomic_write(
            manifest_path or self._manifest_path(version),
            ser.serialize(manifest),
        )
        if written:
            _metrics.STORE_SPILL_BYTES.inc(written)
        v_ms = int(manifest["created_ns"] // 1_000_000)
        for name, digest in digests.items():
            _prov.note_hold(
                _prov.frag_id("heal", name), version, digest,
                version_ms=v_ms, role="store",
            )
        if manifest_path is None and self._max_versions:
            self.retire()
        return manifest

    def put_doc(self, doc: Dict[str, Any]) -> Optional[int]:
        """Spill an already-encoded fragment document (the serving
        publisher's ``encode_payload`` output: raw wire bytes per
        fragment plus a digest-bearing manifest) without re-encoding."""
        manifest = doc.get(f"frag:{frags.MANIFEST_FRAG}")
        if not isinstance(manifest, dict) or "fragments" not in manifest:
            return None
        version = int(manifest.get("version", 0))
        _faults.check("store.spill", step=version)
        digests = manifest.get("digests") or {}
        written = 0
        for name in manifest["fragments"]:
            raw = doc.get(f"frag:{name}")
            digest = digests.get(name)
            if raw is None or digest is None:
                return None
            written += self.write_blob(str(digest), raw)
        out = dict(manifest)
        out.setdefault(STORE_MARKER, STORE_FORMAT)
        # serving documents keep their payload family on disk so torn
        # reads audit under the same frag id the serving tier uses
        out.setdefault("payload", "weights")
        _atomic_write(self._manifest_path(version), ser.serialize(out))
        if written:
            _metrics.STORE_SPILL_BYTES.inc(written)
        v_ms = int(manifest.get("created_ns", 0) // 1_000_000)
        for name in manifest["fragments"]:
            _prov.note_hold(
                _prov.frag_id("weights", name), version,
                str(digests.get(name, "")), version_ms=v_ms, role="store",
            )
        if self._max_versions:
            self.retire()
        return version

    def load_state(self, manifest: Dict[str, Any]) -> Any:
        """Reassemble a full state dict from a manifest's blobs, digest-
        verifying every read.  Raises ``ValueError`` loudly on a missing
        or corrupt blob — silently wrong weights are never returned."""
        leaves: Dict[int, Any] = {}
        for name in manifest["fragments"]:
            digest = (manifest.get("digests") or {}).get(name)
            raw = self.read_blob(str(digest)) if digest else None
            if raw is None:
                raise ValueError(
                    f"checkpoint blob for fragment {name!r} "
                    f"({str(digest)[:12]}…) is missing or failed digest "
                    f"verify — refusing to return corrupt state"
                )
            leaves.update(frags.decode_fragment(raw))
        return frags.assemble(manifest, leaves)

    # -------------------------------------------------------- retirement

    def retire(self, keep: Optional[int] = None) -> None:
        """Drop manifests beyond the newest ``keep`` store versions, then
        GC blobs no surviving manifest (store OR durable) references."""
        keep = self._max_versions if keep is None else keep
        if keep:
            store_versions = self.versions()
            for v in store_versions[:-keep]:
                try:
                    os.remove(self._manifest_path(v))
                except OSError:
                    pass
        self.gc_blobs()
        _metrics.STORE_VERSIONS.set(len(self.versions()))

    def gc_blobs(self) -> int:
        """Delete blobs unreferenced by any surviving manifest.  Crash-
        safe: manifests are removed before their blobs, so a half-done
        GC only ever deletes already-unreferenced blobs."""
        referenced = set()
        for _v, path in self._manifest_files():
            manifest = self._read_manifest_file(path)
            if manifest is not None:
                referenced.update(
                    str(d) for d in (manifest.get("digests") or {}).values()
                )
        removed = 0
        try:
            names = os.listdir(self._blob_dir)
        except OSError:
            return 0
        for name in names:
            if name in referenced or ".tmp" in name:
                continue
            try:
                os.remove(os.path.join(self._blob_dir, name))
                removed += 1
            except OSError:
                pass
        return removed

    # ----------------------------------------------------------- catalog

    def catalog(self) -> Dict[int, Dict[str, Any]]:
        """Per-version restore inventory for cut selection: the cut id,
        fragment list, and which fragments this disk can actually serve
        (blob present AND digest-valid) — what ``/store/versions``
        exposes fleet-wide."""
        out: Dict[int, Dict[str, Any]] = {}
        for v in self.versions():
            manifest = self.manifest(v)
            if manifest is None:
                continue
            names = list(manifest.get("fragments") or [])
            ok = [n for n in names if self.fragment(v, n) is not None]
            out[v] = {
                "cut": cut_id(manifest),
                "fragments": names,
                "frags_ok": ok,
                "complete": len(ok) == len(names) and bool(names),
            }
        return out


def select_cut(
    catalogs: Dict[str, Dict[int, Dict[str, Any]]],
) -> Optional[Tuple[int, List[str]]]:
    """Pick the restore cut from the fleet's per-disk catalogs.

    Walks versions newest-first; within a version, disks are grouped by
    cut id (manifest content hash) and a cut is selectable iff the UNION
    of its disks' digest-valid fragments covers the fragment list — a
    version torn on every disk degrades to the newest complete older
    one, never a wedge.  Returns ``(version, ordered source bases)``
    with complete disks first (the primary gets the full deadline in
    ``striped_fetch``), or ``None`` when nothing is restorable (a
    genuinely fresh job).  Deterministic: every replica looking at the
    same catalogs picks the same cut and the same source order."""
    all_versions = sorted(
        {v for cat in catalogs.values() for v in cat}, reverse=True
    )
    for version in all_versions:
        holders = [
            (base, cat[version])
            for base, cat in sorted(catalogs.items())
            if version in cat
        ]
        by_cut: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        for base, ent in holders:
            by_cut.setdefault(str(ent.get("cut")), []).append((base, ent))
        for cut in sorted(by_cut, key=lambda c: (-len(by_cut[c]), c)):
            group = by_cut[cut]
            names = set(group[0][1].get("fragments") or [])
            if not names:
                continue
            covered: set = set()
            for _base, ent in group:
                covered.update(ent.get("frags_ok") or [])
            if names <= covered:
                ordered = sorted(
                    group,
                    key=lambda be: (
                        not be[1].get("complete"),
                        -len(be[1].get("frags_ok") or []),
                        be[0],
                    ),
                )
                return version, [base for base, _ent in ordered]
    return None


def fetch_catalog(
    base: str, timeout: float
) -> Optional[Dict[int, Dict[str, Any]]]:
    """Fetch a peer's store catalog from its checkpoint server's
    ``/store/versions`` resource (plain JSON — not a framed RPC, so the
    wire-schema lock is untouched).  Best-effort: any failure means
    'that disk has nothing for us'."""
    try:
        with urllib.request.urlopen(f"{base}/store/versions", timeout=timeout) as r:
            raw = r.read()
        parsed = json.loads(raw.decode())
        return {int(v): ent for v, ent in parsed.items()}
    except Exception as e:
        logger.debug(f"store catalog fetch from {base} failed: {e}")
        return None


def store_from_env(
    replica_id: str, group_rank: int = 0
) -> Optional[FragmentStore]:
    """Build this rank's :class:`FragmentStore` from ``TORCHFT_STORE_DIR``
    (``None`` when unset — the spill tier is opt-in).  Each rank gets a
    namespace keyed by its stable replica id so restarted processes find
    their own disk, and restore stays rank-symmetric."""
    base = env_str("TORCHFT_STORE_DIR", "")
    if not base:
        return None
    name = replica_id or "replica"
    if group_rank:
        name = f"{name}_r{group_rank}"
    return FragmentStore(os.path.join(base, name))


class StoreSpiller:
    """Single-worker spill executor (the serving publish idiom): the
    training thread hands off a state snapshot and returns immediately;
    encode + disk writes happen on the worker.  A failed spill counts
    ``torchft_store_spill_failures_total`` and skips the version — it
    NEVER raises into (or stalls) a training step."""

    def __init__(self, store: FragmentStore) -> None:
        self._store = store
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="tft_store_spill"
        )
        self._inflight: Any = None
        self._lock = threading.Lock()
        self._shutdown = False

    def submit(
        self, version: int, state_dict: Any, fragments: Optional[int] = None
    ) -> bool:
        """Queue one version for spill.  Returns False (and skips the
        version) when the previous spill is still running — the spill
        tier is best-effort and must never build a backlog that the
        training loop ends up waiting on."""
        with self._lock:
            if self._shutdown:
                return False
            if self._inflight is not None and not self._inflight.done():
                logger.debug(
                    f"store spill of v{version} skipped: previous spill "
                    f"still in flight"
                )
                return False
            self._inflight = self._executor.submit(
                self._spill, version, state_dict, fragments
            )
        return True

    def _spill(
        self, version: int, state_dict: Any, fragments: Optional[int]
    ) -> None:
        try:
            t0 = time.perf_counter()
            self._store.put_state(version, state_dict, fragments)
            logger.debug(
                f"spilled v{version} to {self._store.directory} in "
                f"{time.perf_counter() - t0:.3f}s"
            )
        except Exception as e:
            _metrics.STORE_SPILL_FAILURES.inc()
            logger.warning(f"store spill of v{version} failed (skipped): {e}")

    def flush(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            inflight = self._inflight
        if inflight is not None:
            try:
                inflight.result(timeout=timeout)
            except Exception:
                pass  # already counted + logged by the worker

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
        self._executor.shutdown(wait=True)
