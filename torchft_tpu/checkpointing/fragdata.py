"""Native zero-copy fragment data plane — the Python control side.

The C++ server/client pair in ``native/fragserver.{h,cc}`` owns the
fragment *data* plane: staged payload bytes are served verbatim via
writev out of pooled registered buffers (zero user-space copies
steady-state), and the receive path lands bytes straight into this
process's bufpool buffers and sha256-digests them with the GIL released
(ctypes drops it around every native call).  Python keeps the *control*
plane: plans, manifests, digests-of-record, staging lifecycle, version
advertisement, and ALL telemetry (fault sites, linkstats, provenance,
wire-shaper charging, flight/span records stay in ``fragments.py``).

Wiring:

- ``HTTPTransport`` owns one :class:`FragDataServer` per transport and
  mirrors its raw ``frag:*`` staging into it (begin/stage/finish/retire)
  — the handoff contract in docs/architecture.md;
- the Python HTTP server advertises the native data port at
  ``/nativeport`` (404 = this node serves fragments from Python only);
- ``fragments.fetch_raw`` dispatches raw ``frag_*`` GETs through
  :func:`fetch_native` behind the ``TORCHFT_FRAG_NATIVE`` gate (default
  on when the ``.so`` is present), falling back to the Python path on
  any native miss — Mock transports, non-mirrored resources, and
  gated-off peers keep working unchanged.
"""

from __future__ import annotations

import ctypes
import http.client
import json
import threading
import urllib.error
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from torchft_tpu.utils.bufpool import POOL
from torchft_tpu.utils.env import env_bool

__all__ = [
    "FragDataServer",
    "available",
    "enabled",
    "fetch_native",
    "native_sha256",
    "reset_port_cache",
]

_gate_lock = threading.Lock()
_lib_ok: "Optional[bool]" = None

_U8P = None  # lazily bound ctypes.POINTER(c_uint8)


def _native_lib():
    from torchft_tpu import _native

    return _native.get_lib()


def available() -> bool:
    """True when the native library loads and exposes the fragment C API
    (cached — the first call may trigger the in-place native build)."""
    global _lib_ok
    if _lib_ok is None:
        with _gate_lock:
            if _lib_ok is None:
                try:
                    _lib_ok = bool(
                        hasattr(_native_lib(), "tft_frag_server_create")
                    )
                except Exception:
                    _lib_ok = False
    return bool(_lib_ok)


def enabled() -> bool:
    """The ``TORCHFT_FRAG_NATIVE`` gate: default on when the native
    library is present; ``0`` forces the pure-Python data plane (Mock
    transports, mixed-fleet interop, fallback tests).  Read per call so
    tests can flip the knob without reimporting."""
    if not env_bool("TORCHFT_FRAG_NATIVE", True):
        return False
    return available()


def _u8ptr(arr: np.ndarray):
    global _U8P
    if _U8P is None:
        _U8P = ctypes.POINTER(ctypes.c_uint8)
    return arr.ctypes.data_as(_U8P)


class FragDataServer:
    """Lifecycle wrapper for one native fragment data server.

    ``HTTPTransport`` drives it with the staging handoff contract:
    ``begin(step)`` opens a streaming version, ``stage()`` hands one raw
    payload down (the native side copies ONCE into a pooled registered
    buffer and wakes parked long-pollers), ``finish(step)`` seals the
    version, ``retire(step)`` drops it (non-blocking: buffers referenced
    by in-flight serves are recycled on last deref)."""

    def __init__(self, bind_host: str = "") -> None:
        lib = _native_lib()
        handle = lib.tft_frag_server_create(bind_host.encode(), 0)
        if handle < 0:
            from torchft_tpu import _native

            raise RuntimeError(
                f"native fragserver create failed: {_native.last_error()}"
            )
        self._lib = lib
        self._handle = handle
        self.port = int(lib.tft_frag_server_port(handle))

    def begin(self, step: int) -> None:
        self._lib.tft_frag_begin(self._handle, int(step))

    def stage(self, step: int, resource: str, value) -> bool:
        """Mirror one raw wire-bytes payload; returns False when the
        version is unknown/retired (not mirrored — Python still owns
        serving it)."""
        mv = memoryview(value)
        if not mv.c_contiguous:
            return False
        arr = (
            np.frombuffer(mv, dtype=np.uint8)
            if mv.nbytes
            else np.empty(0, dtype=np.uint8)
        )
        rc = self._lib.tft_frag_stage(
            self._handle,
            int(step),
            resource.encode(),
            _u8ptr(arr),
            arr.nbytes,
        )
        return rc == 0

    def finish(self, step: int) -> None:
        self._lib.tft_frag_finish(self._handle, int(step))

    def retire(self, step: int) -> None:
        self._lib.tft_frag_retire(self._handle, int(step))

    def counters(self) -> "Dict[str, int]":
        from torchft_tpu import _native

        ptr = self._lib.tft_frag_counters(self._handle)
        return json.loads(_native.take_string(ptr))

    def inject(self, mode: str, param_ms: int = 0, count: int = 0) -> None:
        """Chaos hook: the next ``count`` data requests ``drop`` (close
        mid-exchange) or ``delay`` ``param_ms`` before the body."""
        rc = self._lib.tft_frag_inject(
            self._handle, mode.encode(), int(param_ms), int(count)
        )
        if rc != 0:
            raise ValueError(f"bad inject mode: {mode}")

    def shutdown(self) -> None:
        if self._handle >= 0:
            self._lib.tft_server_shutdown(self._handle)
            self._handle = -1


# ---- client-side endpoint resolution --------------------------------------
# One control round trip per base: GET /nativeport on the Python control
# server names the data port (404 = python-only node, cached; transport
# errors are NOT cached so a transient outage can't pin a peer to the
# slow path forever).

_ports_lock = threading.Lock()
_ports: "Dict[str, Optional[int]]" = {}


def reset_port_cache() -> None:
    """Test hook: forget resolved data ports (transports are ephemeral
    in-process, so a stale positive entry can otherwise outlive its
    server across test cases)."""
    with _ports_lock:
        _ports.clear()


def _drop_port(base: str) -> None:
    """Invalidate one cached data-port mapping (the peer restarted, or
    an ephemeral-port collision aliased a dead native server onto a new
    transport's control port) — the next fetch re-resolves."""
    with _ports_lock:
        _ports.pop(base, None)


def _resolve_port(base: str, timeout: float) -> "Optional[int]":
    with _ports_lock:
        if base in _ports:
            return _ports[base]
    u = urlparse(base)
    port: "Optional[int]" = None
    cache = False
    try:
        conn = http.client.HTTPConnection(
            u.hostname or "127.0.0.1",
            u.port or 80,
            timeout=max(timeout, 0.05),
        )
        try:
            conn.request("GET", "/nativeport")
            resp = conn.getresponse()
            body = resp.read()
            cache = True  # a definitive control-plane answer either way
            if resp.status == 200:
                port = int(body.strip() or b"0") or None
        finally:
            conn.close()
    except (OSError, ValueError, http.client.HTTPException):
        port = None
    if cache:
        with _ports_lock:
            if len(_ports) > 4096:
                _ports.clear()
            _ports[base] = port
    return port


def fetch_native(
    base: str, version: int, resource: str, timeout: float
) -> "Optional[Tuple[np.ndarray, str, float]]":
    """Try the native data plane for one raw fragment GET.

    Returns ``(pooled uint8 buffer, sha256 hex, first_byte_seconds)`` on
    success; ``None`` when the caller should fall back to the Python
    path (peer has no native server, the fragment isn't mirrored there,
    or the data connection failed — a transport error also invalidates
    the cached port so a stale mapping cannot pin the slow path).
    Raises ``urllib.error.HTTPError(503)`` for retryable-busy (the
    cut-through long-poll contract) — exactly the exception surface the
    fragment retry policy already handles."""
    port = _resolve_port(base, timeout)
    if port is None:
        return None
    lib = _native_lib()
    u = urlparse(base)
    addr = f"{u.hostname or '127.0.0.1'}:{port}".encode()
    n = ctypes.c_int64(0)
    fb = ctypes.c_double(0.0)
    timeout_ms = max(int(timeout * 1000), 1)
    rc = lib.tft_frag_fetch_begin(
        addr,
        int(version),
        resource.encode(),
        timeout_ms,
        ctypes.byref(n),
        ctypes.byref(fb),
    )
    if rc == 503:
        raise urllib.error.HTTPError(
            f"{base}/checkpoint/{version}/{resource}",
            503,
            "native fragment still streaming",
            None,  # type: ignore[arg-type]
            None,
        )
    if rc < 0:
        _drop_port(base)
        return None  # transport error: Python path decides (it shares
        # the peer's fate — a live peer serves, a dead one raises the
        # URLError the retry/failover ladder already handles)
    if rc != 200:
        return None  # 404 (or anything unexpected): Python owns this one
    nbytes = int(n.value)
    buf = POOL.take(nbytes, np.uint8)
    sha = ctypes.create_string_buffer(65)
    # ctypes releases the GIL here: body receive + sha256 over the wire
    # buffer run native while other Python threads keep executing
    rc = lib.tft_frag_fetch_body(_u8ptr(buf), nbytes, sha, timeout_ms)
    if rc != 0:
        POOL.give(buf)
        _drop_port(base)
        return None  # connection died mid-body: refetch via Python
    return buf, sha.value.decode(), float(fb.value)


def native_sha256(buf) -> "Optional[str]":
    """sha256 hex of one buffer via the native kernel (GIL released), or
    None when the native library is unavailable."""
    if not available():
        return None
    mv = memoryview(buf)
    if not mv.c_contiguous:
        return None
    arr = (
        np.frombuffer(mv, dtype=np.uint8)
        if mv.nbytes
        else np.empty(0, dtype=np.uint8)
    )
    out = ctypes.create_string_buffer(65)
    if _native_lib().tft_sha256_hex(_u8ptr(arr), arr.nbytes, out) != 0:
        return None
    return out.value.decode()
