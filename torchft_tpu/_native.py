"""ctypes bindings for the native coordination core.

Analog of the reference's PyO3 extension module registration
(reference: src/lib.rs:742-758).  The shared library is built from
``native/`` by ``make``; if missing it is built on first import (the target
environment always has g++/make).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(_PKG_DIR), "native")
_LIB_NAME = "libtorchft_tpu_native.so"

_build_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None

# Signature of a lighthouse /metrics supplement provider: writes exposition
# text into (buf, cap); returns bytes written, or the negated required size
# when cap is too small.  Called from native HTTP threads — ctypes acquires
# the GIL around the Python callable automatically.
METRICS_PROVIDER_CFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.POINTER(ctypes.c_char), ctypes.c_int
)

# Signature of the distributed-tracing span sink: receives one finished
# native span as a JSON C string (tracing.py forwards it to the Python
# exporter).  Called from native RPC handler threads — ctypes acquires
# the GIL around the Python callable automatically.
SPAN_SINK_CFUNC = ctypes.CFUNCTYPE(None, ctypes.c_char_p)


def loaded() -> bool:
    """True when the native library has already been loaded in this
    process — lets optional wiring (the tracing span sink) avoid
    triggering a native build as an import side effect."""
    return _lib is not None


def _build() -> None:
    result = subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-j", str(os.cpu_count() or 2)],
        capture_output=True,
        text=True,
    )
    if result.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{result.stdout}\n{result.stderr}"
        )


def _stale(lib_path: str) -> bool:
    """True when any native source/Makefile is newer than the built .so."""
    try:
        built = os.path.getmtime(lib_path)
        for name in os.listdir(_NATIVE_DIR):
            if name == "smoke.cc":
                # sanitizer smoke driver: not linked into the .so, so a
                # newer copy must not make the lib look perpetually stale
                # (make would no-op and never advance the .so mtime)
                continue
            if name.endswith((".cc", ".h")) or name == "Makefile":
                if os.path.getmtime(os.path.join(_NATIVE_DIR, name)) > built:
                    return True
    except OSError:
        return True  # unreadable state: let make decide
    return False


def _find_lib() -> str:
    """Locate (or build) the shared library.  Search order:

    1. ``TORCHFT_NATIVE_LIB`` — explicit override (deployment images);
    2. the repo-layout ``native/`` source tree — editable/dev installs,
       built on first import when missing (g++/make are baked into the
       target environment).  The source tree outranks a staged ``.so``
       so a dev checkout where ``pip wheel .`` once copied a build into
       the package dir never silently shadows later native/ rebuilds;
    3. the packaged ``.so`` next to this module — wheel installs (staged
       by setup.py's build_py hook; no source tree present there).
    """
    from torchft_tpu.utils.env import env_str

    env = env_str("TORCHFT_NATIVE_LIB")
    if env:
        if not os.path.exists(env):
            raise FileNotFoundError(f"TORCHFT_NATIVE_LIB={env} does not exist")
        return env
    if os.path.isdir(_NATIVE_DIR):
        repo = os.path.join(_NATIVE_DIR, _LIB_NAME)
        # rebuild when STALE, not just missing: a pulled source change
        # with a previously built (gitignored) .so would otherwise load a
        # library missing newly bound symbols — ctypes raises
        # AttributeError inside get_lib() and every coordination server
        # hard-fails on functionality unrelated to the new symbols
        if not os.path.exists(repo) or _stale(repo):
            _build()
        return repo
    packaged = os.path.join(_PKG_DIR, _LIB_NAME)
    if os.path.exists(packaged):
        return packaged
    raise RuntimeError(
        "native core not found: no packaged .so, no native/ source tree, "
        "and TORCHFT_NATIVE_LIB unset"
    )


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_find_lib())

        lib.tft_last_error.restype = ctypes.c_char_p
        lib.tft_free.argtypes = [ctypes.c_void_p]

        lib.tft_lighthouse_create.restype = ctypes.c_int64
        lib.tft_lighthouse_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            # status plane: status_page_size, straggler_topk, timeline_ring
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            # weight-serving tier: serving_fanout (distribution-tree arity)
            ctypes.c_int64,
            # coordination-plane HA: peers (comma list of the OTHER
            # lighthouse peers; empty = single mode) + lease_timeout_ms
            ctypes.c_char_p, ctypes.c_int64,
        ]
        lib.tft_lighthouse_ha_info.restype = ctypes.c_void_p
        lib.tft_lighthouse_ha_info.argtypes = [ctypes.c_int64]
        lib.tft_manager_create.restype = ctypes.c_int64
        lib.tft_manager_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tft_store_create.restype = ctypes.c_int64
        lib.tft_store_create.argtypes = [ctypes.c_char_p, ctypes.c_int]

        lib.tft_server_address.restype = ctypes.c_void_p
        lib.tft_server_address.argtypes = [ctypes.c_int64]
        lib.tft_server_shutdown.restype = ctypes.c_int
        lib.tft_server_shutdown.argtypes = [ctypes.c_int64]

        lib.tft_lighthouse_set_metrics_provider.restype = ctypes.c_int
        lib.tft_lighthouse_set_metrics_provider.argtypes = [
            ctypes.c_int64, METRICS_PROVIDER_CFUNC,
        ]

        lib.tft_set_span_sink.restype = ctypes.c_int
        lib.tft_set_span_sink.argtypes = [SPAN_SINK_CFUNC]

        lib.tft_manager_report_progress.restype = ctypes.c_int
        lib.tft_manager_report_progress.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p,
        ]

        lib.tft_manager_report_summary.restype = ctypes.c_int
        lib.tft_manager_report_summary.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
        ]

        lib.tft_manager_report_links.restype = ctypes.c_int
        lib.tft_manager_report_links.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
        ]

        lib.tft_manager_report_fragments.restype = ctypes.c_int
        lib.tft_manager_report_fragments.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
        ]

        lib.tft_compute_quorum_results.restype = ctypes.c_void_p
        lib.tft_compute_quorum_results.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
        ]

        # Fused host codec (native/quant.cc) — GIL-free memory-bandwidth
        # kernels for BOTH DCN wire formats (int8 + fp8_e4m3); bit-exact
        # on finite inputs against the numpy codec in ops/quantization.py
        # (which stays as the reference semantics / fallback).
        _f32p = ctypes.POINTER(ctypes.c_float)
        _i8p = ctypes.POINTER(ctypes.c_int8)
        lib.tft_quant_int8.restype = None
        lib.tft_quant_int8.argtypes = [
            _f32p, ctypes.c_int64, ctypes.c_int64, _f32p, _i8p,
        ]
        lib.tft_dequant_fma.restype = None
        lib.tft_dequant_fma.argtypes = [
            _i8p, _f32p, ctypes.c_int64, ctypes.c_int64, _f32p, ctypes.c_int,
        ]
        _u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.tft_quant_fp8.restype = None
        lib.tft_quant_fp8.argtypes = [
            _f32p, ctypes.c_int64, ctypes.c_int64, _f32p, _u8p,
        ]
        lib.tft_dequant_fp8_fma.restype = None
        lib.tft_dequant_fp8_fma.argtypes = [
            _u8p, _f32p, _f32p, ctypes.c_int64, ctypes.c_int64, _f32p,
            ctypes.c_int,
        ]
        lib.tft_div_f32.restype = None
        lib.tft_div_f32.argtypes = [_f32p, ctypes.c_int64, ctypes.c_float]
        # Row-range entry points: same kernels over [r0, r1) of a shared
        # buffer — the threaded-codec surface (rows are independent, so
        # disjoint ranges are data-race-free; ops/codec_pool.py fans one
        # chunk across these with the GIL released).
        _i64 = ctypes.c_int64
        lib.tft_quant_int8_rows.restype = None
        lib.tft_quant_int8_rows.argtypes = [_f32p, _i64, _i64, _i64, _f32p, _i8p]
        lib.tft_quant_fp8_rows.restype = None
        lib.tft_quant_fp8_rows.argtypes = [_f32p, _i64, _i64, _i64, _f32p, _u8p]
        lib.tft_dequant_fma_rows.restype = None
        lib.tft_dequant_fma_rows.argtypes = [
            _i8p, _f32p, _i64, _i64, _i64, _f32p, ctypes.c_int,
        ]
        lib.tft_dequant_fp8_fma_rows.restype = None
        lib.tft_dequant_fp8_fma_rows.argtypes = [
            _u8p, _f32p, _f32p, _i64, _i64, _i64, _f32p, ctypes.c_int,
        ]
        lib.tft_div_f32_rows.restype = None
        lib.tft_div_f32_rows.argtypes = [_f32p, _i64, _i64, _i64, ctypes.c_float]

        # Native zero-copy fragment data plane (native/fragserver.{h,cc}).
        # Server lifecycle + the staging mirror HTTPTransport drives, and
        # the two-phase GIL-free fetch client fragments.py dispatches to
        # behind the TORCHFT_FRAG_NATIVE gate.
        lib.tft_frag_server_create.restype = ctypes.c_int64
        lib.tft_frag_server_create.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tft_frag_server_port.restype = ctypes.c_int
        lib.tft_frag_server_port.argtypes = [ctypes.c_int64]
        lib.tft_frag_begin.restype = ctypes.c_int
        lib.tft_frag_begin.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.tft_frag_stage.restype = ctypes.c_int
        lib.tft_frag_stage.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_char_p, _u8p, _i64,
        ]
        lib.tft_frag_finish.restype = ctypes.c_int
        lib.tft_frag_finish.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.tft_frag_retire.restype = ctypes.c_int
        lib.tft_frag_retire.argtypes = [ctypes.c_int64, ctypes.c_int64]
        lib.tft_frag_counters.restype = ctypes.c_void_p
        lib.tft_frag_counters.argtypes = [ctypes.c_int64]
        lib.tft_frag_inject.restype = ctypes.c_int
        lib.tft_frag_inject.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, _i64, _i64,
        ]
        lib.tft_frag_fetch_begin.restype = ctypes.c_int
        lib.tft_frag_fetch_begin.argtypes = [
            ctypes.c_char_p, _i64, ctypes.c_char_p, _i64,
            ctypes.POINTER(_i64), ctypes.POINTER(ctypes.c_double),
        ]
        lib.tft_frag_fetch_body.restype = ctypes.c_int
        lib.tft_frag_fetch_body.argtypes = [
            _u8p, _i64, ctypes.c_char_p, _i64,
        ]
        lib.tft_frag_fetch_abort.restype = None
        lib.tft_frag_fetch_abort.argtypes = []
        lib.tft_frag_client_close.restype = None
        lib.tft_frag_client_close.argtypes = []
        lib.tft_frag_client_error.restype = ctypes.c_char_p
        lib.tft_frag_client_error.argtypes = []
        lib.tft_sha256_hex.restype = ctypes.c_int
        lib.tft_sha256_hex.argtypes = [_u8p, _i64, ctypes.c_char_p]
        _lib = lib
        return _lib


def last_error() -> str:
    return get_lib().tft_last_error().decode()


def take_string(ptr: int) -> str:
    """Copy a malloc'd C string into Python and free it."""
    lib = get_lib()
    if not ptr:
        raise RuntimeError(last_error())
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.tft_free(ptr)
