"""ctypes bindings for the native coordination core.

Analog of the reference's PyO3 extension module registration
(reference: src/lib.rs:742-758).  The shared library is built from
``native/`` by ``make``; if missing it is built on first import (the target
environment always has g++/make).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtorchft_tpu_native.so")

_build_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None


def _build() -> None:
    result = subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-j", str(os.cpu_count() or 2)],
        capture_output=True,
        text=True,
    )
    if result.returncode != 0:
        raise RuntimeError(
            f"native build failed:\n{result.stdout}\n{result.stderr}"
        )


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            _build()
        lib = ctypes.CDLL(_LIB_PATH)

        lib.tft_last_error.restype = ctypes.c_char_p
        lib.tft_free.argtypes = [ctypes.c_void_p]

        lib.tft_lighthouse_create.restype = ctypes.c_int64
        lib.tft_lighthouse_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.tft_manager_create.restype = ctypes.c_int64
        lib.tft_manager_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.tft_store_create.restype = ctypes.c_int64
        lib.tft_store_create.argtypes = [ctypes.c_char_p, ctypes.c_int]

        lib.tft_server_address.restype = ctypes.c_void_p
        lib.tft_server_address.argtypes = [ctypes.c_int64]
        lib.tft_server_shutdown.restype = ctypes.c_int
        lib.tft_server_shutdown.argtypes = [ctypes.c_int64]

        lib.tft_compute_quorum_results.restype = ctypes.c_void_p
        lib.tft_compute_quorum_results.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int,
        ]
        _lib = lib
        return _lib


def last_error() -> str:
    return get_lib().tft_last_error().decode()


def take_string(ptr: int) -> str:
    """Copy a malloc'd C string into Python and free it."""
    lib = get_lib()
    if not ptr:
        raise RuntimeError(last_error())
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.tft_free(ptr)
