// Lighthouse: the cluster-wide membership & quorum authority.
//
// TPU-native C++ rebuild of the reference's Rust lighthouse
// (reference: src/lighthouse.rs). One lighthouse process (or in-process
// server) per job; replica-group managers call quorum() (blocking until a
// quorum containing them forms) and heartbeat(). Serves framed-JSON RPC and
// an HTML status dashboard on the same port (protocol sniffed per
// connection).
//
// Quorum decision rules (parity with reference src/lighthouse.rs:141-269):
//   - healthy = heartbeat within heartbeat_timeout_ms (joining counts).
//   - shrink_only: candidates filtered to previous-quorum members.
//   - fast quorum: all previous-quorum members healthy & participating.
//   - else: >= min_replicas healthy participants, AND strictly more than
//     half of all healthy replicas participating (split-brain guard), AND
//     either all healthy replicas joined or join_timeout_ms elapsed since
//     the first joiner (straggler wait).
//   - quorum_id bumps when membership changed vs previous quorum, or any
//     member reported commit_failures > 0.
#pragma once

#include <condition_variable>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <tuple>
#include <vector>

#include "net.h"

namespace tft {

struct QuorumMember {
  std::string replica_id;
  std::string address;        // manager RPC address
  std::string store_address;  // rendezvous store address
  int64_t step = 0;
  int64_t world_size = 1;
  bool shrink_only = false;
  int64_t commit_failures = 0;
  // Online parallelism switching: the member's current/staged layout
  // epoch (monotone; min==max across a quorum is the fleet-wide layout
  // commit signal — docs/protocol.md "Layout epochs").
  int64_t layout_epoch = 0;
  std::string data;  // opaque JSON passthrough (layout shard manifest)

  Json to_json() const;
  static QuorumMember from_json(const Json& j);
};

struct Quorum {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  int64_t created_ms = 0;  // wall-clock ms since unix epoch

  Json to_json() const;
  static Quorum from_json(const Json& j);
};

struct LighthouseOpt {
  std::string bind_host;  // advertise host; empty = machine hostname
  int port = 0;
  int64_t min_replicas = 1;
  int64_t join_timeout_ms = 100;
  int64_t quorum_tick_ms = 100;
  int64_t heartbeat_timeout_ms = 5000;
  // Weight-serving tier: children per interior node of the synthesized
  // fan-out distribution tree (serving_plan RPC).
  int64_t serving_fanout = 2;
  // Coordination-plane HA (docs/architecture.md "Coordination-plane
  // HA"): comma list of the OTHER lighthouse peers' RPC addresses.
  // Empty = single-process mode — no election thread, always leader,
  // term 0, wire-identical to the pre-HA server.
  std::string peers;
  // Leadership lease duration: the leader renews every lease/4; a
  // follower whose granted promise lapses for a full lease window
  // becomes a candidate (takeover-on-expiry).
  int64_t lease_timeout_ms = 1000;
  // Fleet-scale status plane (see docs/observability.md):
  // default page size for /status.json row arrays (and the dashboard
  // tables) — the default document stays small at any fleet size.
  int64_t status_page_size = 16;
  // straggler rows exported per-replica on /metrics and in the status
  // summary's worst-K list; the full table is only in the paginated rows.
  int64_t straggler_topk = 8;
  // steps retained in the rolling cluster timeline (/timeline.json).
  int64_t timeline_ring = 256;
};

class LighthouseServer : public RpcServer {
 public:
  explicit LighthouseServer(const LighthouseOpt& opt);
  ~LighthouseServer() override;

  void start_serving();
  void stop();

  // Exposed for unit tests: run one quorum decision against current state.
  // Returns the quorum participants if a quorum formed (state updated).
  bool tick_for_test();

  // Prometheus /metrics supplement: a callback that writes additional
  // exposition text (the embedding process's metric registry) into the
  // caller's buffer.  Contract: returns bytes written, or the negated
  // required size when the buffer is too small (caller retries bigger).
  // NULL clears.  Called from HTTP handler threads — for the Python
  // (ctypes) provider that implies a GIL acquisition per scrape, which is
  // fine at scrape rates.
  using MetricsProvider = int (*)(char* buf, int cap);
  void set_metrics_provider(MetricsProvider provider);

  // Coordination-plane HA introspection (tests, the fleet helper, the
  // C API): {"enabled", "term", "is_leader", "leader", "peers"}.
  Json ha_info();

 protected:
  Json handle(const std::string& method, const Json& params,
              int64_t timeout_ms) override;
  const char* server_kind() const override { return "lighthouse"; }
  void handle_http(int fd, const std::string& request_head) override;
  void wake_blocked() override;

 private:
  struct ParticipantDetails {
    QuorumMember member;
    int64_t joined_ms = 0;
    // Monotone registration token: a quorum handler that EXITS without a
    // quorum (timeout/shutdown) deregisters its own entry, but only if no
    // newer handler for the same replica_id has re-registered since — a
    // dead requester must not linger as a "ghost participant" that
    // satisfies the next formation's barrier without anyone waiting on
    // the result (see rpc_quorum).
    int64_t reg_token = 0;
  };

  // Pure decision function over current state; returns participants if a
  // quorum can form now, plus a human-readable reason either way.  When
  // the answer is "not yet, but pure time passage can change it" (the
  // join-timeout straggler wait), *wake_deadline_ms is lowered to the
  // moment the decision must be re-run even with no state change.
  std::optional<std::vector<QuorumMember>> quorum_compute(
      int64_t now, std::string* reason, int64_t* wake_deadline_ms = nullptr);
  // Runs one tick under mu_: pop expired heartbeats into the dirty set,
  // and only when the dirty set is non-empty (or a timed deadline
  // passed) re-run the decision: compute, bump quorum_id, broadcast.
  // Steady-state cost is O(1), not O(fleet).
  void tick_locked(int64_t now);
  void tick_loop();
  // Heartbeat bookkeeping funnel: updates heartbeats_ + the expiry index,
  // and marks rid dirty only on a freshness TRANSITION (new or was-stale)
  // — a refresh of an already-fresh replica cannot change the quorum
  // decision, so it must not cost a recompute (caller holds mu_).
  void touch_heartbeat_locked(const std::string& rid, int64_t now);
  void drop_heartbeat_locked(const std::string& rid);

  Json rpc_quorum(const Json& params, int64_t timeout_ms);
  Json rpc_heartbeat(const Json& params);
  Json rpc_serving_heartbeat(const Json& params);
  Json rpc_serving_plan(const Json& params);
  Json rpc_lease(const Json& params);
  void note_summary_locked(const std::string& rid, const Json& summary,
                           int64_t now);
  // Fold one replica's link digest ({"host", "rows"}) into the fleet
  // host-pair matrix (caller holds mu_).
  void note_links_locked(const Json& links, int64_t now);
  // The fleet link matrix (the "links" RPC and GET /links.json); locks
  // mu_ internally.  Paginated like status_json; fleet truth (version,
  // totals, worst WAN pair) is on every page.
  Json links_json(int64_t page, int64_t per_page);
  // Fold one replica's fragment-provenance digest ({"host", "frags"})
  // into the fleet per-(host, frag_id) version matrix (caller holds
  // mu_).  UPSERT per row — digests are partial by design.
  void note_fragments_locked(const Json& fragments, int64_t now);
  // The fleet fragment-version matrix (the "fragments" RPC and GET
  // /fragments.json); locks mu_ internally.  Paginated like links_json;
  // fleet truth (version, totals, worst-K stalest rows) on every page.
  Json fragments_json(int64_t page, int64_t per_page);
  std::string render_status_html(int64_t page);
  std::string render_metrics();

  // Per-replica progress piggybacked on heartbeat/quorum RPCs — the
  // straggler-telemetry substrate.  step_changed_at_ms is LIGHTHOUSE
  // clock (stamped when a strictly larger step is first observed), so
  // straggler math never depends on cross-host clock sync;
  // last_step_wall_ms is the sender-clock stamp, reported for display.
  struct ReplicaProgress {
    int64_t step = -1;
    int64_t step_changed_at_ms = 0;
    int64_t last_step_wall_ms = 0;
    std::string inflight_op;
  };

  // One straggler-table row (computed, not stored).
  struct StragglerInfo {
    std::string replica_id;
    int64_t step = 0;
    int64_t step_lag = 0;          // max tracked step - this step
    int64_t progress_age_ms = 0;   // since last observed step advance
    int64_t last_step_wall_ms = 0; // sender-clock stamp, as reported
    double score = 0.0;            // age / median live age (~1 = typical)
    std::string inflight_op;
    bool stale = false;            // heartbeat past timeout
  };

  // One bucket of the rolling cluster step-timeline: aggregated from the
  // per-replica summaries piggybacked on heartbeats.  Phase stats are
  // mean+max over the replicas' own per-step values (each replica
  // reports its local value; the cluster keeps sum/n/max — medians of
  // 64 streams would need per-report storage the ring deliberately
  // avoids).
  struct PhaseAgg {
    int64_t n = 0;
    double sum_ms = 0.0;
    double max_ms = 0.0;
  };
  struct StepBucket {
    int64_t step = 0;
    int64_t first_ms = 0;  // lighthouse clock: first report for this step
    int64_t last_ms = 0;   // ... and the latest
    int64_t reports = 0;
    std::set<std::string> replicas;  // distinct reporters (≤ fleet size)
    std::map<std::string, PhaseAgg> phases;
    double codec_busy_s = 0.0;  // summed across reports
    double wire_busy_s = 0.0;
  };

  // One registered weight-serving participant (serving_heartbeat RPC).
  // Roles: "publisher" (a training-side WeightPublisher, the tree's
  // source of truth) or "server" (a relay/leaf replica).  version is the
  // newest weight version the member holds; the plan's latest_version is
  // the max over publishers — the pull target every server converges to.
  struct ServingMember {
    std::string replica_id;
    std::string address;   // HTTP checkpoint-transport base address
    std::string role;      // "publisher" | "server"
    int64_t version = 0;
    int64_t capacity = 0;  // max children (0 = opt_.serving_fanout)
    int64_t last_hb_ms = 0;
    // Serving staleness ledger: the PUBLISH wall-clock stamp (ms) of
    // `version`, minted on the publisher's clock and carried unmodified
    // through the distribution tree — staleness_ms compares two stamps
    // from the SAME clock (latest publisher stamp minus the member's),
    // so cross-host clock skew cancels out.  0 = unknown (pre-ledger
    // member or version 0).
    int64_t version_ms = 0;
  };

  // One aggregated fleet link-state row, keyed (reporting host, peer
  // host, plane) — the heartbeat-piggybacked digests land here with
  // per-host latest-wins replacement, so the table is bounded by
  // hosts x digest size (the digest itself is worst-K bounded at the
  // replica, utils/linkstats.py).
  struct LinkRow {
    std::string src_host;
    std::string peer;    // may be a host#gN pseudo-host (WAN-keyed)
    std::string plane;   // "reduction" | "fragments" | "rpc"
    bool local = false;
    double goodput_bps = 0.0;
    double rtt_ms = 0.0;      // first-byte p50
    double rtt_p99_ms = 0.0;  // first-byte p99
    int64_t samples = 0;
    int64_t bytes = 0;
    int64_t updated_ms = 0;  // lighthouse clock at last report
  };

  // One fleet fragment-version-matrix row, keyed (holder host, frag_id)
  // — the heartbeat-piggybacked provenance digests
  // (checkpointing/provenance.py maybe_digest) land here with per-row
  // UPSERT (a digest is PARTIAL: worst-K stalest + changed-since-last,
  // so replacing all of a host's rows would forget fragments that
  // simply didn't change).  version_ms is the PUBLISH wall-stamp of the
  // held version, minted on the publisher's clock and carried
  // unmodified by every holder, so staleness (freshest stamp for the
  // frag minus this row's stamp) is skew-free.
  struct FragRow {
    std::string host;
    std::string frag;     // "<payload>/<layout index>", e.g. "weights/0"
    int64_t version = 0;
    std::string digest8;  // first 8 hex chars of the fragment sha256
    int64_t version_ms = 0;  // publish stamp (publisher clock; 0=unknown)
    int64_t held_ms = 0;     // holder clock: when the hold was recorded
    bool pub = false;        // reported by the publishing process itself
    int64_t updated_ms = 0;  // lighthouse clock at last report
  };

 private:
  // Weight-serving tier bookkeeping (caller holds mu_).  Membership
  // changes (join, role change, heartbeat expiry) bump serving_epoch_
  // — the PR 10 layout-epoch idiom: the epoch is monotone and never
  // reused, so replicas adopting "the plan at epoch E" can never
  // disagree about which tree E names.  The plan itself is synthesized
  // deterministically from the replica_id-ordered membership at read
  // time (same members => same tree), so there is no cached document to
  // go stale: any read under mu_ sees a consistent (epoch, tree) pair.
  void serving_gc_locked(int64_t now);
  int64_t serving_latest_version_locked() const;
  // Publish stamp (publisher-clock ms) of the newest published version —
  // the staleness ledger's reference point (0 = unknown).
  int64_t serving_latest_version_ms_locked() const;

  // Record progress for rid (caller holds mu_).
  void note_progress_locked(const std::string& rid, int64_t step,
                            int64_t last_step_wall_ms,
                            const std::string& inflight_op, int64_t now);
  // Straggler table over replicas with a heartbeat entry AND progress
  // (caller holds mu_).
  std::vector<StragglerInfo> compute_stragglers_locked(int64_t now);
  // Worst-K rows by straggler score (K = straggler_topk), stale rows
  // first-class — the bounded tier /metrics and the summary document use.
  // The rows overload sorts/truncates a table the caller already
  // computed (scoring the fleet twice per scrape under mu_ is exactly
  // the O(n) tax this PR removes); the now overload is the convenience
  // for callers that need nothing but the worst-K.
  std::vector<StragglerInfo> worst_stragglers(std::vector<StragglerInfo> rows);
  std::vector<StragglerInfo> worst_stragglers_locked(int64_t now);
  // The one status document served by the status RPC and /status.json
  // (locks mu_ internally).  page < 0 = the default first page;
  // per_page <= 0 = opt_.status_page_size; non-empty replica_filter
  // shards every row array down to that replica id (no paging).
  Json status_json(int64_t page, int64_t per_page,
                   const std::string& replica_filter);
  Json status_json() { return status_json(-1, 0, ""); }
  // The rolling cluster step-timeline (/timeline.json and the
  // "timeline" RPC); locks mu_ internally.
  Json timeline_json();

  // ---- coordination-plane HA (leased leadership) -------------------------
  // Lighthouse state is SOFT (heartbeats, registrations and serving
  // membership rebuild through client re-registration), so failover needs
  // no log replication — only monotonicity: the leader's term (monotone
  // across takeovers, enforced by majority lease acknowledgement) prefixes
  // every id the lighthouse mints, `(term << 32) | seq`, so quorum_id and
  // the serving plan epoch stay strictly monotone across a leader change
  // with zero state transfer.  In single-process mode term stays 0 and the
  // ids are bit-identical to the pre-HA server.
  bool ha_enabled() const { return !peers_.empty(); }
  // Throws NotLeaderError naming the current holder when this peer is not
  // the leader (caller holds mu_); no-op in single-process mode.
  void require_leader_locked(const char* method);
  void become_leader_locked(int64_t term, int64_t now);
  void bump_serving_epoch_locked();
  void election_loop();
  static int64_t ha_epoch_id(int64_t term, int64_t seq) {
    return (term << 32) | (seq & 0xffffffffLL);
  }

  std::vector<std::string> peers_;  // the OTHER peers (empty = single mode)
  int64_t term_ = 0;                // term this peer currently leads under
  bool is_leader_ = true;           // single-process mode: always leader
  int64_t lease_until_ms_ = 0;      // self-lease validity while leading
  int64_t promised_term_ = 0;       // highest term this peer lease-granted
  std::string promised_to_;         // candidate holding that promise
  int64_t promise_expires_ms_ = 0;  // grant freshness (renewals refresh it)
  int64_t max_seen_term_ = 0;       // refusal replies teach us the ceiling
  int64_t takeovers_total_ = 0;
  int64_t lease_requests_total_ = 0;
  // Lighthouse-peer observability federation (ISSUE 15): per-peer lease
  // channel state recorded by the election thread's renewal/candidacy
  // rounds, served in /status.json "ha.ha_peers" and /metrics so ONE
  // leader scrape covers the whole coordination plane.  last_ack_ms is
  // THIS peer's clock at the last successful lease reply (0 = never);
  // term/takeovers/promise_remaining_ms/holder echo the reply.
  struct HaPeerState {
    int64_t term = 0;
    bool granted = false;
    int64_t last_ack_ms = 0;
    int64_t takeovers = 0;
    int64_t promise_remaining_ms = 0;
    std::string holder;
  };
  std::map<std::string, HaPeerState> ha_peers_state_;
  void record_peer_lease_locked(const std::string& peer, const Json& reply,
                                int64_t now);
  // Low 32 bits of the term-prefixed ids; reset to 0 at takeover.
  int64_t quorum_seq_in_term_ = 0;
  int64_t serving_seq_in_term_ = 0;
  std::thread election_thread_;

  LighthouseOpt opt_;

  std::mutex mu_;
  CondVar quorum_cv_;
  std::map<std::string, ParticipantDetails> participants_;
  std::map<std::string, int64_t> heartbeats_;
  // Incremental-quorum bookkeeping.  hb_expiry_/hb_pos_ index heartbeats_
  // by expiry time so a tick pops exactly the replicas whose freshness
  // transitioned instead of rescanning the fleet; dirty_ holds the
  // replicas whose quorum-relevant state (registration, freshness,
  // member fields) changed since the decision last ran; wake_deadline_ms_
  // is the next PURELY time-driven decision change (join-timeout wait).
  std::multimap<int64_t, std::string> hb_expiry_;
  std::map<std::string, std::multimap<int64_t, std::string>::iterator> hb_pos_;
  std::set<std::string> dirty_;
  int64_t wake_deadline_ms_ = INT64_MAX;
  // replica_id -> progress (pruned with heartbeats_ on supersession).
  std::map<std::string, ReplicaProgress> progress_;
  // Weight-serving membership (replica_id-ordered: the plan synthesis
  // is deterministic across rebuilds with unchanged membership) plus
  // the monotone plan epoch and the cached synthesized plan document.
  std::map<std::string, ServingMember> serving_;
  int64_t serving_epoch_ = 0;
  int64_t serving_heartbeats_total_ = 0;
  // Fleet link-state matrix keyed (src_host, peer, plane).  Rows age in
  // place when a host stops reporting (a faulted links plane degrades to
  // stale age_ms, never missing data) — memory stays bounded because a
  // host's next digest replaces ALL of its rows.
  std::map<std::tuple<std::string, std::string, std::string>, LinkRow>
      links_;
  // Monotone matrix version: the HA id idiom (term << 32 | seq), so a
  // reader comparing versions across a leader failover still orders
  // snapshots correctly with zero state transfer.
  int64_t links_version_ = 0;
  int64_t links_seq_in_term_ = 0;
  int64_t links_reports_total_ = 0;
  // Fleet fragment-version matrix keyed (holder host, frag_id).  Rows
  // upsert (digests are partial) and age in place when a host stops
  // reporting; memory stays bounded by hosts x held fragments, with a
  // per-report row cap as the hostile-reporter backstop.
  std::map<std::pair<std::string, std::string>, FragRow> fragments_;
  // Monotone matrix version under the same HA id idiom as links_.
  int64_t fragments_version_ = 0;
  int64_t fragments_seq_in_term_ = 0;
  int64_t fragments_reports_total_ = 0;
  // Rolling cluster step-timeline, keyed by step, capped to
  // opt_.timeline_ring buckets (oldest step evicted).
  std::map<int64_t, StepBucket> timeline_;
  // Fast-restart supersession bookkeeping: id -> eviction wall time (ms).
  // Presence is the supersession stamp: an evicted incarnation can never
  // re-register, heartbeat, or evict its successor (one-directional — the
  // lighthouse's arrival order IS the incarnation order).  Stamps are
  // effectively permanent (a zombie may go silent arbitrarily long and
  // must still be rejected on its eventual retry); a large count cap is
  // the only prune, as an extreme-restart-storm memory backstop.
  std::map<std::string, int64_t> evicted_at_ms_;
  std::optional<Quorum> prev_quorum_;
  int64_t quorum_id_ = 0;
  // Broadcast: monotonically increasing sequence of formed quorums.
  int64_t quorum_seq_ = 0;
  int64_t next_reg_token_ = 0;
  Quorum latest_quorum_;
  std::string last_reason_;

  // Native telemetry counters (served on GET /metrics, guarded by mu_).
  int64_t quorums_formed_total_ = 0;
  int64_t quorum_requests_total_ = 0;
  int64_t heartbeats_total_ = 0;
  // Tick-cost observability: every tick (including the O(1) skip path —
  // cheap ticks are the claim) lands in a fixed-bucket histogram, and
  // the dirty-set size the last decision consumed is exported as a
  // gauge, so "bounded tick cost" is measured, not assumed.
  static constexpr double kTickBuckets[] = {
      1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 2.5e-2, 1e-1, 1.0};
  static constexpr int kNumTickBuckets =
      static_cast<int>(sizeof(kTickBuckets) / sizeof(kTickBuckets[0]));
  int64_t tick_bucket_counts_[kNumTickBuckets + 1] = {0};  // +1: +Inf
  int64_t tick_count_ = 0;
  double tick_sum_s_ = 0.0;
  int64_t dirty_last_decision_ = 0;
  void observe_tick_locked(double seconds);

  std::mutex provider_mu_;
  MetricsProvider metrics_provider_ = nullptr;

  std::thread tick_thread_;
};

}  // namespace tft
