// Lighthouse: the cluster-wide membership & quorum authority.
//
// TPU-native C++ rebuild of the reference's Rust lighthouse
// (reference: src/lighthouse.rs). One lighthouse process (or in-process
// server) per job; replica-group managers call quorum() (blocking until a
// quorum containing them forms) and heartbeat(). Serves framed-JSON RPC and
// an HTML status dashboard on the same port (protocol sniffed per
// connection).
//
// Quorum decision rules (parity with reference src/lighthouse.rs:141-269):
//   - healthy = heartbeat within heartbeat_timeout_ms (joining counts).
//   - shrink_only: candidates filtered to previous-quorum members.
//   - fast quorum: all previous-quorum members healthy & participating.
//   - else: >= min_replicas healthy participants, AND strictly more than
//     half of all healthy replicas participating (split-brain guard), AND
//     either all healthy replicas joined or join_timeout_ms elapsed since
//     the first joiner (straggler wait).
//   - quorum_id bumps when membership changed vs previous quorum, or any
//     member reported commit_failures > 0.
#pragma once

#include <condition_variable>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <thread>
#include <vector>

#include "net.h"

namespace tft {

struct QuorumMember {
  std::string replica_id;
  std::string address;        // manager RPC address
  std::string store_address;  // rendezvous store address
  int64_t step = 0;
  int64_t world_size = 1;
  bool shrink_only = false;
  int64_t commit_failures = 0;
  std::string data;  // opaque JSON passthrough

  Json to_json() const;
  static QuorumMember from_json(const Json& j);
};

struct Quorum {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  int64_t created_ms = 0;  // wall-clock ms since unix epoch

  Json to_json() const;
  static Quorum from_json(const Json& j);
};

struct LighthouseOpt {
  std::string bind_host;  // advertise host; empty = machine hostname
  int port = 0;
  int64_t min_replicas = 1;
  int64_t join_timeout_ms = 100;
  int64_t quorum_tick_ms = 100;
  int64_t heartbeat_timeout_ms = 5000;
};

class LighthouseServer : public RpcServer {
 public:
  explicit LighthouseServer(const LighthouseOpt& opt);
  ~LighthouseServer() override;

  void start_serving();
  void stop();

  // Exposed for unit tests: run one quorum decision against current state.
  // Returns the quorum participants if a quorum formed (state updated).
  bool tick_for_test();

  // Prometheus /metrics supplement: a callback that writes additional
  // exposition text (the embedding process's metric registry) into the
  // caller's buffer.  Contract: returns bytes written, or the negated
  // required size when the buffer is too small (caller retries bigger).
  // NULL clears.  Called from HTTP handler threads — for the Python
  // (ctypes) provider that implies a GIL acquisition per scrape, which is
  // fine at scrape rates.
  using MetricsProvider = int (*)(char* buf, int cap);
  void set_metrics_provider(MetricsProvider provider);

 protected:
  Json handle(const std::string& method, const Json& params,
              int64_t timeout_ms) override;
  void handle_http(int fd, const std::string& request_head) override;
  void wake_blocked() override;

 private:
  struct ParticipantDetails {
    QuorumMember member;
    int64_t joined_ms = 0;
    // Monotone registration token: a quorum handler that EXITS without a
    // quorum (timeout/shutdown) deregisters its own entry, but only if no
    // newer handler for the same replica_id has re-registered since — a
    // dead requester must not linger as a "ghost participant" that
    // satisfies the next formation's barrier without anyone waiting on
    // the result (see rpc_quorum).
    int64_t reg_token = 0;
  };

  // Pure decision function over current state; returns participants if a
  // quorum can form now, plus a human-readable reason either way.
  std::optional<std::vector<QuorumMember>> quorum_compute(int64_t now,
                                                          std::string* reason);
  // Runs one tick under mu_: compute, bump quorum_id, broadcast.
  void tick_locked(int64_t now);
  void tick_loop();

  Json rpc_quorum(const Json& params, int64_t timeout_ms);
  Json rpc_heartbeat(const Json& params);
  std::string render_status_html();
  std::string render_status_json();
  std::string render_metrics();

  // Per-replica progress piggybacked on heartbeat/quorum RPCs — the
  // straggler-telemetry substrate.  step_changed_at_ms is LIGHTHOUSE
  // clock (stamped when a strictly larger step is first observed), so
  // straggler math never depends on cross-host clock sync;
  // last_step_wall_ms is the sender-clock stamp, reported for display.
  struct ReplicaProgress {
    int64_t step = -1;
    int64_t step_changed_at_ms = 0;
    int64_t last_step_wall_ms = 0;
    std::string inflight_op;
  };

  // One straggler-table row (computed, not stored).
  struct StragglerInfo {
    std::string replica_id;
    int64_t step = 0;
    int64_t step_lag = 0;          // max tracked step - this step
    int64_t progress_age_ms = 0;   // since last observed step advance
    int64_t last_step_wall_ms = 0; // sender-clock stamp, as reported
    double score = 0.0;            // age / median live age (~1 = typical)
    std::string inflight_op;
    bool stale = false;            // heartbeat past timeout
  };

 private:
  // Record progress for rid (caller holds mu_).
  void note_progress_locked(const std::string& rid, int64_t step,
                            int64_t last_step_wall_ms,
                            const std::string& inflight_op, int64_t now);
  // Straggler table over replicas with a heartbeat entry AND progress
  // (caller holds mu_).
  std::vector<StragglerInfo> compute_stragglers_locked(int64_t now);
  // The one status document served by the status RPC and /status.json
  // (locks mu_ internally).
  Json status_json();

  LighthouseOpt opt_;

  std::mutex mu_;
  CondVar quorum_cv_;
  std::map<std::string, ParticipantDetails> participants_;
  std::map<std::string, int64_t> heartbeats_;
  // replica_id -> progress (pruned with heartbeats_ on supersession).
  std::map<std::string, ReplicaProgress> progress_;
  // Fast-restart supersession bookkeeping: id -> eviction wall time (ms).
  // Presence is the supersession stamp: an evicted incarnation can never
  // re-register, heartbeat, or evict its successor (one-directional — the
  // lighthouse's arrival order IS the incarnation order).  Stamps are
  // effectively permanent (a zombie may go silent arbitrarily long and
  // must still be rejected on its eventual retry); a large count cap is
  // the only prune, as an extreme-restart-storm memory backstop.
  std::map<std::string, int64_t> evicted_at_ms_;
  std::optional<Quorum> prev_quorum_;
  int64_t quorum_id_ = 0;
  // Broadcast: monotonically increasing sequence of formed quorums.
  int64_t quorum_seq_ = 0;
  int64_t next_reg_token_ = 0;
  Quorum latest_quorum_;
  std::string last_reason_;

  // Native telemetry counters (served on GET /metrics, guarded by mu_).
  int64_t quorums_formed_total_ = 0;
  int64_t quorum_requests_total_ = 0;
  int64_t heartbeats_total_ = 0;

  std::mutex provider_mu_;
  MetricsProvider metrics_provider_ = nullptr;

  std::thread tick_thread_;
};

}  // namespace tft
