// Native zero-copy fragment data plane (ROADMAP item 3).
//
// The fragment hot path — serving relay pulls, striped heal, cold
// restore — used to cross the Python HTTP handlers byte by byte.  This
// server owns ONLY the data plane: Python stages raw wire-byte fragment
// payloads down at stage time (one copy into a pooled registered
// buffer), and every subsequent serve is a writev straight out of that
// buffer — zero user-space copies steady-state, no GIL anywhere.
// Python keeps all control: plans, manifests, digests-of-record,
// staging lifecycle, version advertisement.
//
// Semantics mirror the Python fragment plane exactly so the client can
// fall back per-fetch:
//   * streaming (begun, unfinished) version + missing fragment -> the
//     request PARKS on a condvar up to the long-poll window, then
//     answers 503 retryable-busy (the cut-through contract);
//   * complete version + missing fragment -> 404 (the fragment was
//     never raw-staged natively; Python owns it);
//   * unknown/retired version -> 404 (Python decides: store-serve,
//     legacy encode, or a real miss).
// All responses are keep-alive: the client pipelines fetches over one
// persistent connection per (thread, endpoint).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "net.h"

namespace tft {

// One staged fragment payload in a pool-recycled buffer.  `refs` counts
// in-flight serves (guarded by the server mutex); a retire that lands
// while a serve holds a ref marks the buffer zombie and the LAST deref
// recycles it — retire never blocks on the wire.
struct FragBuf {
  std::vector<uint8_t> data;  // capacity-pooled backing store
  size_t len = 0;             // staged payload length (<= data.size())
  int refs = 0;
  bool retired = false;
};

struct FragCounters {
  int64_t pool_hits = 0;
  int64_t pool_misses = 0;
  int64_t stage_copy_bytes = 0;  // the ONE copy: Python buffer -> pool
  int64_t serve_copies = 0;      // must stay 0: serve is pure writev
  int64_t serve_bytes = 0;
  int64_t serves = 0;
  int64_t parked_waits = 0;  // long-polls that actually waited
  int64_t busy_replies = 0;  // 503 retryable-busy answers
  int64_t miss_replies = 0;  // 404 fall-back-to-Python answers
  int64_t injected_drops = 0;
  int64_t injected_delays = 0;
};

class FragServer : public RpcServer {
 public:
  // bind_host may be "" (all interfaces); port 0 picks a free port.
  FragServer(const std::string& bind_host, int port);
  ~FragServer() override;

  // Staging lifecycle (driven by HTTPTransport's control plane).  All
  // return 0 on success, -1 on unknown/retired step (mirror of the
  // Python staging KeyError — callers treat it as "not mirrored").
  int begin(int64_t step);
  int stage(int64_t step, const std::string& resource, const uint8_t* data,
            size_t len);
  int finish(int64_t step);
  int retire(int64_t step);

  FragCounters counters() const;
  Json counters_json() const;

  // Fault injection for chaos tests: the next `count` data requests
  // either drop (close mid-exchange) or delay `param_ms` before the
  // body.  mode: "off" | "drop" | "delay".
  int inject(const std::string& mode, int64_t param_ms, int64_t count);

 protected:
  Json handle(const std::string& method, const Json& params,
              int64_t timeout_ms) override;
  const char* server_kind() const override { return "fragserver"; }
  bool handle_http_keepalive(int fd, const std::string& request_head) override;
  void wake_blocked() override;

 private:
  struct Version {
    bool complete = false;
    std::map<std::string, std::shared_ptr<FragBuf>> frags;  // by resource
  };

  std::shared_ptr<FragBuf> pool_take(size_t len);
  void pool_give_locked(FragBuf& buf);
  void deref(const std::shared_ptr<FragBuf>& buf);
  bool reply_simple(int fd, int status, const std::string& body);
  bool serve_frag(int fd, const std::shared_ptr<FragBuf>& buf);

  mutable std::mutex mu_;
  CondVar cv_;  // fragment-landed / shutdown wakeups for parked readers
  std::map<int64_t, Version> versions_;
  // Free-list keyed by exact capacity: fragment sizes repeat across
  // publishes, so steady-state stage traffic is all pool hits (the
  // bufpool miss-flat idiom, natively).
  std::map<size_t, std::vector<std::vector<uint8_t>>> pool_;
  FragCounters counters_;
  // injection state (guarded by mu_)
  int inject_mode_ = 0;  // 0 off, 1 drop, 2 delay
  int64_t inject_param_ms_ = 0;
  int64_t inject_count_ = 0;
};

// ---- native fragment client ---------------------------------------------
// Two-phase fetch so Python can own buffer allocation (its bufpool)
// while the byte-moving phase runs without the GIL (ctypes releases it
// around every call):
//   frag_fetch_begin  -> request on a per-(thread, endpoint) persistent
//                        connection; parses the response head; returns
//                        the HTTP status (200/404/503) or -1 transport
//                        error, with content length out.
//   frag_fetch_body   -> drains the body straight into the caller's
//                        buffer and computes sha256 over it in-place.
// A begin that returned 200 MUST be followed by exactly one body/abort.

int frag_fetch_begin(const std::string& addr, int64_t step,
                     const std::string& resource, int64_t timeout_ms,
                     int64_t* content_len, double* first_byte_s);
int frag_fetch_body(uint8_t* buf, int64_t cap, char* sha_hex_out /*65B*/,
                    int64_t timeout_ms);
void frag_fetch_abort();
void frag_client_close();
const std::string& frag_client_error();

// Streaming SHA-256 over one buffer, lowercase hex into out[64] + NUL.
void sha256_hex(const uint8_t* data, size_t len, char* out_hex65);

}  // namespace tft
