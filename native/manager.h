// Manager: per-replica-group coordination server.
//
// TPU-native C++ rebuild of the reference's Rust manager
// (reference: src/manager.rs). Runs on the group's rank-0 host, embedded in
// the trainer process. Aggregates the group's local ranks:
//   - quorum(): collects all world_size ranks' requests (storing each rank's
//     checkpoint transport metadata), then the last-arriving rank triggers
//     one Lighthouse quorum RPC; the resulting cluster quorum is turned into
//     per-rank instructions by compute_quorum_results and broadcast to all
//     blocked local waiters. Lighthouse failures retried quorum_retries
//     times with client re-creation (reference: src/manager.rs:250-327).
//   - should_commit(): barriers all local ranks, ANDs their votes
//     (reference: src/manager.rs:423-479).
//   - checkpoint_metadata(rank): serves the stored transport metadata.
//   - kill(): exits the process (chaos/dashboard endpoint).
// A background thread heartbeats the Lighthouse every heartbeat_interval_ms.
#pragma once

#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>

#include "lighthouse.h"
#include "net.h"

namespace tft {

struct QuorumResult {
  int64_t quorum_id = 0;
  std::string recover_src_manager_address;
  std::optional<int64_t> recover_src_replica_rank;
  std::vector<int64_t> recover_dst_replica_ranks;
  std::string store_address;
  int64_t max_step = 0;
  std::optional<int64_t> max_replica_rank;
  int64_t max_world_size = 0;
  int64_t replica_rank = 0;
  int64_t replica_world_size = 0;
  bool heal = false;
  int64_t commit_failures = 0;
  // Online parallelism switching: the layout-epoch spread across the
  // quorum (min == max == E commits a staged layout at epoch E fleet-
  // wide) and the participant roster in replica-rank order (replica_id,
  // manager address, layout_epoch, opaque shard manifest) — what lets
  // every group compute the same reshard slice-diff plan locally.
  int64_t max_layout_epoch = 0;
  int64_t min_layout_epoch = 0;
  std::vector<Json> participants;

  Json to_json() const;
};

// Pure function: turn a cluster Quorum into per-replica instructions.
// Parity with reference src/manager.rs:489-624. Throws if replica_id is not
// in the quorum.
QuorumResult compute_quorum_results(const std::string& replica_id,
                                    int64_t group_rank, const Quorum& quorum,
                                    bool init_sync);

struct ManagerOpt {
  std::string replica_id;
  std::string lighthouse_addr;
  std::string bind_host;  // advertise host for this manager server
  int port = 0;
  std::string store_address;  // the group's rendezvous store
  int64_t world_size = 1;     // local ranks in this replica group
  int64_t heartbeat_interval_ms = 100;
  int64_t connect_timeout_ms = 10000;
  int64_t quorum_retries = 0;
};

class ManagerServer : public RpcServer {
 public:
  explicit ManagerServer(const ManagerOpt& opt);
  ~ManagerServer() override;

  void start_serving();
  void stop();

  // Straggler telemetry: record this replica group's training progress;
  // the heartbeat loop piggybacks it (step, last_step_wall_ms,
  // inflight_op) on every lighthouse heartbeat.  Called by the Python
  // Manager at quorum entry and after each commit.
  void report_progress(int64_t step, const std::string& inflight_op);

  // Cluster step-timeline: record this group's per-step digest (JSON
  // object: step, phase_ms{...}, codec_busy_s, wire_busy_s).  The next
  // heartbeat carries it ONCE (consumed on send — a digest describes one
  // step; re-sending it every 100 ms heartbeat would overcount it in the
  // lighthouse's per-step aggregates).
  void report_summary(const Json& summary);

  // Link-state plane: record this replica's bounded link digest (JSON
  // object: host, rows[...] — utils/linkstats.py maybe_digest).  Same
  // consumed-on-send contract as report_summary: the next heartbeat
  // carries it ONCE, restored on RPC failure unless a newer digest
  // arrived (the fleet matrix keeps per-host latest, so duplicates are
  // harmless but wasteful).
  void report_links(const Json& links);

  // Fragment provenance plane: record this replica's bounded fragment
  // version-vector digest (JSON object: host, frags[...] —
  // checkpointing/provenance.py maybe_digest).  Same consumed-on-send /
  // restored-on-failure contract as report_links; the lighthouse folds
  // it into the fleet per-(host, frag_id) version matrix
  // (/fragments.json).
  void report_fragments(const Json& fragments);

 protected:
  Json handle(const std::string& method, const Json& params,
              int64_t timeout_ms) override;
  const char* server_kind() const override { return "manager"; }
  void wake_blocked() override;

 private:
  Json rpc_quorum(const Json& params, int64_t timeout_ms);
  Json rpc_should_commit(const Json& params, int64_t timeout_ms);
  void run_quorum(QuorumMember member, int64_t timeout_ms);
  void heartbeat_loop();

  ManagerOpt opt_;

  std::mutex mu_;
  CondVar cv_;
  // quorum round state
  std::map<int64_t, std::string> checkpoint_metadata_;  // rank -> metadata
  std::set<int64_t> quorum_participants_;
  int64_t quorum_round_seq_ = 0;
  std::optional<Quorum> latest_quorum_;    // result of round quorum_round_seq_
  std::string quorum_error_;               // non-empty if round failed
  // should_commit round state
  std::set<int64_t> commit_votes_;
  std::set<int64_t> commit_failures_;
  int64_t commit_round_seq_ = 0;
  int64_t commit_step_ = -1;  // step the open barrier round is voting on
  bool commit_decision_ = false;

  // progress state piggybacked on heartbeats (guarded by mu_)
  int64_t progress_step_ = -1;
  int64_t progress_wall_ms_ = 0;  // wall clock when step last advanced
  std::string progress_op_;
  // pending per-step digest; consumed by the next heartbeat (mu_)
  std::optional<Json> pending_summary_;
  // pending link-state digest; same consumed-on-send contract (mu_)
  std::optional<Json> pending_links_;
  // pending fragment-provenance digest; same contract (mu_)
  std::optional<Json> pending_fragments_;

  std::thread heartbeat_thread_;
  // Lighthouse quorum calls run on detached threads (bounded by the request
  // timeout); stop() waits for this to reach zero before destruction.
  std::atomic<int> inflight_quorums_{0};
};

}  // namespace tft
