#include "lighthouse.h"

#include <unistd.h>
#include <string.h>

#include <algorithm>
#include <cctype>
#include <chrono>
#include <cstdio>
#include <random>
#include <set>
#include <sstream>

namespace tft {

namespace {
int64_t wall_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// Prometheus label-value escaping (backslash, quote, newline).
std::string escape_label(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}
}  // namespace

Json QuorumMember::to_json() const {
  Json j = Json::object();
  j["replica_id"] = replica_id;
  j["address"] = address;
  j["store_address"] = store_address;
  j["step"] = step;
  j["world_size"] = world_size;
  j["shrink_only"] = shrink_only;
  j["commit_failures"] = commit_failures;
  j["layout_epoch"] = layout_epoch;
  j["data"] = data;
  return j;
}

QuorumMember QuorumMember::from_json(const Json& j) {
  QuorumMember m;
  m.replica_id = j.get("replica_id").as_string();
  m.address = j.get("address").as_string();
  m.store_address = j.get("store_address").as_string();
  m.step = j.get("step").as_int();
  m.world_size = j.get("world_size").as_int(1);
  m.shrink_only = j.get("shrink_only").as_bool();
  m.commit_failures = j.get("commit_failures").as_int();
  m.layout_epoch = j.get("layout_epoch").as_int(0);
  m.data = j.get("data").as_string();
  return m;
}

Json Quorum::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = quorum_id;
  Json parts = Json::array();
  for (const auto& p : participants) parts.push_back(p.to_json());
  j["participants"] = parts;
  j["created_ms"] = created_ms;
  return j;
}

Quorum Quorum::from_json(const Json& j) {
  Quorum q;
  q.quorum_id = j.get("quorum_id").as_int();
  for (const auto& p : j.get("participants").as_array())
    q.participants.push_back(QuorumMember::from_json(p));
  q.created_ms = j.get("created_ms").as_int();
  return q;
}

LighthouseServer::LighthouseServer(const LighthouseOpt& opt)
    : RpcServer(opt.bind_host, opt.port), opt_(opt) {
  peers_ = split_endpoints(opt_.peers);
  // Normalize the lease ONCE so every consumer (rpc_lease promise
  // stamps, become_leader, the election loop's round-validity bound)
  // agrees on the same value — a floor applied only in the elector
  // would let a sub-floor configuration elect on already-expired
  // grants.
  opt_.lease_timeout_ms = std::max<int64_t>(opt_.lease_timeout_ms, 40);
  // HA mode starts as a follower: leadership must be won by majority
  // lease acknowledgement, never assumed.
  if (ha_enabled()) is_leader_ = false;
}

LighthouseServer::~LighthouseServer() { stop(); }

void LighthouseServer::start_serving() {
  start();
  tick_thread_ = std::thread([this] { tick_loop(); });
  if (ha_enabled())
    election_thread_ = std::thread([this] { election_loop(); });
}

void LighthouseServer::stop() {
  shutdown();  // idempotent; closes conns and calls wake_blocked()
  if (tick_thread_.joinable()) tick_thread_.join();
  if (election_thread_.joinable()) election_thread_.join();
}

void LighthouseServer::wake_blocked() {
  std::lock_guard<std::mutex> g(mu_);
  quorum_cv_.notify_all();
}

void LighthouseServer::tick_loop() {
  while (!stopping_.load()) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      tick_locked(now_ms());
    }
    usleep(static_cast<useconds_t>(opt_.quorum_tick_ms * 1000));
  }
}

void LighthouseServer::touch_heartbeat_locked(const std::string& rid,
                                              int64_t now) {
  auto hb = heartbeats_.find(rid);
  // Dirty only on a freshness TRANSITION: a refresh of an already-fresh
  // replica cannot change the quorum decision, so it must not cost a
  // recompute — this is what keeps steady-state ticks O(1) while the
  // whole fleet heartbeats.
  bool was_fresh =
      hb != heartbeats_.end() && now - hb->second < opt_.heartbeat_timeout_ms;
  if (!was_fresh) dirty_.insert(rid);
  heartbeats_[rid] = now;
  auto pos = hb_pos_.find(rid);
  if (pos != hb_pos_.end()) hb_expiry_.erase(pos->second);
  hb_pos_[rid] =
      hb_expiry_.emplace(now + opt_.heartbeat_timeout_ms, rid);
}

void LighthouseServer::drop_heartbeat_locked(const std::string& rid) {
  heartbeats_.erase(rid);
  auto pos = hb_pos_.find(rid);
  if (pos != hb_pos_.end()) {
    hb_expiry_.erase(pos->second);
    hb_pos_.erase(pos);
  }
  dirty_.insert(rid);
}

std::optional<std::vector<QuorumMember>> LighthouseServer::quorum_compute(
    int64_t now, std::string* reason, int64_t* wake_deadline_ms) {
  // Healthy = heartbeat seen within the timeout window.
  std::set<std::string> healthy_replicas;
  for (const auto& [rid, last] : heartbeats_)
    if (now - last < opt_.heartbeat_timeout_ms) healthy_replicas.insert(rid);

  std::vector<const ParticipantDetails*> healthy_participants;
  for (const auto& [rid, det] : participants_)
    if (healthy_replicas.count(rid)) healthy_participants.push_back(&det);

  std::vector<QuorumMember> candidates;
  for (const auto* det : healthy_participants)
    candidates.push_back(det->member);
  std::sort(candidates.begin(), candidates.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  bool shrink_only = std::any_of(
      healthy_participants.begin(), healthy_participants.end(),
      [](const ParticipantDetails* d) { return d->member.shrink_only; });

  std::ostringstream meta;
  meta << "[" << healthy_participants.size() << "/" << participants_.size()
       << " participants healthy][" << healthy_replicas.size()
       << " heartbeating][shrink_only=" << (shrink_only ? "true" : "false")
       << "]";

  if (prev_quorum_.has_value()) {
    std::set<std::string> prev_ids;
    for (const auto& p : prev_quorum_->participants)
      prev_ids.insert(p.replica_id);

    if (shrink_only) {
      std::vector<QuorumMember> filtered;
      for (auto& c : candidates)
        if (prev_ids.count(c.replica_id)) filtered.push_back(c);
      candidates = std::move(filtered);
    }

    // Fast quorum: every member of the previous quorum is again a healthy
    // participant — no need to wait for join timeout.
    std::set<std::string> participating;
    for (const auto* d : healthy_participants)
      participating.insert(d->member.replica_id);
    bool fast = std::all_of(
        prev_ids.begin(), prev_ids.end(),
        [&](const std::string& id) { return participating.count(id) > 0; });
    if (fast) {
      *reason = "Fast quorum found! " + meta.str();
      return candidates;
    }
  }

  if (static_cast<int64_t>(healthy_participants.size()) < opt_.min_replicas) {
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need min_replicas " +
              std::to_string(opt_.min_replicas) + " " + meta.str();
    return std::nullopt;
  }

  // Split-brain guard: strictly more than half of all healthy replicas must
  // be participating.
  if (healthy_participants.size() <= healthy_replicas.size() / 2) {
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need at least half of " +
              std::to_string(healthy_replicas.size()) + " healthy workers " +
              meta.str();
    return std::nullopt;
  }

  bool all_healthy_joined =
      healthy_participants.size() == healthy_replicas.size();
  int64_t first_joined = now;
  for (const auto* d : healthy_participants)
    first_joined = std::min(first_joined, d->joined_ms);
  if (!all_healthy_joined && now - first_joined < opt_.join_timeout_ms) {
    // The only "no" that flips to "yes" by pure time passage: tell the
    // tick loop when to look again so the dirty-set gate can't sleep
    // through the join-timeout expiry.
    if (wake_deadline_ms != nullptr)
      *wake_deadline_ms = std::min(*wake_deadline_ms,
                                   first_joined + opt_.join_timeout_ms);
    *reason = "Valid quorum with " +
              std::to_string(healthy_participants.size()) +
              " participants, waiting for " +
              std::to_string(healthy_replicas.size() -
                             healthy_participants.size()) +
              " healthy but not participating stragglers due to join timeout " +
              meta.str();
    return std::nullopt;
  }

  *reason = "Valid quorum found " + meta.str();
  return candidates;
}

void LighthouseServer::observe_tick_locked(double seconds) {
  int b = 0;
  while (b < kNumTickBuckets && seconds > kTickBuckets[b]) ++b;
  tick_bucket_counts_[b] += 1;
  tick_count_ += 1;
  tick_sum_s_ += seconds;
}

void LighthouseServer::tick_locked(int64_t now) {
  auto t0 = std::chrono::steady_clock::now();
  // Pop heartbeats whose freshness expired since the last tick: the only
  // time-driven healthy-set change.  The expiry index is kept current by
  // touch_heartbeat_locked, so everything popped here genuinely
  // transitioned (a refresh re-inserted it at its new expiry).
  while (!hb_expiry_.empty() && hb_expiry_.begin()->first <= now) {
    const std::string rid = hb_expiry_.begin()->second;
    hb_pos_.erase(rid);
    hb_expiry_.erase(hb_expiry_.begin());
    dirty_.insert(rid);
  }
  // Weight-serving membership expiry: a dead serving replica must bump
  // the plan epoch promptly (the tree re-forms around it) even with no
  // serving RPC traffic.  O(serving fleet), microseconds at any
  // plausible size — the quorum dirty-set gate below is unaffected.
  serving_gc_locked(now);
  // HA: only a leader with a live lease may form quorums — a deposed or
  // lease-lapsed peer forming one could mint an id behind the current
  // leader's.  Heartbeat-expiry bookkeeping above keeps running.
  if (ha_enabled() && (!is_leader_ || now_ms() >= lease_until_ms_)) {
    observe_tick_locked(
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count());
    return;
  }
  // Dirty-set gate: with no state change and no timed deadline due, the
  // last decision is still the decision — skip the O(fleet) recompute.
  if (dirty_.empty() && now < wake_deadline_ms_) {
    // The gauge tracks the most recent TICK (0 = skipped), not the last
    // decision: an idle fleet must read ~0, not echo its join burst.
    dirty_last_decision_ = 0;
    observe_tick_locked(
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count());
    return;
  }
  dirty_last_decision_ = static_cast<int64_t>(dirty_.size());
  dirty_.clear();
  wake_deadline_ms_ = INT64_MAX;

  std::string reason;
  auto maybe = quorum_compute(now, &reason, &wake_deadline_ms_);
  last_reason_ = reason;
  if (!maybe.has_value()) {
    observe_tick_locked(
        std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
            .count());
    return;
  }

  std::vector<QuorumMember>& parts = *maybe;

  bool membership_changed = true;
  if (prev_quorum_.has_value()) {
    std::vector<std::string> a, b;
    for (const auto& p : parts) a.push_back(p.replica_id);
    for (const auto& p : prev_quorum_->participants) b.push_back(p.replica_id);
    membership_changed = a != b;
  }
  bool commit_failure = std::any_of(
      parts.begin(), parts.end(),
      [](const QuorumMember& p) { return p.commit_failures > 0; });
  if (membership_changed || commit_failure) {
    // Term-prefixed id (coordination-plane HA): (term << 32) | seq stays
    // strictly monotone across a leader change with zero state transfer
    // — a new leader's higher term dominates any predecessor's seq.  In
    // single-process mode term is 0 and this is the pre-HA +1.
    quorum_seq_in_term_ += 1;
    quorum_id_ = ha_epoch_id(term_, quorum_seq_in_term_);
  }

  Quorum q;
  q.quorum_id = quorum_id_;
  q.participants = parts;
  q.created_ms = wall_ms();

  prev_quorum_ = q;
  participants_.clear();
  // Consuming the registrations flips the cached reason back to "not
  // ready" — knowable without a recompute, so say it directly.  The old
  // full-rescan loop re-derived it by re-dirtying every participant,
  // which made each post-formation decision O(fleet) and pinned the
  // dirty gauge at fleet size even in steady state.
  last_reason_ = "Quorum " + std::to_string(quorum_id_) +
                 " formed with " + std::to_string(parts.size()) +
                 " members; waiting for new participants";
  latest_quorum_ = q;
  quorum_seq_ += 1;
  quorums_formed_total_ += 1;
  quorum_cv_.notify_all();
  observe_tick_locked(
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count());
}

bool LighthouseServer::tick_for_test() {
  std::lock_guard<std::mutex> g(mu_);
  int64_t seq = quorum_seq_;
  tick_locked(now_ms());
  return quorum_seq_ != seq;
}

// ---------------------------------------------------------------------------
// Coordination-plane HA: leased leadership among a static peer set.
// Lighthouse state is soft, so a takeover transfers nothing — the new
// leader's higher term prefixes every id it mints ((term << 32) | seq)
// and clients rebuild the membership/serving tables by re-registering.
// At-most-one-leader-per-term is enforced by the grant rule below: a
// peer's promised term is monotone, and a term granted to one candidate
// is never granted to another.
// ---------------------------------------------------------------------------

void LighthouseServer::require_leader_locked(const char* method) {
  if (!ha_enabled()) return;
  // A leader whose lease lapsed (renewals not landing) must stop
  // serving IMMEDIATELY, not when the election thread next notices: a
  // higher-term leader may already exist, and ids minted here would
  // regress behind its.  The election thread still does the formal
  // step-down/re-campaign.
  if (is_leader_ && now_ms() < lease_until_ms_) return;
  // Freshest hint: whoever holds this peer's current promise.  An empty
  // hint tells the client to keep walking its endpoint list.
  std::string hint =
      (now_ms() < promise_expires_ms_ && promised_to_ != address())
          ? promised_to_
          : "";
  throw NotLeaderError(
      std::string("lighthouse: not the leader for ") + method +
          (hint.empty() ? " (no leader known)" : " (leader: " + hint + ")"),
      hint);
}

Json LighthouseServer::rpc_lease(const Json& params) {
  std::lock_guard<std::mutex> g(mu_);
  lease_requests_total_ += 1;
  int64_t term = params.get("term").as_int();
  std::string candidate = params.get("candidate").as_string();
  if (candidate.empty()) throw std::runtime_error("lease: missing candidate");
  int64_t now = now_ms();
  // Grant rule (at-most-one-leader-per-term + lease safety):
  //   - renewal: the promise holder may refresh/raise its own term;
  //   - takeover: a NEW candidate needs a strictly higher term AND an
  //     unshielded promise slot.  The shield is the lease: a fresh grant
  //     to ANOTHER peer protects a live leader from impatient
  //     candidates.  This peer's own FAILED-candidacy self-promise does
  //     not shield (nobody leads on it; making rivals wait a lease for
  //     it just split-votes the election into lockstep) — unless this
  //     peer actually leads, in which case its own record shields like
  //     any granted lease.
  bool renewal = candidate == promised_to_ && term >= promised_term_;
  bool shielded = now < promise_expires_ms_ &&
                  !(promised_to_ == address() && !is_leader_) &&
                  !promised_to_.empty();
  bool takeover = term > promised_term_ && !shielded;
  bool granted = renewal || takeover;
  if (granted) {
    promised_term_ = term;
    promised_to_ = candidate;
    promise_expires_ms_ = now + opt_.lease_timeout_ms;
    if (is_leader_ && term > term_) {
      // We just acknowledged a higher-term leadership: stop serving NOW
      // so blocked quorum waiters fail over instead of timing out.
      is_leader_ = false;
      quorum_cv_.notify_all();
    }
  } else {
    max_seen_term_ = std::max(max_seen_term_, term);
  }
  Json out = Json::object();
  out["granted"] = granted;
  out["term"] = promised_term_;
  out["holder"] = promised_to_;
  // Observability federation (ISSUE 15): ride the existing lease
  // channel so the leader can serve per-peer coordination-plane health
  // (/status.json "ha.ha_peers") without a new RPC or a per-peer scrape.
  out["takeovers"] = takeovers_total_;
  out["promise_remaining_ms"] =
      promise_expires_ms_ > now ? promise_expires_ms_ - now : 0;
  return out;
}

void LighthouseServer::record_peer_lease_locked(const std::string& peer,
                                                const Json& reply,
                                                int64_t now) {
  HaPeerState& st = ha_peers_state_[peer];
  st.last_ack_ms = now;
  st.granted = reply.get("granted").as_bool();
  st.term = reply.get("term").as_int();
  if (reply.has("takeovers")) st.takeovers = reply.get("takeovers").as_int();
  if (reply.has("promise_remaining_ms"))
    st.promise_remaining_ms = reply.get("promise_remaining_ms").as_int();
  st.holder = reply.get("holder").as_string();
}

void LighthouseServer::become_leader_locked(int64_t term, int64_t now) {
  // ``now`` is the winning round's START, not its end: each grantor's
  // promise expires one lease after its grant was GIVEN (>= round
  // start), so anchoring our own lease at the round start guarantees we
  // stop serving before any grantor's promise can lapse and enable a
  // successor — the grant-side and leader-side lease clocks can only
  // disagree by clock RATE drift, never by round duration.
  is_leader_ = true;
  term_ = term;
  lease_until_ms_ = now + opt_.lease_timeout_ms;
  promised_term_ = term;
  promised_to_ = address();
  promise_expires_ms_ = now + opt_.lease_timeout_ms;
  takeovers_total_ += 1;
  // Fresh term => fresh low words: every id this leadership mints is
  // strictly larger than anything a lower-term leader could have minted.
  quorum_seq_in_term_ = 0;
  serving_seq_in_term_ = 0;
  quorum_id_ = ha_epoch_id(term_, 0);
  serving_epoch_ = ha_epoch_id(term_, 0);
  // Soft state from any PREVIOUS leadership of this peer is stale (the
  // fleet re-registered elsewhere in between): drop it and let clients
  // rebuild it, exactly as they would against a brand-new process.
  // Supersession stamps are deliberately kept — extra zombie safety when
  // this peer happens to remember them.
  participants_.clear();
  progress_.clear();
  heartbeats_.clear();
  hb_expiry_.clear();
  hb_pos_.clear();
  dirty_.clear();
  serving_.clear();
  prev_quorum_.reset();
  wake_deadline_ms_ = INT64_MAX;
  last_reason_ = "leadership takeover (term " + std::to_string(term_) +
                 "); waiting for participants to re-register";
  fprintf(stderr,
          "[torchft lighthouse %s] leadership takeover: term %lld\n",
          address().c_str(), static_cast<long long>(term_));
}

void LighthouseServer::bump_serving_epoch_locked() {
  serving_seq_in_term_ += 1;
  serving_epoch_ = ha_epoch_id(term_, serving_seq_in_term_);
}

namespace {
// One lease exchange with a SINGLE connect attempt: electors probe dead
// peers on every round, and a backoff-retry connect would burn most of
// a round's budget on a corpse (measured: perpetual split votes at
// small leases).  Returns false on any transport failure.
bool lease_rpc(const std::string& addr, const Json& lease_params,
               int64_t budget_ms, Json* reply) {
  int64_t deadline = now_ms() + budget_ms;
  int fd = connect_once(addr, budget_ms, nullptr);
  if (fd < 0) return false;
  Json req = Json::object();
  req["method"] = "lease";
  req["params"] = lease_params;
  req["timeout_ms"] = budget_ms;
  std::string raw;
  bool ok = send_frame(fd, req.dump(), deadline, nullptr) &&
            recv_frame(fd, &raw, deadline, nullptr);
  ::close(fd);
  if (!ok) return false;
  try {
    Json resp = Json::parse(raw);
    if (!resp.get("ok").as_bool()) return false;
    *reply = resp.get("result");
    return true;
  } catch (const std::exception&) {
    return false;
  }
}
}  // namespace

void LighthouseServer::election_loop() {
  std::mt19937_64 rng(std::random_device{}() ^
                      static_cast<uint64_t>(
                          reinterpret_cast<uintptr_t>(this)));
  const int64_t lease = opt_.lease_timeout_ms;  // floor-normalized in ctor
  const int64_t tick = std::max<int64_t>(lease / 4, 10);
  // Per-peer lease-RPC budget, sized so a FULL round (renewal or
  // candidacy) fits well inside one lease window: leases are anchored
  // at round start, so a round that outlived the window would be
  // acting on already-expired acknowledgements.
  const int64_t rpc_budget = std::max<int64_t>(
      std::min<int64_t>(
          lease / (2 * std::max<int64_t>(
                           static_cast<int64_t>(peers_.size()), 1)),
          1000),
      20);
  // Deterministic candidacy stagger: peers campaign in sorted-address
  // order, one tick apart.  The first candidate's lease request lands on
  // the later ones well inside their stagger window, turning them into
  // shielded followers instead of same-term split voters.
  int64_t stagger_ms = 0;
  {
    std::vector<std::string> all = peers_;
    all.push_back(address());
    std::sort(all.begin(), all.end());
    for (size_t i = 0; i < all.size(); ++i)
      if (all[i] == address()) stagger_ms = static_cast<int64_t>(i) * tick;
  }
  auto interruptible_sleep = [this](int64_t ms) {
    int64_t slept = 0;
    while (slept < ms && !stopping_.load()) {
      int64_t slice = std::min<int64_t>(ms - slept, 50);
      usleep(static_cast<useconds_t>(slice * 1000));
      slept += slice;
    }
  };
  while (!stopping_.load()) {
    bool leading;
    int64_t my_term;
    {
      std::lock_guard<std::mutex> g(mu_);
      leading = is_leader_;
      my_term = term_;
    }
    if (leading) {
      // Renew: one lease RPC per peer; self + grants must stay majority.
      // The extended lease is anchored at the ROUND START — a grantor's
      // promise expires one lease after its grant, so an end-anchored
      // clock would let a leader outlive its grantors by the round
      // duration and overlap a successor (model-checker finding).
      int64_t round_start = now_ms();
      Json lp = Json::object();
      lp["term"] = my_term;
      lp["candidate"] = address();
      int grants = 1;  // self
      for (const auto& peer : peers_) {
        if (stopping_.load()) return;
        Json r;
        if (lease_rpc(peer, lp, rpc_budget, &r)) {
          std::lock_guard<std::mutex> g(mu_);
          record_peer_lease_locked(peer, r, now_ms());
          if (r.get("granted").as_bool()) {
            grants += 1;
          } else {
            max_seen_term_ =
                std::max(max_seen_term_, r.get("term").as_int());
          }
        }
        // unreachable peer: counts as a missing grant (and its
        // ha_peers last-ack age keeps growing — the federation signal)
      }
      std::lock_guard<std::mutex> g(mu_);
      int64_t now = now_ms();
      if (is_leader_ && term_ == my_term) {
        if (now - round_start < lease &&
            grants * 2 > static_cast<int>(peers_.size()) + 1) {
          lease_until_ms_ =
              std::max(lease_until_ms_, round_start + lease);
          // refresh our own promise too: a live leader's own peer must
          // shield it from takeover exactly like every other grantor
          promised_term_ = my_term;
          promised_to_ = address();
          promise_expires_ms_ =
              std::max(promise_expires_ms_, round_start + lease);
        } else if (now >= lease_until_ms_) {
          // lost the majority for a full lease window: step down loudly
          // so blocked quorum waiters fail over instead of timing out
          is_leader_ = false;
          quorum_cv_.notify_all();
        }
      }
    } else {
      bool stale;
      {
        std::lock_guard<std::mutex> g(mu_);
        // Free to campaign when the granted promise lapsed (dead leader)
        // OR we only ever promised ourselves (a failed candidacy — no
        // leader is shielded by it, so waiting out our own stamp would
        // just slow the election down).
        stale = now_ms() >= promise_expires_ms_ ||
                promised_to_ == address() || promised_to_.empty();
      }
      if (stale && stagger_ms > 0) {
        // Give earlier-sorted candidates a head start: their lease
        // request usually lands during the stagger and shields us into
        // a follower (the atomic gate below then skips the campaign).
        interruptible_sleep(stagger_ms);
      }
      if (stale && !stopping_.load()) {
        // Candidacy: pick a term above anything we promised or saw
        // refused, self-grant it (same rule as rpc_lease — our own
        // promise lapsed), then ask the peers.  The whole round must
        // complete within ONE lease window: each peer's grant is only
        // valid for a lease from the moment it was given, so a round
        // bounded by the candidacy start guarantees every counted grant
        // is still un-expired at election time (the model checker found
        // the stale-grant two-leader interleaving this rules out).
        int64_t round_start = now_ms();
        int64_t cand_term = 0;
        {
          // The campaign gate and the self-grant are ONE critical
          // section, re-evaluated here rather than trusting the earlier
          // snapshot: a rival's lease grant may have landed on this
          // peer since (or during the stagger), and overwriting that
          // fresh promise with a self-grant would un-shield a possibly
          // winning leader — the check-then-grant race the model's
          // atomic e_candidate transition cannot exhibit.
          std::lock_guard<std::mutex> g(mu_);
          int64_t nw = now_ms();
          bool free_to_campaign = nw >= promise_expires_ms_ ||
                                  promised_to_ == address() ||
                                  promised_to_.empty();
          if (free_to_campaign) {
            cand_term =
                std::max(std::max(promised_term_, max_seen_term_), term_) +
                1;
            promised_term_ = cand_term;
            promised_to_ = address();
            promise_expires_ms_ = nw + lease;
          }
        }
        if (cand_term == 0) {
          // shielded meanwhile: back to following
          interruptible_sleep(tick);
          continue;
        }
        Json lp = Json::object();
        lp["term"] = cand_term;
        lp["candidate"] = address();
        int grants = 1;  // self
        for (const auto& peer : peers_) {
          if (stopping_.load()) return;
          Json r;
          if (lease_rpc(peer, lp, rpc_budget, &r)) {
            std::lock_guard<std::mutex> g(mu_);
            record_peer_lease_locked(peer, r, now_ms());
            if (r.get("granted").as_bool()) {
              grants += 1;
            } else {
              max_seen_term_ =
                  std::max(max_seen_term_, r.get("term").as_int());
            }
          }
        }
        std::lock_guard<std::mutex> g(mu_);
        if (now_ms() - round_start < lease &&
            grants * 2 > static_cast<int>(peers_.size()) + 1 &&
            promised_term_ == cand_term && promised_to_ == address()) {
          // lease anchored at the round START (see become_leader_locked)
          become_leader_locked(cand_term, round_start);
        }
      }
    }
    // Jittered sleep breaks any residual candidate symmetry the stagger
    // missed, sliced so stop() never waits out a full tick.
    interruptible_sleep(tick + static_cast<int64_t>(
                                   rng() % static_cast<uint64_t>(tick + 1)));
  }
}

Json LighthouseServer::ha_info() {
  std::lock_guard<std::mutex> g(mu_);
  bool leading = !ha_enabled() || is_leader_;
  Json out = Json::object();
  out["enabled"] = ha_enabled();
  out["term"] = term_;
  out["is_leader"] = leading;
  out["leader"] =
      leading ? address()
              : ((now_ms() < promise_expires_ms_ && promised_to_ != address())
                     ? promised_to_
                     : "");
  out["peers"] = static_cast<int64_t>(peers_.size());
  out["takeovers_total"] = takeovers_total_;
  out["quorum_id"] = quorum_id_;
  return out;
}

Json LighthouseServer::handle(const std::string& method, const Json& params,
                              int64_t timeout_ms) {
  // Peer-to-peer lease traffic is served by every peer; everything else
  // is leader-only in HA mode — a follower answers NOT_LEADER with the
  // freshest holder hint so clients jump straight to the leader (its
  // soft state is the only truthful copy).
  if (method == "lease") return rpc_lease(params);
  {
    std::lock_guard<std::mutex> g(mu_);
    require_leader_locked(method.c_str());
  }
  if (method == "quorum") return rpc_quorum(params, timeout_ms);
  if (method == "heartbeat") return rpc_heartbeat(params);
  if (method == "serving_heartbeat") return rpc_serving_heartbeat(params);
  if (method == "serving_plan") return rpc_serving_plan(params);
  // One status document for the RPC and GET /status.json: the dashboard
  // schema IS the programmatic schema (tests assert they round-trip),
  // including the pagination/shard controls.
  if (method == "status")
    return status_json(params.get("page").as_int(-1),
                       params.get("per_page").as_int(0),
                       params.get("replica").as_string());
  if (method == "timeline") return timeline_json();
  // Fleet link-state matrix: same document as GET /links.json.
  if (method == "links")
    return links_json(params.get("page").as_int(-1),
                      params.get("per_page").as_int(0));
  // Fleet fragment-version matrix: same document as GET /fragments.json.
  if (method == "fragments")
    return fragments_json(params.get("page").as_int(-1),
                          params.get("per_page").as_int(0));
  throw std::runtime_error("lighthouse: unknown method " + method);
}

Json LighthouseServer::rpc_quorum(const Json& params, int64_t timeout_ms) {
  QuorumMember requester = QuorumMember::from_json(params.get("member"));
  if (requester.replica_id.empty())
    throw std::runtime_error("missing requester replica_id");

  std::unique_lock<std::mutex> lk(mu_);
  int64_t now = now_ms();
  quorum_requests_total_ += 1;
  // Supersession is one-directional: an incarnation that has been evicted
  // (a newer incarnation of the same logical replica joined after it) can
  // never re-register or evict its successor, even if the old process is
  // still alive (hung, partitioned-then-rescheduled) and retries.  The
  // lighthouse's arrival order IS the incarnation order — uuid4 suffixes
  // carry none of their own.
  {
    auto ev = evicted_at_ms_.find(requester.replica_id);
    if (ev != evicted_at_ms_.end()) {
      ev->second = now;  // still calling -> still alive -> keep the stamp
      throw std::runtime_error(
          "superseded by a newer incarnation of this replica");
    }
  }
  // Implicit heartbeat + registration (+ progress: the member's step is
  // the freshest progress signal the straggler table can get).
  touch_heartbeat_locked(requester.replica_id, now);
  note_progress_locked(requester.replica_id, requester.step, 0, "quorum", now);
  int64_t my_token = ++next_reg_token_;
  participants_[requester.replica_id] = {requester, now, my_token};
  dirty_.insert(requester.replica_id);  // registration changes the decision
  // Fast-restart supersession: replica ids carry a ":uuid" incarnation
  // suffix (Manager appends it precisely so a restarted replica is not
  // confused with its dead predecessor). A new incarnation of the same
  // logical replica therefore proves the old one is gone — evict its
  // heartbeat immediately instead of letting the stale entry hold the
  // quorum in the join-timeout wait until heartbeat expiry. Measured:
  // cuts rejoin-quorum formation from ~join_timeout to the next tick.
  //
  // Convention: the segment after the last ':' is the INCARNATION suffix
  // (the Manager always appends ":uuid4"), so two ids sharing a non-empty
  // prefix are incarnations of one logical replica — at most one can be a
  // live process, and the newest joiner is it.  The superseded entry is
  // removed from heartbeats_ AND participants_ (a kill can land while the
  // old incarnation is blocked inside rpc_quorum, leaving its request
  // registered), and stamped in evicted_at_ms_ so the dead incarnation's
  // ghost handler thread (its client is gone but the handler blocks until
  // its RPC deadline) aborts instead of re-inserting the stale state from
  // its wait loop, and its background heartbeats are ignored (see
  // rpc_heartbeat).  Empty prefixes never match: default replica_id=""
  // gives every replica the ":uuid" shape — distinct logical replicas.
  {
    auto prefix_of = [](const std::string& id) {
      auto pos = id.rfind(':');
      return pos == std::string::npos ? id : id.substr(0, pos);
    };
    const std::string new_prefix = prefix_of(requester.replica_id);
    if (!new_prefix.empty()) {
      std::vector<std::string> superseded;
      for (const auto& [rid, last] : heartbeats_) {
        (void)last;
        if (rid != requester.replica_id && prefix_of(rid) == new_prefix)
          superseded.push_back(rid);
      }
      for (const auto& rid : superseded) {
        evicted_at_ms_[rid] = now;
        participants_.erase(rid);
        progress_.erase(rid);
        drop_heartbeat_locked(rid);  // also marks the decision dirty
      }
    }
    // Stamps are effectively PERMANENT: supersession is one-directional
    // for the lifetime of the job, because a superseded-but-still-alive
    // zombie may go silent for arbitrarily long (its manager stops
    // heartbeating on the superseded reply; a hung process can sleep
    // through any timeout) and must still be rejected when it finally
    // retries — otherwise it re-registers and evicts the live successor.
    // Each stamp is ~50 bytes and one is created per real restart, so
    // memory is bounded in practice; the count cap below is an
    // extreme-storm backstop (oldest first), far beyond any real job.
    constexpr size_t kMaxEvictionStamps = 100000;
    while (evicted_at_ms_.size() > kMaxEvictionStamps) {
      auto oldest = evicted_at_ms_.begin();
      for (auto it = evicted_at_ms_.begin(); it != evicted_at_ms_.end(); ++it)
        if (it->second < oldest->second) oldest = it;
      evicted_at_ms_.erase(oldest);
    }
  }
  int64_t seen_seq = quorum_seq_;
  // Proactive tick so a completing quorum doesn't wait for the next tick.
  tick_locked(now);

  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  // While blocked, keep our own heartbeat fresh in wait slices: a waiter is
  // by definition alive, and letting it age out would wedge quorum formation
  // for clients without a background heartbeat thread.
  auto wait_slice = std::chrono::milliseconds(
      std::max<int64_t>(1, std::min<int64_t>(opt_.heartbeat_timeout_ms / 2,
                                             1000)));
  // A handler that exits WITHOUT a quorum must take its registration with
  // it (token-guarded: never remove a newer handler's re-registration of
  // the same id).  Otherwise a dead requester lingers as a ghost
  // participant for up to one wait slice past its deadline, satisfying
  // the next formation's barrier with nobody behind it — the peer passes
  // the barrier alone and the real retry misses the quorum (measured as
  // a repeating 5 s miss in the restart-storm soak test).
  auto deregister_if_mine = [&]() {
    auto it = participants_.find(requester.replica_id);
    if (it != participants_.end() && it->second.reg_token == my_token) {
      participants_.erase(it);
      dirty_.insert(requester.replica_id);
    }
  };
  while (true) {
    // Leadership lost while this requester was parked: error out NOW so
    // the client's failover walk re-registers at the new leader instead
    // of waiting out its full quorum timeout on a deposed peer.
    if (ha_enabled() && (!is_leader_ || now_ms() >= lease_until_ms_)) {
      deregister_if_mine();
      require_leader_locked("quorum");  // throws NotLeaderError
    }
    // Superseded by a newer incarnation after we entered: abort BEFORE
    // re-registering anything (see eviction block above) — this handler
    // belongs to a replica whose replacement has already joined.  (The
    // entry check above guarantees we were not stamped at entry, so
    // presence alone means "evicted after we entered".)
    if (evicted_at_ms_.count(requester.replica_id))
      throw std::runtime_error(
          "superseded by a newer incarnation of this replica");
    if (quorum_seq_ != seen_seq) {
      seen_seq = quorum_seq_;
      const Quorum& q = latest_quorum_;
      bool included = std::any_of(
          q.participants.begin(), q.participants.end(),
          [&](const QuorumMember& p) {
            return p.replica_id == requester.replica_id;
          });
      if (included) {
        Json out = Json::object();
        out["quorum"] = q.to_json();
        return out;
      }
      // A quorum formed without us (e.g. we registered right after a tick
      // cleared participants) — re-register and keep waiting.
      my_token = ++next_reg_token_;
      participants_[requester.replica_id] = {requester, now_ms(), my_token};
      dirty_.insert(requester.replica_id);
    }
    if (stopping_.load()) {
      deregister_if_mine();
      throw std::runtime_error("lighthouse shutting down");
    }
    touch_heartbeat_locked(requester.replica_id, now_ms());
    if (std::chrono::steady_clock::now() >= deadline) {
      deregister_if_mine();
      throw TimeoutError("timeout waiting for quorum");
    }
    quorum_cv_.wait_for(lk, wait_slice);
  }
}

Json LighthouseServer::rpc_heartbeat(const Json& params) {
  std::lock_guard<std::mutex> g(mu_);
  const std::string rid = params.get("replica_id").as_string();
  heartbeats_total_ += 1;
  Json out = Json::object();
  // A superseded incarnation's background heartbeat thread must not
  // resurrect its heartbeats_ entry — that would make the zombie "healthy
  // but not participating" and wedge quorum behind join_timeout for as
  // long as the zombie lives.  Tell the caller instead of recording, and
  // REFRESH the stamp: a zombie that is still heartbeating is still alive,
  // so its stamp must outlive the age-based prune for as long as it keeps
  // calling (the prune only clears stamps of incarnations gone silent).
  auto ev = evicted_at_ms_.find(rid);
  if (ev != evicted_at_ms_.end()) {
    ev->second = now_ms();
    out["superseded"] = true;
    return out;
  }
  int64_t now = now_ms();
  touch_heartbeat_locked(rid, now);
  // Progress piggyback (optional params; a bare heartbeat stays valid):
  // step/last_step_wall_ms/inflight_op feed per-replica step-lag and
  // straggler-score telemetry.
  int64_t step = params.get("step").as_int(-1);
  if (step >= 0) {
    note_progress_locked(rid, step, params.get("last_step_wall_ms").as_int(0),
                         params.get("inflight_op").as_string(), now);
  }
  // Step-summary piggyback (optional): the replica's per-step digest
  // (phase timings, codec/wire busy) folds into the rolling cluster
  // timeline served at /timeline.json.
  const Json& summary = params.get("summary");
  if (summary.is_object()) note_summary_locked(rid, summary, now);
  // Link-digest piggyback (optional): the replica's bounded link table
  // folds into the fleet host-pair matrix served at /links.json.
  const Json& links = params.get("links");
  if (links.is_object()) note_links_locked(links, now);
  // Fragment-provenance piggyback (optional): the replica's bounded
  // version-vector digest folds into the fleet fragment matrix served
  // at /fragments.json.
  const Json& fragments = params.get("fragments");
  if (fragments.is_object()) note_fragments_locked(fragments, now);
  return out;
}

// ---------------------------------------------------------------------------
// Weight-serving tier: serving replicas register with serving_heartbeat;
// the lighthouse synthesizes the fan-out distribution tree served by
// serving_plan.  Membership changes bump the monotone serving epoch
// (the PR 10 layout-epoch idiom): a replica that adopted "epoch E" and
// one that adopted "epoch F" can never believe they share a tree, so a
// mid-churn tree switch is fleet-atomic without any extra round.
// ---------------------------------------------------------------------------

void LighthouseServer::serving_gc_locked(int64_t now) {
  // Expire members whose serving heartbeat went stale; any expiry is a
  // membership change => epoch bump (the tree re-forms around it).
  bool changed = false;
  for (auto it = serving_.begin(); it != serving_.end();) {
    if (now - it->second.last_hb_ms >= opt_.heartbeat_timeout_ms) {
      it = serving_.erase(it);
      changed = true;
    } else {
      ++it;
    }
  }
  if (changed) bump_serving_epoch_locked();
}

int64_t LighthouseServer::serving_latest_version_locked() const {
  // The pull target: newest version any PUBLISHER holds.  Server-held
  // versions don't count — a relay can never be ahead of its source.
  int64_t v = 0;
  for (const auto& [rid, m] : serving_) {
    (void)rid;
    if (m.role == "publisher") v = std::max(v, m.version);
  }
  return v;
}

int64_t LighthouseServer::serving_latest_version_ms_locked() const {
  // The staleness reference: publish stamp of the newest published
  // version.  Same clock as every member's version_ms (the publisher
  // mints both), so (latest_ms - member_ms) is skew-free.
  int64_t v = -1, vms = 0;
  for (const auto& [rid, m] : serving_) {
    (void)rid;
    if (m.role != "publisher") continue;
    if (m.version > v || (m.version == v && m.version_ms > vms)) {
      v = m.version;
      vms = m.version_ms;
    }
  }
  return vms;
}

Json LighthouseServer::rpc_serving_heartbeat(const Json& params) {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  serving_heartbeats_total_ += 1;
  serving_gc_locked(now);
  ServingMember m;
  m.replica_id = params.get("replica_id").as_string();
  if (m.replica_id.empty())
    throw std::runtime_error("serving_heartbeat: missing replica_id");
  m.address = params.get("address").as_string();
  m.role = params.get("role").as_string();
  if (m.role != "publisher" && m.role != "server")
    throw std::runtime_error(
        "serving_heartbeat: role must be publisher|server, got " + m.role);
  m.version = params.get("version").as_int(0);
  m.capacity = params.get("capacity").as_int(0);
  // Staleness ledger: publish wall-stamp of the held version, carried on
  // the publisher's clock (0 = unknown).  Not a tree-shape field.
  m.version_ms = params.get("version_ms").as_int(0);
  m.last_hb_ms = now;
  auto it = serving_.find(m.replica_id);
  // Epoch bumps only on TREE-SHAPE changes (join, address/role/capacity
  // change) — a version advance is the steady-state publish cadence and
  // must not re-plan the fleet every step.
  bool shape_changed =
      it == serving_.end() || it->second.address != m.address ||
      it->second.role != m.role || it->second.capacity != m.capacity;
  serving_[m.replica_id] = m;
  if (shape_changed) bump_serving_epoch_locked();
  // Fragment-provenance piggyback (optional): serving members (relays,
  // publishers) carry the same digest managers do, so the fleet matrix
  // sees every holder regardless of which heartbeat plane it rides.
  const Json& fragments = params.get("fragments");
  if (fragments.is_object()) note_fragments_locked(fragments, now);
  Json out = Json::object();
  out["plan_epoch"] = serving_epoch_;
  out["latest_version"] = serving_latest_version_locked();
  return out;
}

Json LighthouseServer::rpc_serving_plan(const Json& params) {
  (void)params;
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  serving_gc_locked(now);
  // Deterministic synthesis from the replica_id-ordered membership:
  // publishers are the tree's sources (root pulls from the
  // max-version publisher); servers are laid out BFS — node i's parent
  // is the earliest node with a free child slot (per-node capacity, or
  // the configured fanout) — so the same membership always yields the
  // same tree on every read, and a membership delta moves the minimum
  // number of edges (sorted order is stable under churn).
  std::vector<const ServingMember*> servers;
  std::string root_source;
  int64_t root_version = -1;
  Json publishers = Json::array();
  for (const auto& [rid, m] : serving_) {
    (void)rid;
    if (m.role == "publisher") {
      Json p = Json::object();
      p["replica_id"] = m.replica_id;
      p["address"] = m.address;
      p["version"] = m.version;
      p["version_ms"] = m.version_ms;
      publishers.push_back(p);
      if (m.version > root_version) {
        root_version = m.version;
        root_source = m.address;
      }
    } else {
      servers.push_back(&m);
    }
  }
  std::vector<int64_t> depth(servers.size(), 0);
  std::vector<int64_t> children(servers.size(), 0);
  std::vector<std::string> parent(servers.size(), "");
  // BFS slot queue: (server index, remaining child slots).
  std::vector<std::pair<size_t, int64_t>> slots;
  size_t head = 0;
  for (size_t i = 0; i < servers.size(); ++i) {
    int64_t cap = servers[i]->capacity > 0 ? servers[i]->capacity
                                           : opt_.serving_fanout;
    if (i > 0) {
      while (head < slots.size() && slots[head].second <= 0) ++head;
      if (head < slots.size()) {
        size_t pi = slots[head].first;
        slots[head].second -= 1;
        parent[i] = servers[pi]->address;
        depth[i] = depth[pi] + 1;
        children[pi] += 1;
      }
    }
    slots.emplace_back(i, cap);
  }
  Json nodes = Json::array();
  int64_t max_depth = 0;
  int64_t staleness_unknown = 0;
  const int64_t latest_ms = serving_latest_version_ms_locked();
  // Worst-K stalest serving nodes: ranked over KNOWN stamps only — an
  // unknown stamp (-1) is "no data", not "infinitely stale"; mixing it
  // into the ranking would either hide it (sorted last) or fake a
  // number.  Unknown nodes are counted distinctly instead.
  std::vector<std::pair<int64_t, size_t>> ranked;
  for (size_t i = 0; i < servers.size(); ++i) {
    Json n = Json::object();
    n["replica_id"] = servers[i]->replica_id;
    n["address"] = servers[i]->address;
    n["parent"] = parent[i];  // "" = root (pulls from root_source)
    n["depth"] = depth[i];
    n["children"] = children[i];
    // Per-node slot budget the BFS consumed (0 = the plan-wide fanout):
    // plan verifiers/adapters need the INPUT bound, not just the
    // resulting child count, to check the fanout invariant.
    n["capacity"] = servers[i]->capacity;
    n["version"] = servers[i]->version;
    // Staleness ledger: how far behind the newest PUBLISH this node's
    // held version is, in publish-clock ms (-1 = unknown — the node has
    // not yet reported a stamped version).  Both stamps are minted by
    // publishers, so the difference is skew-free across hosts.
    n["version_ms"] = servers[i]->version_ms;
    bool known = latest_ms > 0 && servers[i]->version_ms > 0;
    int64_t stale_ms =
        known ? std::max<int64_t>(latest_ms - servers[i]->version_ms, 0)
              : -1;
    n["staleness_ms"] = stale_ms;
    // Renderer contract: "is -1 unknown or a value?" must not be an
    // inline sentinel test at every consumer — the flag names it.
    n["staleness_known"] = known;
    if (known)
      ranked.emplace_back(stale_ms, i);
    else
      staleness_unknown += 1;
    nodes.push_back(n);
    max_depth = std::max(max_depth, depth[i]);
  }
  std::sort(ranked.begin(), ranked.end(),
            [&servers](const std::pair<int64_t, size_t>& a,
                       const std::pair<int64_t, size_t>& b) {
              if (a.first != b.first) return a.first > b.first;
              return servers[a.second]->replica_id <
                     servers[b.second]->replica_id;
            });
  Json stalest = Json::array();
  size_t topk = std::min<size_t>(
      ranked.size(), static_cast<size_t>(opt_.straggler_topk));
  for (size_t i = 0; i < topk; ++i) {
    Json w = Json::object();
    w["replica_id"] = servers[ranked[i].second]->replica_id;
    w["version"] = servers[ranked[i].second]->version;
    w["staleness_ms"] = ranked[i].first;
    stalest.push_back(w);
  }
  Json out = Json::object();
  out["epoch"] = serving_epoch_;
  out["generated_ms"] = wall_ms();
  out["fanout"] = opt_.serving_fanout;
  out["latest_version"] = serving_latest_version_locked();
  out["latest_version_ms"] = latest_ms;
  out["root_source"] = root_source;
  out["publishers"] = publishers;
  out["nodes"] = nodes;
  out["depth"] = max_depth;
  out["stalest"] = stalest;
  out["staleness_unknown"] = staleness_unknown;
  return out;
}

void LighthouseServer::note_summary_locked(const std::string& rid,
                                           const Json& summary, int64_t now) {
  int64_t step = summary.get("step").as_int(-1);
  if (step < 0) return;
  if (static_cast<int64_t>(timeline_.size()) >= opt_.timeline_ring &&
      !timeline_.empty() && step < timeline_.begin()->first &&
      timeline_.count(step) == 0) {
    return;  // older than the full ring's horizon: evicted, stay evicted
  }
  StepBucket& b = timeline_[step];
  if (b.reports == 0) {
    b.step = step;
    b.first_ms = now;
  }
  b.last_ms = now;
  b.reports += 1;
  b.replicas.insert(rid);
  for (const auto& [phase, val] : summary.get("phase_ms").as_object()) {
    PhaseAgg& agg = b.phases[phase];
    double ms = val.as_double(0.0);
    agg.n += 1;
    agg.sum_ms += ms;
    agg.max_ms = std::max(agg.max_ms, ms);
  }
  b.codec_busy_s += summary.get("codec_busy_s").as_double(0.0);
  b.wire_busy_s += summary.get("wire_busy_s").as_double(0.0);
  while (static_cast<int64_t>(timeline_.size()) > opt_.timeline_ring)
    timeline_.erase(timeline_.begin());
}

Json LighthouseServer::timeline_json() {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  Json out = Json::object();
  out["quorum_id"] = quorum_id_;
  out["now_ms"] = wall_ms();
  out["ring"] = opt_.timeline_ring;
  out["steps_tracked"] = static_cast<int64_t>(timeline_.size());
  Json steps = Json::array();
  for (const auto& [step, b] : timeline_) {
    (void)step;
    Json row = Json::object();
    row["step"] = b.step;
    row["replicas"] = static_cast<int64_t>(b.replicas.size());
    row["reports"] = b.reports;
    row["first_ms"] = b.first_ms;
    row["last_ms"] = b.last_ms;
    row["span_ms"] = b.last_ms - b.first_ms;
    Json phases = Json::object();
    for (const auto& [name, agg] : b.phases) {
      Json p = Json::object();
      p["n"] = agg.n;
      p["mean_ms"] = agg.n > 0 ? agg.sum_ms / static_cast<double>(agg.n) : 0.0;
      p["max_ms"] = agg.max_ms;
      phases[name] = p;
    }
    row["phases"] = phases;
    row["codec_busy_s"] = b.codec_busy_s;
    row["wire_busy_s"] = b.wire_busy_s;
    steps.push_back(row);
  }
  out["steps"] = steps;
  // Worst-K straggler snapshot rides along so one /timeline.json scrape
  // answers both "what was the fleet doing" and "who is holding it up"
  // (torchft-diagnose --timeline consumes exactly this document).
  Json worst = Json::array();
  for (const auto& s : worst_stragglers_locked(now)) {
    Json row = Json::object();
    row["replica_id"] = s.replica_id;
    row["step"] = s.step;
    row["step_lag"] = s.step_lag;
    row["progress_age_ms"] = s.progress_age_ms;
    row["straggler_score"] = s.score;
    row["inflight_op"] = s.inflight_op;
    row["stale"] = s.stale;
    worst.push_back(row);
  }
  out["stragglers_worst"] = worst;
  return out;
}

// ---------------------------------------------------------------------------
// Fleet link-state plane: replicas piggyback their bounded link digests
// (utils/linkstats.py maybe_digest) on heartbeats; the lighthouse folds
// them into a host-pair matrix.  Per-host latest-wins replacement keeps
// the table bounded by hosts x digest size; a host that stops reporting
// leaves its rows aging in place (stale age_ms, never missing data) —
// the chaos-degradation contract of the lighthouse.links fault site.
// ---------------------------------------------------------------------------

void LighthouseServer::note_links_locked(const Json& links, int64_t now) {
  const std::string host = links.get("host").as_string();
  if (host.empty()) return;
  const Json& rows = links.get("rows");
  if (!rows.is_array()) return;
  for (auto it = links_.begin(); it != links_.end();) {
    if (std::get<0>(it->first) == host)
      it = links_.erase(it);
    else
      ++it;
  }
  // Defensive row cap: the digest is worst-K bounded at the replica, but
  // a hostile/miswired reporter must not grow the matrix unboundedly.
  size_t n = 0;
  for (const Json& r : rows.as_array()) {
    if (!r.is_object() || ++n > 64) continue;
    LinkRow row;
    row.src_host = host;
    row.peer = r.get("peer").as_string();
    row.plane = r.get("plane").as_string();
    if (row.peer.empty() || row.plane.empty()) continue;
    row.local = r.get("local").as_bool(false);
    row.goodput_bps = r.get("goodput_bps").as_double(0.0);
    row.rtt_ms = r.get("rtt_ms").as_double(0.0);
    row.rtt_p99_ms = r.get("rtt_p99_ms").as_double(0.0);
    row.samples = r.get("samples").as_int(0);
    row.bytes = r.get("bytes").as_int(0);
    row.updated_ms = now;
    links_[{host, row.peer, row.plane}] = row;
  }
  links_reports_total_ += 1;
  // Monotone matrix version, ordered across leader failovers by the HA
  // id idiom — equal versions name an identical matrix.
  links_version_ = ha_epoch_id(term_, ++links_seq_in_term_);
}

void LighthouseServer::note_fragments_locked(const Json& fragments,
                                             int64_t now) {
  const std::string host = fragments.get("host").as_string();
  if (host.empty()) return;
  const Json& rows = fragments.get("frags");
  if (!rows.is_array()) return;
  // UPSERT per row — NOT the links wipe-all: a provenance digest is
  // partial (worst-K stalest + changed-since-last-report), so a host's
  // unchanged fragments must keep their previous rows.  Defensive row
  // cap: the digest is bounded at the replica, but a hostile/miswired
  // reporter must not grow the matrix unboundedly.
  size_t n = 0;
  for (const Json& r : rows.as_array()) {
    if (!r.is_object() || ++n > 128) continue;
    FragRow row;
    row.host = host;
    row.frag = r.get("frag").as_string();
    if (row.frag.empty()) continue;
    row.version = r.get("version").as_int(0);
    row.digest8 = r.get("digest8").as_string();
    row.version_ms = r.get("version_ms").as_int(0);
    row.held_ms = r.get("held_ms").as_int(0);
    row.pub = r.get("pub").as_bool(false);
    row.updated_ms = now;
    // Version-vector fold: a holder's newer version for a frag_id
    // replaces its older row; a stale duplicate (an out-of-order
    // restored digest) must not roll the matrix backwards.
    auto it = fragments_.find({host, row.frag});
    if (it != fragments_.end() && it->second.version > row.version)
      continue;
    fragments_[{host, row.frag}] = row;
  }
  fragments_reports_total_ += 1;
  fragments_version_ = ha_epoch_id(term_, ++fragments_seq_in_term_);
}

void LighthouseServer::note_progress_locked(const std::string& rid,
                                            int64_t step,
                                            int64_t last_step_wall_ms,
                                            const std::string& inflight_op,
                                            int64_t now) {
  if (step < 0) return;
  ReplicaProgress& p = progress_[rid];
  if (step > p.step) {
    // Stamped on OBSERVED advance with the lighthouse clock: straggler
    // ages stay meaningful without cross-host clock sync.
    p.step = step;
    p.step_changed_at_ms = now;
  } else if (p.step_changed_at_ms == 0) {
    p.step_changed_at_ms = now;  // first report at step 0
  }
  if (last_step_wall_ms > 0) p.last_step_wall_ms = last_step_wall_ms;
  p.inflight_op = inflight_op;
}

std::vector<LighthouseServer::StragglerInfo>
LighthouseServer::compute_stragglers_locked(int64_t now) {
  // Rows: every replica the lighthouse still tracks (a heartbeats_ entry;
  // superseded incarnations are pruned) that has reported progress.  A
  // replica with a stale heartbeat stays in the table until eviction —
  // the dead replica's growing lag/score is exactly the signal the
  // operator needs BEFORE the quorum shrinks around it.
  std::vector<StragglerInfo> rows;
  int64_t max_step = 0;
  for (const auto& [rid, p] : progress_) {
    if (!heartbeats_.count(rid)) continue;
    max_step = std::max(max_step, p.step);
  }
  std::vector<int64_t> fresh_ages;
  for (const auto& [rid, p] : progress_) {
    auto hb = heartbeats_.find(rid);
    if (hb == heartbeats_.end()) continue;
    StragglerInfo row;
    row.replica_id = rid;
    row.step = p.step;
    row.step_lag = max_step - p.step;
    row.progress_age_ms = std::max<int64_t>(now - p.step_changed_at_ms, 0);
    row.last_step_wall_ms = p.last_step_wall_ms;
    row.inflight_op = p.inflight_op;
    row.stale = (now - hb->second) >= opt_.heartbeat_timeout_ms;
    if (!row.stale) fresh_ages.push_back(row.progress_age_ms);
    rows.push_back(std::move(row));
  }
  // Score = progress age normalized by the median age of replicas with a
  // fresh heartbeat (~1 = typical cadence; a wedged or dead replica's
  // score grows without bound).  Median over the fresh cohort so one dead
  // replica cannot drag the baseline up and hide itself.
  std::sort(fresh_ages.begin(), fresh_ages.end());
  double median = fresh_ages.empty()
                      ? 1.0
                      : static_cast<double>(
                            fresh_ages[fresh_ages.size() / 2]);
  if (median < 1.0) median = 1.0;
  for (auto& row : rows)
    row.score = static_cast<double>(row.progress_age_ms) / median;
  return rows;
}

std::vector<LighthouseServer::StragglerInfo>
LighthouseServer::worst_stragglers(std::vector<StragglerInfo> rows) {
  // Stale rows first (a dead replica is always worth a row), then by
  // descending score — the bounded "summary tier" every unbounded
  // surface (per-replica /metrics labels, the dashboard straggler
  // table, the default status document) renders instead of the fleet.
  std::sort(rows.begin(), rows.end(),
            [](const StragglerInfo& a, const StragglerInfo& b) {
              if (a.stale != b.stale) return a.stale > b.stale;
              if (a.score != b.score) return a.score > b.score;
              return a.replica_id < b.replica_id;
            });
  if (static_cast<int64_t>(rows.size()) > opt_.straggler_topk)
    rows.resize(static_cast<size_t>(opt_.straggler_topk));
  return rows;
}

std::vector<LighthouseServer::StragglerInfo>
LighthouseServer::worst_stragglers_locked(int64_t now) {
  return worst_stragglers(compute_stragglers_locked(now));
}

namespace {
// Minimal query-string parser: "/p?a=1&b=x" -> {a:"1", b:"x"}.  Values
// are used as integers or replica ids; %-unescaping covers the one
// character replica ids legitimately carry in queries (%3A for ':').
std::map<std::string, std::string> parse_query(const std::string& path) {
  std::map<std::string, std::string> out;
  auto qpos = path.find('?');
  if (qpos == std::string::npos) return out;
  std::string q = path.substr(qpos + 1);
  size_t start = 0;
  while (start <= q.size()) {
    size_t amp = q.find('&', start);
    std::string kv = q.substr(
        start, amp == std::string::npos ? std::string::npos : amp - start);
    auto eq = kv.find('=');
    if (eq != std::string::npos) {
      std::string key = kv.substr(0, eq);
      std::string val = kv.substr(eq + 1);
      std::string decoded;
      for (size_t i = 0; i < val.size(); ++i) {
        // Decode only well-formed escapes; a malformed one (%zz, trailing
        // %) passes through literally instead of throwing out of the
        // HTTP handler and dropping the request with no response.
        if (val[i] == '%' && i + 2 < val.size() &&
            std::isxdigit(static_cast<unsigned char>(val[i + 1])) &&
            std::isxdigit(static_cast<unsigned char>(val[i + 2]))) {
          decoded += static_cast<char>(
              std::stoi(val.substr(i + 1, 2), nullptr, 16));
          i += 2;
        } else if (val[i] == '+') {
          decoded += ' ';
        } else {
          decoded += val[i];
        }
      }
      out[key] = decoded;
    }
    if (amp == std::string::npos) break;
    start = amp + 1;
  }
  return out;
}

int64_t query_int(const std::map<std::string, std::string>& q,
                  const std::string& key, int64_t dflt) {
  auto it = q.find(key);
  if (it == q.end()) return dflt;
  try {
    return std::stoll(it->second);
  } catch (const std::exception&) {
    return dflt;
  }
}
}  // namespace

void LighthouseServer::handle_http(int fd, const std::string& request_head) {
  // First line: "METHOD /path HTTP/1.1"
  std::istringstream is(request_head);
  std::string method, full_path;
  is >> method >> full_path;
  auto query = parse_query(full_path);
  std::string path = full_path.substr(0, full_path.find('?'));

  if (method == "POST" && path.rfind("/replica/", 0) == 0) {
    // /replica/{id}/kill — forward a kill RPC to that replica's manager.
    std::string rest = path.substr(strlen("/replica/"));
    size_t slash = rest.find('/');
    if (slash != std::string::npos && rest.substr(slash) == "/kill") {
      std::string replica_id = rest.substr(0, slash);
      std::string addr;
      {
        std::lock_guard<std::mutex> g(mu_);
        if (prev_quorum_.has_value())
          for (const auto& p : prev_quorum_->participants)
            if (p.replica_id == replica_id) addr = p.address;
      }
      if (addr.empty()) {
        http_reply(fd, 404, "text/plain", "replica not found\n");
        return;
      }
      Json params = Json::object();
      params["msg"] = "killed from dashboard";
      Json result;
      std::string err;
      // Kill exits the remote process mid-RPC, so failure to read a reply is
      // expected; fire and report accepted.
      call_rpc(addr, "kill", params, 5000, &result, &err);
      http_reply(fd, 200, "text/plain", "kill sent to " + replica_id + "\n");
      return;
    }
  }
  if (method == "GET" && (path == "/" || path == "/status")) {
    http_reply(fd, 200, "text/html",
               render_status_html(query_int(query, "page", 0)));
    return;
  }
  if (method == "GET" && path == "/status.json") {
    auto rep = query.find("replica");
    http_reply(fd, 200, "application/json",
               status_json(query_int(query, "page", -1),
                           query_int(query, "per_page", 0),
                           rep == query.end() ? "" : rep->second)
                   .dump());
    return;
  }
  if (method == "GET" && path == "/timeline.json") {
    http_reply(fd, 200, "application/json", timeline_json().dump());
    return;
  }
  if (method == "GET" && path == "/serving.json") {
    // Same document as the serving_plan RPC (the dashboard idiom: the
    // HTTP surface IS the programmatic surface).
    http_reply(fd, 200, "application/json",
               rpc_serving_plan(Json::object()).dump());
    return;
  }
  if (method == "GET" && path == "/links.json") {
    // Same document as the links RPC: the fleet link-state matrix.
    http_reply(fd, 200, "application/json",
               links_json(query_int(query, "page", -1),
                          query_int(query, "per_page", 0))
                   .dump());
    return;
  }
  if (method == "GET" && path == "/fragments.json") {
    // Same document as the fragments RPC: the fleet fragment matrix.
    http_reply(fd, 200, "application/json",
               fragments_json(query_int(query, "page", -1),
                              query_int(query, "per_page", 0))
                   .dump());
    return;
  }
  if (method == "GET" && path == "/metrics") {
    http_reply(fd, 200, "text/plain; version=0.0.4", render_metrics());
    return;
  }
  http_reply(fd, 404, "text/plain", "not found\n");
}

void LighthouseServer::set_metrics_provider(MetricsProvider provider) {
  std::lock_guard<std::mutex> g(provider_mu_);
  metrics_provider_ = provider;
}

std::string LighthouseServer::render_metrics() {
  // Prometheus text exposition 0.0.4: native lighthouse counters/gauges,
  // then whatever the embedding process's registry supplies (the Python
  // side registers a provider that renders torchft_tpu.utils.metrics).
  std::ostringstream os;
  {
    std::lock_guard<std::mutex> g(mu_);
    int64_t now = now_ms();
    int64_t fresh = 0;
    for (const auto& [rid, ts] : heartbeats_)
      if (now - ts < opt_.heartbeat_timeout_ms) fresh += 1;
    os << "# HELP torchft_lighthouse_quorums_formed_total Quorums formed "
          "since lighthouse start\n"
       << "# TYPE torchft_lighthouse_quorums_formed_total counter\n"
       << "torchft_lighthouse_quorums_formed_total " << quorums_formed_total_
       << "\n"
       << "# HELP torchft_lighthouse_quorum_requests_total Quorum RPC "
          "requests received\n"
       << "# TYPE torchft_lighthouse_quorum_requests_total counter\n"
       << "torchft_lighthouse_quorum_requests_total "
       << quorum_requests_total_ << "\n"
       << "# HELP torchft_lighthouse_heartbeats_total Heartbeat RPCs "
          "received\n"
       << "# TYPE torchft_lighthouse_heartbeats_total counter\n"
       << "torchft_lighthouse_heartbeats_total " << heartbeats_total_ << "\n"
       << "# HELP torchft_lighthouse_quorum_id Current quorum id\n"
       << "# TYPE torchft_lighthouse_quorum_id gauge\n"
       << "torchft_lighthouse_quorum_id " << quorum_id_ << "\n"
       << "# HELP torchft_lighthouse_participants Participants waiting in "
          "the next quorum\n"
       << "# TYPE torchft_lighthouse_participants gauge\n"
       << "torchft_lighthouse_participants "
       << static_cast<int64_t>(participants_.size()) << "\n"
       << "# HELP torchft_lighthouse_heartbeats_live Replicas with a fresh "
          "heartbeat\n"
       << "# TYPE torchft_lighthouse_heartbeats_live gauge\n"
       << "torchft_lighthouse_heartbeats_live " << fresh << "\n";
    // Coordination-plane HA: leadership term, role and takeover count.
    // Exported in single-process mode too (term 0, leader 1) so alerting
    // rules need no mode switch.
    os << "# HELP torchft_lighthouse_term Leadership term this peer "
          "leads/last led under (prefixes quorum_id and the serving "
          "epoch as (term << 32) | seq)\n"
       << "# TYPE torchft_lighthouse_term gauge\n"
       << "torchft_lighthouse_term " << term_ << "\n"
       << "# HELP torchft_lighthouse_is_leader 1 when this peer serves "
          "leader-only RPCs (single-process mode: always 1)\n"
       << "# TYPE torchft_lighthouse_is_leader gauge\n"
       << "torchft_lighthouse_is_leader "
       << ((!ha_enabled() || is_leader_) ? 1 : 0) << "\n"
       << "# HELP torchft_lighthouse_takeovers_total Leadership takeovers "
          "won by this peer since start\n"
       << "# TYPE torchft_lighthouse_takeovers_total counter\n"
       << "torchft_lighthouse_takeovers_total " << takeovers_total_ << "\n"
       << "# HELP torchft_lighthouse_lease_requests_total Lease RPCs "
          "received from peer electors\n"
       << "# TYPE torchft_lighthouse_lease_requests_total counter\n"
       << "torchft_lighthouse_lease_requests_total " << lease_requests_total_
       << "\n";
    // Peer federation (ISSUE 15): the lease channel doubles as the
    // coordination plane's health feed — per-peer series are bounded by
    // the static endpoint list, so cardinality is a config constant.
    if (!ha_peers_state_.empty()) {
      os << "# HELP torchft_lighthouse_peer_term Peer's promised "
            "leadership term at its last lease ack\n"
         << "# TYPE torchft_lighthouse_peer_term gauge\n";
      for (const auto& [addr, st] : ha_peers_state_)
        os << "torchft_lighthouse_peer_term{peer=\"" << addr << "\"} "
           << st.term << "\n";
      os << "# HELP torchft_lighthouse_peer_lease_ack_age_ms Milliseconds "
            "since the peer last answered a lease RPC (-1 = never)\n"
         << "# TYPE torchft_lighthouse_peer_lease_ack_age_ms gauge\n";
      for (const auto& [addr, st] : ha_peers_state_)
        os << "torchft_lighthouse_peer_lease_ack_age_ms{peer=\"" << addr
           << "\"} " << (st.last_ack_ms > 0 ? now - st.last_ack_ms : -1)
           << "\n";
      // no _total suffix: this is a GAUGE echo of the peer's own
      // counter (last observed value, resets invisible here)
      os << "# HELP torchft_lighthouse_peer_takeovers Leadership "
            "takeovers the peer reported at its last lease ack\n"
         << "# TYPE torchft_lighthouse_peer_takeovers gauge\n";
      for (const auto& [addr, st] : ha_peers_state_)
        os << "torchft_lighthouse_peer_takeovers{peer=\"" << addr
           << "\"} " << st.takeovers << "\n";
    }
    // Tick-cost telemetry: the incremental-quorum claim, measured.
    os << "# HELP torchft_lighthouse_tick_seconds Quorum tick wall time "
          "(includes the O(1) dirty-set skip path)\n"
       << "# TYPE torchft_lighthouse_tick_seconds histogram\n";
    int64_t cum = 0;
    char num[64];
    for (int b = 0; b < kNumTickBuckets; ++b) {
      cum += tick_bucket_counts_[b];
      snprintf(num, sizeof(num), "%g", kTickBuckets[b]);
      os << "torchft_lighthouse_tick_seconds_bucket{le=\"" << num << "\"} "
         << cum << "\n";
    }
    cum += tick_bucket_counts_[kNumTickBuckets];
    os << "torchft_lighthouse_tick_seconds_bucket{le=\"+Inf\"} " << cum
       << "\n";
    snprintf(num, sizeof(num), "%.9g", tick_sum_s_);
    os << "torchft_lighthouse_tick_seconds_sum " << num << "\n"
       << "torchft_lighthouse_tick_seconds_count " << tick_count_ << "\n"
       << "# HELP torchft_lighthouse_dirty_replicas Replicas the most "
          "recent quorum tick re-evaluated (0 = dirty-set skip; steady "
          "state is far below fleet size)\n"
       << "# TYPE torchft_lighthouse_dirty_replicas gauge\n"
       << "torchft_lighthouse_dirty_replicas " << dirty_last_decision_
       << "\n";
    // Straggler telemetry: per-replica step lag and score, computed from
    // the progress piggybacked on heartbeat/quorum RPCs.  A dead replica
    // keeps exporting a growing lag until it is superseded/evicted — the
    // alerting window BEFORE the quorum shrinks around it.  Per-replica
    // labels are the BOUNDED worst-K tier (straggler_topk): at fleet
    // scale the scrape stays O(K), with fleet-wide truth preserved by
    // the aggregate max/count gauges below (docs/observability.md
    // "metric cardinality" — the metrics-cardinality lint pass enforces
    // the same rule on the Python registry).
    auto all_rows = compute_stragglers_locked(now);
    int64_t max_lag = 0;
    for (const auto& s : all_rows) max_lag = std::max(max_lag, s.step_lag);
    auto stragglers = worst_stragglers(all_rows);
    os << "# HELP torchft_replica_step_lag Steps behind the most advanced "
          "tracked replica (worst-K replicas only)\n"
       << "# TYPE torchft_replica_step_lag gauge\n";
    for (const auto& s : stragglers)
      os << "torchft_replica_step_lag{replica=\""
         << escape_label(s.replica_id) << "\"} " << s.step_lag << "\n";
    os << "# HELP torchft_straggler_score Progress age over the median "
          "fresh-replica age (~1 = typical; large = straggling/dead; "
          "worst-K replicas only)\n"
       << "# TYPE torchft_straggler_score gauge\n";
    for (const auto& s : stragglers) {
      char buf[64];
      snprintf(buf, sizeof(buf), "%.6g", s.score);
      os << "torchft_straggler_score{replica=\""
         << escape_label(s.replica_id) << "\"} " << buf << "\n";
    }
    os << "# HELP torchft_replica_step_lag_max Fleet-wide maximum step "
          "lag (unbounded-cardinality truth, one series)\n"
       << "# TYPE torchft_replica_step_lag_max gauge\n"
       << "torchft_replica_step_lag_max " << max_lag << "\n"
       << "# HELP torchft_stragglers_tracked Replicas in the full "
          "straggler table (worst-K of these are exported per replica)\n"
       << "# TYPE torchft_stragglers_tracked gauge\n"
       << "torchft_stragglers_tracked "
       << static_cast<int64_t>(all_rows.size()) << "\n";
    // Weight-serving tier: registered members, plan epoch and the
    // newest published version (bounded: three series at any fleet
    // size — the full tree lives in /serving.json).
    int64_t serving_pubs = 0, serving_srvs = 0;
    for (const auto& [rid, m] : serving_) {
      (void)rid;
      (m.role == "publisher" ? serving_pubs : serving_srvs) += 1;
    }
    os << "# HELP torchft_lighthouse_serving_replicas Registered "
          "weight-serving members by role\n"
       << "# TYPE torchft_lighthouse_serving_replicas gauge\n"
       << "torchft_lighthouse_serving_replicas{role=\"publisher\"} "
       << serving_pubs << "\n"
       << "torchft_lighthouse_serving_replicas{role=\"server\"} "
       << serving_srvs << "\n"
       << "# HELP torchft_lighthouse_serving_epoch Weight-serving plan "
          "epoch (monotone; bumps on serving membership change)\n"
       << "# TYPE torchft_lighthouse_serving_epoch gauge\n"
       << "torchft_lighthouse_serving_epoch " << serving_epoch_ << "\n"
       << "# HELP torchft_lighthouse_serving_latest_version Newest weight "
          "version any registered publisher holds\n"
       << "# TYPE torchft_lighthouse_serving_latest_version gauge\n"
       << "torchft_lighthouse_serving_latest_version "
       << serving_latest_version_locked() << "\n"
       << "# HELP torchft_lighthouse_serving_heartbeats_total "
          "serving_heartbeat RPCs received\n"
       << "# TYPE torchft_lighthouse_serving_heartbeats_total counter\n"
       << "torchft_lighthouse_serving_heartbeats_total "
       << serving_heartbeats_total_ << "\n";
    // Serving staleness ledger: worst publish->node lag across the
    // fleet, skew-free (both stamps publisher-clock).  One series.
    {
      int64_t latest_ms = serving_latest_version_ms_locked();
      int64_t worst_stale = 0;
      for (const auto& [rid, m] : serving_) {
        (void)rid;
        if (latest_ms > 0 && m.version_ms > 0)
          worst_stale =
              std::max(worst_stale, latest_ms - m.version_ms);
      }
      os << "# HELP torchft_lighthouse_serving_staleness_ms_max Worst "
            "publish-to-node version staleness across serving members "
            "(publisher-clock ms; per-node rows live in /serving.json)\n"
         << "# TYPE torchft_lighthouse_serving_staleness_ms_max gauge\n"
         << "torchft_lighthouse_serving_staleness_ms_max " << worst_stale
         << "\n";
    }
    // Link-state plane: bounded aggregates plus the worst-K WAN rows by
    // goodput — the straggler-tier cardinality rule.  Named
    // torchft_lighthouse_link_* (not torchft_link_*) so an embedding
    // Python process exporting its own replica-local torchft_link_*
    // gauges through the provider below never collides family names in
    // the combined scrape.
    {
      std::set<std::string> link_hosts;
      std::vector<const LinkRow*> wan;
      for (const auto& [key, row] : links_) {
        link_hosts.insert(std::get<0>(key));
        if (!row.local && row.goodput_bps > 0.0) wan.push_back(&row);
      }
      std::sort(wan.begin(), wan.end(),
                [](const LinkRow* a, const LinkRow* b) {
                  return a->goodput_bps < b->goodput_bps;
                });
      os << "# HELP torchft_lighthouse_link_rows Link-matrix rows "
            "tracked (full matrix in /links.json)\n"
         << "# TYPE torchft_lighthouse_link_rows gauge\n"
         << "torchft_lighthouse_link_rows "
         << static_cast<int64_t>(links_.size()) << "\n"
         << "# HELP torchft_lighthouse_link_hosts Hosts reporting link "
            "digests\n"
         << "# TYPE torchft_lighthouse_link_hosts gauge\n"
         << "torchft_lighthouse_link_hosts "
         << static_cast<int64_t>(link_hosts.size()) << "\n"
         << "# HELP torchft_lighthouse_link_reports_total Link digests "
            "folded into the matrix\n"
         << "# TYPE torchft_lighthouse_link_reports_total counter\n"
         << "torchft_lighthouse_link_reports_total " << links_reports_total_
         << "\n"
         << "# HELP torchft_lighthouse_link_goodput_min_bytes_per_s "
            "Lowest estimated WAN goodput across the fleet "
            "(unbounded-cardinality truth, one series)\n"
         << "# TYPE torchft_lighthouse_link_goodput_min_bytes_per_s gauge\n"
         << "torchft_lighthouse_link_goodput_min_bytes_per_s "
         << (wan.empty() ? 0.0 : wan.front()->goodput_bps) << "\n";
      if (!wan.empty()) {
        size_t k = std::min<size_t>(
            wan.size(), static_cast<size_t>(opt_.straggler_topk));
        os << "# HELP torchft_lighthouse_link_goodput_bytes_per_s "
              "Estimated goodput of the worst-K WAN links (bounded "
              "tier)\n"
           << "# TYPE torchft_lighthouse_link_goodput_bytes_per_s gauge\n";
        char buf[64];
        for (size_t i = 0; i < k; ++i) {
          snprintf(buf, sizeof(buf), "%.6g", wan[i]->goodput_bps);
          os << "torchft_lighthouse_link_goodput_bytes_per_s{src=\""
             << escape_label(wan[i]->src_host) << "\",peer=\""
             << escape_label(wan[i]->peer) << "\",plane=\""
             << escape_label(wan[i]->plane) << "\"} " << buf << "\n";
        }
        os << "# HELP torchft_lighthouse_link_rtt_p99_ms First-byte p99 "
              "of the worst-K WAN links (bounded tier)\n"
           << "# TYPE torchft_lighthouse_link_rtt_p99_ms gauge\n";
        for (size_t i = 0; i < k; ++i) {
          snprintf(buf, sizeof(buf), "%.6g", wan[i]->rtt_p99_ms);
          os << "torchft_lighthouse_link_rtt_p99_ms{src=\""
             << escape_label(wan[i]->src_host) << "\",peer=\""
             << escape_label(wan[i]->peer) << "\",plane=\""
             << escape_label(wan[i]->plane) << "\"} " << buf << "\n";
        }
      }
    }
    // Fragment provenance plane: bounded counts plus the worst-K stalest
    // (host, frag) rows — same cardinality discipline as the link tier.
    {
      std::set<std::string> frag_hosts;
      std::map<std::string, int64_t> frag_latest;
      for (const auto& [key, row] : fragments_) {
        frag_hosts.insert(key.first);
        int64_t& lm = frag_latest[key.second];
        lm = std::max(lm, row.version_ms);
      }
      std::vector<std::pair<int64_t, const FragRow*>> ranked;
      for (const auto& [key, row] : fragments_) {
        (void)key;
        auto lm = frag_latest.find(row.frag);
        if (lm == frag_latest.end() || lm->second <= 0 ||
            row.version_ms <= 0)
          continue;  // unknown stamps are listed in the matrix, not ranked
        ranked.emplace_back(
            std::max<int64_t>(lm->second - row.version_ms, 0), &row);
      }
      std::sort(ranked.begin(), ranked.end(),
                [](const std::pair<int64_t, const FragRow*>& a,
                   const std::pair<int64_t, const FragRow*>& b) {
                  return a.first > b.first;
                });
      os << "# HELP torchft_lighthouse_fragment_rows Fragment-matrix "
            "rows tracked (full matrix in /fragments.json)\n"
         << "# TYPE torchft_lighthouse_fragment_rows gauge\n"
         << "torchft_lighthouse_fragment_rows "
         << static_cast<int64_t>(fragments_.size()) << "\n"
         << "# HELP torchft_lighthouse_fragment_hosts Hosts reporting "
            "fragment digests\n"
         << "# TYPE torchft_lighthouse_fragment_hosts gauge\n"
         << "torchft_lighthouse_fragment_hosts "
         << static_cast<int64_t>(frag_hosts.size()) << "\n"
         << "# HELP torchft_lighthouse_fragment_reports_total Fragment "
            "digests folded into the matrix\n"
         << "# TYPE torchft_lighthouse_fragment_reports_total counter\n"
         << "torchft_lighthouse_fragment_reports_total "
         << fragments_reports_total_ << "\n"
         << "# HELP torchft_lighthouse_fragment_staleness_ms_max Worst "
            "per-fragment publish-stamp staleness across holders "
            "(publisher-clock ms; per-row truth in /fragments.json)\n"
         << "# TYPE torchft_lighthouse_fragment_staleness_ms_max gauge\n"
         << "torchft_lighthouse_fragment_staleness_ms_max "
         << (ranked.empty() ? 0 : ranked.front().first) << "\n";
      if (!ranked.empty()) {
        size_t k = std::min<size_t>(
            ranked.size(), static_cast<size_t>(opt_.straggler_topk));
        os << "# HELP torchft_lighthouse_fragment_staleness_ms "
              "Publish-stamp staleness of the worst-K stalest "
              "(host, frag) rows (bounded tier)\n"
           << "# TYPE torchft_lighthouse_fragment_staleness_ms gauge\n";
        for (size_t i = 0; i < k; ++i) {
          os << "torchft_lighthouse_fragment_staleness_ms{host=\""
             << escape_label(ranked[i].second->host) << "\",frag=\""
             << escape_label(ranked[i].second->frag) << "\"} "
             << ranked[i].first << "\n";
        }
      }
    }
  }
  {
    std::lock_guard<std::mutex> g(provider_mu_);
    if (metrics_provider_ != nullptr) {
      std::vector<char> buf(1 << 16);
      int n = metrics_provider_(buf.data(), static_cast<int>(buf.size()));
      // Retry with growing headroom: the registry can gain label children
      // between the probe and the re-render, so sizing exactly to the
      // first -needed can come up short again.
      for (int attempt = 0; n < 0 && attempt < 4; ++attempt) {
        buf.resize(static_cast<size_t>(-n) + (buf.size() >> 1) + 4096);
        n = metrics_provider_(buf.data(), static_cast<int>(buf.size()));
      }
      if (n > 0)
        os.write(buf.data(), std::min<int>(n, static_cast<int>(buf.size())));
    }
  }
  return os.str();
}

namespace {
// Page [page*per_page, (page+1)*per_page) of 0..total; returns [lo, hi).
// Overflow-proof against attacker-sized query values: any page past the
// last row is an empty slice, never a wrapped product serving page 0.
std::pair<size_t, size_t> page_bounds(size_t total, int64_t page,
                                      int64_t per_page) {
  size_t pg = static_cast<size_t>(page);
  size_t pp = static_cast<size_t>(per_page);
  size_t lo = (pp == 0 || pg > total / pp) ? total : pg * pp;
  size_t hi = lo + std::min(pp, total - lo);
  return {lo, hi};
}
}  // namespace

Json LighthouseServer::status_json(int64_t page, int64_t per_page,
                                   const std::string& replica_filter) {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  if (per_page <= 0) per_page = opt_.status_page_size;
  // Cap per_page so query-controlled values can't overflow the `pages`
  // arithmetic below (and a single page stays a bounded render anyway).
  if (per_page > 100000) per_page = 100000;
  if (page < 0) page = 0;
  const bool sharded = !replica_filter.empty();
  Json out = Json::object();
  out["quorum_id"] = quorum_id_;
  out["status"] = last_reason_;
  out["reason"] = last_reason_;  // legacy status-RPC field name
  out["num_participants"] = static_cast<int64_t>(participants_.size());
  // live recompute, like the HTML page (reference lighthouse.rs:419)
  std::string live_reason;
  quorum_compute(now, &live_reason);
  out["live_status"] = live_reason;

  // Row arrays are paginated (page/per_page over replica_id order) or —
  // with ?replica= — sharded down to one replica.  Totals and the
  // summary are always fleet-wide, so the default document is truthful
  // about scale while staying O(page) in bytes.
  auto straggler_rows = compute_stragglers_locked(now);
  int64_t max_step = 0;
  for (const auto& s : straggler_rows) max_step = std::max(max_step, s.step);

  size_t hb_total = heartbeats_.size();
  size_t st_total = straggler_rows.size();
  size_t pq_total =
      prev_quorum_.has_value() ? prev_quorum_->participants.size() : 0;

  Json hbs = Json::array();
  {
    // heartbeats_ is replica_id-ordered (std::map): slice directly.
    auto [lo, hi] = sharded ? std::pair<size_t, size_t>{0, hb_total}
                            : page_bounds(hb_total, page, per_page);
    size_t i = 0;
    int64_t fresh = 0, stale = 0;
    for (const auto& [rid, ts] : heartbeats_) {
      bool is_stale = (now - ts) >= opt_.heartbeat_timeout_ms;
      (is_stale ? stale : fresh) += 1;
      bool in_page = sharded ? rid == replica_filter : (i >= lo && i < hi);
      if (in_page) {
        Json h = Json::object();
        h["replica_id"] = rid;
        h["age_ms"] = now - ts;
        h["stale"] = is_stale;
        hbs.push_back(h);
      }
      ++i;
    }
    out["heartbeats_fresh"] = fresh;
    out["heartbeats_stale"] = stale;
  }
  out["heartbeats"] = hbs;
  out["heartbeats_total"] = static_cast<int64_t>(hb_total);

  Json stragglers = Json::array();
  {
    // compute_stragglers_locked iterates progress_ (ordered): sliceable.
    auto [lo, hi] = sharded ? std::pair<size_t, size_t>{0, st_total}
                            : page_bounds(st_total, page, per_page);
    for (size_t i = 0; i < straggler_rows.size(); ++i) {
      const auto& s = straggler_rows[i];
      bool in_page =
          sharded ? s.replica_id == replica_filter : (i >= lo && i < hi);
      if (!in_page) continue;
      Json row = Json::object();
      row["replica_id"] = s.replica_id;
      row["step"] = s.step;
      row["step_lag"] = s.step_lag;
      row["progress_age_ms"] = s.progress_age_ms;
      row["last_step_wall_ms"] = s.last_step_wall_ms;
      row["straggler_score"] = s.score;
      row["inflight_op"] = s.inflight_op;
      row["stale"] = s.stale;
      stragglers.push_back(row);
    }
  }
  out["stragglers"] = stragglers;
  out["stragglers_total"] = static_cast<int64_t>(st_total);
  out["max_step"] = max_step;

  if (prev_quorum_.has_value()) {
    Json q = Json::object();
    q["quorum_id"] = prev_quorum_->quorum_id;
    q["created_ms"] = prev_quorum_->created_ms;
    q["age_ms"] = wall_ms() - prev_quorum_->created_ms;
    q["num_participants"] = static_cast<int64_t>(pq_total);
    int64_t pq_max_step = 0;
    for (const auto& p : prev_quorum_->participants)
      pq_max_step = std::max(pq_max_step, p.step);
    Json parts = Json::array();
    auto [lo, hi] = sharded ? std::pair<size_t, size_t>{0, pq_total}
                            : page_bounds(pq_total, page, per_page);
    for (size_t i = 0; i < pq_total; ++i) {
      const auto& p = prev_quorum_->participants[i];
      bool in_page =
          sharded ? p.replica_id == replica_filter : (i >= lo && i < hi);
      if (!in_page) continue;
      // full member fields (the pre-unification status RPC served
      // QuorumMember::to_json — consumers may rely on any of them) plus
      // the dashboard's derived "recovering" flag
      Json m = p.to_json();
      m["recovering"] = p.step < pq_max_step;
      parts.push_back(m);
    }
    q["participants"] = parts;
    out["prev_quorum"] = q;
  }

  // Pagination envelope + the always-small summary (worst-K stragglers):
  // at any fleet size the DEFAULT document answers "is the job healthy
  // and who is holding it up" without paging.
  size_t rows_max = std::max(hb_total, std::max(st_total, pq_total));
  out["page"] = page;
  out["per_page"] = per_page;
  out["pages"] = static_cast<int64_t>(
      (rows_max + static_cast<size_t>(per_page) - 1) /
      static_cast<size_t>(per_page));
  if (sharded) out["replica"] = replica_filter;
  // Weight-serving tier summary: always-small (counts + epoch + latest
  // version), never the member list — /serving.json and the
  // serving_plan RPC carry the full tree.
  {
    int64_t publishers = 0, servers = 0;
    for (const auto& [rid, m] : serving_) {
      (void)rid;
      (m.role == "publisher" ? publishers : servers) += 1;
    }
    Json serving = Json::object();
    serving["epoch"] = serving_epoch_;
    serving["publishers"] = publishers;
    serving["servers"] = servers;
    serving["latest_version"] = serving_latest_version_locked();
    out["serving"] = serving;
  }

  // Coordination-plane HA block: served from EVERY peer over HTTP (the
  // status RPC is leader-only, but each peer's /status.json names the
  // leader it believes in — the fleet helper and tests read this).
  {
    bool leading = !ha_enabled() || is_leader_;
    Json ha = Json::object();
    ha["enabled"] = ha_enabled();
    ha["term"] = term_;
    ha["is_leader"] = leading;
    ha["leader"] =
        leading ? address()
                : ((now < promise_expires_ms_ && promised_to_ != address())
                       ? promised_to_
                       : "");
    ha["takeovers_total"] = takeovers_total_;
    // Peer federation (ISSUE 15): per-peer lease-channel state, so one
    // scrape of the leader answers "is every peer of the coordination
    // plane alive, current, and acking leases" — no per-peer scrape.
    // Rows exist once the election thread has exchanged leases; a peer
    // that stopped answering keeps its last row with a growing
    // last_ack_age_ms.
    Json ha_peers = Json::array();
    for (const auto& [addr, st] : ha_peers_state_) {
      Json row = Json::object();
      row["address"] = addr;
      row["term"] = st.term;
      row["granted"] = st.granted;
      row["last_ack_age_ms"] =
          st.last_ack_ms > 0 ? now - st.last_ack_ms : -1;
      row["promise_remaining_ms"] = st.promise_remaining_ms;
      row["takeovers_total"] = st.takeovers;
      row["holder"] = st.holder;
      ha_peers.push_back(row);
    }
    ha["ha_peers"] = ha_peers;
    out["ha"] = ha;
  }

  Json summary = Json::object();
  summary["replicas_tracked"] = static_cast<int64_t>(hb_total);
  summary["participants_waiting"] =
      static_cast<int64_t>(participants_.size());
  summary["quorum_id"] = quorum_id_;
  summary["max_step"] = max_step;
  summary["timeline_steps"] = static_cast<int64_t>(timeline_.size());
  Json worst = Json::array();
  for (const auto& s : worst_stragglers(straggler_rows)) {
    Json row = Json::object();
    row["replica_id"] = s.replica_id;
    row["step_lag"] = s.step_lag;
    row["straggler_score"] = s.score;
    row["stale"] = s.stale;
    row["inflight_op"] = s.inflight_op;
    worst.push_back(row);
  }
  summary["stragglers_worst"] = worst;
  out["summary"] = summary;
  return out;
}

Json LighthouseServer::links_json(int64_t page, int64_t per_page) {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  if (per_page <= 0) per_page = opt_.status_page_size;
  if (per_page > 100000) per_page = 100000;
  if (page < 0) page = 0;
  Json out = Json::object();
  out["version"] = links_version_;
  out["now_ms"] = wall_ms();
  out["reports_total"] = links_reports_total_;
  std::set<std::string> hosts;
  for (const auto& [key, row] : links_) {
    (void)row;
    hosts.insert(std::get<0>(key));
  }
  out["hosts"] = static_cast<int64_t>(hosts.size());
  size_t total = links_.size();
  out["rows_total"] = static_cast<int64_t>(total);
  out["page"] = page;
  out["per_page"] = per_page;
  out["pages"] = static_cast<int64_t>(
      (total + static_cast<size_t>(per_page) - 1) /
      static_cast<size_t>(per_page));
  // Fleet truth on every page: the worst WAN link (lowest goodput with
  // any estimate) — the slow_link culprit signal's one-row summary.
  const LinkRow* worst = nullptr;
  for (const auto& [key, row] : links_) {
    (void)key;
    if (row.local || row.goodput_bps <= 0.0) continue;
    if (worst == nullptr || row.goodput_bps < worst->goodput_bps)
      worst = &row;
  }
  if (worst != nullptr) {
    Json w = Json::object();
    w["src"] = worst->src_host;
    w["peer"] = worst->peer;
    w["plane"] = worst->plane;
    w["goodput_bps"] = worst->goodput_bps;
    w["rtt_p99_ms"] = worst->rtt_p99_ms;
    out["worst"] = w;
  }
  Json rows = Json::array();
  auto [lo, hi] = page_bounds(total, page, per_page);
  size_t i = 0;
  for (const auto& [key, row] : links_) {
    (void)key;
    if (i >= lo && i < hi) {
      Json r = Json::object();
      r["src"] = row.src_host;
      r["peer"] = row.peer;
      r["plane"] = row.plane;
      r["local"] = row.local;
      r["goodput_bps"] = row.goodput_bps;
      r["rtt_ms"] = row.rtt_ms;
      r["rtt_p99_ms"] = row.rtt_p99_ms;
      r["samples"] = row.samples;
      r["bytes"] = row.bytes;
      r["age_ms"] = now - row.updated_ms;
      rows.push_back(r);
    }
    ++i;
  }
  out["rows"] = rows;
  return out;
}

Json LighthouseServer::fragments_json(int64_t page, int64_t per_page) {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  if (per_page <= 0) per_page = opt_.status_page_size;
  if (per_page > 100000) per_page = 100000;
  if (page < 0) page = 0;
  Json out = Json::object();
  out["version"] = fragments_version_;
  out["now_ms"] = wall_ms();
  out["reports_total"] = fragments_reports_total_;
  // Per-fragment freshness reference: the NEWEST publish stamp any
  // holder reports for that frag_id.  Both stamps ride the manifest
  // unmodified from the publisher, so the difference is skew-free —
  // the serving staleness-ledger idiom applied per fragment.
  std::map<std::string, int64_t> latest_ms;
  std::set<std::string> hosts;
  for (const auto& [key, row] : fragments_) {
    hosts.insert(key.first);
    int64_t& lm = latest_ms[key.second];
    lm = std::max(lm, row.version_ms);
  }
  out["hosts"] = static_cast<int64_t>(hosts.size());
  out["frags"] = static_cast<int64_t>(latest_ms.size());
  size_t total = fragments_.size();
  out["rows_total"] = static_cast<int64_t>(total);
  out["page"] = page;
  out["per_page"] = per_page;
  out["pages"] = static_cast<int64_t>(
      (total + static_cast<size_t>(per_page) - 1) /
      static_cast<size_t>(per_page));
  auto staleness_of = [&latest_ms](const FragRow& row) -> int64_t {
    auto lm = latest_ms.find(row.frag);
    if (lm == latest_ms.end() || lm->second <= 0 || row.version_ms <= 0)
      return -1;  // unknown stamp: never fake freshness
    return std::max<int64_t>(lm->second - row.version_ms, 0);
  };
  // Fleet truth on every page: the worst-K stalest (host, frag) rows —
  // the bounded tier the dashboard and /metrics render; unknown-stamp
  // rows are excluded from the ranking (they are listed, not ranked).
  std::vector<std::pair<int64_t, const FragRow*>> ranked;
  for (const auto& [key, row] : fragments_) {
    (void)key;
    int64_t s = staleness_of(row);
    if (s >= 0) ranked.emplace_back(s, &row);
  }
  std::sort(ranked.begin(), ranked.end(),
            [](const std::pair<int64_t, const FragRow*>& a,
               const std::pair<int64_t, const FragRow*>& b) {
              if (a.first != b.first) return a.first > b.first;
              if (a.second->host != b.second->host)
                return a.second->host < b.second->host;
              return a.second->frag < b.second->frag;
            });
  Json stalest = Json::array();
  size_t topk = std::min<size_t>(
      ranked.size(), static_cast<size_t>(opt_.straggler_topk));
  for (size_t i = 0; i < topk; ++i) {
    Json w = Json::object();
    w["host"] = ranked[i].second->host;
    w["frag"] = ranked[i].second->frag;
    w["version"] = ranked[i].second->version;
    w["staleness_ms"] = ranked[i].first;
    stalest.push_back(w);
  }
  out["stalest"] = stalest;
  Json rows = Json::array();
  auto [lo, hi] = page_bounds(total, page, per_page);
  size_t i = 0;
  for (const auto& [key, row] : fragments_) {
    (void)key;
    if (i >= lo && i < hi) {
      Json r = Json::object();
      r["host"] = row.host;
      r["frag"] = row.frag;
      r["version"] = row.version;
      r["digest8"] = row.digest8;
      r["version_ms"] = row.version_ms;
      r["staleness_ms"] = staleness_of(row);
      r["pub"] = row.pub;
      r["age_ms"] = now - row.updated_ms;
      rows.push_back(r);
    }
    ++i;
  }
  out["rows"] = rows;
  return out;
}

std::string LighthouseServer::render_status_html(int64_t page) {
  // Parity with the reference's askama status page
  // (reference templates/status.html:1-52, src/lighthouse.rs:415-452):
  // live next-quorum status, prev-quorum summary (id, participant count,
  // age), per-member card fields (step/manager/store/world_size) with a
  // "recovering" badge when behind max_step, a kill button, and a
  // heartbeat list with an "old" marker past the heartbeat timeout.
  // Auto-refresh via meta refresh instead of htmx (no JS dependency).
  //
  // Fleet scale: row tables render ONE page (?page=N, status_page_size
  // rows) and the straggler table the worst-K by score — at 64+ churning
  // replicas the page stays a constant-size render, with totals and
  // next/prev links making the cut visible instead of silent.
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  const size_t per_page = static_cast<size_t>(opt_.status_page_size);
  if (page < 0) page = 0;
  // Recompute the quorum reason LIVE like the reference's get_status
  // (lighthouse.rs:419) rather than echoing the last tick's.
  std::string live_reason;
  quorum_compute(now, &live_reason);
  std::ostringstream os;
  os << "<!doctype html><html><head><title>torchft_tpu lighthouse</title>"
     << "<meta http-equiv=\"refresh\" content=\"2\">"
     << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
        "collapse}td,th{border:1px solid #888;padding:4px 8px}"
        "tr.recovering{background:#fff3cd}li.old{color:#b00}</style>"
     << "</head><body><h1>torchft_tpu lighthouse</h1>"
     << "<p>quorum_id: " << quorum_id_ << "</p>";
  if (ha_enabled()) {
    os << "<p>HA: " << (is_leader_ ? "LEADER" : "follower") << " &middot; "
       << "term " << term_ << " &middot; " << peers_.size()
       << " peer(s) &middot; takeovers " << takeovers_total_ << "</p>";
    if (!ha_peers_state_.empty()) {
      os << "<table><tr><th>peer</th><th>term</th><th>granted</th>"
         << "<th>last lease ack (ms)</th><th>promise left (ms)</th>"
         << "<th>takeovers</th></tr>";
      for (const auto& [addr, st] : ha_peers_state_) {
        int64_t age = st.last_ack_ms > 0 ? now - st.last_ack_ms : -1;
        bool stale = age < 0 || age > 2 * opt_.lease_timeout_ms;
        os << "<tr class=\"" << (stale ? "recovering" : "healthy")
           << "\"><td>" << addr << "</td><td>" << st.term << "</td><td>"
           << (st.granted ? "yes" : "no") << "</td><td>" << age
           << "</td><td>" << st.promise_remaining_ms << "</td><td>"
           << st.takeovers << "</td></tr>";
      }
      os << "</table>";
    }
  }
  os << "<p>next quorum status: " << live_reason << "</p>";
  size_t max_rows = std::max(
      heartbeats_.size(),
      prev_quorum_.has_value() ? prev_quorum_->participants.size() : 0);
  size_t pages = (max_rows + per_page - 1) / per_page;
  if (pages > 1) {
    os << "<p>page " << page << " of " << pages << " (" << per_page
       << " rows/page)";
    if (page > 0) os << " &middot; <a href=\"/status?page=" << (page - 1)
                     << "\">prev</a>";
    if (static_cast<size_t>(page) + 1 < pages)
      os << " &middot; <a href=\"/status?page=" << (page + 1)
         << "\">next</a>";
    os << "</p>";
  }
  if (prev_quorum_.has_value()) {
    int64_t age_ms = wall_ms() - prev_quorum_->created_ms;
    os << "<h2>previous quorum (id " << prev_quorum_->quorum_id << ")</h2>"
       << "<p>participants: " << prev_quorum_->participants.size()
       << " &middot; quorum age: " << (age_ms / 1000.0) << "s</p>"
       << "<table><tr><th>replica</th><th>step</th><th>manager</th>"
       << "<th>store</th><th>world</th><th>heartbeat age (ms)</th>"
       << "<th>state</th><th></th></tr>";
    int64_t max_step = 0;
    for (const auto& p : prev_quorum_->participants)
      max_step = std::max(max_step, p.step);
    auto [lo, hi] =
        page_bounds(prev_quorum_->participants.size(), page,
                    static_cast<int64_t>(per_page));
    for (size_t i = lo; i < hi; ++i) {
      const auto& p = prev_quorum_->participants[i];
      auto hb = heartbeats_.find(p.replica_id);
      int64_t age = hb == heartbeats_.end() ? -1 : now - hb->second;
      bool recovering = p.step < max_step;
      os << "<tr class=\"" << (recovering ? "recovering" : "healthy")
         << "\"><td>" << p.replica_id << "</td><td>" << p.step << "</td><td>"
         << p.address << "</td><td>" << p.store_address << "</td><td>"
         << p.world_size << "</td><td>" << age << "</td><td>"
         << (recovering ? "recovering" : "healthy") << "</td>"
         << "<td><form method=post action=\"/replica/" << p.replica_id
         << "/kill\"><button>kill</button></form></td></tr>";
    }
    os << "</table>";
  }
  {
    auto tracked_rows = compute_stragglers_locked(now);
    size_t tracked = tracked_rows.size();
    auto stragglers = worst_stragglers(std::move(tracked_rows));
    if (!stragglers.empty()) {
      os << "<h2>straggler telemetry (worst " << stragglers.size() << " of "
         << tracked << " by score)</h2>"
         << "<table><tr><th>replica</th><th>step</th><th>step lag</th>"
         << "<th>progress age (ms)</th><th>score</th><th>in-flight op</th>"
         << "<th>heartbeat</th></tr>";
      for (const auto& s : stragglers) {
        char score[64];
        snprintf(score, sizeof(score), "%.2f", s.score);
        os << "<tr class=\"" << (s.stale ? "recovering" : "healthy")
           << "\"><td>" << s.replica_id << "</td><td>" << s.step
           << "</td><td>" << s.step_lag << "</td><td>" << s.progress_age_ms
           << "</td><td>" << score << "</td><td>"
           << (s.inflight_op.empty() ? "-" : s.inflight_op) << "</td><td>"
           << (s.stale ? "stale" : "fresh") << "</td></tr>";
      }
      os << "</table>";
    }
  }
  if (!links_.empty()) {
    // Worst-K WAN links by estimated goodput — the same bounded tier
    // /metrics exports; the full matrix is one click away.
    std::vector<const LinkRow*> wan;
    for (const auto& [key, row] : links_) {
      (void)key;
      if (!row.local && row.goodput_bps > 0.0) wan.push_back(&row);
    }
    std::sort(wan.begin(), wan.end(),
              [](const LinkRow* a, const LinkRow* b) {
                return a->goodput_bps < b->goodput_bps;
              });
    size_t k = std::min<size_t>(
        wan.size(), static_cast<size_t>(opt_.straggler_topk));
    os << "<h2>link state (worst " << k << " of " << wan.size()
       << " WAN links, " << links_.size()
       << " rows &middot; <a href=\"/links.json\">matrix</a>)</h2>";
    if (k > 0) {
      os << "<table><tr><th>src</th><th>peer</th><th>plane</th>"
         << "<th>goodput (MB/s)</th><th>rtt p50 (ms)</th>"
         << "<th>rtt p99 (ms)</th><th>samples</th><th>age (ms)</th></tr>";
      for (size_t i = 0; i < k; ++i) {
        char gp[64], p50[64], p99[64];
        snprintf(gp, sizeof(gp), "%.2f", wan[i]->goodput_bps / 1e6);
        snprintf(p50, sizeof(p50), "%.2f", wan[i]->rtt_ms);
        snprintf(p99, sizeof(p99), "%.2f", wan[i]->rtt_p99_ms);
        int64_t age = now - wan[i]->updated_ms;
        bool stale = age > 5 * opt_.heartbeat_timeout_ms;
        os << "<tr class=\"" << (stale ? "recovering" : "healthy")
           << "\"><td>" << wan[i]->src_host << "</td><td>" << wan[i]->peer
           << "</td><td>" << wan[i]->plane << "</td><td>" << gp
           << "</td><td>" << p50 << "</td><td>" << p99 << "</td><td>"
           << wan[i]->samples << "</td><td>" << age << "</td></tr>";
      }
      os << "</table>";
    }
  }
  if (!fragments_.empty()) {
    // Worst-K stalest (host, frag) rows — the same bounded tier
    // /metrics exports; the full matrix is one click away.  Staleness
    // is publish-stamp vs the freshest stamp any holder reports for
    // that frag (skew-free); unknown stamps are counted, not ranked.
    std::map<std::string, int64_t> frag_latest;
    for (const auto& [key, row] : fragments_) {
      int64_t& lm = frag_latest[key.second];
      lm = std::max(lm, row.version_ms);
    }
    std::vector<std::pair<int64_t, const FragRow*>> ranked;
    int64_t unknown = 0;
    for (const auto& [key, row] : fragments_) {
      (void)key;
      auto lm = frag_latest.find(row.frag);
      if (lm == frag_latest.end() || lm->second <= 0 ||
          row.version_ms <= 0) {
        unknown += 1;
        continue;
      }
      ranked.emplace_back(
          std::max<int64_t>(lm->second - row.version_ms, 0), &row);
    }
    std::sort(ranked.begin(), ranked.end(),
              [](const std::pair<int64_t, const FragRow*>& a,
                 const std::pair<int64_t, const FragRow*>& b) {
                return a.first > b.first;
              });
    size_t k = std::min<size_t>(
        ranked.size(), static_cast<size_t>(opt_.straggler_topk));
    os << "<h2>fragment provenance (stalest " << k << " of "
       << fragments_.size() << " rows";
    if (unknown > 0) os << ", " << unknown << " unknown stamp(s)";
    os << " &middot; <a href=\"/fragments.json\">matrix</a>)</h2>";
    if (k > 0) {
      os << "<table><tr><th>host</th><th>frag</th><th>version</th>"
         << "<th>digest</th><th>staleness (ms)</th><th>age (ms)</th>"
         << "</tr>";
      for (size_t i = 0; i < k; ++i) {
        const FragRow* row = ranked[i].second;
        int64_t age = now - row->updated_ms;
        bool stale = age > 5 * opt_.heartbeat_timeout_ms;
        os << "<tr class=\"" << (stale ? "recovering" : "healthy")
           << "\"><td>" << row->host << "</td><td>" << row->frag
           << "</td><td>" << row->version << "</td><td>" << row->digest8
           << "</td><td>" << ranked[i].first << "</td><td>" << age
           << "</td></tr>";
      }
      os << "</table>";
    }
  }
  if (!serving_.empty()) {
    int64_t pubs = 0, srvs = 0, unknown = 0;
    int64_t latest_ms = serving_latest_version_ms_locked();
    int64_t worst_stale = 0;
    for (const auto& [rid, m] : serving_) {
      (void)rid;
      (m.role == "publisher" ? pubs : srvs) += 1;
      // Unknown stamps render as a distinct count — never as a fake
      // number in the worst-staleness figure (which ranks known only).
      if (latest_ms > 0 && m.version_ms > 0)
        worst_stale = std::max(worst_stale, latest_ms - m.version_ms);
      else if (m.role != "publisher")
        unknown += 1;
    }
    os << "<h2>weight-serving tier</h2><p>epoch " << serving_epoch_
       << " &middot; " << pubs << " publisher(s) &middot; " << srvs
       << " server(s) &middot; latest version "
       << serving_latest_version_locked()
       << " &middot; worst staleness " << worst_stale << "ms";
    if (unknown > 0) os << " &middot; " << unknown << " unknown";
    os << " &middot; <a href=\"/serving.json\">plan</a></p>";
  }
  {
    os << "<h2>pending participants (" << participants_.size()
       << ")</h2><ul>";
    auto [lo, hi] = page_bounds(participants_.size(), page,
                                static_cast<int64_t>(per_page));
    size_t i = 0;
    for (const auto& [rid, det] : participants_) {
      if (i >= lo && i < hi)
        os << "<li>" << rid << " (step " << det.member.step << ")</li>";
      ++i;
    }
  }
  os << "</ul><h2>heartbeats (" << heartbeats_.size() << ")</h2><ul>";
  {
    auto [lo, hi] = page_bounds(heartbeats_.size(), page,
                                static_cast<int64_t>(per_page));
    size_t i = 0;
    for (const auto& [rid, ts] : heartbeats_) {
      if (i >= lo && i < hi) {
        int64_t age = now - ts;
        bool old = age >= opt_.heartbeat_timeout_ms;
        os << "<li class=\"" << (old ? "old" : "fresh") << "\">" << rid
           << ": seen " << (age / 1000.0) << "s ago"
           << (old ? " (stale)" : "") << "</li>";
      }
      ++i;
    }
  }
  os << "</ul></body></html>";
  return os.str();
}

}  // namespace tft
