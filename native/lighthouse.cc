#include "lighthouse.h"

#include <unistd.h>
#include <string.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <set>
#include <sstream>

namespace tft {

namespace {
int64_t wall_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// Prometheus label-value escaping (backslash, quote, newline).
std::string escape_label(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out += c;
  }
  return out;
}
}  // namespace

Json QuorumMember::to_json() const {
  Json j = Json::object();
  j["replica_id"] = replica_id;
  j["address"] = address;
  j["store_address"] = store_address;
  j["step"] = step;
  j["world_size"] = world_size;
  j["shrink_only"] = shrink_only;
  j["commit_failures"] = commit_failures;
  j["data"] = data;
  return j;
}

QuorumMember QuorumMember::from_json(const Json& j) {
  QuorumMember m;
  m.replica_id = j.get("replica_id").as_string();
  m.address = j.get("address").as_string();
  m.store_address = j.get("store_address").as_string();
  m.step = j.get("step").as_int();
  m.world_size = j.get("world_size").as_int(1);
  m.shrink_only = j.get("shrink_only").as_bool();
  m.commit_failures = j.get("commit_failures").as_int();
  m.data = j.get("data").as_string();
  return m;
}

Json Quorum::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = quorum_id;
  Json parts = Json::array();
  for (const auto& p : participants) parts.push_back(p.to_json());
  j["participants"] = parts;
  j["created_ms"] = created_ms;
  return j;
}

Quorum Quorum::from_json(const Json& j) {
  Quorum q;
  q.quorum_id = j.get("quorum_id").as_int();
  for (const auto& p : j.get("participants").as_array())
    q.participants.push_back(QuorumMember::from_json(p));
  q.created_ms = j.get("created_ms").as_int();
  return q;
}

LighthouseServer::LighthouseServer(const LighthouseOpt& opt)
    : RpcServer(opt.bind_host, opt.port), opt_(opt) {}

LighthouseServer::~LighthouseServer() { stop(); }

void LighthouseServer::start_serving() {
  start();
  tick_thread_ = std::thread([this] { tick_loop(); });
}

void LighthouseServer::stop() {
  shutdown();  // idempotent; closes conns and calls wake_blocked()
  if (tick_thread_.joinable()) tick_thread_.join();
}

void LighthouseServer::wake_blocked() {
  std::lock_guard<std::mutex> g(mu_);
  quorum_cv_.notify_all();
}

void LighthouseServer::tick_loop() {
  while (!stopping_.load()) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      tick_locked(now_ms());
    }
    usleep(static_cast<useconds_t>(opt_.quorum_tick_ms * 1000));
  }
}

std::optional<std::vector<QuorumMember>> LighthouseServer::quorum_compute(
    int64_t now, std::string* reason) {
  // Healthy = heartbeat seen within the timeout window.
  std::set<std::string> healthy_replicas;
  for (const auto& [rid, last] : heartbeats_)
    if (now - last < opt_.heartbeat_timeout_ms) healthy_replicas.insert(rid);

  std::vector<const ParticipantDetails*> healthy_participants;
  for (const auto& [rid, det] : participants_)
    if (healthy_replicas.count(rid)) healthy_participants.push_back(&det);

  std::vector<QuorumMember> candidates;
  for (const auto* det : healthy_participants)
    candidates.push_back(det->member);
  std::sort(candidates.begin(), candidates.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  bool shrink_only = std::any_of(
      healthy_participants.begin(), healthy_participants.end(),
      [](const ParticipantDetails* d) { return d->member.shrink_only; });

  std::ostringstream meta;
  meta << "[" << healthy_participants.size() << "/" << participants_.size()
       << " participants healthy][" << healthy_replicas.size()
       << " heartbeating][shrink_only=" << (shrink_only ? "true" : "false")
       << "]";

  if (prev_quorum_.has_value()) {
    std::set<std::string> prev_ids;
    for (const auto& p : prev_quorum_->participants)
      prev_ids.insert(p.replica_id);

    if (shrink_only) {
      std::vector<QuorumMember> filtered;
      for (auto& c : candidates)
        if (prev_ids.count(c.replica_id)) filtered.push_back(c);
      candidates = std::move(filtered);
    }

    // Fast quorum: every member of the previous quorum is again a healthy
    // participant — no need to wait for join timeout.
    std::set<std::string> participating;
    for (const auto* d : healthy_participants)
      participating.insert(d->member.replica_id);
    bool fast = std::all_of(
        prev_ids.begin(), prev_ids.end(),
        [&](const std::string& id) { return participating.count(id) > 0; });
    if (fast) {
      *reason = "Fast quorum found! " + meta.str();
      return candidates;
    }
  }

  if (static_cast<int64_t>(healthy_participants.size()) < opt_.min_replicas) {
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need min_replicas " +
              std::to_string(opt_.min_replicas) + " " + meta.str();
    return std::nullopt;
  }

  // Split-brain guard: strictly more than half of all healthy replicas must
  // be participating.
  if (healthy_participants.size() <= healthy_replicas.size() / 2) {
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need at least half of " +
              std::to_string(healthy_replicas.size()) + " healthy workers " +
              meta.str();
    return std::nullopt;
  }

  bool all_healthy_joined =
      healthy_participants.size() == healthy_replicas.size();
  int64_t first_joined = now;
  for (const auto* d : healthy_participants)
    first_joined = std::min(first_joined, d->joined_ms);
  if (!all_healthy_joined && now - first_joined < opt_.join_timeout_ms) {
    *reason = "Valid quorum with " +
              std::to_string(healthy_participants.size()) +
              " participants, waiting for " +
              std::to_string(healthy_replicas.size() -
                             healthy_participants.size()) +
              " healthy but not participating stragglers due to join timeout " +
              meta.str();
    return std::nullopt;
  }

  *reason = "Valid quorum found " + meta.str();
  return candidates;
}

void LighthouseServer::tick_locked(int64_t now) {
  std::string reason;
  auto maybe = quorum_compute(now, &reason);
  last_reason_ = reason;
  if (!maybe.has_value()) return;

  std::vector<QuorumMember>& parts = *maybe;

  bool membership_changed = true;
  if (prev_quorum_.has_value()) {
    std::vector<std::string> a, b;
    for (const auto& p : parts) a.push_back(p.replica_id);
    for (const auto& p : prev_quorum_->participants) b.push_back(p.replica_id);
    membership_changed = a != b;
  }
  bool commit_failure = std::any_of(
      parts.begin(), parts.end(),
      [](const QuorumMember& p) { return p.commit_failures > 0; });
  if (membership_changed || commit_failure) quorum_id_ += 1;

  Quorum q;
  q.quorum_id = quorum_id_;
  q.participants = parts;
  q.created_ms = wall_ms();

  prev_quorum_ = q;
  participants_.clear();
  latest_quorum_ = q;
  quorum_seq_ += 1;
  quorums_formed_total_ += 1;
  quorum_cv_.notify_all();
}

bool LighthouseServer::tick_for_test() {
  std::lock_guard<std::mutex> g(mu_);
  int64_t seq = quorum_seq_;
  tick_locked(now_ms());
  return quorum_seq_ != seq;
}

Json LighthouseServer::handle(const std::string& method, const Json& params,
                              int64_t timeout_ms) {
  if (method == "quorum") return rpc_quorum(params, timeout_ms);
  if (method == "heartbeat") return rpc_heartbeat(params);
  // One status document for the RPC and GET /status.json: the dashboard
  // schema IS the programmatic schema (tests assert they round-trip).
  if (method == "status") return status_json();
  throw std::runtime_error("lighthouse: unknown method " + method);
}

Json LighthouseServer::rpc_quorum(const Json& params, int64_t timeout_ms) {
  QuorumMember requester = QuorumMember::from_json(params.get("member"));
  if (requester.replica_id.empty())
    throw std::runtime_error("missing requester replica_id");

  std::unique_lock<std::mutex> lk(mu_);
  int64_t now = now_ms();
  quorum_requests_total_ += 1;
  // Supersession is one-directional: an incarnation that has been evicted
  // (a newer incarnation of the same logical replica joined after it) can
  // never re-register or evict its successor, even if the old process is
  // still alive (hung, partitioned-then-rescheduled) and retries.  The
  // lighthouse's arrival order IS the incarnation order — uuid4 suffixes
  // carry none of their own.
  {
    auto ev = evicted_at_ms_.find(requester.replica_id);
    if (ev != evicted_at_ms_.end()) {
      ev->second = now;  // still calling -> still alive -> keep the stamp
      throw std::runtime_error(
          "superseded by a newer incarnation of this replica");
    }
  }
  // Implicit heartbeat + registration (+ progress: the member's step is
  // the freshest progress signal the straggler table can get).
  heartbeats_[requester.replica_id] = now;
  note_progress_locked(requester.replica_id, requester.step, 0, "quorum", now);
  int64_t my_token = ++next_reg_token_;
  participants_[requester.replica_id] = {requester, now, my_token};
  // Fast-restart supersession: replica ids carry a ":uuid" incarnation
  // suffix (Manager appends it precisely so a restarted replica is not
  // confused with its dead predecessor). A new incarnation of the same
  // logical replica therefore proves the old one is gone — evict its
  // heartbeat immediately instead of letting the stale entry hold the
  // quorum in the join-timeout wait until heartbeat expiry. Measured:
  // cuts rejoin-quorum formation from ~join_timeout to the next tick.
  //
  // Convention: the segment after the last ':' is the INCARNATION suffix
  // (the Manager always appends ":uuid4"), so two ids sharing a non-empty
  // prefix are incarnations of one logical replica — at most one can be a
  // live process, and the newest joiner is it.  The superseded entry is
  // removed from heartbeats_ AND participants_ (a kill can land while the
  // old incarnation is blocked inside rpc_quorum, leaving its request
  // registered), and stamped in evicted_at_ms_ so the dead incarnation's
  // ghost handler thread (its client is gone but the handler blocks until
  // its RPC deadline) aborts instead of re-inserting the stale state from
  // its wait loop, and its background heartbeats are ignored (see
  // rpc_heartbeat).  Empty prefixes never match: default replica_id=""
  // gives every replica the ":uuid" shape — distinct logical replicas.
  {
    auto prefix_of = [](const std::string& id) {
      auto pos = id.rfind(':');
      return pos == std::string::npos ? id : id.substr(0, pos);
    };
    const std::string new_prefix = prefix_of(requester.replica_id);
    if (!new_prefix.empty()) {
      for (auto it = heartbeats_.begin(); it != heartbeats_.end();) {
        if (it->first != requester.replica_id &&
            prefix_of(it->first) == new_prefix) {
          evicted_at_ms_[it->first] = now;
          participants_.erase(it->first);
          progress_.erase(it->first);
          it = heartbeats_.erase(it);
        } else {
          ++it;
        }
      }
    }
    // Stamps are effectively PERMANENT: supersession is one-directional
    // for the lifetime of the job, because a superseded-but-still-alive
    // zombie may go silent for arbitrarily long (its manager stops
    // heartbeating on the superseded reply; a hung process can sleep
    // through any timeout) and must still be rejected when it finally
    // retries — otherwise it re-registers and evicts the live successor.
    // Each stamp is ~50 bytes and one is created per real restart, so
    // memory is bounded in practice; the count cap below is an
    // extreme-storm backstop (oldest first), far beyond any real job.
    constexpr size_t kMaxEvictionStamps = 100000;
    while (evicted_at_ms_.size() > kMaxEvictionStamps) {
      auto oldest = evicted_at_ms_.begin();
      for (auto it = evicted_at_ms_.begin(); it != evicted_at_ms_.end(); ++it)
        if (it->second < oldest->second) oldest = it;
      evicted_at_ms_.erase(oldest);
    }
  }
  int64_t seen_seq = quorum_seq_;
  // Proactive tick so a completing quorum doesn't wait for the next tick.
  tick_locked(now);

  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  // While blocked, keep our own heartbeat fresh in wait slices: a waiter is
  // by definition alive, and letting it age out would wedge quorum formation
  // for clients without a background heartbeat thread.
  auto wait_slice = std::chrono::milliseconds(
      std::max<int64_t>(1, std::min<int64_t>(opt_.heartbeat_timeout_ms / 2,
                                             1000)));
  // A handler that exits WITHOUT a quorum must take its registration with
  // it (token-guarded: never remove a newer handler's re-registration of
  // the same id).  Otherwise a dead requester lingers as a ghost
  // participant for up to one wait slice past its deadline, satisfying
  // the next formation's barrier with nobody behind it — the peer passes
  // the barrier alone and the real retry misses the quorum (measured as
  // a repeating 5 s miss in the restart-storm soak test).
  auto deregister_if_mine = [&]() {
    auto it = participants_.find(requester.replica_id);
    if (it != participants_.end() && it->second.reg_token == my_token)
      participants_.erase(it);
  };
  while (true) {
    // Superseded by a newer incarnation after we entered: abort BEFORE
    // re-registering anything (see eviction block above) — this handler
    // belongs to a replica whose replacement has already joined.  (The
    // entry check above guarantees we were not stamped at entry, so
    // presence alone means "evicted after we entered".)
    if (evicted_at_ms_.count(requester.replica_id))
      throw std::runtime_error(
          "superseded by a newer incarnation of this replica");
    if (quorum_seq_ != seen_seq) {
      seen_seq = quorum_seq_;
      const Quorum& q = latest_quorum_;
      bool included = std::any_of(
          q.participants.begin(), q.participants.end(),
          [&](const QuorumMember& p) {
            return p.replica_id == requester.replica_id;
          });
      if (included) {
        Json out = Json::object();
        out["quorum"] = q.to_json();
        return out;
      }
      // A quorum formed without us (e.g. we registered right after a tick
      // cleared participants) — re-register and keep waiting.
      my_token = ++next_reg_token_;
      participants_[requester.replica_id] = {requester, now_ms(), my_token};
    }
    if (stopping_.load()) {
      deregister_if_mine();
      throw std::runtime_error("lighthouse shutting down");
    }
    heartbeats_[requester.replica_id] = now_ms();
    if (std::chrono::steady_clock::now() >= deadline) {
      deregister_if_mine();
      throw TimeoutError("timeout waiting for quorum");
    }
    quorum_cv_.wait_for(lk, wait_slice);
  }
}

Json LighthouseServer::rpc_heartbeat(const Json& params) {
  std::lock_guard<std::mutex> g(mu_);
  const std::string rid = params.get("replica_id").as_string();
  heartbeats_total_ += 1;
  Json out = Json::object();
  // A superseded incarnation's background heartbeat thread must not
  // resurrect its heartbeats_ entry — that would make the zombie "healthy
  // but not participating" and wedge quorum behind join_timeout for as
  // long as the zombie lives.  Tell the caller instead of recording, and
  // REFRESH the stamp: a zombie that is still heartbeating is still alive,
  // so its stamp must outlive the age-based prune for as long as it keeps
  // calling (the prune only clears stamps of incarnations gone silent).
  auto ev = evicted_at_ms_.find(rid);
  if (ev != evicted_at_ms_.end()) {
    ev->second = now_ms();
    out["superseded"] = true;
    return out;
  }
  int64_t now = now_ms();
  heartbeats_[rid] = now;
  // Progress piggyback (optional params; a bare heartbeat stays valid):
  // step/last_step_wall_ms/inflight_op feed per-replica step-lag and
  // straggler-score telemetry.
  int64_t step = params.get("step").as_int(-1);
  if (step >= 0) {
    note_progress_locked(rid, step, params.get("last_step_wall_ms").as_int(0),
                         params.get("inflight_op").as_string(), now);
  }
  return out;
}

void LighthouseServer::note_progress_locked(const std::string& rid,
                                            int64_t step,
                                            int64_t last_step_wall_ms,
                                            const std::string& inflight_op,
                                            int64_t now) {
  if (step < 0) return;
  ReplicaProgress& p = progress_[rid];
  if (step > p.step) {
    // Stamped on OBSERVED advance with the lighthouse clock: straggler
    // ages stay meaningful without cross-host clock sync.
    p.step = step;
    p.step_changed_at_ms = now;
  } else if (p.step_changed_at_ms == 0) {
    p.step_changed_at_ms = now;  // first report at step 0
  }
  if (last_step_wall_ms > 0) p.last_step_wall_ms = last_step_wall_ms;
  p.inflight_op = inflight_op;
}

std::vector<LighthouseServer::StragglerInfo>
LighthouseServer::compute_stragglers_locked(int64_t now) {
  // Rows: every replica the lighthouse still tracks (a heartbeats_ entry;
  // superseded incarnations are pruned) that has reported progress.  A
  // replica with a stale heartbeat stays in the table until eviction —
  // the dead replica's growing lag/score is exactly the signal the
  // operator needs BEFORE the quorum shrinks around it.
  std::vector<StragglerInfo> rows;
  int64_t max_step = 0;
  for (const auto& [rid, p] : progress_) {
    if (!heartbeats_.count(rid)) continue;
    max_step = std::max(max_step, p.step);
  }
  std::vector<int64_t> fresh_ages;
  for (const auto& [rid, p] : progress_) {
    auto hb = heartbeats_.find(rid);
    if (hb == heartbeats_.end()) continue;
    StragglerInfo row;
    row.replica_id = rid;
    row.step = p.step;
    row.step_lag = max_step - p.step;
    row.progress_age_ms = std::max<int64_t>(now - p.step_changed_at_ms, 0);
    row.last_step_wall_ms = p.last_step_wall_ms;
    row.inflight_op = p.inflight_op;
    row.stale = (now - hb->second) >= opt_.heartbeat_timeout_ms;
    if (!row.stale) fresh_ages.push_back(row.progress_age_ms);
    rows.push_back(std::move(row));
  }
  // Score = progress age normalized by the median age of replicas with a
  // fresh heartbeat (~1 = typical cadence; a wedged or dead replica's
  // score grows without bound).  Median over the fresh cohort so one dead
  // replica cannot drag the baseline up and hide itself.
  std::sort(fresh_ages.begin(), fresh_ages.end());
  double median = fresh_ages.empty()
                      ? 1.0
                      : static_cast<double>(
                            fresh_ages[fresh_ages.size() / 2]);
  if (median < 1.0) median = 1.0;
  for (auto& row : rows)
    row.score = static_cast<double>(row.progress_age_ms) / median;
  return rows;
}

void LighthouseServer::handle_http(int fd, const std::string& request_head) {
  // First line: "METHOD /path HTTP/1.1"
  std::istringstream is(request_head);
  std::string method, path;
  is >> method >> path;

  if (method == "POST" && path.rfind("/replica/", 0) == 0) {
    // /replica/{id}/kill — forward a kill RPC to that replica's manager.
    std::string rest = path.substr(strlen("/replica/"));
    size_t slash = rest.find('/');
    if (slash != std::string::npos && rest.substr(slash) == "/kill") {
      std::string replica_id = rest.substr(0, slash);
      std::string addr;
      {
        std::lock_guard<std::mutex> g(mu_);
        if (prev_quorum_.has_value())
          for (const auto& p : prev_quorum_->participants)
            if (p.replica_id == replica_id) addr = p.address;
      }
      if (addr.empty()) {
        http_reply(fd, 404, "text/plain", "replica not found\n");
        return;
      }
      Json params = Json::object();
      params["msg"] = "killed from dashboard";
      Json result;
      std::string err;
      // Kill exits the remote process mid-RPC, so failure to read a reply is
      // expected; fire and report accepted.
      call_rpc(addr, "kill", params, 5000, &result, &err);
      http_reply(fd, 200, "text/plain", "kill sent to " + replica_id + "\n");
      return;
    }
  }
  if (method == "GET" && (path == "/" || path == "/status")) {
    http_reply(fd, 200, "text/html", render_status_html());
    return;
  }
  if (method == "GET" && path == "/status.json") {
    http_reply(fd, 200, "application/json", render_status_json());
    return;
  }
  if (method == "GET" && path == "/metrics") {
    http_reply(fd, 200, "text/plain; version=0.0.4", render_metrics());
    return;
  }
  http_reply(fd, 404, "text/plain", "not found\n");
}

void LighthouseServer::set_metrics_provider(MetricsProvider provider) {
  std::lock_guard<std::mutex> g(provider_mu_);
  metrics_provider_ = provider;
}

std::string LighthouseServer::render_metrics() {
  // Prometheus text exposition 0.0.4: native lighthouse counters/gauges,
  // then whatever the embedding process's registry supplies (the Python
  // side registers a provider that renders torchft_tpu.utils.metrics).
  std::ostringstream os;
  {
    std::lock_guard<std::mutex> g(mu_);
    int64_t now = now_ms();
    int64_t fresh = 0;
    for (const auto& [rid, ts] : heartbeats_)
      if (now - ts < opt_.heartbeat_timeout_ms) fresh += 1;
    os << "# HELP torchft_lighthouse_quorums_formed_total Quorums formed "
          "since lighthouse start\n"
       << "# TYPE torchft_lighthouse_quorums_formed_total counter\n"
       << "torchft_lighthouse_quorums_formed_total " << quorums_formed_total_
       << "\n"
       << "# HELP torchft_lighthouse_quorum_requests_total Quorum RPC "
          "requests received\n"
       << "# TYPE torchft_lighthouse_quorum_requests_total counter\n"
       << "torchft_lighthouse_quorum_requests_total "
       << quorum_requests_total_ << "\n"
       << "# HELP torchft_lighthouse_heartbeats_total Heartbeat RPCs "
          "received\n"
       << "# TYPE torchft_lighthouse_heartbeats_total counter\n"
       << "torchft_lighthouse_heartbeats_total " << heartbeats_total_ << "\n"
       << "# HELP torchft_lighthouse_quorum_id Current quorum id\n"
       << "# TYPE torchft_lighthouse_quorum_id gauge\n"
       << "torchft_lighthouse_quorum_id " << quorum_id_ << "\n"
       << "# HELP torchft_lighthouse_participants Participants waiting in "
          "the next quorum\n"
       << "# TYPE torchft_lighthouse_participants gauge\n"
       << "torchft_lighthouse_participants "
       << static_cast<int64_t>(participants_.size()) << "\n"
       << "# HELP torchft_lighthouse_heartbeats_live Replicas with a fresh "
          "heartbeat\n"
       << "# TYPE torchft_lighthouse_heartbeats_live gauge\n"
       << "torchft_lighthouse_heartbeats_live " << fresh << "\n";
    // Straggler telemetry: per-replica step lag and score, computed from
    // the progress piggybacked on heartbeat/quorum RPCs.  A dead replica
    // keeps exporting a growing lag until it is superseded/evicted — the
    // alerting window BEFORE the quorum shrinks around it.
    auto stragglers = compute_stragglers_locked(now);
    os << "# HELP torchft_replica_step_lag Steps behind the most advanced "
          "tracked replica\n"
       << "# TYPE torchft_replica_step_lag gauge\n";
    for (const auto& s : stragglers)
      os << "torchft_replica_step_lag{replica=\""
         << escape_label(s.replica_id) << "\"} " << s.step_lag << "\n";
    os << "# HELP torchft_straggler_score Progress age over the median "
          "fresh-replica age (~1 = typical; large = straggling/dead)\n"
       << "# TYPE torchft_straggler_score gauge\n";
    for (const auto& s : stragglers) {
      char buf[64];
      snprintf(buf, sizeof(buf), "%.6g", s.score);
      os << "torchft_straggler_score{replica=\""
         << escape_label(s.replica_id) << "\"} " << buf << "\n";
    }
  }
  {
    std::lock_guard<std::mutex> g(provider_mu_);
    if (metrics_provider_ != nullptr) {
      std::vector<char> buf(1 << 16);
      int n = metrics_provider_(buf.data(), static_cast<int>(buf.size()));
      // Retry with growing headroom: the registry can gain label children
      // between the probe and the re-render, so sizing exactly to the
      // first -needed can come up short again.
      for (int attempt = 0; n < 0 && attempt < 4; ++attempt) {
        buf.resize(static_cast<size_t>(-n) + (buf.size() >> 1) + 4096);
        n = metrics_provider_(buf.data(), static_cast<int>(buf.size()));
      }
      if (n > 0)
        os.write(buf.data(), std::min<int>(n, static_cast<int>(buf.size())));
    }
  }
  return os.str();
}

std::string LighthouseServer::render_status_json() { return status_json().dump(); }

Json LighthouseServer::status_json() {
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  Json out = Json::object();
  out["quorum_id"] = quorum_id_;
  out["status"] = last_reason_;
  out["reason"] = last_reason_;  // legacy status-RPC field name
  out["num_participants"] = static_cast<int64_t>(participants_.size());
  // live recompute, like the HTML page (reference lighthouse.rs:419)
  std::string live_reason;
  quorum_compute(now, &live_reason);
  out["live_status"] = live_reason;
  Json hbs = Json::array();
  for (const auto& [rid, ts] : heartbeats_) {
    Json h = Json::object();
    h["replica_id"] = rid;
    h["age_ms"] = now - ts;
    h["stale"] = (now - ts) >= opt_.heartbeat_timeout_ms;
    hbs.push_back(h);
  }
  out["heartbeats"] = hbs;
  // Straggler telemetry (same rows as /metrics and the dashboard table).
  Json stragglers = Json::array();
  int64_t max_step = 0;
  for (const auto& s : compute_stragglers_locked(now)) {
    Json row = Json::object();
    row["replica_id"] = s.replica_id;
    row["step"] = s.step;
    row["step_lag"] = s.step_lag;
    row["progress_age_ms"] = s.progress_age_ms;
    row["last_step_wall_ms"] = s.last_step_wall_ms;
    row["straggler_score"] = s.score;
    row["inflight_op"] = s.inflight_op;
    row["stale"] = s.stale;
    stragglers.push_back(row);
    max_step = std::max(max_step, s.step);
  }
  out["stragglers"] = stragglers;
  out["max_step"] = max_step;
  if (prev_quorum_.has_value()) {
    Json q = Json::object();
    q["quorum_id"] = prev_quorum_->quorum_id;
    q["created_ms"] = prev_quorum_->created_ms;
    q["age_ms"] = wall_ms() - prev_quorum_->created_ms;
    int64_t max_step = 0;
    for (const auto& p : prev_quorum_->participants)
      max_step = std::max(max_step, p.step);
    Json parts = Json::array();
    for (const auto& p : prev_quorum_->participants) {
      // full member fields (the pre-unification status RPC served
      // QuorumMember::to_json — consumers may rely on any of them) plus
      // the dashboard's derived "recovering" flag
      Json m = p.to_json();
      m["recovering"] = p.step < max_step;
      parts.push_back(m);
    }
    q["participants"] = parts;
    out["prev_quorum"] = q;
  }
  return out;
}

std::string LighthouseServer::render_status_html() {
  // Parity with the reference's askama status page
  // (reference templates/status.html:1-52, src/lighthouse.rs:415-452):
  // live next-quorum status, prev-quorum summary (id, participant count,
  // age), per-member card fields (step/manager/store/world_size) with a
  // "recovering" badge when behind max_step, a kill button, and a full
  // heartbeat list with an "old" marker past the heartbeat timeout.
  // Auto-refresh via meta refresh instead of htmx (no JS dependency).
  std::lock_guard<std::mutex> g(mu_);
  int64_t now = now_ms();
  // Recompute the quorum reason LIVE like the reference's get_status
  // (lighthouse.rs:419) rather than echoing the last tick's.
  std::string live_reason;
  quorum_compute(now, &live_reason);
  std::ostringstream os;
  os << "<!doctype html><html><head><title>torchft_tpu lighthouse</title>"
     << "<meta http-equiv=\"refresh\" content=\"2\">"
     << "<style>body{font-family:monospace;margin:2em}table{border-collapse:"
        "collapse}td,th{border:1px solid #888;padding:4px 8px}"
        "tr.recovering{background:#fff3cd}li.old{color:#b00}</style>"
     << "</head><body><h1>torchft_tpu lighthouse</h1>"
     << "<p>quorum_id: " << quorum_id_ << "</p>"
     << "<p>next quorum status: " << live_reason << "</p>";
  if (prev_quorum_.has_value()) {
    int64_t age_ms = wall_ms() - prev_quorum_->created_ms;
    os << "<h2>previous quorum (id " << prev_quorum_->quorum_id << ")</h2>"
       << "<p>participants: " << prev_quorum_->participants.size()
       << " &middot; quorum age: " << (age_ms / 1000.0) << "s</p>"
       << "<table><tr><th>replica</th><th>step</th><th>manager</th>"
       << "<th>store</th><th>world</th><th>heartbeat age (ms)</th>"
       << "<th>state</th><th></th></tr>";
    int64_t max_step = 0;
    for (const auto& p : prev_quorum_->participants)
      max_step = std::max(max_step, p.step);
    for (const auto& p : prev_quorum_->participants) {
      auto hb = heartbeats_.find(p.replica_id);
      int64_t age = hb == heartbeats_.end() ? -1 : now - hb->second;
      bool recovering = p.step < max_step;
      os << "<tr class=\"" << (recovering ? "recovering" : "healthy")
         << "\"><td>" << p.replica_id << "</td><td>" << p.step << "</td><td>"
         << p.address << "</td><td>" << p.store_address << "</td><td>"
         << p.world_size << "</td><td>" << age << "</td><td>"
         << (recovering ? "recovering" : "healthy") << "</td>"
         << "<td><form method=post action=\"/replica/" << p.replica_id
         << "/kill\"><button>kill</button></form></td></tr>";
    }
    os << "</table>";
  }
  {
    auto stragglers = compute_stragglers_locked(now);
    if (!stragglers.empty()) {
      os << "<h2>straggler telemetry</h2>"
         << "<table><tr><th>replica</th><th>step</th><th>step lag</th>"
         << "<th>progress age (ms)</th><th>score</th><th>in-flight op</th>"
         << "<th>heartbeat</th></tr>";
      for (const auto& s : stragglers) {
        char score[64];
        snprintf(score, sizeof(score), "%.2f", s.score);
        os << "<tr class=\"" << (s.stale ? "recovering" : "healthy")
           << "\"><td>" << s.replica_id << "</td><td>" << s.step
           << "</td><td>" << s.step_lag << "</td><td>" << s.progress_age_ms
           << "</td><td>" << score << "</td><td>"
           << (s.inflight_op.empty() ? "-" : s.inflight_op) << "</td><td>"
           << (s.stale ? "stale" : "fresh") << "</td></tr>";
      }
      os << "</table>";
    }
  }
  os << "<h2>pending participants (" << participants_.size() << ")</h2><ul>";
  for (const auto& [rid, det] : participants_)
    os << "<li>" << rid << " (step " << det.member.step << ")</li>";
  os << "</ul><h2>heartbeats (" << heartbeats_.size() << ")</h2><ul>";
  for (const auto& [rid, ts] : heartbeats_) {
    int64_t age = now - ts;
    bool old = age >= opt_.heartbeat_timeout_ms;
    os << "<li class=\"" << (old ? "old" : "fresh") << "\">" << rid
       << ": seen " << (age / 1000.0) << "s ago"
       << (old ? " (stale)" : "") << "</li>";
  }
  os << "</ul></body></html>";
  return os.str();
}

}  // namespace tft
