// Sanitizer smoke driver: two replica groups drive live quorum + commit
// rounds through a real Lighthouse, concurrently, then everything shuts
// down cleanly.
//
// Built by `make SANITIZE=thread smoke` (or address) as a standalone
// executable so the sanitizer runtime owns the process from startup —
// dlopen'ing an instrumented .so into an uninstrumented Python would
// leave TSan blind to the interpreter's threads.  Exercised paths: the
// accept-loop + per-connection threads (net.cc), the lighthouse tick
// thread + quorum barrier (lighthouse.cc), both managers' heartbeat
// threads and detached quorum threads racing report_progress and the
// commit barrier (manager.cc), and full shutdown teardown.
//
// Exit 0 and a final "SMOKE OK" line mean the protocol ran; ThreadSanitizer
// reports (if any) go to stderr and flip the exit code via
// TSAN_OPTIONS=exitcode / halt_on_error set by the test harness
// (tests/test_native_sanitize.py).

#include <cstdio>
#include <string>
#include <thread>

#include "lighthouse.h"
#include "manager.h"
#include "net.h"
#include "store.h"

namespace {

constexpr int kRounds = 3;
constexpr int64_t kRpcTimeoutMs = 15000;

int drive_round(const std::string& manager_addr, int round) {
  tft::Json params = tft::Json::object();
  params["group_rank"] = static_cast<int64_t>(0);
  params["init_sync"] = true;
  params["checkpoint_metadata"] = std::string("smoke-meta");
  params["step"] = static_cast<int64_t>(round);
  params["shrink_only"] = false;
  params["commit_failures"] = static_cast<int64_t>(0);

  tft::Json result;
  std::string err;
  if (!tft::call_rpc(manager_addr, "quorum", params, kRpcTimeoutMs, &result,
                     &err)) {
    fprintf(stderr, "smoke: quorum rpc to %s failed: %s\n",
            manager_addr.c_str(), err.c_str());
    return 1;
  }
  if (result.get("replica_world_size").as_int() != 2) {
    fprintf(stderr, "smoke: expected replica_world_size=2, got %lld\n",
            static_cast<long long>(result.get("replica_world_size").as_int()));
    return 1;
  }

  tft::Json commit = tft::Json::object();
  commit["group_rank"] = static_cast<int64_t>(0);
  commit["should_commit"] = true;
  if (!tft::call_rpc(manager_addr, "should_commit", commit, kRpcTimeoutMs,
                     &result, &err)) {
    fprintf(stderr, "smoke: should_commit rpc to %s failed: %s\n",
            manager_addr.c_str(), err.c_str());
    return 1;
  }
  if (!result.get("should_commit").as_bool()) {
    fprintf(stderr, "smoke: unanimous true votes decided false\n");
    return 1;
  }
  return 0;
}

}  // namespace

int main() {
  tft::LighthouseOpt lopt;
  lopt.bind_host = "127.0.0.1";
  lopt.min_replicas = 2;
  lopt.join_timeout_ms = 2000;
  lopt.quorum_tick_ms = 10;
  lopt.heartbeat_timeout_ms = 5000;
  tft::LighthouseServer lighthouse(lopt);
  lighthouse.start_serving();

  tft::StoreServer store("127.0.0.1", 0);
  store.start();

  auto make_opt = [&](const std::string& id) {
    tft::ManagerOpt mopt;
    mopt.replica_id = id;
    mopt.lighthouse_addr = lighthouse.address();
    mopt.bind_host = "127.0.0.1";
    mopt.store_address = store.address();
    mopt.world_size = 1;
    mopt.heartbeat_interval_ms = 20;  // hot heartbeats: more thread traffic
    mopt.connect_timeout_ms = 5000;
    mopt.quorum_retries = 1;
    return mopt;
  };
  tft::ManagerServer m0(make_opt("replica_0"));
  tft::ManagerServer m1(make_opt("replica_1"));
  m0.start_serving();
  m1.start_serving();

  int failures = 0;
  for (int round = 0; round < kRounds; ++round) {
    // progress reports race the heartbeat thread's reads — on purpose
    m0.report_progress(round, "quorum");
    m1.report_progress(round, "quorum");
    int f0 = 0, f1 = 0;
    std::thread t0([&] { f0 = drive_round(m0.address(), round); });
    std::thread t1([&] { f1 = drive_round(m1.address(), round); });
    t0.join();
    t1.join();
    failures += f0 + f1;
    if (failures) break;
  }

  m0.stop();
  m1.stop();
  lighthouse.stop();
  store.shutdown();

  if (failures) {
    printf("SMOKE FAIL\n");
    return 1;
  }
  printf("SMOKE OK\n");
  return 0;
}
