// Sanitizer smoke driver: two replica groups drive live quorum + commit
// rounds through a real Lighthouse, concurrently, then everything shuts
// down cleanly.
//
// Built by `make SANITIZE=thread smoke` (or address) as a standalone
// executable so the sanitizer runtime owns the process from startup —
// dlopen'ing an instrumented .so into an uninstrumented Python would
// leave TSan blind to the interpreter's threads.  Exercised paths: the
// accept-loop + per-connection threads (net.cc), the lighthouse tick
// thread + quorum barrier (lighthouse.cc), both managers' heartbeat
// threads and detached quorum threads racing report_progress and the
// commit barrier (manager.cc), and full shutdown teardown.
//
// Exit 0 and a final "SMOKE OK" line mean the protocol ran; ThreadSanitizer
// reports (if any) go to stderr and flip the exit code via
// TSAN_OPTIONS=exitcode / halt_on_error set by the test harness
// (tests/test_native_sanitize.py).

#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "fragserver.h"
#include "lighthouse.h"
#include "manager.h"
#include "net.h"
#include "store.h"

// Row-range codec entry points (native/quant.cc).  Declared here rather
// than via a header: the codec is consumed through ctypes in production,
// and this driver only needs the threaded-surface prototypes.
extern "C" {
void tft_quant_int8_rows(const float* in, int64_t r0, int64_t r1,
                         int64_t cols, float* scales, int8_t* payload);
void tft_quant_fp8_rows(const float* in, int64_t r0, int64_t r1,
                        int64_t cols, float* scales, uint8_t* payload);
void tft_dequant_fma_rows(const int8_t* payload, const float* scales,
                          int64_t r0, int64_t r1, int64_t cols, float* acc,
                          int overwrite);
void tft_dequant_fp8_fma_rows(const uint8_t* payload, const float* scales,
                              const float* lut256, int64_t r0, int64_t r1,
                              int64_t cols, float* acc, int overwrite);
void tft_div_f32_rows(float* acc, int64_t r0, int64_t r1, int64_t cols,
                      float div);
}

namespace {

constexpr int kRounds = 3;
constexpr int64_t kRpcTimeoutMs = 15000;

// Concurrent codec round: N threads drive the row-range codec over
// DISJOINT row blocks of SHARED buffers — exactly the access pattern the
// Python worker pool (ops/codec_pool.py) produces in the chunked
// quantized-collective pipeline.  Under TSan this proves the threaded
// surface is data-race-free; the result check proves the row-range
// delegation decodes back to the input within int8 grid error.
int codec_round() {
  constexpr int64_t kRows = 256, kCols = 512;
  constexpr int kThreads = 4;
  std::vector<float> in(kRows * kCols);
  for (int64_t i = 0; i < kRows * kCols; ++i) {
    in[i] = 0.001f * static_cast<float>((i * 2654435761u) % 2001) - 1.0f;
  }
  std::vector<float> scales(kRows), fp8_scales(kRows), acc(kRows * kCols);
  std::vector<int8_t> payload(kRows * kCols);
  std::vector<uint8_t> fp8_payload(kRows * kCols);
  // identity-ish LUT stand-in for ml_dtypes' table: the smoke checks
  // thread-safety of the shared-read pattern, not fp8 decode values
  std::vector<float> lut(256);
  for (int i = 0; i < 256; ++i) lut[i] = static_cast<float>(i);

  auto block = [&](int t) {
    const int64_t r0 = kRows * t / kThreads;
    const int64_t r1 = kRows * (t + 1) / kThreads;
    tft_quant_int8_rows(in.data(), r0, r1, kCols, scales.data(),
                        payload.data());
    tft_quant_fp8_rows(in.data(), r0, r1, kCols, fp8_scales.data(),
                       fp8_payload.data());
    tft_dequant_fma_rows(payload.data(), scales.data(), r0, r1, kCols,
                         acc.data(), 1);
    tft_dequant_fp8_fma_rows(fp8_payload.data(), fp8_scales.data(),
                             lut.data(), r0, r1, kCols, acc.data(), 0);
    tft_div_f32_rows(acc.data(), r0, r1, kCols, 2.0f);
  };
  std::vector<std::thread> threads;
  threads.reserve(kThreads);
  for (int t = 0; t < kThreads; ++t) threads.emplace_back(block, t);
  for (auto& th : threads) th.join();

  for (int64_t r = 0; r < kRows; ++r) {
    for (int64_t c = 0; c < kCols; ++c) {
      const float x = in[r * kCols + c];
      // acc = (int8_dequant(x) + lut_term) / 2; bound only the int8 leg
      const float int8_leg =
          2.0f * acc[r * kCols + c] -
          lut[fp8_payload[r * kCols + c]] * fp8_scales[r];
      if (std::fabs(int8_leg - x) > scales[r] * 0.51f + 1e-6f) {
        fprintf(stderr, "smoke: codec mismatch at (%lld,%lld)\n",
                static_cast<long long>(r), static_cast<long long>(c));
        return 1;
      }
    }
  }
  return 0;
}

// Serving-tier round: N threads heartbeat the serving role (publisher +
// servers) against the live lighthouse while others read the plan — the
// serving bookkeeping shares mu_ with the quorum tick thread, so under
// TSan this proves the new paths race neither each other nor the tick.
int serving_round(const std::string& lighthouse_addr) {
  constexpr int kServers = 4;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  threads.reserve(kServers + 2);
  for (int s = 0; s < kServers; ++s) {
    threads.emplace_back([&, s] {
      for (int i = 0; i < 5; ++i) {
        tft::Json params = tft::Json::object();
        params["replica_id"] = std::string("smoke_srv") + std::to_string(s);
        params["address"] =
            std::string("http://s") + std::to_string(s) + ":1";
        params["role"] = std::string("server");
        params["version"] = static_cast<int64_t>(i);
        params["capacity"] = static_cast<int64_t>(0);
        tft::Json result;
        std::string err;
        if (!tft::call_rpc(lighthouse_addr, "serving_heartbeat", params,
                           kRpcTimeoutMs, &result, &err)) {
          fprintf(stderr, "smoke: serving_heartbeat failed: %s\n",
                  err.c_str());
          failures = 1;
          return;
        }
      }
    });
  }
  threads.emplace_back([&] {
    tft::Json params = tft::Json::object();
    params["replica_id"] = std::string("smoke_pub");
    params["address"] = std::string("http://p:1");
    params["role"] = std::string("publisher");
    params["version"] = static_cast<int64_t>(7);
    tft::Json result;
    std::string err;
    if (!tft::call_rpc(lighthouse_addr, "serving_heartbeat", params,
                       kRpcTimeoutMs, &result, &err)) {
      fprintf(stderr, "smoke: publisher heartbeat failed: %s\n", err.c_str());
      failures = 1;
    }
  });
  threads.emplace_back([&] {
    for (int i = 0; i < 5; ++i) {
      tft::Json result;
      std::string err;
      if (!tft::call_rpc(lighthouse_addr, "serving_plan", tft::Json::object(),
                         kRpcTimeoutMs, &result, &err)) {
        fprintf(stderr, "smoke: serving_plan failed: %s\n", err.c_str());
        failures = 1;
        return;
      }
    }
  });
  for (auto& th : threads) th.join();
  if (failures.load()) return failures.load();
  // final plan sanity: 4 servers placed, publisher is the root source
  tft::Json result;
  std::string err;
  if (!tft::call_rpc(lighthouse_addr, "serving_plan", tft::Json::object(),
                     kRpcTimeoutMs, &result, &err)) {
    fprintf(stderr, "smoke: final serving_plan failed: %s\n", err.c_str());
    return 1;
  }
  if (result.get("nodes").as_array().size() != kServers ||
      result.get("root_source").as_string() != "http://p:1" ||
      result.get("latest_version").as_int() != 7) {
    fprintf(stderr, "smoke: serving plan shape wrong\n");
    return 1;
  }
  return 0;
}

// Coordination-plane HA election round: three lighthouse peers with
// leased leadership in ONE process — election threads, lease RPC
// handlers and the HaRpcClient failover walk all race under TSan.
// Drives: cold-start election, a quorum through the multi-endpoint
// client, leader kill, takeover at a higher term, and a post-takeover
// quorum whose term-prefixed id strictly dominates the first.
int pick_free_port() {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  struct sockaddr_in sa = {};
  sa.sin_family = AF_INET;
  sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  sa.sin_port = 0;
  if (::bind(fd, reinterpret_cast<struct sockaddr*>(&sa), sizeof(sa)) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(sa);
  getsockname(fd, reinterpret_cast<struct sockaddr*>(&sa), &len);
  int port = ntohs(sa.sin_port);
  ::close(fd);
  return port;
}

int election_round() {
  constexpr int kPeers = 3;
  constexpr int64_t kLeaseMs = 200;
  std::vector<int> ports;
  for (int i = 0; i < kPeers; ++i) {
    int p = pick_free_port();
    if (p < 0) {
      fprintf(stderr, "smoke: pick_free_port failed\n");
      return 1;
    }
    ports.push_back(p);
  }
  std::vector<std::string> endpoints;
  endpoints.reserve(kPeers);
  for (int p : ports)
    endpoints.push_back("127.0.0.1:" + std::to_string(p));
  std::vector<std::unique_ptr<tft::LighthouseServer>> peers;
  for (int i = 0; i < kPeers; ++i) {
    tft::LighthouseOpt opt;
    opt.bind_host = "127.0.0.1";
    opt.port = ports[i];
    opt.min_replicas = 1;
    opt.join_timeout_ms = 100;
    opt.quorum_tick_ms = 20;
    opt.heartbeat_timeout_ms = 5000;
    opt.lease_timeout_ms = kLeaseMs;
    std::string others;
    for (int j = 0; j < kPeers; ++j) {
      if (j == i) continue;
      if (!others.empty()) others += ",";
      others += endpoints[j];
    }
    opt.peers = others;
    peers.push_back(std::make_unique<tft::LighthouseServer>(opt));
    peers.back()->start_serving();
  }
  auto leader_of = [&](int64_t* term) -> int {
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::seconds(30);
    while (std::chrono::steady_clock::now() < deadline) {
      for (int i = 0; i < kPeers; ++i) {
        if (!peers[i]) continue;
        tft::Json info = peers[i]->ha_info();
        if (info.get("is_leader").as_bool()) {
          if (term) *term = info.get("term").as_int();
          return i;
        }
      }
      usleep(10 * 1000);
    }
    return -1;
  };
  std::string all = endpoints[0] + "," + endpoints[1] + "," + endpoints[2];
  int64_t term1 = 0, term2 = 0;
  int failures = 0;
  int leader = leader_of(&term1);
  if (leader < 0) {
    fprintf(stderr, "smoke: no leader elected\n");
    failures = 1;
  }
  int64_t qid1 = 0;
  if (!failures) {
    tft::HaRpcClient cli(all);
    try {
      tft::Json member = tft::Json::object();
      member["replica_id"] = std::string("ha_smoke:1");
      member["step"] = static_cast<int64_t>(0);
      tft::Json params = tft::Json::object();
      params["member"] = member;
      tft::Json r = cli.call("quorum", params, kRpcTimeoutMs);
      qid1 = r.get("quorum").get("quorum_id").as_int();
    } catch (const std::exception& e) {
      fprintf(stderr, "smoke: HA quorum 1 failed: %s\n", e.what());
      failures = 1;
    }
  }
  if (!failures) {
    peers[leader]->stop();
    peers[leader].reset();  // SIGKILL stand-in: the endpoint goes dead
    int next = leader_of(&term2);
    if (next < 0 || next == leader || term2 <= term1) {
      fprintf(stderr, "smoke: takeover failed (next=%d terms %lld->%lld)\n",
              next, static_cast<long long>(term1),
              static_cast<long long>(term2));
      failures = 1;
    }
  }
  if (!failures) {
    tft::HaRpcClient cli(all);
    try {
      tft::Json member = tft::Json::object();
      member["replica_id"] = std::string("ha_smoke:2");
      member["step"] = static_cast<int64_t>(1);
      tft::Json params = tft::Json::object();
      params["member"] = member;
      tft::Json r = cli.call("quorum", params, kRpcTimeoutMs);
      int64_t qid2 = r.get("quorum").get("quorum_id").as_int();
      if (qid2 <= qid1 || (qid2 >> 32) <= (qid1 >> 32)) {
        fprintf(stderr,
                "smoke: quorum_id not term-monotone across takeover "
                "(%lld -> %lld)\n",
                static_cast<long long>(qid1), static_cast<long long>(qid2));
        failures = 1;
      }
    } catch (const std::exception& e) {
      fprintf(stderr, "smoke: HA quorum 2 failed: %s\n", e.what());
      failures = 1;
    }
  }
  for (auto& p : peers) {
    if (p) p->stop();
  }
  return failures;
}

// Fragment data-plane round: concurrent stagers race long-poll readers
// on the zero-copy fragment server while a retirer drops the PREVIOUS
// version mid-stream — the refcounted serve-vs-retire race, the condvar
// park/wake path, the buffer pool recycle, and the per-thread persistent
// client connections all run together under the sanitizer.  Readers of
// the live version assert bitwise payloads + sha; readers of the retired
// version tolerate any outcome (that race is exactly the point) but must
// keep the begin/body protocol balanced.
int fragment_round() {
  constexpr int kStagers = 3;
  constexpr int kReaders = 3;
  constexpr int kFragsPerStager = 4;
  constexpr int kVersions = 3;
  constexpr size_t kFragBytes = 64 * 1024;

  tft::FragServer server("127.0.0.1", 0);  // ctor starts the accept loop
  const std::string addr = "127.0.0.1:" + std::to_string(server.port());

  auto frag_name = [](int s, int i) {
    return "frag_w" + std::to_string(s) + "_" + std::to_string(i);
  };
  auto payload_for = [](int v, int s, int i) {
    std::vector<uint8_t> p(kFragBytes);
    for (size_t j = 0; j < kFragBytes; ++j) {
      p[j] = static_cast<uint8_t>((v * 131 + s * 31 + i * 7 + j) & 0xff);
    }
    return p;
  };

  std::atomic<int> failures{0};
  for (int v = 0; v < kVersions && !failures.load(); ++v) {
    server.begin(v);
    std::vector<std::thread> threads;
    // stagers: disjoint fragment names, jittered so readers park first
    for (int s = 0; s < kStagers; ++s) {
      threads.emplace_back([&, s, v] {
        for (int i = 0; i < kFragsPerStager; ++i) {
          usleep(1000 * ((s + i) % 3));
          auto p = payload_for(v, s, i);
          if (server.stage(v, frag_name(s, i), p.data(), p.size()) != 0) {
            fprintf(stderr, "smoke: frag stage %s failed\n",
                    frag_name(s, i).c_str());
            failures = 1;
            return;
          }
        }
      });
    }
    // readers: long-poll every fragment of the LIVE version to bitwise
    // equality (503 = parked-then-busy, retry; anything else is a bug)
    for (int r = 0; r < kReaders; ++r) {
      threads.emplace_back([&, v] {
        for (int s = 0; s < kStagers; ++s) {
          for (int i = 0; i < kFragsPerStager; ++i) {
            const auto expect = payload_for(v, s, i);
            bool got_it = false;
            for (int attempt = 0; attempt < 2000 && !failures.load();
                 ++attempt) {
              int64_t n = 0;
              double fb = 0;
              int rc = tft::frag_fetch_begin(addr, v, frag_name(s, i), 5000,
                                             &n, &fb);
              if (rc == 503) {
                continue;  // parked server-side already; re-poll
              }
              if (rc != 200 || n != static_cast<int64_t>(expect.size())) {
                fprintf(stderr, "smoke: frag fetch %s rc=%d n=%lld\n",
                        frag_name(s, i).c_str(), rc,
                        static_cast<long long>(n));
                failures = 1;
                break;
              }
              std::vector<uint8_t> got(expect.size());
              char sha[65];
              if (tft::frag_fetch_body(got.data(),
                                       static_cast<int64_t>(got.size()), sha,
                                       5000) != 0) {
                fprintf(stderr, "smoke: frag body %s failed: %s\n",
                        frag_name(s, i).c_str(),
                        tft::frag_client_error().c_str());
                failures = 1;
                break;
              }
              char want[65];
              tft::sha256_hex(expect.data(), expect.size(), want);
              if (got != expect || std::string(sha) != want) {
                fprintf(stderr, "smoke: frag %s payload/sha mismatch\n",
                        frag_name(s, i).c_str());
                failures = 1;
                break;
              }
              got_it = true;
              break;
            }
            if (!got_it && !failures.load()) {
              fprintf(stderr, "smoke: frag %s never landed\n",
                      frag_name(s, i).c_str());
              failures = 1;
            }
            if (failures.load()) break;
          }
          if (failures.load()) break;
        }
        tft::frag_client_close();
      });
    }
    if (v > 0) {
      // retirer: drop the previous version while old-readers still pull
      // it — exercises retire racing in-flight serves (last-deref
      // recycle) and retire racing parked long-polls
      threads.emplace_back([&, v] {
        usleep(500);
        server.retire(v - 1);
      });
      threads.emplace_back([&, v] {
        for (int i = 0; i < 10; ++i) {
          int64_t n = 0;
          double fb = 0;
          int rc = tft::frag_fetch_begin(addr, v - 1, frag_name(i % kStagers, 0),
                                         1000, &n, &fb);
          if (rc == 200) {
            std::vector<uint8_t> scratch(static_cast<size_t>(n));
            char sha[65];
            tft::frag_fetch_body(scratch.data(), n, sha, 5000);
          }
          // 404/503/-1 are all legal outcomes of the retire race
        }
        tft::frag_client_close();
      });
    }
    for (auto& th : threads) th.join();
    server.finish(v);
  }

  const tft::FragCounters c = server.counters();
  if (!failures.load() && c.serve_copies != 0) {
    fprintf(stderr, "smoke: serve_copies=%lld (zero-copy broken)\n",
            static_cast<long long>(c.serve_copies));
    failures = 1;
  }
  const int64_t expect_serves =
      static_cast<int64_t>(kReaders) * kStagers * kFragsPerStager * kVersions;
  if (!failures.load() && c.serves < expect_serves) {
    fprintf(stderr, "smoke: serves=%lld < %lld\n",
            static_cast<long long>(c.serves),
            static_cast<long long>(expect_serves));
    failures = 1;
  }
  server.shutdown();
  return failures.load();
}

int drive_round(const std::string& manager_addr, int round) {
  tft::Json params = tft::Json::object();
  params["group_rank"] = static_cast<int64_t>(0);
  params["init_sync"] = true;
  params["checkpoint_metadata"] = std::string("smoke-meta");
  params["step"] = static_cast<int64_t>(round);
  params["shrink_only"] = false;
  params["commit_failures"] = static_cast<int64_t>(0);

  tft::Json result;
  std::string err;
  if (!tft::call_rpc(manager_addr, "quorum", params, kRpcTimeoutMs, &result,
                     &err)) {
    fprintf(stderr, "smoke: quorum rpc to %s failed: %s\n",
            manager_addr.c_str(), err.c_str());
    return 1;
  }
  if (result.get("replica_world_size").as_int() != 2) {
    fprintf(stderr, "smoke: expected replica_world_size=2, got %lld\n",
            static_cast<long long>(result.get("replica_world_size").as_int()));
    return 1;
  }

  tft::Json commit = tft::Json::object();
  commit["group_rank"] = static_cast<int64_t>(0);
  commit["should_commit"] = true;
  if (!tft::call_rpc(manager_addr, "should_commit", commit, kRpcTimeoutMs,
                     &result, &err)) {
    fprintf(stderr, "smoke: should_commit rpc to %s failed: %s\n",
            manager_addr.c_str(), err.c_str());
    return 1;
  }
  if (!result.get("should_commit").as_bool()) {
    fprintf(stderr, "smoke: unanimous true votes decided false\n");
    return 1;
  }
  return 0;
}

}  // namespace

int main() {
  if (codec_round()) {
    printf("SMOKE FAIL\n");
    return 1;
  }
  printf("CODEC OK\n");

  if (election_round()) {
    printf("SMOKE FAIL\n");
    return 1;
  }
  printf("ELECTION OK\n");

  if (fragment_round()) {
    printf("SMOKE FAIL\n");
    return 1;
  }
  printf("FRAGMENT OK\n");

  tft::LighthouseOpt lopt;
  lopt.bind_host = "127.0.0.1";
  lopt.min_replicas = 2;
  lopt.join_timeout_ms = 2000;
  lopt.quorum_tick_ms = 10;
  lopt.heartbeat_timeout_ms = 5000;
  tft::LighthouseServer lighthouse(lopt);
  lighthouse.start_serving();

  tft::StoreServer store("127.0.0.1", 0);
  store.start();

  auto make_opt = [&](const std::string& id) {
    tft::ManagerOpt mopt;
    mopt.replica_id = id;
    mopt.lighthouse_addr = lighthouse.address();
    mopt.bind_host = "127.0.0.1";
    mopt.store_address = store.address();
    mopt.world_size = 1;
    mopt.heartbeat_interval_ms = 20;  // hot heartbeats: more thread traffic
    mopt.connect_timeout_ms = 5000;
    mopt.quorum_retries = 1;
    return mopt;
  };
  tft::ManagerServer m0(make_opt("replica_0"));
  tft::ManagerServer m1(make_opt("replica_1"));
  m0.start_serving();
  m1.start_serving();

  int failures = 0;
  for (int round = 0; round < kRounds; ++round) {
    // progress reports race the heartbeat thread's reads — on purpose
    m0.report_progress(round, "quorum");
    m1.report_progress(round, "quorum");
    int f0 = 0, f1 = 0, fs = 0;
    std::thread t0([&] { f0 = drive_round(m0.address(), round); });
    std::thread t1([&] { f1 = drive_round(m1.address(), round); });
    // serving traffic races the quorum rounds + tick thread on mu_
    std::thread ts([&] { fs = serving_round(lighthouse.address()); });
    t0.join();
    t1.join();
    ts.join();
    failures += f0 + f1 + fs;
    if (failures) break;
  }
  if (!failures) printf("SERVING OK\n");

  m0.stop();
  m1.stop();
  lighthouse.stop();
  store.shutdown();

  if (failures) {
    printf("SMOKE FAIL\n");
    return 1;
  }
  printf("SMOKE OK\n");
  return 0;
}
