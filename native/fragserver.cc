// Native zero-copy fragment data plane — see fragserver.h for the
// contract.  Server side: staged payloads live in pool-recycled buffers
// and every serve is one sendmsg (header iovec + payload iovec) straight
// from the staged buffer — the serve path never copies payload bytes in
// user space (FragCounters::serve_copies stays 0 by construction).
// Client side: two-phase fetch with per-(thread, endpoint) persistent
// connections; the body phase lands bytes straight in the caller's
// buffer and digests them in place — Python calls it through ctypes,
// which releases the GIL for the duration.
#include "fragserver.h"

#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <cctype>
#include <cstdio>
#include <cstring>
#include <sstream>

namespace tft {

namespace {

// ---- SHA-256 (FIPS 180-4), self-contained ------------------------------
// The digest of record stays Python's hashlib at stage/verify control
// points; this native copy exists so the receive path can verify the
// wire buffer without re-entering the interpreter.  Bit-identical to
// hashlib.sha256 by construction (same algorithm, tested end to end).

constexpr uint32_t kShaK[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

// ---- SHA-NI fast path ----------------------------------------------------
// The x86 SHA extensions run the compression rounds in hardware — about
// an order of magnitude over the scalar block below, and the receive
// path digests EVERY wire buffer in-line, so this is the data plane's
// throughput floor.  Runtime-dispatched; the scalar block remains the
// portable fallback (and the bit-identical reference).
#if defined(__x86_64__) && defined(__GNUC__)
#define TFT_SHA_NI 1

#include <cpuid.h>
#include <immintrin.h>

__attribute__((target("sha,ssse3,sse4.1"))) void sha256_blocks_ni(
    uint32_t state[8], const uint8_t* data, size_t blocks) {
  const __m128i kMask =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i tmp = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[0]));
  __m128i st1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&state[4]));
  tmp = _mm_shuffle_epi32(tmp, 0xB1);        // CDAB
  st1 = _mm_shuffle_epi32(st1, 0x1B);        // EFGH
  __m128i st0 = _mm_alignr_epi8(tmp, st1, 8);   // ABEF
  st1 = _mm_blend_epi16(st1, tmp, 0xF0);        // CDGH

  while (blocks > 0) {
    const __m128i abef_save = st0;
    const __m128i cdgh_save = st1;
    __m128i msg, msg0, msg1, msg2, msg3;

    // rounds 0-3
    msg = _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 0));
    msg0 = _mm_shuffle_epi8(msg, kMask);
    msg = _mm_add_epi32(
        msg0, _mm_set_epi64x(0xE9B5DBA5B5C0FBCFULL, 0x71374491428A2F98ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);

    // rounds 4-7
    msg1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 16));
    msg1 = _mm_shuffle_epi8(msg1, kMask);
    msg = _mm_add_epi32(
        msg1, _mm_set_epi64x(0xAB1C5ED5923F82A4ULL, 0x59F111F13956C25BULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg0 = _mm_sha256msg1_epu32(msg0, msg1);

    // rounds 8-11
    msg2 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 32));
    msg2 = _mm_shuffle_epi8(msg2, kMask);
    msg = _mm_add_epi32(
        msg2, _mm_set_epi64x(0x550C7DC3243185BEULL, 0x12835B01D807AA98ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg1 = _mm_sha256msg1_epu32(msg1, msg2);

    // rounds 12-15
    msg3 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(data + 48));
    msg3 = _mm_shuffle_epi8(msg3, kMask);
    msg = _mm_add_epi32(
        msg3, _mm_set_epi64x(0xC19BF1749BDC06A7ULL, 0x80DEB1FE72BE5D74ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg3, msg2, 4);
    msg0 = _mm_add_epi32(msg0, tmp);
    msg0 = _mm_sha256msg2_epu32(msg0, msg3);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg2 = _mm_sha256msg1_epu32(msg2, msg3);

    // rounds 16-19
    msg = _mm_add_epi32(
        msg0, _mm_set_epi64x(0x240CA1CC0FC19DC6ULL, 0xEFBE4786E49B69C1ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg0, msg3, 4);
    msg1 = _mm_add_epi32(msg1, tmp);
    msg1 = _mm_sha256msg2_epu32(msg1, msg0);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg3 = _mm_sha256msg1_epu32(msg3, msg0);

    // rounds 20-23
    msg = _mm_add_epi32(
        msg1, _mm_set_epi64x(0x76F988DA5CB0A9DCULL, 0x4A7484AA2DE92C6FULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg1, msg0, 4);
    msg2 = _mm_add_epi32(msg2, tmp);
    msg2 = _mm_sha256msg2_epu32(msg2, msg1);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg0 = _mm_sha256msg1_epu32(msg0, msg1);

    // rounds 24-27
    msg = _mm_add_epi32(
        msg2, _mm_set_epi64x(0xBF597FC7B00327C8ULL, 0xA831C66D983E5152ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg2, msg1, 4);
    msg3 = _mm_add_epi32(msg3, tmp);
    msg3 = _mm_sha256msg2_epu32(msg3, msg2);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg1 = _mm_sha256msg1_epu32(msg1, msg2);

    // rounds 28-31
    msg = _mm_add_epi32(
        msg3, _mm_set_epi64x(0x1429296706CA6351ULL, 0xD5A79147C6E00BF3ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg3, msg2, 4);
    msg0 = _mm_add_epi32(msg0, tmp);
    msg0 = _mm_sha256msg2_epu32(msg0, msg3);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg2 = _mm_sha256msg1_epu32(msg2, msg3);

    // rounds 32-35
    msg = _mm_add_epi32(
        msg0, _mm_set_epi64x(0x53380D134D2C6DFCULL, 0x2E1B213827B70A85ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg0, msg3, 4);
    msg1 = _mm_add_epi32(msg1, tmp);
    msg1 = _mm_sha256msg2_epu32(msg1, msg0);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg3 = _mm_sha256msg1_epu32(msg3, msg0);

    // rounds 36-39
    msg = _mm_add_epi32(
        msg1, _mm_set_epi64x(0x92722C8581C2C92EULL, 0x766A0ABB650A7354ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg1, msg0, 4);
    msg2 = _mm_add_epi32(msg2, tmp);
    msg2 = _mm_sha256msg2_epu32(msg2, msg1);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg0 = _mm_sha256msg1_epu32(msg0, msg1);

    // rounds 40-43
    msg = _mm_add_epi32(
        msg2, _mm_set_epi64x(0xC76C51A3C24B8B70ULL, 0xA81A664BA2BFE8A1ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg2, msg1, 4);
    msg3 = _mm_add_epi32(msg3, tmp);
    msg3 = _mm_sha256msg2_epu32(msg3, msg2);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg1 = _mm_sha256msg1_epu32(msg1, msg2);

    // rounds 44-47
    msg = _mm_add_epi32(
        msg3, _mm_set_epi64x(0x106AA070F40E3585ULL, 0xD6990624D192E819ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg3, msg2, 4);
    msg0 = _mm_add_epi32(msg0, tmp);
    msg0 = _mm_sha256msg2_epu32(msg0, msg3);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg2 = _mm_sha256msg1_epu32(msg2, msg3);

    // rounds 48-51
    msg = _mm_add_epi32(
        msg0, _mm_set_epi64x(0x34B0BCB52748774CULL, 0x1E376C0819A4C116ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg0, msg3, 4);
    msg1 = _mm_add_epi32(msg1, tmp);
    msg1 = _mm_sha256msg2_epu32(msg1, msg0);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);
    msg3 = _mm_sha256msg1_epu32(msg3, msg0);

    // rounds 52-55
    msg = _mm_add_epi32(
        msg1, _mm_set_epi64x(0x682E6FF35B9CCA4FULL, 0x4ED8AA4A391C0CB3ULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg1, msg0, 4);
    msg2 = _mm_add_epi32(msg2, tmp);
    msg2 = _mm_sha256msg2_epu32(msg2, msg1);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);

    // rounds 56-59
    msg = _mm_add_epi32(
        msg2, _mm_set_epi64x(0x8CC7020884C87814ULL, 0x78A5636F748F82EEULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    tmp = _mm_alignr_epi8(msg2, msg1, 4);
    msg3 = _mm_add_epi32(msg3, tmp);
    msg3 = _mm_sha256msg2_epu32(msg3, msg2);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);

    // rounds 60-63
    msg = _mm_add_epi32(
        msg3, _mm_set_epi64x(0xC67178F2BEF9A3F7ULL, 0xA4506CEB90BEFFFAULL));
    st1 = _mm_sha256rnds2_epu32(st1, st0, msg);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    st0 = _mm_sha256rnds2_epu32(st0, st1, msg);

    st0 = _mm_add_epi32(st0, abef_save);
    st1 = _mm_add_epi32(st1, cdgh_save);
    data += 64;
    --blocks;
  }

  tmp = _mm_shuffle_epi32(st0, 0x1B);        // FEBA
  st1 = _mm_shuffle_epi32(st1, 0xB1);        // DCHG
  st0 = _mm_blend_epi16(tmp, st1, 0xF0);     // DCBA
  st1 = _mm_alignr_epi8(st1, tmp, 8);        // HGFE -> EFGH slots
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[0]), st0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&state[4]), st1);
}

bool detect_sha_ni() {
  // CPUID directly (not __builtin_cpu_supports: clang rejects "sha"):
  // leaf 7 EBX bit 29 = SHA extensions; leaf 1 ECX bits 19/9 = SSE4.1
  // and SSSE3, which the shuffles in the kernel above also need.
  unsigned int eax = 0, ebx = 0, ecx = 0, edx = 0;
  if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
  if (!(ebx & (1u << 29))) return false;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx & (1u << 19)) && (ecx & (1u << 9));
}

const bool kShaNi = detect_sha_ni();
#endif  // __x86_64__ && __GNUC__

struct Sha256 {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (static_cast<uint32_t>(p[4 * i]) << 24) |
             (static_cast<uint32_t>(p[4 * i + 1]) << 16) |
             (static_cast<uint32_t>(p[4 * i + 2]) << 8) |
             static_cast<uint32_t>(p[4 * i + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + kShaK[i] + w[i];
      uint32_t s0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g;
      g = f;
      f = e;
      e = d + t1;
      d = c;
      c = b;
      b = a;
      a = t1 + t2;
    }
    h[0] += a;
    h[1] += b;
    h[2] += c;
    h[3] += d;
    h[4] += e;
    h[5] += f;
    h[6] += g;
    h[7] += hh;
  }

  void blocks(const uint8_t* p, size_t nblocks) {
#ifdef TFT_SHA_NI
    if (kShaNi) {
      sha256_blocks_ni(h, p, nblocks);
      return;
    }
#endif
    for (size_t i = 0; i < nblocks; ++i) block(p + 64 * i);
  }

  void update(const uint8_t* data, size_t n) {
    total += n;
    if (buflen > 0) {
      while (n > 0 && buflen < 64) {
        buf[buflen++] = *data++;
        --n;
      }
      if (buflen == 64) {
        blocks(buf, 1);
        buflen = 0;
      }
    }
    if (n >= 64) {
      size_t nb = n / 64;
      blocks(data, nb);
      data += nb * 64;
      n -= nb * 64;
    }
    while (n > 0) {
      buf[buflen++] = *data++;
      --n;
    }
  }

  void finish(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t lenbuf[8];
    for (int i = 0; i < 8; ++i)
      lenbuf[i] = static_cast<uint8_t>(bits >> (56 - 8 * i));
    update(lenbuf, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = static_cast<uint8_t>(h[i] >> 24);
      out[4 * i + 1] = static_cast<uint8_t>(h[i] >> 16);
      out[4 * i + 2] = static_cast<uint8_t>(h[i] >> 8);
      out[4 * i + 3] = static_cast<uint8_t>(h[i]);
    }
  }
};

bool poll_fd(int fd, short events, int64_t deadline_ms) {
  for (;;) {
    int64_t remain = deadline_ms - now_ms();
    if (remain <= 0) return false;
    struct pollfd pfd = {fd, events, 0};
    int rc = poll(&pfd, 1, static_cast<int>(std::min<int64_t>(remain, 1000)));
    if (rc > 0) return true;
    if (rc < 0 && errno != EINTR && errno != EAGAIN) return false;
  }
}

// sendmsg loop over a (header, payload) pair honoring partial writes —
// the zero-copy serve primitive.  Never touches payload bytes.
bool sendv_all(int fd, const char* hdr, size_t hdr_len, const uint8_t* body,
               size_t body_len, int64_t deadline_ms) {
  size_t off = 0;
  const size_t total = hdr_len + body_len;
  while (off < total) {
    if (!poll_fd(fd, POLLOUT, deadline_ms)) return false;
    struct iovec iov[2];
    int cnt = 0;
    if (off < hdr_len) {
      iov[cnt].iov_base = const_cast<char*>(hdr) + off;
      iov[cnt].iov_len = hdr_len - off;
      ++cnt;
      iov[cnt].iov_base = const_cast<uint8_t*>(body);
      iov[cnt].iov_len = body_len;
      ++cnt;
    } else {
      iov[cnt].iov_base = const_cast<uint8_t*>(body) + (off - hdr_len);
      iov[cnt].iov_len = body_len - (off - hdr_len);
      ++cnt;
    }
    struct msghdr msg = {};
    msg.msg_iov = iov;
    msg.msg_iovlen = cnt;
    ssize_t rc = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (rc < 0) {
      if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return false;
    }
    off += static_cast<size_t>(rc);
  }
  return true;
}

constexpr int64_t kLongPollMs = 250;      // cut-through park window
constexpr int64_t kLongPollCapMs = 5000;  // X-TFT-Poll-Ms request cap
constexpr int64_t kServeTimeoutMs = 60000;
constexpr size_t kPoolPerSizeCap = 64;    // recycled buffers kept per size

}  // namespace

void sha256_hex(const uint8_t* data, size_t len, char* out_hex65) {
  Sha256 s;
  if (len > 0) s.update(data, len);
  uint8_t digest[32];
  s.finish(digest);
  static const char* hex = "0123456789abcdef";
  for (int i = 0; i < 32; ++i) {
    out_hex65[2 * i] = hex[digest[i] >> 4];
    out_hex65[2 * i + 1] = hex[digest[i] & 0xf];
  }
  out_hex65[64] = '\0';
}

// ---- server --------------------------------------------------------------

FragServer::FragServer(const std::string& bind_host, int port)
    : RpcServer(bind_host, port) {
  start();
}

FragServer::~FragServer() {
  // Drain connection threads BEFORE members (cv_, versions_) go away;
  // RpcServer::shutdown is CAS-idempotent so an explicit earlier call
  // (tft_server_shutdown) makes this a no-op.
  shutdown();
}

Json FragServer::handle(const std::string& method, const Json&, int64_t) {
  throw std::runtime_error("fragserver speaks HTTP only: " + method);
}

void FragServer::wake_blocked() {
  std::lock_guard<std::mutex> g(mu_);
  cv_.notify_all();
}

std::shared_ptr<FragBuf> FragServer::pool_take(size_t len) {
  // caller holds mu_
  auto buf = std::make_shared<FragBuf>();
  auto it = pool_.find(len);
  if (it != pool_.end() && !it->second.empty()) {
    buf->data = std::move(it->second.back());
    it->second.pop_back();
    ++counters_.pool_hits;
  } else {
    buf->data.resize(len);
    ++counters_.pool_misses;
  }
  buf->len = len;
  return buf;
}

void FragServer::pool_give_locked(FragBuf& buf) {
  // caller holds mu_
  if (buf.data.empty()) return;
  auto& slot = pool_[buf.data.size()];
  if (slot.size() < kPoolPerSizeCap) slot.push_back(std::move(buf.data));
  buf.data.clear();
  buf.len = 0;
}

void FragServer::deref(const std::shared_ptr<FragBuf>& buf) {
  std::lock_guard<std::mutex> g(mu_);
  if (--buf->refs == 0 && buf->retired) pool_give_locked(*buf);
}

int FragServer::begin(int64_t step) {
  std::lock_guard<std::mutex> g(mu_);
  versions_[step];  // streaming slot (complete=false)
  cv_.notify_all();  // readers parked on a future version re-check
  return 0;
}

int FragServer::stage(int64_t step, const std::string& resource,
                      const uint8_t* data, size_t len) {
  std::shared_ptr<FragBuf> buf;
  {
    std::lock_guard<std::mutex> g(mu_);
    if (versions_.find(step) == versions_.end()) return -1;
    buf = pool_take(len);
  }
  // The one copy in the plane: Python's staged buffer -> the pooled
  // registered buffer, outside the lock so concurrent stagers overlap.
  if (len > 0) memcpy(buf->data.data(), data, len);
  std::lock_guard<std::mutex> g(mu_);
  auto it = versions_.find(step);
  if (it == versions_.end()) {
    // retired while we copied: recycle, report not-mirrored
    pool_give_locked(*buf);
    return -1;
  }
  auto& slot = it->second.frags[resource];
  if (slot) {
    // restage of the same resource: retire the old buffer
    slot->retired = true;
    if (slot->refs == 0) pool_give_locked(*slot);
  }
  slot = buf;
  counters_.stage_copy_bytes += static_cast<int64_t>(len);
  cv_.notify_all();
  return 0;
}

int FragServer::finish(int64_t step) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = versions_.find(step);
  if (it == versions_.end()) return -1;
  it->second.complete = true;
  cv_.notify_all();
  return 0;
}

int FragServer::retire(int64_t step) {
  std::lock_guard<std::mutex> g(mu_);
  auto it = versions_.find(step);
  if (it == versions_.end()) return -1;
  for (auto& kv : it->second.frags) {
    kv.second->retired = true;
    if (kv.second->refs == 0) pool_give_locked(*kv.second);
    // else: in-flight serves finish from the zombie buffer; the last
    // deref recycles it — retire never waits on the wire
  }
  versions_.erase(it);
  cv_.notify_all();  // parked readers re-check and answer 404
  return 0;
}

FragCounters FragServer::counters() const {
  std::lock_guard<std::mutex> g(mu_);
  return counters_;
}

Json FragServer::counters_json() const {
  FragCounters c = counters();
  Json out = Json::object();
  out["pool_hits"] = c.pool_hits;
  out["pool_misses"] = c.pool_misses;
  out["stage_copy_bytes"] = c.stage_copy_bytes;
  out["serve_copies"] = c.serve_copies;
  out["serve_bytes"] = c.serve_bytes;
  out["serves"] = c.serves;
  out["parked_waits"] = c.parked_waits;
  out["busy_replies"] = c.busy_replies;
  out["miss_replies"] = c.miss_replies;
  out["injected_drops"] = c.injected_drops;
  out["injected_delays"] = c.injected_delays;
  return out;
}

int FragServer::inject(const std::string& mode, int64_t param_ms,
                       int64_t count) {
  std::lock_guard<std::mutex> g(mu_);
  if (mode == "off") {
    inject_mode_ = 0;
    inject_count_ = 0;
  } else if (mode == "drop") {
    inject_mode_ = 1;
    inject_count_ = count;
  } else if (mode == "delay") {
    inject_mode_ = 2;
    inject_param_ms_ = param_ms;
    inject_count_ = count;
  } else {
    return -1;
  }
  return 0;
}

bool FragServer::reply_simple(int fd, int status, const std::string& body) {
  const char* reason = status == 200   ? "OK"
                       : status == 404 ? "Not Found"
                       : status == 503 ? "Service Unavailable"
                                       : "Error";
  std::ostringstream os;
  os << "HTTP/1.1 " << status << " " << reason << "\r\n"
     << "Content-Type: text/plain\r\n"
     << "Content-Length: " << body.size() << "\r\n"
     << "Connection: keep-alive\r\n\r\n"
     << body;
  std::string s = os.str();
  return write_all(fd, s.data(), s.size(), now_ms() + kServeTimeoutMs,
                   nullptr);
}

bool FragServer::serve_frag(int fd, const std::shared_ptr<FragBuf>& buf) {
  char hdr[160];
  int hdr_len = snprintf(hdr, sizeof(hdr),
                         "HTTP/1.1 200 OK\r\n"
                         "Content-Type: application/octet-stream\r\n"
                         "Content-Length: %zu\r\n"
                         "Connection: keep-alive\r\n\r\n",
                         buf->len);
  bool ok = sendv_all(fd, hdr, static_cast<size_t>(hdr_len),
                      buf->data.data(), buf->len,
                      now_ms() + kServeTimeoutMs);
  {
    std::lock_guard<std::mutex> g(mu_);
    if (ok) {
      ++counters_.serves;
      counters_.serve_bytes += static_cast<int64_t>(buf->len);
    }
  }
  deref(buf);
  return ok;
}

bool FragServer::handle_http_keepalive(int fd,
                                       const std::string& request_head) {
  // First line: "GET /checkpoint/{step}/{resource} HTTP/1.1"
  std::istringstream is(request_head);
  std::string method, path;
  is >> method >> path;
  if (method != "GET") return reply_simple(fd, 404, "not found\n");
  int64_t step = 0;
  std::string resource;
  {
    const std::string prefix = "/checkpoint/";
    if (path.rfind(prefix, 0) != 0)
      return reply_simple(fd, 404, "not found\n");
    std::string rest = path.substr(prefix.size());
    size_t slash = rest.find('/');
    if (slash == std::string::npos || slash == 0 ||
        slash + 1 >= rest.size())
      return reply_simple(fd, 404, "not found\n");
    try {
      step = std::stoll(rest.substr(0, slash));
    } catch (const std::exception&) {
      return reply_simple(fd, 404, "not found\n");
    }
    resource = rest.substr(slash + 1);
  }

  // Client-requested park window (X-TFT-Poll-Ms): how long the caller
  // can afford us to hold a not-yet-staged fragment before 503.  Absent
  // header keeps the legacy 250 ms window (mixed-fleet peers).
  int64_t poll_ms = kLongPollMs;
  {
    std::string lower = request_head;
    std::transform(lower.begin(), lower.end(), lower.begin(),
                   [](unsigned char ch) { return std::tolower(ch); });
    size_t hp = lower.find("\r\nx-tft-poll-ms:");
    if (hp != std::string::npos) {
      try {
        poll_ms = std::stoll(request_head.substr(hp + 16));
      } catch (const std::exception&) {
      }
      poll_ms = std::max<int64_t>(
          0, std::min<int64_t>(poll_ms, kLongPollCapMs));
    }
  }

  // chaos-test fault injection (the native analog of the Python-side
  // serving.frag/transport.heal.frag sites, which fire before dispatch)
  int64_t delay_ms = 0;
  {
    std::lock_guard<std::mutex> g(mu_);
    if (inject_count_ > 0 && inject_mode_ != 0) {
      --inject_count_;
      if (inject_mode_ == 1) {
        ++counters_.injected_drops;
        return false;  // close mid-exchange: client sees transport error
      }
      ++counters_.injected_delays;
      delay_ms = inject_param_ms_;
    }
  }
  if (delay_ms > 0) usleep(static_cast<useconds_t>(delay_ms) * 1000);

  std::shared_ptr<FragBuf> buf;
  bool waited = false;
  {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(poll_ms);
    for (;;) {
      auto it = versions_.find(step);
      if (it == versions_.end()) {
        // Unknown version. If it is newer than everything staged here the
        // upstream simply has not begun it yet (cut-through race between a
        // child's first fetch wave and the parent's begin): park inside the
        // client's poll window instead of bouncing the caller onto the
        // Python fallback plane. Versions at or below the staged max are
        // retired or never existed — answer 404 immediately.
        bool future =
            versions_.empty() || step > versions_.rbegin()->first;
        if (!future || std::chrono::steady_clock::now() >= deadline) {
          if (waited) ++counters_.parked_waits;
          ++counters_.miss_replies;
          lk.unlock();
          return reply_simple(fd, 404, "unknown version\n");
        }
        if (stopping_.load()) {
          lk.unlock();
          return false;
        }
        waited = true;
        cv_.wait_until(lk, deadline);
        continue;
      }
      auto fit = it->second.frags.find(resource);
      if (fit != it->second.frags.end()) {
        buf = fit->second;
        ++buf->refs;
        break;
      }
      if (it->second.complete) {
        // complete and missing: the fragment was never raw-staged here;
        // the Python control plane owns it (or it truly does not exist)
        ++counters_.miss_replies;
        lk.unlock();
        return reply_simple(fd, 404, "no such fragment\n");
      }
      if (stopping_.load()) {
        lk.unlock();
        return false;
      }
      // streaming version, fragment not landed yet: park (cut-through)
      waited = true;
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        // one last re-check under the lock, then retryable-busy
        auto it2 = versions_.find(step);
        if (it2 != versions_.end()) {
          auto fit2 = it2->second.frags.find(resource);
          if (fit2 != it2->second.frags.end()) {
            buf = fit2->second;
            ++buf->refs;
            break;
          }
        }
        if (waited) ++counters_.parked_waits;
        ++counters_.busy_replies;
        lk.unlock();
        return reply_simple(fd, 503, "streaming\n");
      }
    }
    if (waited) ++counters_.parked_waits;
  }
  return serve_frag(fd, buf);
}

// ---- client --------------------------------------------------------------

namespace {

struct PendingBody {
  int fd = -1;
  std::string addr;
  int64_t remaining = 0;
};

struct ClientState {
  std::map<std::string, int> conns;  // endpoint -> connected fd
  PendingBody pending;
  ~ClientState() {
    for (auto& kv : conns) ::close(kv.second);
    // pending.fd is always present in conns
  }
};

thread_local ClientState g_cli;
thread_local std::string g_cli_err;

void cli_drop(const std::string& addr) {
  auto it = g_cli.conns.find(addr);
  if (it != g_cli.conns.end()) {
    ::close(it->second);
    g_cli.conns.erase(it);
  }
  if (g_cli.pending.addr == addr) g_cli.pending = PendingBody{};
}

// Read the response head WITHOUT overshooting into the body: peek a
// window, look for the blank-line terminator, consume exactly what
// belongs to the head.  A handful of syscalls per response instead of
// two per byte.
bool read_head(int fd, std::string* head, int64_t deadline_ms,
               int64_t* first_byte_ms) {
  head->clear();
  char window[1024];
  bool first = true;
  while (head->size() < 64 * 1024) {
    // optimistic peek first; poll only when nothing is queued yet (the
    // common case on a kept-alive loopback exchange skips the poll)
    ssize_t rc = ::recv(fd, window, sizeof(window), MSG_PEEK);
    if (rc == 0) return false;
    if (rc < 0) {
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK) return false;
      if (!poll_fd(fd, POLLIN, deadline_ms)) return false;
      continue;
    }
    if (first) {
      if (first_byte_ms) *first_byte_ms = now_ms();
      first = false;
    }
    // the terminator can straddle the previously-consumed tail: search
    // with 3 bytes of overlap into what this window appends
    size_t prev = head->size();
    head->append(window, static_cast<size_t>(rc));
    size_t pos = head->find("\r\n\r\n", prev >= 3 ? prev - 3 : 0);
    size_t consume = pos == std::string::npos
                         ? static_cast<size_t>(rc)
                         : pos + 4 - prev;
    if (!read_exact(fd, window, consume, deadline_ms, nullptr)) return false;
    if (pos != std::string::npos) {
      head->resize(pos + 4);
      return true;
    }
  }
  return false;
}

int parse_status(const std::string& head) {
  // "HTTP/1.1 NNN ..."
  size_t sp = head.find(' ');
  if (sp == std::string::npos || sp + 4 > head.size()) return -1;
  try {
    return std::stoi(head.substr(sp + 1, 3));
  } catch (const std::exception&) {
    return -1;
  }
}

int64_t parse_content_length(const std::string& head) {
  // our server emits exactly "Content-Length: N\r\n"
  const std::string key = "Content-Length:";
  size_t pos = head.find(key);
  if (pos == std::string::npos) return -1;
  try {
    return std::stoll(head.substr(pos + key.size()));
  } catch (const std::exception&) {
    return -1;
  }
}

}  // namespace

int frag_fetch_begin(const std::string& addr, int64_t step,
                     const std::string& resource, int64_t timeout_ms,
                     int64_t* content_len, double* first_byte_s) {
  if (g_cli.pending.fd >= 0) {
    // a begin without its body/abort is a caller bug; recover by
    // dropping the wedged connection
    cli_drop(g_cli.pending.addr);
  }
  int64_t deadline = now_ms() + timeout_ms;
  // Client-driven cut-through park: tell the server how long WE can
  // afford it to hold a not-yet-staged fragment before answering 503.
  // Parking server-side (woken by stage()) beats a 503 + client retry
  // ladder — no duplicate request load, no backoff sleeps — but the
  // park must end before our own deadline or we would misread the
  // stall as a dead connection and drop to the Python path.
  int64_t poll_ms = std::min<int64_t>(timeout_ms - 150, kLongPollCapMs);
  std::string req = "GET /checkpoint/" + std::to_string(step) + "/" +
                    resource + " HTTP/1.1\r\nHost: " + addr +
                    "\r\nConnection: keep-alive\r\n";
  if (poll_ms > 0)
    req += "X-TFT-Poll-Ms: " + std::to_string(poll_ms) + "\r\n";
  req += "\r\n";
  for (int attempt = 0; attempt < 2; ++attempt) {
    bool fresh = false;
    int fd;
    auto it = g_cli.conns.find(addr);
    if (it != g_cli.conns.end()) {
      fd = it->second;
    } else {
      std::string err;
      fd = connect_once(addr, std::max<int64_t>(deadline - now_ms(), 1),
                        &err);
      if (fd < 0) {
        g_cli_err = "frag connect " + addr + ": " + err;
        return -1;
      }
      g_cli.conns[addr] = fd;
      fresh = true;
    }
    int64_t t0 = now_ms();
    int64_t first_byte_at = t0;
    std::string head;
    if (!write_all(fd, req.data(), req.size(), deadline, nullptr) ||
        !read_head(fd, &head, deadline, &first_byte_at)) {
      // a reused keep-alive connection may have been closed under us:
      // retry exactly once on a fresh connection
      cli_drop(addr);
      if (fresh || now_ms() >= deadline) {
        g_cli_err = "frag fetch " + addr + ": connection lost";
        return -1;
      }
      continue;
    }
    int status = parse_status(head);
    int64_t length = parse_content_length(head);
    if (status < 0 || length < 0) {
      cli_drop(addr);
      g_cli_err = "frag fetch " + addr + ": malformed response";
      return -1;
    }
    if (first_byte_s)
      *first_byte_s = static_cast<double>(first_byte_at - t0) / 1000.0;
    if (status == 200) {
      g_cli.pending.fd = fd;
      g_cli.pending.addr = addr;
      g_cli.pending.remaining = length;
      if (content_len) *content_len = length;
      return 200;
    }
    // small control body (404/503 text): drain it, keep the connection
    char scratch[256];
    int64_t left = length;
    while (left > 0) {
      size_t take = static_cast<size_t>(
          std::min<int64_t>(left, static_cast<int64_t>(sizeof(scratch))));
      if (!read_exact(fd, scratch, take, deadline, nullptr)) {
        cli_drop(addr);
        break;
      }
      left -= static_cast<int64_t>(take);
    }
    if (content_len) *content_len = 0;
    return status;
  }
  g_cli_err = "frag fetch " + addr + ": retries exhausted";
  return -1;
}

int frag_fetch_body(uint8_t* buf, int64_t cap, char* sha_hex_out,
                    int64_t timeout_ms) {
  if (g_cli.pending.fd < 0) {
    g_cli_err = "frag body: no pending fetch";
    return -1;
  }
  PendingBody p = g_cli.pending;
  g_cli.pending = PendingBody{};
  if (cap < p.remaining) {
    cli_drop(p.addr);
    g_cli_err = "frag body: buffer too small";
    return -1;
  }
  if (!read_exact(p.fd, reinterpret_cast<char*>(buf),
                  static_cast<size_t>(p.remaining),
                  now_ms() + timeout_ms, nullptr)) {
    cli_drop(p.addr);
    g_cli_err = "frag body " + p.addr + ": connection lost mid-body";
    return -1;
  }
  if (sha_hex_out)
    sha256_hex(buf, static_cast<size_t>(p.remaining), sha_hex_out);
  return 0;
}

void frag_fetch_abort() {
  if (g_cli.pending.fd >= 0) cli_drop(g_cli.pending.addr);
}

void frag_client_close() {
  for (auto& kv : g_cli.conns) ::close(kv.second);
  g_cli.conns.clear();
  g_cli.pending = PendingBody{};
}

const std::string& frag_client_error() { return g_cli_err; }

}  // namespace tft
