// C API exposing the native coordination servers to Python via ctypes.
//
// Analog of the reference's PyO3 binding layer (reference: src/lib.rs:742-758
// registers ManagerServer/LighthouseServer/... as Python classes). Here the
// Python side (torchft_tpu/_native.py + coordination.py) owns the client
// protocol (framed JSON over TCP) directly; the C API only manages server
// lifecycles plus a pure-function entry for quorum-result math so tests can
// exercise it natively.
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>

#include "fragserver.h"
#include "lighthouse.h"
#include "manager.h"
#include "store.h"

namespace {

thread_local std::string g_last_error;

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(malloc(s.size() + 1));
  memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

struct ServerHandle {
  enum class Kind { Lighthouse, Manager, Store, Frag } kind;
  std::unique_ptr<tft::RpcServer> server;
};

std::mutex g_mu;
std::map<int64_t, ServerHandle> g_servers;
int64_t g_next_handle = 1;

int64_t register_server(ServerHandle h) {
  std::lock_guard<std::mutex> g(g_mu);
  int64_t id = g_next_handle++;
  g_servers[id] = std::move(h);
  return id;
}

tft::RpcServer* find_server(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_servers.find(h);
  return it == g_servers.end() ? nullptr : it->second.server.get();
}

}  // namespace

extern "C" {

const char* tft_last_error() { return g_last_error.c_str(); }

void tft_free(char* p) { free(p); }

int64_t tft_lighthouse_create(const char* bind_host, int port,
                              int64_t min_replicas, int64_t join_timeout_ms,
                              int64_t quorum_tick_ms,
                              int64_t heartbeat_timeout_ms,
                              int64_t status_page_size,
                              int64_t straggler_topk, int64_t timeline_ring,
                              int64_t serving_fanout, const char* peers,
                              int64_t lease_timeout_ms) {
  try {
    tft::LighthouseOpt opt;
    opt.bind_host = bind_host ? bind_host : "";
    opt.port = port;
    opt.min_replicas = min_replicas;
    opt.join_timeout_ms = join_timeout_ms;
    opt.quorum_tick_ms = quorum_tick_ms;
    opt.heartbeat_timeout_ms = heartbeat_timeout_ms;
    if (status_page_size > 0) opt.status_page_size = status_page_size;
    if (straggler_topk > 0) opt.straggler_topk = straggler_topk;
    if (timeline_ring > 0) opt.timeline_ring = timeline_ring;
    if (serving_fanout > 0) opt.serving_fanout = serving_fanout;
    // Coordination-plane HA: comma list of the OTHER lighthouse peers
    // (empty/NULL = single-process mode) + leadership lease duration.
    opt.peers = peers ? peers : "";
    if (lease_timeout_ms > 0) opt.lease_timeout_ms = lease_timeout_ms;
    auto server = std::make_unique<tft::LighthouseServer>(opt);
    server->start_serving();
    return register_server(
        {ServerHandle::Kind::Lighthouse, std::move(server)});
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

int64_t tft_manager_create(const char* replica_id, const char* lighthouse_addr,
                           const char* bind_host, int port,
                           const char* store_address, int64_t world_size,
                           int64_t heartbeat_interval_ms,
                           int64_t connect_timeout_ms,
                           int64_t quorum_retries) {
  try {
    tft::ManagerOpt opt;
    opt.replica_id = replica_id ? replica_id : "";
    opt.lighthouse_addr = lighthouse_addr ? lighthouse_addr : "";
    opt.bind_host = bind_host ? bind_host : "";
    opt.port = port;
    opt.store_address = store_address ? store_address : "";
    opt.world_size = world_size;
    opt.heartbeat_interval_ms = heartbeat_interval_ms;
    opt.connect_timeout_ms = connect_timeout_ms;
    opt.quorum_retries = quorum_retries;
    auto server = std::make_unique<tft::ManagerServer>(opt);
    server->start_serving();
    return register_server({ServerHandle::Kind::Manager, std::move(server)});
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

int64_t tft_store_create(const char* bind_host, int port) {
  try {
    auto server = std::make_unique<tft::StoreServer>(
        bind_host ? bind_host : "", port);
    server->start();
    return register_server({ServerHandle::Kind::Store, std::move(server)});
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

char* tft_server_address(int64_t h) {
  tft::RpcServer* s = find_server(h);
  if (!s) {
    g_last_error = "bad server handle";
    return nullptr;
  }
  return dup_string(s->address());
}

int tft_server_shutdown(int64_t h) {
  std::unique_ptr<tft::RpcServer> server;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_servers.find(h);
    if (it == g_servers.end()) {
      g_last_error = "bad server handle";
      return -1;
    }
    server = std::move(it->second.server);
    g_servers.erase(it);
  }
  // Destructor runs stop()/shutdown() for each server type.
  server.reset();
  return 0;
}

// Install (or clear, with NULL) the Prometheus /metrics supplement on a
// lighthouse: the provider writes extra exposition text (the embedding
// process's metric registry) appended to the native metrics.  See
// LighthouseServer::MetricsProvider for the buffer contract.
int tft_lighthouse_set_metrics_provider(int64_t h,
                                        int (*provider)(char*, int)) {
  tft::RpcServer* s = find_server(h);
  auto* lighthouse = dynamic_cast<tft::LighthouseServer*>(s);
  if (lighthouse == nullptr) {
    g_last_error = "bad lighthouse handle";
    return -1;
  }
  lighthouse->set_metrics_provider(provider);
  return 0;
}

// Coordination-plane HA introspection: one JSON object
// {"enabled","term","is_leader","leader","peers","takeovers_total",
// "quorum_id"} for a lighthouse handle (the fleet helper and tests poll
// this to find the current leader without a wire round trip).
char* tft_lighthouse_ha_info(int64_t h) {
  tft::RpcServer* s = find_server(h);
  auto* lighthouse = dynamic_cast<tft::LighthouseServer*>(s);
  if (lighthouse == nullptr) {
    g_last_error = "bad lighthouse handle";
    return nullptr;
  }
  return dup_string(lighthouse->ha_info().dump());
}

// Install (or clear, with NULL) the process-wide span sink: the native
// servers' rpc.<method> spans (and any other native emit_span caller) are
// relayed as one JSON object per span to this callback — the Python side
// registers a ctypes function that forwards into its trace exporter
// (torchft_tpu/utils/tracing.py install_native_span_sink).
int tft_set_span_sink(void (*sink)(const char*)) {
  tft::set_span_sink(sink);
  return 0;
}

// Record a replica group's training progress on its manager server; the
// heartbeat loop piggybacks it on lighthouse heartbeats (straggler
// telemetry — see ManagerServer::report_progress).
int tft_manager_report_progress(int64_t h, int64_t step,
                                const char* inflight_op) {
  tft::RpcServer* s = find_server(h);
  auto* manager = dynamic_cast<tft::ManagerServer*>(s);
  if (manager == nullptr) {
    g_last_error = "bad manager handle";
    return -1;
  }
  manager->report_progress(step, inflight_op ? inflight_op : "");
  return 0;
}

// Record a replica group's per-step digest (JSON: step, phase_ms,
// codec_busy_s, wire_busy_s); the heartbeat loop piggybacks it so the
// lighthouse can aggregate the rolling cluster step-timeline
// (/timeline.json).  Invalid JSON is rejected here rather than poisoning
// the heartbeat path.
int tft_manager_report_summary(int64_t h, const char* summary_json) {
  tft::RpcServer* s = find_server(h);
  auto* manager = dynamic_cast<tft::ManagerServer*>(s);
  if (manager == nullptr) {
    g_last_error = "bad manager handle";
    return -1;
  }
  try {
    tft::Json summary =
        tft::Json::parse(summary_json ? summary_json : "{}");
    if (!summary.is_object()) throw std::runtime_error("summary: not an object");
    manager->report_summary(summary);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
  return 0;
}

// Record a replica's bounded link-state digest (JSON: host, rows[...]);
// the heartbeat loop piggybacks it once (consumed-on-send) so the
// lighthouse can fold it into the fleet host-pair matrix (/links.json).
// Invalid JSON is rejected here rather than poisoning the heartbeat path.
int tft_manager_report_links(int64_t h, const char* links_json) {
  tft::RpcServer* s = find_server(h);
  auto* manager = dynamic_cast<tft::ManagerServer*>(s);
  if (manager == nullptr) {
    g_last_error = "bad manager handle";
    return -1;
  }
  try {
    tft::Json links = tft::Json::parse(links_json ? links_json : "{}");
    if (!links.is_object()) throw std::runtime_error("links: not an object");
    manager->report_links(links);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
  return 0;
}

// Record a replica's bounded fragment-provenance digest (JSON: host,
// frags[...]); the heartbeat loop piggybacks it once (consumed-on-send)
// so the lighthouse can fold it into the fleet per-(host, frag_id)
// version matrix (/fragments.json).  Invalid JSON is rejected here
// rather than poisoning the heartbeat path.
int tft_manager_report_fragments(int64_t h, const char* fragments_json) {
  tft::RpcServer* s = find_server(h);
  auto* manager = dynamic_cast<tft::ManagerServer*>(s);
  if (manager == nullptr) {
    g_last_error = "bad manager handle";
    return -1;
  }
  try {
    tft::Json fragments =
        tft::Json::parse(fragments_json ? fragments_json : "{}");
    if (!fragments.is_object())
      throw std::runtime_error("fragments: not an object");
    manager->report_fragments(fragments);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
  return 0;
}

// ---- native zero-copy fragment data plane (fragserver.{h,cc}) ----------
// Server lifecycle + staging mirror: Python's HTTPTransport keeps the
// control plane (plans, manifests, digests, version advertisement) and
// hands raw fragment payload bytes down here at stage time; every
// subsequent serve is a writev out of the pooled buffer with zero
// user-space copies.

static tft::FragServer* find_frag(int64_t h) {
  auto* s = dynamic_cast<tft::FragServer*>(find_server(h));
  if (s == nullptr) g_last_error = "bad fragserver handle";
  return s;
}

int64_t tft_frag_server_create(const char* bind_host, int port) {
  try {
    auto server = std::make_unique<tft::FragServer>(
        bind_host ? bind_host : "", port);
    return register_server({ServerHandle::Kind::Frag, std::move(server)});
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

int tft_frag_server_port(int64_t h) {
  tft::FragServer* s = find_frag(h);
  return s == nullptr ? -1 : s->port();
}

int tft_frag_begin(int64_t h, int64_t step) {
  tft::FragServer* s = find_frag(h);
  return s == nullptr ? -1 : s->begin(step);
}

int tft_frag_stage(int64_t h, int64_t step, const char* resource,
                   const uint8_t* data, int64_t len) {
  tft::FragServer* s = find_frag(h);
  if (s == nullptr || resource == nullptr || len < 0) return -1;
  return s->stage(step, resource, data, static_cast<size_t>(len));
}

int tft_frag_finish(int64_t h, int64_t step) {
  tft::FragServer* s = find_frag(h);
  return s == nullptr ? -1 : s->finish(step);
}

int tft_frag_retire(int64_t h, int64_t step) {
  tft::FragServer* s = find_frag(h);
  return s == nullptr ? -1 : s->retire(step);
}

char* tft_frag_counters(int64_t h) {
  tft::FragServer* s = find_frag(h);
  if (s == nullptr) return nullptr;
  return dup_string(s->counters_json().dump());
}

// Chaos-test fault injection on the data server: the next `count`
// requests drop (close mid-exchange) or delay `param_ms` before the
// body.  mode: "off" | "drop" | "delay".
int tft_frag_inject(int64_t h, const char* mode, int64_t param_ms,
                    int64_t count) {
  tft::FragServer* s = find_frag(h);
  if (s == nullptr || mode == nullptr) return -1;
  return s->inject(mode, param_ms, count);
}

// Two-phase GIL-free fetch client (per-thread persistent connections —
// ctypes releases the GIL around both calls, so the byte-moving +
// digest phase never touches the interpreter).  begin returns the HTTP
// status (200/404/503) or -1 on transport error (tft_frag_client_error).
int tft_frag_fetch_begin(const char* addr, int64_t step,
                         const char* resource, int64_t timeout_ms,
                         int64_t* content_len, double* first_byte_s) {
  if (addr == nullptr || resource == nullptr) return -1;
  return tft::frag_fetch_begin(addr, step, resource, timeout_ms,
                               content_len, first_byte_s);
}

int tft_frag_fetch_body(uint8_t* buf, int64_t cap, char* sha_hex_out,
                        int64_t timeout_ms) {
  if (buf == nullptr) return -1;
  return tft::frag_fetch_body(buf, cap, sha_hex_out, timeout_ms);
}

void tft_frag_fetch_abort() { tft::frag_fetch_abort(); }

void tft_frag_client_close() { tft::frag_client_close(); }

const char* tft_frag_client_error() {
  thread_local std::string err;
  err = tft::frag_client_error();
  return err.c_str();
}

// Native SHA-256 over one buffer (lowercase hex into out65) — exposed so
// tests can cross-check the wire digest against hashlib.
int tft_sha256_hex(const uint8_t* data, int64_t len, char* out65) {
  if ((data == nullptr && len > 0) || len < 0 || out65 == nullptr) return -1;
  tft::sha256_hex(data, static_cast<size_t>(len), out65);
  return 0;
}

// Pure quorum-result math, exposed for unit tests: input/output JSON.
char* tft_compute_quorum_results(const char* replica_id, int64_t group_rank,
                                 const char* quorum_json, int init_sync) {
  try {
    tft::Quorum quorum =
        tft::Quorum::from_json(tft::Json::parse(quorum_json));
    tft::QuorumResult result = tft::compute_quorum_results(
        replica_id ? replica_id : "", group_rank, quorum, init_sync != 0);
    return dup_string(result.to_json().dump());
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

}  // extern "C"
