#include "net.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <random>

namespace tft {

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t wall_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// ---- distributed tracing -------------------------------------------------

TraceCtx& current_trace() {
  static thread_local TraceCtx ctx;
  return ctx;
}

TraceCtx parse_traceparent(const std::string& tp) {
  // "00-<32 hex>-<16 hex>-<2 hex flags>"; anything malformed parses to an
  // invalid (ignored) context — a hostile peer must not break the server.
  TraceCtx out;
  if (tp.size() != 2 + 1 + 32 + 1 + 16 + 1 + 2) return out;
  if (tp[2] != '-' || tp[35] != '-' || tp[52] != '-') return out;
  auto is_hex = [](const std::string& s, size_t off, size_t n) {
    for (size_t i = off; i < off + n; ++i) {
      char c = s[i];
      if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
            (c >= 'A' && c <= 'F')))
        return false;
    }
    return true;
  };
  if (!is_hex(tp, 3, 32) || !is_hex(tp, 36, 16) || !is_hex(tp, 53, 2))
    return out;
  out.trace_id = tp.substr(3, 32);
  out.parent_span_id = tp.substr(36, 16);
  out.sampled = tp.substr(53, 2) != "00";
  return out;
}

std::string format_traceparent(const TraceCtx& ctx) {
  return "00-" + ctx.trace_id + "-" + ctx.parent_span_id + "-" +
         (ctx.sampled ? "01" : "00");
}

std::string new_span_id() {
  static thread_local std::mt19937_64 rng(
      std::random_device{}() ^
      static_cast<uint64_t>(
          std::chrono::steady_clock::now().time_since_epoch().count()));
  uint64_t v = rng();
  char buf[17];
  snprintf(buf, sizeof(buf), "%016llx",
           static_cast<unsigned long long>(v));
  return std::string(buf, 16);
}

namespace {
std::mutex g_span_sink_mu;
SpanSink g_span_sink = nullptr;
std::atomic<bool> g_span_sink_set{false};
}  // namespace

void set_span_sink(SpanSink sink) {
  std::lock_guard<std::mutex> g(g_span_sink_mu);
  g_span_sink = sink;
  g_span_sink_set.store(sink != nullptr);
}

bool span_sink_active() { return g_span_sink_set.load(); }

void emit_span(const std::string& name, const TraceCtx& ctx,
               int64_t start_ns, int64_t end_ns, bool ok,
               const Json& attributes) {
  if (!ctx.valid()) return;
  Json span = Json::object();
  span["name"] = name;
  span["trace_id"] = ctx.trace_id;
  span["span_id"] = new_span_id();
  span["parent_span_id"] = ctx.parent_span_id;
  span["start_ns"] = start_ns;
  span["end_ns"] = end_ns;
  span["ok"] = ok;
  span["attributes"] = attributes;
  std::string doc = span.dump();
  // Hold the mutex across the call: the Python side clears the sink
  // before releasing its callback object, and a cleared sink must mean
  // "no in-flight invocation either".
  std::lock_guard<std::mutex> g(g_span_sink_mu);
  if (g_span_sink != nullptr) g_span_sink(doc.c_str());
}

namespace {

bool wait_fd(int fd, short events, int64_t deadline_ms) {
  while (true) {
    int64_t remain = deadline_ms - now_ms();
    if (remain <= 0) return false;
    struct pollfd pfd = {fd, events, 0};
    int rc = poll(&pfd, 1, static_cast<int>(std::min<int64_t>(remain, 1000)));
    if (rc > 0) return true;
    if (rc < 0 && errno != EINTR) return false;
  }
}

void set_nonblocking(int fd, bool nb) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (nb)
    fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  else
    fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
}

bool split_addr(const std::string& addr, std::string* host, std::string* port) {
  // Accept host:port and [v6::addr]:port forms.
  if (!addr.empty() && addr[0] == '[') {
    size_t close = addr.find(']');
    if (close == std::string::npos || close + 1 >= addr.size() ||
        addr[close + 1] != ':')
      return false;
    *host = addr.substr(1, close - 1);
    *port = addr.substr(close + 2);
    return true;
  }
  size_t colon = addr.rfind(':');
  if (colon == std::string::npos) return false;
  *host = addr.substr(0, colon);
  *port = addr.substr(colon + 1);
  return true;
}

}  // namespace

// The read/write loops below try the socket call FIRST and poll only on
// EAGAIN: steady-state data is already queued (loopback, fast LAN), so
// the optimistic order halves the syscall count of every exchange — on
// small hosts the data plane is syscall-bound before it is wire-bound.
bool read_exact(int fd, char* buf, size_t n, int64_t deadline_ms,
                std::string* err) {
  size_t got = 0;
  while (got < n) {
    ssize_t rc = ::recv(fd, buf + got, n - got, 0);
    if (rc == 0) {
      if (err) *err = "connection closed by peer";
      return false;
    }
    if (rc < 0) {
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK) {
        if (err) *err = std::string("recv: ") + strerror(errno);
        return false;
      }
      if (!wait_fd(fd, POLLIN, deadline_ms)) {
        if (err) *err = "timeout: read deadline exceeded";
        return false;
      }
      continue;
    }
    got += static_cast<size_t>(rc);
  }
  return true;
}

bool write_all(int fd, const char* buf, size_t n, int64_t deadline_ms,
               std::string* err) {
  size_t sent = 0;
  while (sent < n) {
    ssize_t rc = ::send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (rc < 0) {
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK) {
        if (err) *err = std::string("send: ") + strerror(errno);
        return false;
      }
      if (!wait_fd(fd, POLLOUT, deadline_ms)) {
        if (err) *err = "timeout: write deadline exceeded";
        return false;
      }
      continue;
    }
    sent += static_cast<size_t>(rc);
  }
  return true;
}

bool peek_bytes(int fd, char* buf, size_t n, int64_t deadline_ms) {
  size_t got = 0;
  while (got < n) {
    ssize_t rc = ::recv(fd, buf, n, MSG_PEEK);
    if (rc <= 0) {
      if (rc < 0 && errno == EINTR) continue;
      if (rc < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        if (!wait_fd(fd, POLLIN, deadline_ms)) return false;
        continue;
      }
      return false;
    }
    got = static_cast<size_t>(rc);
    if (got >= n) return true;
    // partial peek: wait for more queued bytes before re-peeking
    if (!wait_fd(fd, POLLIN, deadline_ms)) return false;
  }
  return true;
}

bool read_http_head(int fd, std::string* head, int64_t deadline_ms) {
  // Peek a window, find the blank-line terminator, consume exactly the
  // head — a handful of syscalls per request instead of two per byte,
  // without ever overshooting into a following request on the same
  // kept-alive connection.
  head->clear();
  char window[1024];
  while (head->size() < 64 * 1024) {
    ssize_t rc = ::recv(fd, window, sizeof(window), MSG_PEEK);
    if (rc == 0) return false;
    if (rc < 0) {
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK) return false;
      if (!wait_fd(fd, POLLIN, deadline_ms)) return false;
      continue;
    }
    size_t prev = head->size();
    head->append(window, static_cast<size_t>(rc));
    // the terminator can straddle the previously-consumed tail: search
    // with 3 bytes of overlap into what this window appended
    size_t pos = head->find("\r\n\r\n", prev >= 3 ? prev - 3 : 0);
    size_t want = (pos == std::string::npos)
                      ? static_cast<size_t>(rc)
                      : pos + 4 - prev;
    if (!read_exact(fd, window, want, deadline_ms, nullptr)) return false;
    if (pos != std::string::npos) {
      head->resize(pos + 4);
      return true;
    }
    // window held no terminator yet: everything peeked belongs to the
    // head; loop for the next window (wait_fd inside the EAGAIN branch
    // paces us when the peer is slow)
  }
  return false;  // oversized head
}

bool send_frame(int fd, const std::string& payload, int64_t deadline_ms,
                std::string* err) {
  if (payload.size() > kMaxFrameBytes) {
    if (err) *err = "frame too large";
    return false;
  }
  uint32_t len = htonl(static_cast<uint32_t>(payload.size()));
  char hdr[4];
  memcpy(hdr, &len, 4);
  std::string buf;
  buf.reserve(payload.size() + 4);
  buf.append(hdr, 4);
  buf.append(payload);
  return write_all(fd, buf.data(), buf.size(), deadline_ms, err);
}

bool recv_frame(int fd, std::string* payload, int64_t deadline_ms,
                std::string* err, int64_t body_timeout_ms) {
  char hdr[4];
  if (!read_exact(fd, hdr, 4, deadline_ms, err)) return false;
  uint32_t len;
  memcpy(&len, hdr, 4);
  len = ntohl(len);
  if (len > kMaxFrameBytes) {
    if (err) *err = "frame too large";
    return false;
  }
  payload->resize(len);
  if (len == 0) return true;
  int64_t body_deadline = deadline_ms;
  if (body_timeout_ms > 0)
    body_deadline = std::min(deadline_ms, now_ms() + body_timeout_ms);
  return read_exact(fd, payload->data(), len, body_deadline, err);
}

int connect_once(const std::string& addr, int64_t timeout_ms,
                 std::string* err) {
  std::string host, port;
  if (!split_addr(addr, &host, &port)) {
    if (err) *err = "bad address: " + addr;
    return -1;
  }
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = getaddrinfo(host.empty() ? "127.0.0.1" : host.c_str(), port.c_str(),
                       &hints, &res);
  if (rc != 0) {
    if (err) *err = std::string("getaddrinfo: ") + gai_strerror(rc);
    return -1;
  }
  int64_t deadline = now_ms() + timeout_ms;
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    set_nonblocking(fd, true);
    rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc == 0 || (rc < 0 && errno == EINPROGRESS)) {
      if (wait_fd(fd, POLLOUT, deadline)) {
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen);
        if (soerr == 0) {
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
          freeaddrinfo(res);
          return fd;
        }
        if (err) *err = std::string("connect: ") + strerror(soerr);
      } else if (err) {
        *err = "timeout: connect deadline exceeded";
      }
    } else if (err) {
      *err = std::string("connect: ") + strerror(errno);
    }
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0 && err && err->empty()) *err = "connect failed";
  return -1;
}

int connect_with_retry(const std::string& addr, int64_t timeout_ms,
                       std::string* err) {
  int64_t deadline = now_ms() + timeout_ms;
  int64_t backoff = 100;
  static thread_local std::mt19937 rng(std::random_device{}());
  std::string last_err;
  while (true) {
    int64_t remain = deadline - now_ms();
    if (remain <= 0) break;
    int fd = connect_once(addr, std::min<int64_t>(remain, 5000), &last_err);
    if (fd >= 0) return fd;
    remain = deadline - now_ms();
    if (remain <= 0) break;
    std::uniform_int_distribution<int64_t> jitter(0, backoff / 2);
    int64_t sleep_ms = std::min<int64_t>(backoff + jitter(rng), remain);
    usleep(static_cast<useconds_t>(sleep_ms * 1000));
    backoff = std::min<int64_t>(static_cast<int64_t>(backoff * 1.5), 10000);
  }
  if (err) *err = "timeout: connect to " + addr + " failed: " + last_err;
  return -1;
}

bool call_rpc(const std::string& addr, const std::string& method,
              const Json& params, int64_t timeout_ms, Json* result,
              std::string* err) {
  int64_t deadline = now_ms() + timeout_ms;
  int fd = connect_with_retry(addr, timeout_ms, err);
  if (fd < 0) return false;
  Json req = Json::object();
  req["method"] = method;
  req["params"] = params;
  req["timeout_ms"] = timeout_ms;
  if (current_trace().valid())
    req["traceparent"] = format_traceparent(current_trace());
  bool ok = send_frame(fd, req.dump(), deadline, err);
  std::string reply;
  if (ok) ok = recv_frame(fd, &reply, deadline, err);
  ::close(fd);
  if (!ok) return false;
  Json resp;
  try {
    resp = Json::parse(reply);
  } catch (const std::exception& e) {
    if (err) *err = std::string("bad reply: ") + e.what();
    return false;
  }
  if (!resp.get("ok").as_bool()) {
    if (err) *err = resp.get("error").as_string();
    return false;
  }
  if (result) *result = resp.get("result");
  return true;
}

std::vector<std::string> split_endpoints(const std::string& addrs) {
  std::vector<std::string> out;
  size_t start = 0;
  while (start <= addrs.size()) {
    size_t comma = addrs.find(',', start);
    std::string part = addrs.substr(
        start, comma == std::string::npos ? std::string::npos : comma - start);
    size_t a = part.find_first_not_of(" \t");
    size_t b = part.find_last_not_of(" \t");
    if (a != std::string::npos) out.push_back(part.substr(a, b - a + 1));
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
  return out;
}

RpcClient::~RpcClient() { close(); }

void RpcClient::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

Json RpcClient::call(const std::string& method, const Json& params,
                     int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  std::string err;
  for (int attempt = 0; attempt < 2; attempt++) {
    if (fd_ < 0) {
      fd_ = connect_with_retry(addr_, deadline - now_ms(), &err);
      if (fd_ < 0) throw TimeoutError(err);
    }
    Json req = Json::object();
    req["method"] = method;
    req["params"] = params;
    req["timeout_ms"] = std::max<int64_t>(deadline - now_ms(), 1);
    // Propagate this thread's trace context downstream (e.g. the native
    // manager's lighthouse call continuing the Python client's round).
    if (current_trace().valid())
      req["traceparent"] = format_traceparent(current_trace());
    std::string reply;
    if (send_frame(fd_, req.dump(), deadline, &err) &&
        recv_frame(fd_, &reply, deadline, &err)) {
      Json resp = Json::parse(reply);
      if (!resp.get("ok").as_bool()) {
        std::string msg = resp.get("error").as_string();
        std::string code = resp.get("code").as_string();
        if (code == "timeout") throw TimeoutError(msg);
        if (code == "not_leader")
          throw NotLeaderError(msg, resp.get("leader").as_string());
        throw std::runtime_error(msg);
      }
      return resp.get("result");
    }
    // Connection-level failure: drop the socket; retry once if it broke
    // mid-call (e.g. server restarted) and we still have budget.
    close();
    if (err.rfind("timeout:", 0) == 0) throw TimeoutError(err);
  }
  throw std::runtime_error("rpc " + method + " to " + addr_ + " failed: " + err);
}

HaRpcClient::HaRpcClient(const std::string& addrs)
    : endpoints_(split_endpoints(addrs)) {
  if (endpoints_.empty()) endpoints_.push_back(addrs);
}

HaRpcClient::~HaRpcClient() { close(); }

void HaRpcClient::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  connected_addr_.clear();
}

std::string HaRpcClient::current() const {
  if (!connected_addr_.empty()) return connected_addr_;
  if (!redirect_.empty()) return redirect_;
  return endpoints_[cur_ % endpoints_.size()];
}

void HaRpcClient::advance() {
  redirect_.clear();
  cur_ = (cur_ + 1) % endpoints_.size();
}

Json HaRpcClient::call(const std::string& method, const Json& params,
                       int64_t timeout_ms) {
  int64_t deadline = now_ms() + timeout_ms;
  std::string err, last_err;
  // Hop budget PER PASS: every endpoint may be probed directly and once
  // more via a redirect before the pass ends — bounds a redirect cycle
  // between two confused followers.  A pass that found no servable
  // leader (fleet mid-election / restarting) is retried with a short
  // growing backoff inside the caller's deadline, mirroring the Python
  // client's _WALK_POLICY — the budget, never the pass count, bounds
  // the wait.
  const int max_hops = static_cast<int>(endpoints_.size()) * 2 + 2;
  int64_t backoff_ms = 50;
  while (true) {
    for (int hop = 0; hop < max_hops; ++hop) {
      int64_t remain = deadline - now_ms();
      if (remain <= 0)
        throw TimeoutError("timeout: rpc " + method +
                           " exhausted its deadline walking lighthouse "
                           "endpoints: " + last_err);
      std::string addr = !redirect_.empty() ? redirect_ : endpoints_[cur_];
      if (fd_ < 0 || connected_addr_ != addr) {
        close();
        // Bounded connect slice: with peers to fail over to, a dead
        // endpoint must cost ~a slice, not the caller's deadline.  The
        // single-endpoint form keeps RpcClient's full-budget retry.
        int64_t slice = endpoints_.size() > 1
                            ? std::min<int64_t>(remain, 1500)
                            : remain;
        fd_ = endpoints_.size() > 1 ? connect_once(addr, slice, &err)
                                    : connect_with_retry(addr, slice, &err);
        if (fd_ < 0) {
          last_err = addr + ": " + err;
          advance();
          continue;
        }
        connected_addr_ = addr;
      }
      Json req = Json::object();
      req["method"] = method;
      req["params"] = params;
      req["timeout_ms"] = std::max<int64_t>(deadline - now_ms(), 1);
      if (current_trace().valid())
        req["traceparent"] = format_traceparent(current_trace());
      std::string reply;
      if (!send_frame(fd_, req.dump(), deadline, &err) ||
          !recv_frame(fd_, &reply, deadline, &err)) {
        close();
        last_err = addr + ": " + err;
        // The overall deadline expiring mid-call on a live endpoint is
        // the caller's timeout, not a dead server: surface it.
        if (err.rfind("timeout:", 0) == 0 && deadline - now_ms() <= 0)
          throw TimeoutError(err);
        advance();
        continue;
      }
      Json resp;
      try {
        resp = Json::parse(reply);
      } catch (const std::exception& e) {
        close();
        last_err = addr + std::string(": bad reply: ") + e.what();
        advance();
        continue;
      }
      if (!resp.get("ok").as_bool()) {
        std::string msg = resp.get("error").as_string();
        std::string code = resp.get("code").as_string();
        if (code == "not_leader") {
          // Follow the named holder when there is one; otherwise rotate.
          std::string leader = resp.get("leader").as_string();
          last_err = addr + ": " + msg;
          if (!leader.empty() && leader != addr) {
            redirect_ = leader;
          } else {
            advance();
          }
          continue;
        }
        if (code == "timeout") throw TimeoutError(msg);
        throw std::runtime_error(msg);
      }
      return resp.get("result");
    }
    int64_t remain = deadline - now_ms();
    if (remain <= backoff_ms)
      throw TimeoutError("timeout: rpc " + method +
                         " found no servable lighthouse leader within "
                         "its deadline: " + last_err);
    usleep(static_cast<useconds_t>(backoff_ms * 1000));
    backoff_ms = std::min<int64_t>(backoff_ms * 2, 500);
  }
}

RpcServer::RpcServer(std::string bind_host, int port)
    : bind_host_(std::move(bind_host)), port_(port) {}

RpcServer::~RpcServer() { shutdown(); }

void RpcServer::start() {
  struct sockaddr_in6 sa = {};
  sa.sin6_family = AF_INET6;
  sa.sin6_port = htons(static_cast<uint16_t>(port_));
  sa.sin6_addr = in6addr_any;

  bool v6 = true;
  listen_fd_ = ::socket(AF_INET6, SOCK_STREAM, 0);
  if (listen_fd_ < 0) {
    // Host without IPv6 (e.g. ipv6.disable=1 containers): fall back to v4.
    v6 = false;
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) throw std::runtime_error("socket failed");
  }
  int zero = 0, one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  if (v6) {
    // Dual-stack: accept v4-mapped connections too.
    setsockopt(listen_fd_, IPPROTO_IPV6, IPV6_V6ONLY, &zero, sizeof(zero));
    if (::bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&sa),
               sizeof(sa)) < 0)
      throw std::runtime_error(std::string("bind: ") + strerror(errno));
  } else {
    struct sockaddr_in sa4 = {};
    sa4.sin_family = AF_INET;
    sa4.sin_port = htons(static_cast<uint16_t>(port_));
    sa4.sin_addr.s_addr = INADDR_ANY;
    if (::bind(listen_fd_, reinterpret_cast<struct sockaddr*>(&sa4),
               sizeof(sa4)) < 0)
      throw std::runtime_error(std::string("bind: ") + strerror(errno));
  }
  if (::listen(listen_fd_, 128) < 0)
    throw std::runtime_error(std::string("listen: ") + strerror(errno));

  struct sockaddr_storage bound = {};
  socklen_t slen = sizeof(bound);
  getsockname(listen_fd_, reinterpret_cast<struct sockaddr*>(&bound), &slen);
  if (bound.ss_family == AF_INET6)
    port_ = ntohs(reinterpret_cast<struct sockaddr_in6*>(&bound)->sin6_port);
  else
    port_ = ntohs(reinterpret_cast<struct sockaddr_in*>(&bound)->sin_port);

  std::string host = bind_host_;
  if (host.empty() || host == "::" || host == "0.0.0.0") {
    char name[256];
    if (gethostname(name, sizeof(name)) == 0)
      host = name;
    else
      host = "127.0.0.1";
  }
  address_ = host + ":" + std::to_string(port_);

  accept_thread_ = std::thread([this] { accept_loop(); });
}

void RpcServer::shutdown() {
  bool expected = false;
  if (!stopping_.compare_exchange_strong(expected, true)) return;
  // Wake the blocked accept() (Linux: returns EINVAL after SHUT_RDWR on a
  // listener), JOIN, and only then close/clear the fd: closing first would
  // race the accept thread's read of listen_fd_ — and worse, free the fd
  // number for reuse while accept() still holds it.
  if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
  if (accept_thread_.joinable()) accept_thread_.join();
  if (listen_fd_ >= 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
  {
    // Force blocked reads to return (peer-closed) so threads can exit. The
    // owning connection thread still does the close(), so the fd number
    // cannot be reused out from under us.
    std::lock_guard<std::mutex> g(conn_mu_);
    for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
  }
  wake_blocked();
  // Handlers are bounded by request timeouts; wait for them to drain.
  while (active_conns_.load() > 0) usleep(5 * 1000);
}

void RpcServer::accept_loop() {
  while (!stopping_.load()) {
    struct sockaddr_storage peer;
    socklen_t plen = sizeof(peer);
    int fd = ::accept(listen_fd_, reinterpret_cast<struct sockaddr*>(&peer),
                      &plen);
    if (fd < 0) {
      if (stopping_.load()) return;
      if (errno == EINTR) continue;
      return;  // listener closed
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::lock_guard<std::mutex> g(conn_mu_);
    if (stopping_.load()) {
      ::close(fd);
      return;
    }
    conn_fds_.insert(fd);
    active_conns_.fetch_add(1);
    std::thread([this, fd] {
      serve_conn(fd);
      {
        std::lock_guard<std::mutex> g2(conn_mu_);
        conn_fds_.erase(fd);
        ::close(fd);
      }
      active_conns_.fetch_sub(1);
    }).detach();
  }
}

void RpcServer::serve_conn(int fd) {
  set_nonblocking(fd, true);
  // Sniff: HTTP request lines start with an ASCII method verb.
  char head[4] = {0};
  if (peek_bytes(fd, head, 4, now_ms() + 10000)) {
    if (memcmp(head, "GET ", 4) == 0 || memcmp(head, "POST", 4) == 0 ||
        memcmp(head, "HEAD", 4) == 0) {
      // HTTP loop: read a request head (up to blank line), dispatch, and
      // — when the handler asks for keep-alive — park for the next one.
      // The first head gets the original 10 s window; subsequent heads
      // on a kept-alive connection may idle far longer (a fragment
      // client parks between fetches), bounded so a vanished peer can't
      // pin this thread forever (shutdown() also closes the fd).
      int64_t head_window_ms = 10000;
      while (!stopping_.load()) {
        std::string req;
        if (!read_http_head(fd, &req, now_ms() + head_window_ms))
          return;  // peer closed / idle timeout / oversized head
        bool keep = false;
        try {
          keep = handle_http_keepalive(fd, req);
        } catch (...) {
        }
        if (!keep) return;
        head_window_ms = 300000;
      }
      return;
    }
  }
  while (!stopping_.load()) {
    std::string payload;
    std::string err;
    // Idle connections are fine: wait in 1-day slices for the next request
    // header — but once a header arrives, the body must land within
    // kFrameBodyTimeoutMs so a mid-frame stall cannot pin this thread.
    if (!recv_frame(fd, &payload, now_ms() + 86400000, &err,
                    kFrameBodyTimeoutMs))
      break;
    Json reply = Json::object();
    // Distributed tracing: continue the request envelope's traceparent —
    // the handler runs with it bound thread-locally (downstream native
    // RPC clients re-inject it), and one rpc.<method> span wraps the
    // handler when a sink is registered.  No context, no cost.
    std::string span_method;
    TraceCtx span_ctx;
    int64_t span_t0 = 0;
    try {
      Json req = Json::parse(payload);
      int64_t timeout_ms = req.get("timeout_ms").as_int(60000);
      std::string method = req.get("method").as_string();
      span_ctx = parse_traceparent(req.get("traceparent").as_string());
      if (span_ctx.valid() && span_sink_active()) {
        span_method = method;
        span_t0 = wall_ns();
      }
      current_trace() = span_ctx;
      Json result = handle(method, req.get("params"), timeout_ms);
      reply["ok"] = true;
      reply["result"] = result;
    } catch (const TimeoutError& e) {
      reply["ok"] = false;
      reply["error"] = std::string(e.what());
      reply["code"] = "timeout";
    } catch (const NotLeaderError& e) {
      // Coordination-plane HA: leader-only method on a follower.  The
      // structured code + leader hint is what lets failover clients jump
      // straight to the holder instead of guessing.
      reply["ok"] = false;
      reply["error"] = std::string(e.what());
      reply["code"] = "not_leader";
      reply["leader"] = e.leader();
    } catch (const std::exception& e) {
      reply["ok"] = false;
      reply["error"] = std::string(e.what());
    }
    current_trace() = TraceCtx{};
    if (span_t0 != 0) {
      Json attrs = Json::object();
      attrs["server"] = server_kind();
      attrs["method"] = span_method;
      emit_span("rpc." + span_method, span_ctx, span_t0, wall_ns(),
                reply.get("ok").as_bool(), attrs);
    }
    std::string out = reply.dump();
    if (!send_frame(fd, out, now_ms() + 60000, nullptr)) break;
  }
}

void RpcServer::handle_http(int fd, const std::string&) {
  http_reply(fd, 404, "text/plain", "not found\n");
}

void RpcServer::http_reply(int fd, int status, const std::string& content_type,
                           const std::string& body) {
  const char* reason = status == 200 ? "OK" : status == 404 ? "Not Found"
                                                            : "Error";
  std::ostringstream os;
  os << "HTTP/1.1 " << status << " " << reason << "\r\n"
     << "Content-Type: " << content_type << "\r\n"
     << "Content-Length: " << body.size() << "\r\n"
     << "Connection: close\r\n\r\n"
     << body;
  std::string s = os.str();
  write_all(fd, s.data(), s.size(), now_ms() + 10000, nullptr);
}

}  // namespace tft
