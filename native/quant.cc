// Fused int8 row-quantization codec for the DCN host collective path.
//
// TPU-first rationale: on-device quantization is the Pallas kernel
// (torchft_tpu/ops/pallas_quant.py); this file is the HOST side of the
// wire codec — the analog of the reference's fused Triton quantization
// kernels (reference: torchft/quantization.py:44-430) re-targeted at the
// host CPU that feeds the DCN socket.  The numpy codec in
// torchft_tpu/ops/quantization.py makes ~6 full memory passes (abs temp,
// row max, broadcast multiply temp, rint, astype copy, pack concat); at
// GB-scale pseudograd fragments that is the dominant cost of the
// quantized wire.  These loops fuse each stage into row-blocked passes —
// a 2048-float row lives in L1, so the absmax pass and the scale+round+
// narrow pass read main memory once between them.
//
// Semantics are bit-identical to the numpy reference codec (asserted in
// tests/test_pallas_quant.py::test_native_host_codec_*): same absmax
// threshold for degenerate rows, same f32 reciprocal-scale multiply, same
// round-half-to-even (nearbyintf under the default FP environment ==
// np.rint), same int8 narrowing.
//
// All functions are GIL-free (called via ctypes, which releases the GIL),
// so a rank's codec overlaps the shaped wire sleeps of its peers on a
// shared host.

#include <cfloat>
#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

// Threshold below which a row is encoded as exact zeros with scale 1.0
// (absmax so small that qmax/absmax would overflow f32 — matches the
// numpy codec's `nonzero = absmax > qmax / finfo(f32).max`).
inline bool degenerate(float absmax, float qmax) {
  return !(absmax > qmax / FLT_MAX);
}

}  // namespace

extern "C" {

// Per-row absmax int8 quantize: in[rows*cols] f32 -> scales[rows] f32 +
// payload[rows*cols] int8.  Row-blocked: each row is read from RAM once
// for absmax and is still cache-hot for the quantize pass.
void tft_quant_int8(const float* in, int64_t rows, int64_t cols,
                    float* scales, int8_t* payload) {
  const float qmax = 127.0f;
  for (int64_t r = 0; r < rows; ++r) {
    const float* row = in + r * cols;
    int8_t* out = payload + r * cols;
    float absmax = 0.0f;
    int has_nan = 0;
    for (int64_t c = 0; c < cols; ++c) {
      float a = std::fabs(row[c]);
      absmax = a > absmax ? a : absmax;
      has_nan |= (a != a);
    }
    // NaN propagation (matches numpy's abs().max()): a NaN element sends
    // the row down the degenerate branch (scale 1.0) exactly like the
    // numpy codec — instead of silently encoding the NaN row against a
    // finite absmax.  (Payload bytes of such garbage rows still differ
    // from numpy's astype-of-NaN; row-LEVEL semantics are what agree.)
    if (has_nan) absmax = std::nanf("");
    if (degenerate(absmax, qmax)) {
      scales[r] = 1.0f;
      // numpy path: payload = rint(x * 1.0) -> 0 for |x| < ~1e-36
      std::memset(out, 0, static_cast<size_t>(cols));
      continue;
    }
    scales[r] = absmax / qmax;
    const float inv = qmax / absmax;
    for (int64_t c = 0; c < cols; ++c) {
      // nearbyintf == round-half-to-even under the default FP env ==
      // np.rint; the product is bounded to +-(127 + 1ulp) by absmax
      // scaling, so the int8 narrowing cannot wrap.
      out[c] = static_cast<int8_t>(nearbyintf(row[c] * inv));
    }
  }
}

// Dequantize-accumulate: acc[rows*cols] (f32) op= payload * scale.
// overwrite=1 initializes acc (no zero-fill pass, no separate first add);
// overwrite=0 accumulates.  One int8 read + one f32 write (+ one f32
// read when accumulating) — the numpy path widens to a full f32 temp
// first.
void tft_dequant_fma(const int8_t* payload, const float* scales,
                     int64_t rows, int64_t cols, float* acc, int overwrite) {
  for (int64_t r = 0; r < rows; ++r) {
    const int8_t* row = payload + r * cols;
    float* dst = acc + r * cols;
    const float s = scales[r];
    if (overwrite) {
      for (int64_t c = 0; c < cols; ++c) {
        dst[c] = static_cast<float>(row[c]) * s;
      }
    } else {
      for (int64_t c = 0; c < cols; ++c) {
        dst[c] += static_cast<float>(row[c]) * s;
      }
    }
  }
}

// ---------------------------------------------------------------------------
// fp8_e4m3fn wire format (the reference's fp8e4nv analog)
// ---------------------------------------------------------------------------

namespace {

// f32 -> float8_e4m3fn with round-to-nearest-even.  Bit-exact against
// ml_dtypes' astype on the FULL f32 domain (asserted in
// tests/test_pallas_quant.py::TestNativeFp8Codec), including the
// non-finite corners the "fn" format folds into its NaN code 0x7f:
// NaN, +-inf, and overflow past the 464 midpoint (RNE in the continuous
// code space treats 0x7f as the 480 slot, so 464 rounds even to 0x7e
// = max finite 448 while 465 rounds to 0x7f = NaN — matching ml_dtypes
// exactly).  A NaN pseudograd element therefore round-trips as NaN on
// the wire instead of being laundered into finite +-448 (ADVICE r5):
// downstream NaN detection stays intact on both codec paths.
inline uint8_t f32_to_e4m3(float f) {
  uint32_t b;
  std::memcpy(&b, &f, 4);
  const uint8_t sign = static_cast<uint8_t>((b >> 24) & 0x80u);
  const uint32_t abs = b & 0x7fffffffu;
  if (abs >= 0x7f800000u) return sign | 0x7fu;  // inf / NaN -> NaN code
  if (abs < 0x3c800000u) {
    // |x| < 2^-6 (min normal): subnormal grid k * 2^-9, k in [0, 8] —
    // k == 8 lands exactly on the min normal's code (the encoding is
    // continuous), so one nearbyint covers the sub/normal boundary.
    float a;
    std::memcpy(&a, &abs, 4);
    return sign | static_cast<uint8_t>(nearbyintf(a * 512.0f));
  }
  // normal: RNE on the top 3 mantissa bits, re-bias 127 -> 7.  Mantissa
  // carry flows into the exponent field naturally (continuous encoding);
  // values whose rounded code passes 0x7f saturate at the NaN code, the
  // "fn" overflow rule.
  const uint32_t rounded = abs + 0x7ffffu + ((abs >> 20) & 1u);
  uint32_t e4 = (rounded >> 20) - ((127u - 7u) << 3);
  if (e4 > 0x7fu) e4 = 0x7fu;  // overflow past the top bucket -> NaN code
  return sign | static_cast<uint8_t>(e4);
}

}  // namespace

extern "C" {

// Per-row absmax fp8_e4m3fn quantize (qmax 448): in[rows*cols] f32 ->
// scales[rows] f32 + payload[rows*cols] fp8 bytes.  Same degenerate-row
// rule as int8 (scale 1.0, zero payload).
//
// The non-degenerate (hot) encode loop is BRANCHLESS so gcc vectorizes
// it (the scalar f32_to_e4m3's sub/normal branch blocked that; measured
// ~2x less encode time per element at 2048 cols).  The domain makes
// this safe: absmax-scaled values are either finite with |x| <=
// 448*(1+2^-23) — where plain RNE in code space never passes the max
// finite code 0x7e — or NaN (an inf element times inv==0), which the
// one extra blend folds to the NaN code 0x7f exactly like the scalar
// encoder.  Bit-exactness of both legs vs ml_dtypes is asserted in
// tests/test_pallas_quant.py::TestNativeFp8Codec.
void tft_quant_fp8(const float* in, int64_t rows, int64_t cols,
                   float* scales, uint8_t* payload) {
  const float qmax = 448.0f;
  for (int64_t r = 0; r < rows; ++r) {
    const float* row = in + r * cols;
    uint8_t* out = payload + r * cols;
    float absmax = 0.0f;
    int has_nan = 0;
    for (int64_t c = 0; c < cols; ++c) {
      float a = std::fabs(row[c]);
      absmax = a > absmax ? a : absmax;
      has_nan |= (a != a);
    }
    // NaN-propagating max — see tft_quant_int8
    if (has_nan) absmax = std::nanf("");
    if (degenerate(absmax, qmax)) {
      scales[r] = 1.0f;
      // numpy path: (x * 1.0).astype(fp8) -> +/-0 for |x| < ~1e-36;
      // e4m3 of such tiny values is 0x00 or 0x80 (signed zero) — match
      // the element-wise conversion rather than memset so -0.0 inputs
      // keep their sign bit exactly like ml_dtypes does.  NaN rows land
      // here too (NaN absmax): raw values through the full-domain scalar
      // encoder, so NaN codes round-trip on the wire.
      for (int64_t c = 0; c < cols; ++c) out[c] = f32_to_e4m3(row[c]);
      continue;
    }
    scales[r] = absmax / qmax;
    const float inv = qmax / absmax;
    for (int64_t c = 0; c < cols; ++c) {
      const float f = row[c] * inv;
      uint32_t b;
      std::memcpy(&b, &f, 4);
      const uint32_t sign = (b >> 24) & 0x80u;
      const uint32_t abs = b & 0x7fffffffu;
      // normal leg: RNE on the top 3 mantissa bits, re-bias 127 -> 7
      const uint32_t rounded = abs + 0x7ffffu + ((abs >> 20) & 1u);
      uint32_t e4 = (rounded >> 20) - ((127u - 7u) << 3);
      if (e4 > 0x7fu) e4 = 0x7fu;  // safety clamp, unreachable on-domain
      // subnormal leg: grid k * 2^-9, k in [0, 8] (continuous encoding).
      // Clamp before the f32->int cast: its value is only USED for
      // abs < 2^-6 (where a*512 < 8 and the clamp is a no-op), but it is
      // COMPUTED for every lane, and casting an out-of-range/NaN float
      // to integer is UB ([conv.fpint]; UBSan's float-cast-overflow).
      // NaN/inf compare false, so they clamp too.
      float a;
      std::memcpy(&a, &abs, 4);
      float v = a * 512.0f;
      v = v <= 4096.0f ? v : 4096.0f;
      const uint32_t sub = static_cast<uint32_t>(nearbyintf(v));
      uint32_t mag = abs < 0x3c800000u ? sub : e4;
      // inf * inv==0 gave NaN: fold to the fn NaN code like ml_dtypes
      mag = abs >= 0x7f800000u ? 0x7fu : mag;
      out[c] = static_cast<uint8_t>(sign | mag);
    }
  }
}

// Dequantize-accumulate for fp8 payloads via a caller-supplied 256-entry
// f32 LUT (built in Python FROM ml_dtypes, so decode is bit-exact by
// construction).  acc op= lut[payload] * scale.
void tft_dequant_fp8_fma(const uint8_t* payload, const float* scales,
                         const float* lut256, int64_t rows, int64_t cols,
                         float* acc, int overwrite) {
  for (int64_t r = 0; r < rows; ++r) {
    const uint8_t* row = payload + r * cols;
    float* dst = acc + r * cols;
    const float s = scales[r];
    if (overwrite) {
      for (int64_t c = 0; c < cols; ++c) dst[c] = lut256[row[c]] * s;
    } else {
      for (int64_t c = 0; c < cols; ++c) dst[c] += lut256[row[c]] * s;
    }
  }
}

}  // extern "C"

// Uniform in-place divide (the fused AVG step after accumulation).
// A true divide, not multiply-by-reciprocal: bit-identical to the numpy
// fallback's `acc /= average_by`.
void tft_div_f32(float* acc, int64_t n, float div) {
  for (int64_t i = 0; i < n; ++i) acc[i] /= div;
}

// ---------------------------------------------------------------------------
// row-range entry points (the threaded-codec surface)
// ---------------------------------------------------------------------------
//
// Each takes FULL-buffer base pointers plus a [r0, r1) row range and
// delegates to the whole-buffer kernel on offset pointers, so the pointer
// arithmetic lives here rather than in ctypes call sites.  Rows are
// independent in every kernel above (per-row absmax, per-row scale), so
// concurrent calls over DISJOINT ranges of one buffer are data-race-free
// — this is what lets a small Python worker pool drive one chunk's codec
// across cores with the GIL released (the chunked-pipeline hot path; the
// TSan smoke runs a concurrent round over these, native/smoke.cc).

void tft_quant_int8_rows(const float* in, int64_t r0, int64_t r1,
                         int64_t cols, float* scales, int8_t* payload) {
  tft_quant_int8(in + r0 * cols, r1 - r0, cols, scales + r0,
                 payload + r0 * cols);
}

void tft_quant_fp8_rows(const float* in, int64_t r0, int64_t r1,
                        int64_t cols, float* scales, uint8_t* payload) {
  tft_quant_fp8(in + r0 * cols, r1 - r0, cols, scales + r0,
                payload + r0 * cols);
}

void tft_dequant_fma_rows(const int8_t* payload, const float* scales,
                          int64_t r0, int64_t r1, int64_t cols, float* acc,
                          int overwrite) {
  tft_dequant_fma(payload + r0 * cols, scales + r0, r1 - r0, cols,
                  acc + r0 * cols, overwrite);
}

void tft_dequant_fp8_fma_rows(const uint8_t* payload, const float* scales,
                              const float* lut256, int64_t r0, int64_t r1,
                              int64_t cols, float* acc, int overwrite) {
  tft_dequant_fp8_fma(payload + r0 * cols, scales + r0, lut256, r1 - r0,
                      cols, acc + r0 * cols, overwrite);
}

void tft_div_f32_rows(float* acc, int64_t r0, int64_t r1, int64_t cols,
                      float div) {
  tft_div_f32(acc + r0 * cols, (r1 - r0) * cols, div);
}

}  // extern "C"
