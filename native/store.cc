#include "store.h"

#include <chrono>

namespace tft {

void StoreServer::wake_blocked() {
  std::lock_guard<std::mutex> g(mu_);
  cv_.notify_all();
}

Json StoreServer::handle(const std::string& method, const Json& params,
                         int64_t timeout_ms) {
  if (method == "set") {
    std::lock_guard<std::mutex> g(mu_);
    kv_[params.get("key").as_string()] = params.get("value").as_string();
    cv_.notify_all();
    return Json::object();
  }
  if (method == "get") {
    const std::string key = params.get("key").as_string();
    bool wait = params.get("wait").as_bool(true);
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    while (true) {
      auto it = kv_.find(key);
      if (it != kv_.end()) {
        Json out = Json::object();
        out["value"] = it->second;
        return out;
      }
      if (!wait) throw std::runtime_error("key not found: " + key);
      if (stopping_.load()) throw std::runtime_error("store shutting down");
      if (cv_.wait_until(lk, deadline) == std::cv_status::timeout)
        throw TimeoutError("timeout waiting for key: " + key);
    }
  }
  if (method == "delete_prefix") {
    const std::string prefix = params.get("prefix").as_string();
    std::lock_guard<std::mutex> g(mu_);
    int64_t removed = 0;
    for (auto it = kv_.lower_bound(prefix); it != kv_.end();) {
      if (it->first.compare(0, prefix.size(), prefix) != 0) break;
      it = kv_.erase(it);
      removed++;
    }
    Json out = Json::object();
    out["removed"] = removed;
    return out;
  }
  if (method == "num_keys") {
    std::lock_guard<std::mutex> g(mu_);
    Json out = Json::object();
    out["count"] = static_cast<int64_t>(kv_.size());
    return out;
  }
  throw std::runtime_error("store: unknown method " + method);
}

}  // namespace tft
