#include "manager.h"

#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstdlib>

namespace tft {

Json QuorumResult::to_json() const {
  Json j = Json::object();
  j["quorum_id"] = quorum_id;
  j["recover_src_manager_address"] = recover_src_manager_address;
  if (recover_src_replica_rank.has_value())
    j["recover_src_replica_rank"] = *recover_src_replica_rank;
  else
    j["recover_src_replica_rank"] = nullptr;
  Json dsts = Json::array();
  for (int64_t r : recover_dst_replica_ranks) dsts.push_back(r);
  j["recover_dst_replica_ranks"] = dsts;
  j["store_address"] = store_address;
  j["max_step"] = max_step;
  if (max_replica_rank.has_value())
    j["max_replica_rank"] = *max_replica_rank;
  else
    j["max_replica_rank"] = nullptr;
  j["max_world_size"] = max_world_size;
  j["replica_rank"] = replica_rank;
  j["replica_world_size"] = replica_world_size;
  j["heal"] = heal;
  j["commit_failures"] = commit_failures;
  j["max_layout_epoch"] = max_layout_epoch;
  j["min_layout_epoch"] = min_layout_epoch;
  Json parts = Json::array();
  for (const Json& p : participants) parts.push_back(p);
  j["participants"] = parts;
  return j;
}

QuorumResult compute_quorum_results(const std::string& replica_id,
                                    int64_t group_rank, const Quorum& quorum,
                                    bool init_sync) {
  std::vector<QuorumMember> participants = quorum.participants;
  std::sort(participants.begin(), participants.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  // This replica's rank within the sorted quorum.
  int64_t replica_rank = -1;
  for (size_t i = 0; i < participants.size(); i++)
    if (participants[i].replica_id == replica_id)
      replica_rank = static_cast<int64_t>(i);
  if (replica_rank < 0)
    throw std::runtime_error("replica " + replica_id +
                             " not participating in returned quorum");

  // The cohort at max step defines who is up to date.
  int64_t max_step = 0;
  for (const auto& p : participants) max_step = std::max(max_step, p.step);
  std::vector<int64_t> max_ranks;  // indices into participants
  for (size_t i = 0; i < participants.size(); i++)
    if (participants[i].step == max_step)
      max_ranks.push_back(static_cast<int64_t>(i));

  std::optional<int64_t> max_replica_rank;
  for (size_t i = 0; i < max_ranks.size(); i++)
    if (participants[max_ranks[i]].replica_id == replica_id)
      max_replica_rank = static_cast<int64_t>(i);

  // Primary rendezvous store owner for this local rank: spread local ranks
  // across the up-to-date replicas.
  const QuorumMember& primary =
      participants[max_ranks[group_rank % static_cast<int64_t>(
                                 max_ranks.size())]];

  // Recovery destinations: behind max step, or (init_sync at step 0) every
  // non-primary replica so all start from identical weights.
  bool force_recover = init_sync && max_step == 0;
  std::vector<int64_t> recover_dsts;
  for (size_t i = 0; i < participants.size(); i++) {
    const auto& p = participants[i];
    if (p.step != max_step ||
        (force_recover && primary.replica_id != p.replica_id))
      recover_dsts.push_back(static_cast<int64_t>(i));
  }
  std::vector<int64_t> up_to_date;
  for (size_t i = 0; i < participants.size(); i++)
    if (std::find(recover_dsts.begin(), recover_dsts.end(),
                  static_cast<int64_t>(i)) == recover_dsts.end())
      up_to_date.push_back(static_cast<int64_t>(i));

  // Round-robin recovery sources, offset by group_rank so different local
  // ranks of the same dst replica pull from different sources.
  std::map<int64_t, std::vector<int64_t>> assignments;  // src -> [dst...]
  std::optional<int64_t> recover_src_replica_rank;
  for (size_t i = 0; i < recover_dsts.size(); i++) {
    int64_t src = up_to_date[(static_cast<int64_t>(i) + group_rank) %
                             static_cast<int64_t>(up_to_date.size())];
    assignments[src].push_back(recover_dsts[i]);
    if (recover_dsts[i] == replica_rank) recover_src_replica_rank = src;
  }

  QuorumResult out;
  out.quorum_id = quorum.quorum_id;
  out.recover_src_replica_rank = recover_src_replica_rank;
  if (recover_src_replica_rank.has_value())
    out.recover_src_manager_address =
        participants[*recover_src_replica_rank].address;
  if (assignments.count(replica_rank))
    out.recover_dst_replica_ranks = assignments[replica_rank];
  out.store_address = primary.store_address;
  out.max_step = max_step;
  out.max_replica_rank = max_replica_rank;
  out.max_world_size = static_cast<int64_t>(max_ranks.size());
  out.replica_rank = replica_rank;
  out.replica_world_size = static_cast<int64_t>(participants.size());
  out.heal = recover_src_replica_rank.has_value();
  for (const auto& p : participants)
    out.commit_failures = std::max(out.commit_failures, p.commit_failures);
  out.max_layout_epoch = participants.front().layout_epoch;
  out.min_layout_epoch = participants.front().layout_epoch;
  for (const auto& p : participants) {
    out.max_layout_epoch = std::max(out.max_layout_epoch, p.layout_epoch);
    out.min_layout_epoch = std::min(out.min_layout_epoch, p.layout_epoch);
    Json entry = Json::object();
    entry["replica_id"] = p.replica_id;
    entry["address"] = p.address;
    // step: lets a healing replica identify the max-step cohort and
    // stripe its heal fetch across every up-to-date peer (ISSUE 15)
    entry["step"] = p.step;
    entry["layout_epoch"] = p.layout_epoch;
    entry["data"] = p.data;
    out.participants.push_back(entry);
  }
  return out;
}

ManagerServer::ManagerServer(const ManagerOpt& opt)
    : RpcServer(opt.bind_host, opt.port), opt_(opt) {}

ManagerServer::~ManagerServer() { stop(); }

void ManagerServer::start_serving() {
  start();
  heartbeat_thread_ = std::thread([this] { heartbeat_loop(); });
}

void ManagerServer::stop() {
  shutdown();
  wake_blocked();  // unblock the heartbeat cv wait immediately
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  // Detached quorum threads finish within their request timeout.
  while (inflight_quorums_.load() > 0) usleep(10 * 1000);
}

void ManagerServer::wake_blocked() {
  std::lock_guard<std::mutex> g(mu_);
  cv_.notify_all();
}

void ManagerServer::report_progress(int64_t step,
                                    const std::string& inflight_op) {
  std::lock_guard<std::mutex> g(mu_);
  if (step != progress_step_) {
    progress_step_ = step;
    progress_wall_ms_ = std::chrono::duration_cast<std::chrono::milliseconds>(
                            std::chrono::system_clock::now()
                                .time_since_epoch())
                            .count();
  }
  progress_op_ = inflight_op;
}

void ManagerServer::report_summary(const Json& summary) {
  std::lock_guard<std::mutex> g(mu_);
  pending_summary_ = summary;
}

void ManagerServer::report_links(const Json& links) {
  std::lock_guard<std::mutex> g(mu_);
  pending_links_ = links;
}

void ManagerServer::report_fragments(const Json& fragments) {
  std::lock_guard<std::mutex> g(mu_);
  pending_fragments_ = fragments;
}

void ManagerServer::heartbeat_loop() {
  // Multi-endpoint failover client: with TORCHFT_LIGHTHOUSE as a comma
  // list this walks dead peers and follows NOT_LEADER redirects to the
  // current lease holder; a single endpoint behaves like RpcClient.
  HaRpcClient client(opt_.lighthouse_addr);
  while (!stopping_.load()) {
    Json params = Json::object();
    params["replica_id"] = opt_.replica_id;
    std::optional<Json> summary;
    std::optional<Json> links;
    std::optional<Json> fragments;
    // Piggyback training progress (straggler telemetry): once the Python
    // Manager has reported a step, every heartbeat carries it so the
    // lighthouse can compute per-replica step lag without extra RPCs.
    {
      std::lock_guard<std::mutex> g(mu_);
      if (progress_step_ >= 0) {
        params["step"] = progress_step_;
        params["last_step_wall_ms"] = progress_wall_ms_;
        params["inflight_op"] = progress_op_;
      }
      // Per-step digest rides at most once (cluster timeline aggregates
      // would overcount a re-sent digest); restored below if the RPC
      // fails so a transient lighthouse outage doesn't eat it.
      if (pending_summary_.has_value()) {
        summary = std::move(pending_summary_);
        pending_summary_.reset();
        params["summary"] = *summary;
      }
      // Link digest rides the same way: once, restored on failure.
      if (pending_links_.has_value()) {
        links = std::move(pending_links_);
        pending_links_.reset();
        params["links"] = *links;
      }
      // Fragment-provenance digest: same once/restore contract.
      if (pending_fragments_.has_value()) {
        fragments = std::move(pending_fragments_);
        pending_fragments_.reset();
        params["fragments"] = *fragments;
      }
    }
    try {
      Json reply = client.call("heartbeat", params, opt_.connect_timeout_ms);
      if (reply.get("superseded").as_bool()) {
        // A newer incarnation of this replica registered at the
        // lighthouse: this process is a zombie there, permanently (the
        // eviction stamp never expires).  Stop heartbeating — the
        // lighthouse ignores us anyway, and the quorum path will
        // surface the superseded error to the training loop.
        fprintf(stderr,
                "[torchft manager %s] superseded by a newer incarnation; "
                "stopping heartbeats\n",
                opt_.replica_id.c_str());
        return;
      }
    } catch (const std::exception&) {
      // Lighthouse unreachable: keep trying; quorum path surfaces errors.
      client.close();
      if (summary.has_value() || links.has_value() || fragments.has_value()) {
        // Undelivered digests: put them back unless newer ones arrived.
        std::lock_guard<std::mutex> g(mu_);
        if (summary.has_value() && !pending_summary_.has_value())
          pending_summary_ = std::move(summary);
        if (links.has_value() && !pending_links_.has_value())
          pending_links_ = std::move(links);
        if (fragments.has_value() && !pending_fragments_.has_value())
          pending_fragments_ = std::move(fragments);
      }
    }
    // interruptible sleep: stop() must not wait out a full heartbeat
    // interval (shutdown sits on the recovery-latency critical path), and
    // the cv wait avoids periodic wakeups during normal operation
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait_for(lk, std::chrono::milliseconds(opt_.heartbeat_interval_ms),
                 [this] { return stopping_.load(); });
  }
}

Json ManagerServer::handle(const std::string& method, const Json& params,
                           int64_t timeout_ms) {
  if (method == "quorum") return rpc_quorum(params, timeout_ms);
  if (method == "should_commit") return rpc_should_commit(params, timeout_ms);
  if (method == "checkpoint_metadata") {
    std::lock_guard<std::mutex> g(mu_);
    int64_t rank = params.get("rank").as_int();
    auto it = checkpoint_metadata_.find(rank);
    if (it == checkpoint_metadata_.end())
      throw std::runtime_error("rank not found");
    Json out = Json::object();
    out["checkpoint_metadata"] = it->second;
    return out;
  }
  if (method == "kill") {
    fprintf(stderr, "torchft_tpu manager: got kill request: %s\n",
            params.get("msg").as_string().c_str());
    fflush(stderr);
    _exit(1);
  }
  throw std::runtime_error("manager: unknown method " + method);
}

Json ManagerServer::rpc_quorum(const Json& params, int64_t timeout_ms) {
  int64_t group_rank = params.get("group_rank").as_int();
  bool init_sync = params.get("init_sync").as_bool(true);

  int64_t round;
  {
    std::unique_lock<std::mutex> lk(mu_);
    checkpoint_metadata_[group_rank] =
        params.get("checkpoint_metadata").as_string();

    QuorumMember member;
    member.replica_id = opt_.replica_id;
    member.address = address();
    member.store_address = opt_.store_address;
    member.step = params.get("step").as_int();
    member.world_size = opt_.world_size;
    member.shrink_only = params.get("shrink_only").as_bool();
    member.commit_failures = params.get("commit_failures").as_int();
    member.layout_epoch = params.get("layout_epoch").as_int(0);
    member.data = params.get("layout_data").as_string();

    quorum_participants_.insert(group_rank);
    round = quorum_round_seq_;

    if (static_cast<int64_t>(quorum_participants_.size()) ==
        opt_.world_size) {
      quorum_participants_.clear();
      latest_quorum_.reset();
      quorum_error_.clear();
      // The last-arriving rank's request parameters drive the cluster call
      // (parity with reference src/manager.rs:365-383).  The detached
      // thread inherits this request's trace context so the lighthouse
      // quorum RPC lands in the same per-step trace as the Python
      // client's round (the thread-local does not cross std::thread).
      inflight_quorums_.fetch_add(1);
      TraceCtx tctx = current_trace();
      std::thread([this, member, timeout_ms, tctx] {
        current_trace() = tctx;
        run_quorum(member, timeout_ms);
        inflight_quorums_.fetch_sub(1);
      }).detach();
    }
  }

  std::unique_lock<std::mutex> lk(mu_);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (true) {
    if (quorum_round_seq_ > round) {
      if (!quorum_error_.empty()) throw std::runtime_error(quorum_error_);
      if (!latest_quorum_.has_value())
        // A newer round's last arrival reset the result before this stale
        // waiter woke (its client likely already timed out and retried).
        throw std::runtime_error("quorum round superseded; retry");
      QuorumResult result = compute_quorum_results(
          opt_.replica_id, group_rank, *latest_quorum_, init_sync);
      return result.to_json();
    }
    if (stopping_.load()) throw std::runtime_error("manager shutting down");
    if (cv_.wait_until(lk, deadline) == std::cv_status::timeout)
      throw TimeoutError("timeout waiting for quorum");
  }
}

void ManagerServer::run_quorum(QuorumMember member, int64_t timeout_ms) {
  Json params = Json::object();
  params["member"] = member.to_json();

  std::string error;
  std::optional<Quorum> quorum;
  int64_t retries = std::max<int64_t>(opt_.quorum_retries, 0);
  for (int64_t attempt = 0; attempt <= retries && !stopping_.load();
       attempt++) {
    try {
      // Fresh client per attempt: the lighthouse may have restarted
      // (reference resets its channel on retry, src/manager.rs:303-306).
      // The HA walk inside one attempt already covers endpoint death and
      // leadership movement mid-call.
      HaRpcClient client(opt_.lighthouse_addr);
      Json result = client.call("quorum", params, timeout_ms);
      quorum = Quorum::from_json(result.get("quorum"));
      error.clear();
      break;
    } catch (const std::exception& e) {
      error = e.what();
      if (attempt < retries) {
        int64_t sleep_ms =
            std::max<int64_t>(100, timeout_ms / (retries + 1));
        usleep(static_cast<useconds_t>(sleep_ms * 1000));
      }
    }
  }

  std::lock_guard<std::mutex> g(mu_);
  if (quorum.has_value()) {
    latest_quorum_ = quorum;
    quorum_error_.clear();
  } else {
    quorum_error_ = "lighthouse quorum failed after " +
                    std::to_string(retries) + " retries: " + error;
  }
  quorum_round_seq_ += 1;
  cv_.notify_all();
}

Json ManagerServer::rpc_should_commit(const Json& params, int64_t timeout_ms) {
  int64_t group_rank = params.get("group_rank").as_int();
  int64_t step = params.get("step").as_int(-1);
  bool vote = params.get("should_commit").as_bool();

  std::unique_lock<std::mutex> lk(mu_);
  // Step-tag the barrier round so a stale vote (a delivered-then-resent
  // copy from a broken connection, or a tally left behind by a round that
  // timed out) can never satisfy a later round — the server-side half of
  // the vote-integrity invariant the tft-verify vote sub-model checks
  // (analysis/protocol_model.py).  Ranks advance their step ONLY through
  // a completed barrier, so a vote for a NEWER step proves the open tally
  // belongs to an abandoned round: discard it and start fresh (this also
  // un-wedges a tally orphaned by a crash + re-quorum).  A vote for an
  // OLDER step is the stale copy itself: reject it.
  if (commit_votes_.empty()) {
    commit_step_ = step;
  } else if (step > commit_step_) {
    commit_votes_.clear();
    commit_failures_.clear();
    commit_step_ = step;
  } else if (step < commit_step_) {
    throw std::runtime_error(
        "should_commit vote for step " + std::to_string(step) +
        " in a barrier round voting on step " + std::to_string(commit_step_) +
        " (stale or double-delivered vote)");
  }
  int64_t round = commit_round_seq_;
  if (!vote) commit_failures_.insert(group_rank);
  commit_votes_.insert(group_rank);

  if (static_cast<int64_t>(commit_votes_.size()) == opt_.world_size) {
    commit_decision_ = commit_failures_.empty();
    commit_votes_.clear();
    commit_failures_.clear();
    commit_round_seq_ += 1;
    cv_.notify_all();
  }

  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  while (commit_round_seq_ == round) {
    if (stopping_.load()) throw std::runtime_error("manager shutting down");
    if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
      if (commit_round_seq_ != round) break;  // completed at the deadline
      // The round is still open: withdraw this rank's vote.  A failed
      // commit retries the SAME step, so a tally left behind here would
      // merge with the retry round's fresh votes (and an orphaned no
      // vote would poison its decision) — the step tag above only
      // guards rounds at a DIFFERENT step.
      commit_votes_.erase(group_rank);
      commit_failures_.erase(group_rank);
      throw TimeoutError("timeout waiting for should_commit barrier");
    }
  }
  Json out = Json::object();
  out["should_commit"] = commit_decision_;
  return out;
}

}  // namespace tft
