// In-memory TCP key-value store used for rendezvous.
//
// TPU-native replacement for the reference's reliance on torch's TCPStore
// (reference: torchft/manager.py:277-325 and process_group.py:111-130 use a
// TCPStore for manager-address hand-off and per-quorum process-group
// rendezvous). Methods: set / get(wait) / delete_prefix / num_keys.
#pragma once

#include <condition_variable>
#include <map>
#include <mutex>
#include <string>

#include "net.h"

namespace tft {

class StoreServer : public RpcServer {
 public:
  StoreServer(std::string bind_host, int port)
      : RpcServer(std::move(bind_host), port) {}

 protected:
  Json handle(const std::string& method, const Json& params,
              int64_t timeout_ms) override;
  const char* server_kind() const override { return "store"; }
  void wake_blocked() override;

 private:
  std::mutex mu_;
  CondVar cv_;
  std::map<std::string, std::string> kv_;
};

}  // namespace tft
