// Minimal JSON value + parser + serializer for the coordination protocol.
//
// The coordination wire format (analog of the reference's gRPC protobufs,
// reference: proto/torchft.proto) is length-prefixed JSON objects; this is the
// only JSON implementation the native core depends on. Supports
// null/bool/int64/double/string/array/object, UTF-8 passthrough, \uXXXX
// escapes on parse.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace tft {

class Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

class Json {
 public:
  enum class Type { Null, Bool, Int, Double, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Int), int_(v) {}
  Json(int64_t v) : type_(Type::Int), int_(v) {}
  Json(uint64_t v) : type_(Type::Int), int_(static_cast<int64_t>(v)) {}
  Json(double v) : type_(Type::Double), double_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Int || type_ == Type::Double; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  int64_t as_int(int64_t dflt = 0) const {
    if (type_ == Type::Int) return int_;
    if (type_ == Type::Double) return static_cast<int64_t>(double_);
    return dflt;
  }
  double as_double(double dflt = 0.0) const {
    if (type_ == Type::Double) return double_;
    if (type_ == Type::Int) return static_cast<double>(int_);
    return dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const JsonArray& as_array() const {
    static const JsonArray empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  JsonArray& mutable_array() {
    if (type_ != Type::Array) throw std::runtime_error("json: not an array");
    return arr_;
  }
  const JsonObject& as_object() const {
    static const JsonObject empty;
    return type_ == Type::Object ? obj_ : empty;
  }

  // Object access. get() returns Null json for missing keys.
  const Json& get(const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_.count(key) > 0;
  }
  Json& operator[](const std::string& key) {
    if (type_ == Type::Null) type_ = Type::Object;
    if (type_ != Type::Object) throw std::runtime_error("json: not an object");
    return obj_[key];
  }
  void push_back(Json v) {
    if (type_ == Type::Null) type_ = Type::Array;
    if (type_ != Type::Array) throw std::runtime_error("json: not an array");
    arr_.push_back(std::move(v));
  }

  std::string dump() const {
    std::ostringstream os;
    write(os);
    return os.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

 private:
  void write(std::ostringstream& os) const {
    switch (type_) {
      case Type::Null: os << "null"; break;
      case Type::Bool: os << (bool_ ? "true" : "false"); break;
      case Type::Int: os << int_; break;
      case Type::Double: {
        char buf[32];
        snprintf(buf, sizeof(buf), "%.17g", double_);
        os << buf;
        break;
      }
      case Type::String: write_string(os, str_); break;
      case Type::Array: {
        os << '[';
        bool first = true;
        for (const auto& v : arr_) {
          if (!first) os << ',';
          first = false;
          v.write(os);
        }
        os << ']';
        break;
      }
      case Type::Object: {
        os << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) os << ',';
          first = false;
          write_string(os, k);
          os << ':';
          v.write(os);
        }
        os << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& os, const std::string& s) {
    os << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': os << "\\\""; break;
        case '\\': os << "\\\\"; break;
        case '\n': os << "\\n"; break;
        case '\r': os << "\\r"; break;
        case '\t': os << "\\t"; break;
        case '\b': os << "\\b"; break;
        case '\f': os << "\\f"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            os << buf;
          } else {
            os << c;
          }
      }
    }
    os << '"';
  }

  static void skip_ws(const std::string& t, size_t& pos) {
    while (pos < t.size() &&
           (t[pos] == ' ' || t[pos] == '\t' || t[pos] == '\n' || t[pos] == '\r'))
      pos++;
  }

  static Json parse_value(const std::string& t, size_t& pos) {
    skip_ws(t, pos);
    if (pos >= t.size()) throw std::runtime_error("json: unexpected end");
    char c = t[pos];
    if (c == '{') return parse_object(t, pos);
    if (c == '[') return parse_array(t, pos);
    if (c == '"') return Json(parse_string(t, pos));
    if (c == 't') { expect(t, pos, "true"); return Json(true); }
    if (c == 'f') { expect(t, pos, "false"); return Json(false); }
    if (c == 'n') { expect(t, pos, "null"); return Json(nullptr); }
    return parse_number(t, pos);
  }

  static void expect(const std::string& t, size_t& pos, const char* lit) {
    size_t n = strlen(lit);
    if (t.compare(pos, n, lit) != 0)
      throw std::runtime_error("json: bad literal");
    pos += n;
  }

  static Json parse_object(const std::string& t, size_t& pos) {
    Json out = Json::object();
    pos++;  // '{'
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == '}') { pos++; return out; }
    while (true) {
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != '"')
        throw std::runtime_error("json: expected key");
      std::string key = parse_string(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size() || t[pos] != ':')
        throw std::runtime_error("json: expected ':'");
      pos++;
      out[key] = parse_value(t, pos);
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("json: unexpected end");
      if (t[pos] == ',') { pos++; continue; }
      if (t[pos] == '}') { pos++; return out; }
      throw std::runtime_error("json: expected ',' or '}'");
    }
  }

  static Json parse_array(const std::string& t, size_t& pos) {
    Json out = Json::array();
    pos++;  // '['
    skip_ws(t, pos);
    if (pos < t.size() && t[pos] == ']') { pos++; return out; }
    while (true) {
      out.push_back(parse_value(t, pos));
      skip_ws(t, pos);
      if (pos >= t.size()) throw std::runtime_error("json: unexpected end");
      if (t[pos] == ',') { pos++; continue; }
      if (t[pos] == ']') { pos++; return out; }
      throw std::runtime_error("json: expected ',' or ']'");
    }
  }

  static std::string parse_string(const std::string& t, size_t& pos) {
    pos++;  // '"'
    std::string out;
    while (pos < t.size()) {
      char c = t[pos];
      if (c == '"') { pos++; return out; }
      if (c == '\\') {
        pos++;
        if (pos >= t.size()) break;
        char e = t[pos];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'u': {
            if (pos + 4 >= t.size()) throw std::runtime_error("json: bad \\u");
            unsigned int cp = std::stoul(t.substr(pos + 1, 4), nullptr, 16);
            pos += 4;
            // Encode BMP codepoint as UTF-8 (surrogate pairs combined).
            if (cp >= 0xD800 && cp <= 0xDBFF && pos + 6 < t.size() &&
                t[pos + 1] == '\\' && t[pos + 2] == 'u') {
              unsigned int lo = std::stoul(t.substr(pos + 3, 4), nullptr, 16);
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
              pos += 6;
            }
            if (cp < 0x80) {
              out += static_cast<char>(cp);
            } else if (cp < 0x800) {
              out += static_cast<char>(0xC0 | (cp >> 6));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              out += static_cast<char>(0xE0 | (cp >> 12));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (cp >> 18));
              out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            throw std::runtime_error("json: bad escape");
        }
        pos++;
      } else {
        out += c;
        pos++;
      }
    }
    throw std::runtime_error("json: unterminated string");
  }

  static Json parse_number(const std::string& t, size_t& pos) {
    size_t start = pos;
    bool is_double = false;
    if (pos < t.size() && (t[pos] == '-' || t[pos] == '+')) pos++;
    while (pos < t.size()) {
      char c = t[pos];
      if (c >= '0' && c <= '9') { pos++; continue; }
      if (c == '.' || c == 'e' || c == 'E' || c == '-' || c == '+') {
        if (c == '.' || c == 'e' || c == 'E') is_double = true;
        pos++;
        continue;
      }
      break;
    }
    std::string num = t.substr(start, pos - start);
    if (num.empty()) throw std::runtime_error("json: bad number");
    try {
      if (is_double) return Json(std::stod(num));
      return Json(static_cast<int64_t>(std::stoll(num)));
    } catch (const std::exception&) {
      throw std::runtime_error("json: bad number: " + num);
    }
  }

  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace tft
