// TCP plumbing for the coordination protocol: framed JSON messages with
// deadlines, exponential-backoff connect, and a generic accept-loop server.
//
// Analog of the reference's net/retry layer (reference: src/net.rs:10-36,
// src/retry.rs:8-42): connect retries back off 100ms -> 10s (x1.5 + jitter);
// every read/write takes an absolute deadline so a dead peer can never wedge
// a protocol thread.
#pragma once

#include <pthread.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "json.h"

namespace tft {

// Milliseconds since an arbitrary monotonic epoch.
int64_t now_ms();

// Drop-in condition variable pinned to sanitizer-intercepted primitives.
//
// libstdc++ 10 on glibc >= 2.30 implements
// std::condition_variable::wait_for/wait_until via pthread_cond_clockwait,
// which gcc 10's ThreadSanitizer does NOT intercept: the wait's internal
// mutex unlock/relock is invisible to TSan, which then reports a bogus
// "double lock of a mutex" and — with the mutex's happens-before state
// corrupted — a cascade of false data races on every guarded field.  The
// SANITIZE=thread build (docs/static_analysis.md) is a tier gate, so the
// coordination servers use this wrapper instead: pthread_cond_timedwait
// on a CLOCK_MONOTONIC condattr (both intercepted since forever), with
// identical semantics for this codebase's uses — steady_clock deadlines,
// no spurious-wakeup guarantees beyond the standard's.
class CondVar {
 public:
  CondVar() {
    pthread_condattr_t attr;
    pthread_condattr_init(&attr);
    pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
    pthread_cond_init(&cv_, &attr);
    pthread_condattr_destroy(&attr);
  }
  ~CondVar() { pthread_cond_destroy(&cv_); }
  CondVar(const CondVar&) = delete;
  CondVar& operator=(const CondVar&) = delete;

  void notify_one() { pthread_cond_signal(&cv_); }
  void notify_all() { pthread_cond_broadcast(&cv_); }

  void wait(std::unique_lock<std::mutex>& lk) {
    pthread_cond_wait(&cv_, lk.mutex()->native_handle());
  }

  std::cv_status wait_until(std::unique_lock<std::mutex>& lk,
                            std::chrono::steady_clock::time_point tp) {
    // steady_clock is CLOCK_MONOTONIC on Linux — same epoch as the
    // condattr clock above, so the time_point converts directly.
    int64_t ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                     tp.time_since_epoch())
                     .count();
    if (ns < 0) ns = 0;
    struct timespec ts;
    ts.tv_sec = static_cast<time_t>(ns / 1000000000);
    ts.tv_nsec = static_cast<long>(ns % 1000000000);
    int rc = pthread_cond_timedwait(&cv_, lk.mutex()->native_handle(), &ts);
    return rc == ETIMEDOUT ? std::cv_status::timeout
                           : std::cv_status::no_timeout;
  }

  template <class Rep, class Period>
  std::cv_status wait_for(std::unique_lock<std::mutex>& lk,
                          const std::chrono::duration<Rep, Period>& d) {
    return wait_until(lk, std::chrono::steady_clock::now() + d);
  }

  template <class Rep, class Period, class Pred>
  bool wait_for(std::unique_lock<std::mutex>& lk,
                const std::chrono::duration<Rep, Period>& d, Pred pred) {
    auto deadline = std::chrono::steady_clock::now() + d;
    while (!pred()) {
      if (wait_until(lk, deadline) == std::cv_status::timeout) return pred();
    }
    return true;
  }

 private:
  pthread_cond_t cv_;
};

// ---- distributed tracing -------------------------------------------------
// The framed-JSON request envelope may carry a W3C-traceparent-style
// context ("traceparent": "00-<32hex trace>-<16hex span>-<flags>").  The
// server continues it: the handler runs with the parsed context bound
// thread-locally (so downstream native RPC clients re-inject it), and one
// "rpc.<method>" span around the handler is emitted through the process
// span sink — a C callback the Python side registers (tft_set_span_sink)
// to relay native spans into its exporter, the same provider-callback
// idiom as the lighthouse /metrics supplement.  Everything here is
// zero-cost when no context arrives and no sink is registered.

struct TraceCtx {
  std::string trace_id;        // 32 lowercase hex chars
  std::string parent_span_id;  // 16 lowercase hex chars
  bool sampled = false;

  bool valid() const { return sampled && trace_id.size() == 32; }
};

// This thread's current trace position (request-scoped on server handler
// threads; explicitly copied onto detached protocol threads).
TraceCtx& current_trace();

TraceCtx parse_traceparent(const std::string& tp);
std::string format_traceparent(const TraceCtx& ctx);
std::string new_span_id();
int64_t wall_ns();  // unix-epoch wall clock, matches Python time.time_ns()

using SpanSink = void (*)(const char* span_json);
void set_span_sink(SpanSink sink);
bool span_sink_active();
// Emit one finished span (name, parent = ctx, [start_ns, end_ns], status,
// flat attribute object) to the registered sink; no-op without one.
void emit_span(const std::string& name, const TraceCtx& ctx,
               int64_t start_ns, int64_t end_ns, bool ok,
               const Json& attributes);

// ---- framed message I/O --------------------------------------------------
// Wire format: 4-byte big-endian length, then that many bytes of UTF-8 JSON.

constexpr uint32_t kMaxFrameBytes = 512u * 1024u * 1024u;

// Once a frame's length header has arrived, the body must follow promptly:
// a peer that stalls mid-frame (half-sent request, wedged sender) would
// otherwise hold a server connection thread until the full idle deadline.
constexpr int64_t kFrameBodyTimeoutMs = 30'000;

// All return false on error/timeout (errno-style detail in *err if non-null).
// recv_frame: ``deadline_ms`` bounds the wait for the 4-byte header (idle
// connections may park here); the body additionally gets at most
// ``body_timeout_ms`` from header arrival (0 = header deadline only).
bool send_frame(int fd, const std::string& payload, int64_t deadline_ms,
                std::string* err = nullptr);
bool recv_frame(int fd, std::string* payload, int64_t deadline_ms,
                std::string* err = nullptr, int64_t body_timeout_ms = 0);
// Peek up to n bytes without consuming (used to sniff HTTP vs framed proto).
bool peek_bytes(int fd, char* buf, size_t n, int64_t deadline_ms);
// Read one HTTP request/response head (through the blank line) without
// consuming any following bytes: MSG_PEEK windows + exact consume.
bool read_http_head(int fd, std::string* head, int64_t deadline_ms);
bool read_exact(int fd, char* buf, size_t n, int64_t deadline_ms,
                std::string* err = nullptr);
bool write_all(int fd, const char* buf, size_t n, int64_t deadline_ms,
               std::string* err = nullptr);

// ---- client --------------------------------------------------------------

// Coordination-plane HA: a follower lighthouse answers leader-only
// methods with {"ok":false,"code":"not_leader","leader":"host:port"} —
// the reply's leader hint ("" when no leader is known) rides this
// exception so failover clients can jump straight to the holder instead
// of walking the whole endpoint list.
class NotLeaderError : public std::runtime_error {
 public:
  NotLeaderError(const std::string& what, std::string leader)
      : std::runtime_error(what), leader_(std::move(leader)) {}
  const std::string& leader() const { return leader_; }

 private:
  std::string leader_;
};

// Split "host1:p1,host2:p2,..." into trimmed endpoint addresses.
std::vector<std::string> split_endpoints(const std::string& addrs);

// Connect to "host:port" with exponential backoff until deadline. Returns fd
// or -1 (err filled).
int connect_with_retry(const std::string& addr, int64_t timeout_ms,
                       std::string* err = nullptr);
int connect_once(const std::string& addr, int64_t timeout_ms,
                 std::string* err = nullptr);

// One-shot RPC: connect, send {method, params, timeout_ms}, read reply.
// Returns true and fills *result on {"ok":true}; false with *err otherwise.
bool call_rpc(const std::string& addr, const std::string& method,
              const Json& params, int64_t timeout_ms, Json* result,
              std::string* err);

// Persistent-connection RPC client (one in-flight request at a time).
class RpcClient {
 public:
  explicit RpcClient(std::string addr) : addr_(std::move(addr)) {}
  ~RpcClient();
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  // Connects lazily (with retry/backoff up to connect_timeout). Throws
  // std::runtime_error on failure; TimeoutExpired-style errors carry the
  // "timeout:" prefix so callers can map them.
  Json call(const std::string& method, const Json& params, int64_t timeout_ms);
  void close();

 private:
  std::string addr_;
  int fd_ = -1;
};

// Multi-endpoint failover RPC client (coordination-plane HA): walks a
// static endpoint list, follows NOT_LEADER redirects to the named
// holder, and pins a persistent connection to the endpoint that last
// answered.  A dead endpoint costs one bounded connect slice, never the
// caller's whole deadline; a live endpoint gets the full remaining
// budget (quorum is a long-poll).  With a single endpoint the behavior
// is wire-identical to RpcClient.
class HaRpcClient {
 public:
  explicit HaRpcClient(const std::string& addrs);
  ~HaRpcClient();
  HaRpcClient(const HaRpcClient&) = delete;
  HaRpcClient& operator=(const HaRpcClient&) = delete;

  Json call(const std::string& method, const Json& params, int64_t timeout_ms);
  void close();
  // The endpoint the client is currently pinned to (last success/redirect).
  std::string current() const;

 private:
  void advance();  // drop any redirect hint and rotate to the next endpoint

  std::vector<std::string> endpoints_;
  size_t cur_ = 0;
  std::string redirect_;  // leader hint from a NOT_LEADER reply
  std::string connected_addr_;
  int fd_ = -1;
};

// ---- server --------------------------------------------------------------

// A TCP server running an accept loop; each connection gets a thread that
// reads framed requests {method, params, timeout_ms} and writes replies.
// Subclass hooks: handle(method, params, timeout_ms) -> reply Json, or throw.
// If the first bytes look like HTTP, handle_http is called instead with the
// raw request head; default 404s.
class RpcServer {
 public:
  // bind_host may be "" (all interfaces); port 0 picks a free port.
  RpcServer(std::string bind_host, int port);
  virtual ~RpcServer();

  void start();
  void shutdown();
  // "host:port" with the resolved port. Host is the advertise host
  // (bind host, or the machine hostname when bound to all interfaces).
  std::string address() const { return address_; }
  int port() const { return port_; }

 protected:
  // Returns the reply value for {"ok":true,"result":...}. Throwing
  // std::runtime_error produces {"ok":false,"error":what}. Throwing
  // TimeoutError produces code "timeout".
  virtual Json handle(const std::string& method, const Json& params,
                      int64_t timeout_ms) = 0;
  // Label stamped on this server's rpc.* spans ("lighthouse"/"manager"/
  // "store") so the trace ledger can attribute server time.
  virtual const char* server_kind() const { return "server"; }
  virtual void handle_http(int fd, const std::string& request_head);
  // Keep-alive HTTP hook (the fragment data plane's persistent
  // connections): return true to hold the connection open and read the
  // next request head, false to close after this reply.  The default
  // delegates to the one-shot handle_http above and closes — existing
  // HTTP servers (lighthouse dashboard) are untouched.
  virtual bool handle_http_keepalive(int fd, const std::string& request_head) {
    handle_http(fd, request_head);
    return false;
  }
  // Called during shutdown after stopping_ is set and connection fds are
  // closed, before joining connection threads: wake any handler blocked on
  // an internal condition variable.
  virtual void wake_blocked() {}
  void http_reply(int fd, int status, const std::string& content_type,
                  const std::string& body);

  std::atomic<bool> stopping_{false};

 private:
  void accept_loop();
  void serve_conn(int fd);

  std::string bind_host_;
  std::string address_;
  int port_ = 0;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  // Connection threads are detached; shutdown() closes their fds, calls
  // wake_blocked(), and waits for active_conns_ to drain (handlers are
  // bounded by request timeouts).
  std::atomic<int> active_conns_{0};
  std::set<int> conn_fds_;
  std::mutex conn_mu_;
};

class TimeoutError : public std::runtime_error {
 public:
  explicit TimeoutError(const std::string& what) : std::runtime_error(what) {}
};

}  // namespace tft
