// TCP plumbing for the coordination protocol: framed JSON messages with
// deadlines, exponential-backoff connect, and a generic accept-loop server.
//
// Analog of the reference's net/retry layer (reference: src/net.rs:10-36,
// src/retry.rs:8-42): connect retries back off 100ms -> 10s (x1.5 + jitter);
// every read/write takes an absolute deadline so a dead peer can never wedge
// a protocol thread.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "json.h"

namespace tft {

// Milliseconds since an arbitrary monotonic epoch.
int64_t now_ms();

// ---- framed message I/O --------------------------------------------------
// Wire format: 4-byte big-endian length, then that many bytes of UTF-8 JSON.

constexpr uint32_t kMaxFrameBytes = 512u * 1024u * 1024u;

// All return false on error/timeout (errno-style detail in *err if non-null).
bool send_frame(int fd, const std::string& payload, int64_t deadline_ms,
                std::string* err = nullptr);
bool recv_frame(int fd, std::string* payload, int64_t deadline_ms,
                std::string* err = nullptr);
// Peek up to n bytes without consuming (used to sniff HTTP vs framed proto).
bool peek_bytes(int fd, char* buf, size_t n, int64_t deadline_ms);
bool read_exact(int fd, char* buf, size_t n, int64_t deadline_ms,
                std::string* err = nullptr);
bool write_all(int fd, const char* buf, size_t n, int64_t deadline_ms,
               std::string* err = nullptr);

// ---- client --------------------------------------------------------------

// Connect to "host:port" with exponential backoff until deadline. Returns fd
// or -1 (err filled).
int connect_with_retry(const std::string& addr, int64_t timeout_ms,
                       std::string* err = nullptr);
int connect_once(const std::string& addr, int64_t timeout_ms,
                 std::string* err = nullptr);

// One-shot RPC: connect, send {method, params, timeout_ms}, read reply.
// Returns true and fills *result on {"ok":true}; false with *err otherwise.
bool call_rpc(const std::string& addr, const std::string& method,
              const Json& params, int64_t timeout_ms, Json* result,
              std::string* err);

// Persistent-connection RPC client (one in-flight request at a time).
class RpcClient {
 public:
  explicit RpcClient(std::string addr) : addr_(std::move(addr)) {}
  ~RpcClient();
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  // Connects lazily (with retry/backoff up to connect_timeout). Throws
  // std::runtime_error on failure; TimeoutExpired-style errors carry the
  // "timeout:" prefix so callers can map them.
  Json call(const std::string& method, const Json& params, int64_t timeout_ms);
  void close();

 private:
  std::string addr_;
  int fd_ = -1;
};

// ---- server --------------------------------------------------------------

// A TCP server running an accept loop; each connection gets a thread that
// reads framed requests {method, params, timeout_ms} and writes replies.
// Subclass hooks: handle(method, params, timeout_ms) -> reply Json, or throw.
// If the first bytes look like HTTP, handle_http is called instead with the
// raw request head; default 404s.
class RpcServer {
 public:
  // bind_host may be "" (all interfaces); port 0 picks a free port.
  RpcServer(std::string bind_host, int port);
  virtual ~RpcServer();

  void start();
  void shutdown();
  // "host:port" with the resolved port. Host is the advertise host
  // (bind host, or the machine hostname when bound to all interfaces).
  std::string address() const { return address_; }
  int port() const { return port_; }

 protected:
  // Returns the reply value for {"ok":true,"result":...}. Throwing
  // std::runtime_error produces {"ok":false,"error":what}. Throwing
  // TimeoutError produces code "timeout".
  virtual Json handle(const std::string& method, const Json& params,
                      int64_t timeout_ms) = 0;
  virtual void handle_http(int fd, const std::string& request_head);
  // Called during shutdown after stopping_ is set and connection fds are
  // closed, before joining connection threads: wake any handler blocked on
  // an internal condition variable.
  virtual void wake_blocked() {}
  void http_reply(int fd, int status, const std::string& content_type,
                  const std::string& body);

  std::atomic<bool> stopping_{false};

 private:
  void accept_loop();
  void serve_conn(int fd);

  std::string bind_host_;
  std::string address_;
  int port_ = 0;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  // Connection threads are detached; shutdown() closes their fds, calls
  // wake_blocked(), and waits for active_conns_ to drain (handlers are
  // bounded by request timeouts).
  std::atomic<int> active_conns_{0};
  std::set<int> conn_fds_;
  std::mutex conn_mu_;
};

class TimeoutError : public std::runtime_error {
 public:
  explicit TimeoutError(const std::string& what) : std::runtime_error(what) {}
};

}  // namespace tft
