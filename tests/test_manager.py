"""Manager unit tests with mocked coordination client.

Mirrors reference torchft/manager_test.py:84-911: crafted QuorumResults
drive every Manager state — happy path, async/sync heal, not enough
participants, allreduce error, pg.errored, fixed-with-spares, max_retries.
"""

from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from torchft_tpu.coordination import QuorumResult
from torchft_tpu.manager import Manager, WorldSizeMode
from torchft_tpu.parallel.process_group import (
    ErrorSwallowingProcessGroupWrapper,
    FakeProcessGroupWrapper,
    ProcessGroupDummy,
)


def make_quorum(
    quorum_id=1,
    replica_rank=0,
    replica_world_size=2,
    max_step=0,
    max_replica_rank=0,
    max_world_size=2,
    heal=False,
    **kw,
):
    return QuorumResult(
        quorum_id=quorum_id,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        recover_src_manager_address=kw.get("recover_src_manager_address", ""),
        recover_src_replica_rank=kw.get("recover_src_replica_rank"),
        recover_dst_replica_ranks=kw.get("recover_dst_replica_ranks", []),
        store_address="fakestore:1/",
        max_step=max_step,
        max_replica_rank=max_replica_rank,
        max_world_size=max_world_size,
        heal=heal,
        commit_failures=kw.get("commit_failures", 0),
    )


@pytest.fixture
def manager_ctx():
    """Manager with fully mocked coordination plumbing."""
    patches = [
        patch("torchft_tpu.manager.ManagerServer"),
        patch("torchft_tpu.manager.StoreServer"),
        patch("torchft_tpu.manager.StoreClient"),
        patch("torchft_tpu.manager.ManagerClient"),
    ]
    mocks = [p.start() for p in patches]
    store_client = mocks[2].return_value
    store_client.get.side_effect = lambda key, **kw: {
        "manager_addr": "mock:1",
        "replica_id": "rep0:uuid",
    }[key]
    client = mocks[3].return_value

    transport = MagicMock()
    transport.metadata.return_value = "http://mock"

    def build(pg=None, **kwargs):
        defaults = dict(
            pg=pg or ProcessGroupDummy(),
            min_replica_size=2,
            load_state_dict=lambda sd: None,
            state_dict=lambda: {"w": np.zeros(2)},
            lighthouse_addr="mock-lh:1",
            group_rank=0,
            group_world_size=1,
            checkpoint_transport=transport,
            use_async_quorum=True,
        )
        defaults.update(kwargs)
        return Manager(**defaults)

    yield build, client, transport
    for p in patches:
        p.stop()


class TestManagerHappyPath:
    def test_step_and_commit(self, manager_ctx):
        build, client, transport = manager_ctx
        manager = build()
        client._quorum.return_value = make_quorum()
        client.should_commit.return_value = True

        manager.start_quorum()
        assert manager.num_participants() == 2
        assert manager.is_participating()
        assert manager.participating_rank() == 0

        result = manager.allreduce(np.full(4, 2.0)).wait(timeout=10)
        np.testing.assert_allclose(result, np.full(4, 1.0))  # / participants

        assert manager.should_commit()
        assert manager.current_step() == 1
        assert manager.batches_committed() == 2
        transport.disallow_checkpoint.assert_called()

    def test_pg_configured_on_quorum_change(self, manager_ctx):
        build, client, _ = manager_ctx
        pg = ProcessGroupDummy()
        manager = build(pg=pg)
        client._quorum.return_value = make_quorum(quorum_id=1)
        client.should_commit.return_value = True

        manager.start_quorum()
        manager.wait_quorum()
        assert pg.configure_count == 1
        manager.should_commit()

        # same quorum id -> no reconfigure
        manager.start_quorum()
        manager.wait_quorum()
        assert pg.configure_count == 1

        # new quorum id -> reconfigure
        client._quorum.return_value = make_quorum(quorum_id=2)
        manager.start_quorum()
        manager.wait_quorum()
        assert pg.configure_count == 2

    def test_pytree_allreduce(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build()
        client._quorum.return_value = make_quorum()
        manager.start_quorum()
        grads = {"a": np.full(2, 4.0), "b": [np.full(3, 8.0)]}
        out = manager.allreduce(grads).wait(timeout=10)
        np.testing.assert_allclose(out["a"], np.full(2, 2.0))
        np.testing.assert_allclose(out["b"][0], np.full(3, 4.0))

    def test_jax_array_leaves_pass_through_unmaterialized(self, manager_ctx):
        # device arrays go to the PG unconverted (the device→host sync
        # runs on the PG worker, not the submitting thread); mixed
        # jax/numpy/scalar pytrees still average correctly
        import jax.numpy as jnp

        build, client, _ = manager_ctx
        manager = build()
        client._quorum.return_value = make_quorum()
        manager.start_quorum()
        grads = {"j": jnp.full((4,), 6.0), "n": np.full(2, 4.0), "s": 8.0}
        out = manager.allreduce(grads).wait(timeout=10)
        np.testing.assert_allclose(np.asarray(out["j"]), np.full(4, 3.0))
        np.testing.assert_allclose(out["n"], np.full(2, 2.0))
        np.testing.assert_allclose(np.asarray(out["s"]), 4.0)


class TestManagerHealing:
    def test_async_heal_applies_on_commit(self, manager_ctx):
        build, client, transport = manager_ctx
        loaded = {}
        manager = build(
            load_state_dict=lambda sd: loaded.update(sd),
            state_dict=lambda: {"w": 1},
        )
        client._quorum.return_value = make_quorum(
            replica_rank=1,
            max_step=7,
            max_replica_rank=None,
            max_world_size=1,
            heal=True,
            recover_src_replica_rank=0,
            recover_src_manager_address="peer:1",
        )
        client.should_commit.return_value = True
        client._checkpoint_metadata.return_value = "http://peer"
        transport.recv_checkpoint.return_value = {
            "user": {"default": {"w": 42}},
            "torchft": {"step": 7, "batches_committed": 70},
        }

        with patch("torchft_tpu.manager.ManagerClient") as peer_cls:
            peer_cls.return_value._checkpoint_metadata.return_value = "http://peer"
            manager.start_quorum()
            manager.wait_quorum()

        # healing: not participating this step, contributes zeros
        assert manager._healing
        assert not manager.is_participating()
        result = manager.allreduce(np.full(2, 5.0)).wait(timeout=10)
        np.testing.assert_allclose(result, np.zeros(2))

        # commit applies the healed user state on the main thread
        assert manager.should_commit()
        assert loaded == {"w": 42}
        # step restored from the healed torchft dict then bumped by commit
        assert manager.current_step() == 8

    def test_sync_quorum_heals_eagerly(self, manager_ctx):
        build, client, transport = manager_ctx
        loaded = {}
        manager = build(
            use_async_quorum=False,
            load_state_dict=lambda sd: loaded.update(sd),
            state_dict=lambda: {"w": 0},
        )
        client._quorum.return_value = make_quorum(
            replica_rank=1,
            max_step=3,
            heal=True,
            recover_src_replica_rank=0,
            recover_src_manager_address="peer:1",
        )
        transport.recv_checkpoint.return_value = {
            "user": {"default": {"w": 9}},
            "torchft": {"step": 3, "batches_committed": 6},
        }
        with patch("torchft_tpu.manager.ManagerClient") as peer_cls:
            peer_cls.return_value._checkpoint_metadata.return_value = "meta"
            manager.start_quorum()
        # eager apply: state loaded before returning; participates this step
        assert loaded == {"w": 9}
        assert not manager._healing
        assert manager.is_participating()

    def test_send_checkpoint_to_recovering_peers(self, manager_ctx):
        build, client, transport = manager_ctx
        manager = build()
        client._quorum.return_value = make_quorum(
            recover_dst_replica_ranks=[1, 2], max_step=4
        )
        manager.start_quorum()
        manager.wait_quorum()
        transport.send_checkpoint.assert_called_once()
        kwargs = transport.send_checkpoint.call_args.kwargs
        assert kwargs["dst_ranks"] == [1, 2]
        assert kwargs["step"] == 4
        assert "user" in kwargs["state_dict"] and "torchft" in kwargs["state_dict"]


class TestManagerFailures:
    def test_not_enough_participants_blocks_commit(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build(min_replica_size=3)
        client._quorum.return_value = make_quorum(max_world_size=2)
        client.should_commit.return_value = False
        manager.start_quorum()
        assert not manager.should_commit()
        assert manager.current_step() == 0
        # the local vote must have been False
        assert client.should_commit.call_args.args[2] is False

    def test_allreduce_error_swallowed_and_blocks_commit(self, manager_ctx):
        build, client, _ = manager_ctx
        pg = FakeProcessGroupWrapper(ProcessGroupDummy())
        manager = build(pg=pg)
        client._quorum.return_value = make_quorum()
        client.should_commit.return_value = False
        manager.start_quorum()
        pg.report_future_error(RuntimeError("injected allreduce failure"))
        # the work completes cleanly (with the input) but the error latches
        result = manager.allreduce(np.full(2, 3.0)).wait(timeout=10)
        np.testing.assert_allclose(result, np.full(2, 3.0))
        assert manager.errored() is not None
        assert not manager.should_commit()
        assert client.should_commit.call_args.args[2] is False
        # after the error, allreduce is a no-op passthrough
        np.testing.assert_allclose(
            manager.allreduce(np.full(2, 9.0)).wait(timeout=10), np.full(2, 9.0)
        )

    def test_pg_errored_blocks_commit(self, manager_ctx):
        build, client, _ = manager_ctx
        pg = ErrorSwallowingProcessGroupWrapper(ProcessGroupDummy())
        manager = build(pg=pg)
        client._quorum.return_value = make_quorum()
        client.should_commit.return_value = False
        manager.start_quorum()
        pg.report_error(RuntimeError("pg broke"))
        assert not manager.should_commit()
        assert manager.errored() is not None

    def test_quorum_failure_captured(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build()
        client._quorum.side_effect = TimeoutError("lighthouse down")
        client.should_commit.return_value = False
        manager.start_quorum()
        assert not manager.should_commit()
        assert manager.errored() is not None

    def test_max_retries_raises(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build(max_retries=2, min_replica_size=2)
        client._quorum.return_value = make_quorum(max_world_size=1)
        client.should_commit.return_value = False
        for _ in range(3):
            manager.start_quorum()
            if manager._commit_failures == 2:
                with pytest.raises(RuntimeError, match="max_retries"):
                    manager.should_commit()
            else:
                assert not manager.should_commit()

    def test_commit_failures_reported_to_quorum(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build(min_replica_size=5)
        client._quorum.return_value = make_quorum()
        client.should_commit.return_value = False
        manager.start_quorum()
        assert not manager.should_commit()
        manager.start_quorum()
        manager.wait_quorum()
        # second quorum call carries commit_failures=1
        assert client._quorum.call_args.kwargs["commit_failures"] == 1


class TestWorldSizeModes:
    def test_fixed_with_spares_caps_world(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build(
            min_replica_size=2, world_size_mode=WorldSizeMode.FIXED_WITH_SPARES
        )
        client._quorum.return_value = make_quorum(
            max_world_size=4, max_replica_rank=3
        )
        manager.start_quorum()
        assert manager.num_participants() == 2
        # this replica (rank 3) is a spare -> not participating
        assert not manager.is_participating()
        assert manager.participating_rank() is None


class TestStateDict:
    def test_state_dict_round_trip(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build()
        manager.load_state_dict({"step": 12, "batches_committed": 34})
        assert manager.current_step() == 12
        assert manager.state_dict() == {"step": 12, "batches_committed": 34}

    def test_manager_state_dict_composite(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build(state_dict=lambda: {"w": 5})
        sd = manager._manager_state_dict()
        assert sd["user"]["default"] == {"w": 5}
        assert sd["torchft"] == {"step": 0, "batches_committed": 0}

    def test_multiple_state_dict_fns(self, manager_ctx):
        build, client, _ = manager_ctx
        manager = build()
        loaded = {}
        manager.register_state_dict_fn(
            "frag0", lambda sd: loaded.update(frag0=sd), lambda: "s0"
        )
        manager.register_state_dict_fn(
            "frag1", lambda sd: loaded.update(frag1=sd), lambda: "s1"
        )
        sd = manager._manager_state_dict()
        assert sd["user"]["frag0"] == "s0" and sd["user"]["frag1"] == "s1"


class TestStaleManagerAddr:
    def test_nonzero_rank_probes_past_dead_incarnation_addr(self):
        """After a whole-group fast restart the store still holds the dead
        incarnation's manager address until the new rank 0 republishes; a
        non-zero rank must probe and re-read instead of wiring itself to
        the corpse (manager.py store-handoff loop)."""
        import socket
        import threading
        import time

        from torchft_tpu.coordination import (
            LighthouseServer,
            ManagerServer,
            StoreClient,
            StoreServer,
        )

        lighthouse = LighthouseServer(min_replicas=1)
        store = StoreServer()
        sc = StoreClient(store.address())
        # a port with no listener = the dead incarnation's endpoint
        with socket.socket() as s:
            s.bind(("", 0))
            dead_port = s.getsockname()[1]
        sc.set("manager_addr", f"127.0.0.1:{dead_port}")
        sc.set("replica_id", "grp:dead-incarnation")

        server_box = {}

        def republish():
            time.sleep(0.7)
            server = ManagerServer(
                replica_id="grp:new-incarnation",
                lighthouse_addr=lighthouse.address(),
                store_address=store.address(),
                world_size=2,
                bind=":0",
                heartbeat_interval=0.1,
                connect_timeout=5.0,
                quorum_retries=0,
            )
            server_box["server"] = server
            # the store-handoff contract: replica_id BEFORE manager_addr
            # (a live addr implies the matching id is already visible)
            sc.set("replica_id", "grp:new-incarnation")
            sc.set("manager_addr", server.address())

        t = threading.Thread(target=republish, daemon=True)
        t.start()
        try:
            manager = Manager(
                pg=ProcessGroupDummy(),
                min_replica_size=1,
                load_state_dict=lambda sd: None,
                state_dict=lambda: {"x": np.zeros(1)},
                lighthouse_addr=lighthouse.address(),
                group_rank=1,
                group_world_size=2,
                store_addr=store.address(),
                connect_timeout=5.0,
            )
            # wired to the LIVE incarnation, not the stale published addr
            assert manager.replica_id() == "grp:new-incarnation"
            manager.shutdown()
        finally:
            t.join(timeout=5)
            if "server" in server_box:
                server_box["server"].shutdown()
            sc.close()
            store.shutdown()
            lighthouse.shutdown()

    def test_nonzero_rank_times_out_when_no_live_server_appears(self):
        import socket

        from torchft_tpu.coordination import LighthouseServer, StoreClient, StoreServer

        lighthouse = LighthouseServer(min_replicas=1)
        store = StoreServer()
        sc = StoreClient(store.address())
        with socket.socket() as s:
            s.bind(("", 0))
            dead_port = s.getsockname()[1]
        sc.set("manager_addr", f"127.0.0.1:{dead_port}")
        sc.set("replica_id", "grp:dead")
        try:
            with pytest.raises(TimeoutError, match="unreachable"):
                Manager(
                    pg=ProcessGroupDummy(),
                    min_replica_size=1,
                    load_state_dict=lambda sd: None,
                    state_dict=lambda: {"x": np.zeros(1)},
                    lighthouse_addr=lighthouse.address(),
                    group_rank=1,
                    group_world_size=2,
                    store_addr=store.address(),
                    connect_timeout=2.0,
                )
        finally:
            sc.close()
            store.shutdown()
            lighthouse.shutdown()
