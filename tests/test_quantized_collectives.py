"""Quantized collective correctness vs eager (reference:
torchft/quantization_test.py + collectives_test.py), plus the chunked
overlapped pipeline's invariants: bitwise parity with the monolithic
codec, bufpool steady-state, and mid-pipeline chaos."""

import threading
import time

import numpy as np
import pytest

from tests.test_process_group import make_group, run_parallel, store  # noqa: F401
from torchft_tpu.ops import quantization as q
from torchft_tpu.ops.collectives import allreduce_quantized, reduce_scatter_quantized
from torchft_tpu.parallel.process_group import REDUCE_AVG, REDUCE_SUM


class TestQuantization:
    def test_quantize_round_trip(self):
        rng = np.random.default_rng(0)
        a = rng.standard_normal((16, 256)).astype(np.float32)
        scales, payload = q.quantize(a)
        out = q.dequantize(scales, payload, a.shape, a.dtype)
        # int8 row-scale error bound: absmax/127 per element
        bound = (np.abs(a).max(axis=1, keepdims=True) / 127.0) * 0.51
        assert np.all(np.abs(out - a) <= bound + 1e-7)

    def test_pack_unpack(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        scales, payload = q.quantize(a)
        s2, p2 = q.unpack(q.pack(scales, payload), 3, 4)
        np.testing.assert_array_equal(scales, s2)
        np.testing.assert_array_equal(payload, p2)

    def test_zero_rows(self):
        a = np.zeros((4, 8), dtype=np.float32)
        scales, payload = q.quantize(a)
        out = q.dequantize(scales, payload, a.shape, a.dtype)
        np.testing.assert_array_equal(out, a)

    def test_reduce_quantized(self):
        rng = np.random.default_rng(1)
        arrays = [rng.standard_normal((4, 64)).astype(np.float32) for _ in range(3)]
        bufs = [q.pack(*q.quantize(a)) for a in arrays]
        reduced = q.reduce_quantized(bufs, 4, 64)
        scales, payload = q.unpack(reduced, 4, 64)
        out = q.dequantize(scales, payload, (4, 64), np.float32)
        expected = sum(arrays)
        assert np.abs(out - expected).max() < np.abs(expected).max() * 0.05


class TestQuantizedCollectives:
    @pytest.mark.parametrize("op", [REDUCE_SUM, REDUCE_AVG])
    def test_allreduce_quantized_vs_eager(self, store, op):  # noqa: F811
        world = 3
        pgs = make_group(store, world, prefix="qar")
        rng = np.random.default_rng(7)
        data = [
            [rng.standard_normal((33, 65)).astype(np.float32), rng.standard_normal(100).astype(np.float32)]
            for _ in range(world)
        ]
        expected = [sum(d[i] for d in data) for i in range(2)]
        if op == REDUCE_AVG:
            expected = [e / world for e in expected]

        def run(rank, _):
            return allreduce_quantized(data[rank], op, pgs[rank]).wait(timeout=30)

        for result in run_parallel(world, run):
            for got, want in zip(result, expected):
                assert got.shape == want.shape
                rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
                assert rel < 0.05, f"quantization error too large: {rel}"
        for pg in pgs:
            pg.shutdown()

    def test_allreduce_quantized_average_by(self, store):  # noqa: F811
        # Manager passes the live participant count (not pg size).
        world = 2
        pgs = make_group(store, world, prefix="qavg")
        data = [np.full((8, 16), 2.0, dtype=np.float32) for _ in range(world)]

        def run(rank, _):
            return allreduce_quantized(
                [data[rank]], REDUCE_AVG, pgs[rank], average_by=4
            ).wait(timeout=30)

        for result in run_parallel(world, run):
            np.testing.assert_allclose(result[0], np.full((8, 16), 1.0), rtol=0.02)
        for pg in pgs:
            pg.shutdown()

    def test_reduce_scatter_quantized(self, store):  # noqa: F811
        world = 2
        pgs = make_group(store, world, prefix="qrs")
        rng = np.random.default_rng(3)
        data = [rng.standard_normal((8, 32)).astype(np.float32) for _ in range(world)]
        expected = sum(data)

        def run(rank, _):
            return reduce_scatter_quantized(data[rank], REDUCE_SUM, pgs[rank]).wait(
                timeout=30
            )

        results = run_parallel(world, run)
        for rank, got in enumerate(results):
            want = expected[rank * 4 : (rank + 1) * 4]
            rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
            assert rel < 0.05
        for pg in pgs:
            pg.shutdown()

    def test_rejects_int_arrays(self, store):  # noqa: F811
        pgs = make_group(store, 2, prefix="qint")
        with pytest.raises(ValueError, match="floating point"):
            allreduce_quantized([np.ones(4, dtype=np.int32)], REDUCE_SUM, pgs[0])
        for pg in pgs:
            pg.shutdown()

    def test_device_quantize_matches_host_path(self, store):  # noqa: F811
        """The Pallas (device) quantizer must produce bitwise-identical
        collective results to the host codec — they share the wire format
        (reference integration: torchft/collectives.py:297-415)."""
        import jax.numpy as jnp

        world = 2
        pgs_d = make_group(store, world, prefix="qdev")
        pgs_h = make_group(store, world, prefix="qhost")
        rng = np.random.default_rng(11)
        # big enough that the (rows, 2048) padding is amortized and the
        # wire-byte ratio approaches the codec's 4x
        data = [
            [
                rng.standard_normal((256, 300)).astype(np.float32),
                rng.standard_normal(5000).astype(np.float32),
            ]
            for _ in range(world)
        ]

        def run_device(rank, _):
            # jax arrays + explicit flag exercises the Pallas path (in
            # interpreter mode off-TPU)
            arrays = [jnp.asarray(a) for a in data[rank]]
            w = allreduce_quantized(
                arrays, REDUCE_SUM, pgs_d[rank], device_quantize=True
            )
            out = w.wait(timeout=30)
            return out, w.wire_bytes, w.unquantized_wire_bytes

        def run_host(rank, _):
            return allreduce_quantized(
                data[rank], REDUCE_SUM, pgs_h[rank], device_quantize=False
            ).wait(timeout=30)

        dev_results = run_parallel(world, run_device)
        host_results = run_parallel(world, run_host)
        # The two paths share the wire format but intentionally diverge on
        # a rank's OWN slice: the host path feeds it into the reduce as
        # raw f32 (zero codec error on own data), while the device path
        # quantizes the full matrix in one Pallas launch before the
        # device->host copy.  So: every rank agrees bitwise WITHIN a path
        # (each slice is reduced by exactly one owner, then allgathered),
        # and across paths the results agree to quantization error.
        for arrs in zip(*(r[0] for r in dev_results)):
            for other in arrs[1:]:
                np.testing.assert_array_equal(np.asarray(arrs[0]), np.asarray(other))
        for arrs in zip(*host_results):
            for other in arrs[1:]:
                np.testing.assert_array_equal(arrs[0], other)
        true_sums = [sum(d[i] for d in data) for i in range(2)]
        for (dev_out, wire, unq), host_out in zip(dev_results, host_results):
            for d_arr, h_arr, want in zip(dev_out, host_out, true_sums):
                scale = np.abs(want).max() + 1e-9
                rel_d = np.abs(np.asarray(d_arr) - want).max() / scale
                rel_h = np.abs(h_arr - want).max() / scale
                assert rel_d < 0.05 and rel_h < 0.05, (rel_d, rel_h)
                # the raw-own-slice host path must not be LESS accurate
                # than the all-quantized device path (small tolerance:
                # rounding interplay can tip individual elements)
                assert rel_h <= rel_d * 1.05 + 1e-6, (rel_h, rel_d)
            # measured wire-byte reduction: int8 payload + f32 row scales
            # vs f32 — must be close to 4x for these sizes
            assert wire < unq / 3.5, (wire, unq)
        for pg in pgs_d + pgs_h:
            pg.shutdown()

    def test_manager_quantized_allreduce_device_leaves(self):
        """Manager.allreduce(should_quantize=True) accepts jax-array pytrees
        and routes them through the quantized collective unconverted (the
        device leaves stay device-side until the codec's int8 hop)."""
        import jax.numpy as jnp

        from torchft_tpu.coordination import LighthouseServer
        from torchft_tpu.manager import Manager
        from torchft_tpu.parallel.process_group import ProcessGroupTCP

        lighthouse = LighthouseServer(
            min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
        )
        managers = []
        try:
            for r in range(2):
                managers.append(
                    Manager(
                        pg=ProcessGroupTCP(timeout=20.0),
                        min_replica_size=2,
                        load_state_dict=lambda sd: None,
                        state_dict=lambda: {"x": np.zeros(1)},
                        lighthouse_addr=lighthouse.address(),
                        replica_id=f"qmgr_{r}",
                        group_rank=0,
                        group_world_size=1,
                        use_async_quorum=True,
                        timeout=20.0,
                        quorum_timeout=20.0,
                        # both replicas join fresh at step 0; without this
                        # one of them would heal and contribute zeros
                        init_sync=False,
                    )
                )
            value = {"g": jnp.full((64, 64), 2.0, dtype=jnp.float32)}

            def run(rank, _):
                m = managers[rank]
                m.start_quorum()
                out = m.allreduce(value, should_quantize=True).wait(timeout=30)
                assert m.should_commit()
                return out

            for result in run_parallel(2, run):
                np.testing.assert_allclose(
                    np.asarray(result["g"]), np.full((64, 64), 2.0), rtol=0.02
                )
        finally:
            for m in managers:
                m.shutdown()
            lighthouse.shutdown()


def test_quantize_subnormal_rows_stay_finite():
    """Rows whose absmax is below 127/f32max would overflow the reciprocal
    scale to inf (NaN payloads); they must encode as exact zeros instead."""
    from torchft_tpu.ops import quantization as q

    a = np.full((3, 64), 1e-38, dtype=np.float32)
    a[1] = 0.0
    a[2] = 1.0  # a normal row for contrast
    scales, payload = q.quantize(a)
    assert np.all(np.isfinite(scales))
    out = q.dequantize(scales, payload, a.shape, np.float32)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[0], 0.0)  # sub-quantizable -> zero
    np.testing.assert_array_equal(out[1], 0.0)
    np.testing.assert_allclose(out[2], 1.0, atol=1e-2)


class TestFp8Wire:
    """fp8_e4m3 wire format (the reference's SM90 fp8e4nv analog,
    torchft/quantization.py:30-41): same 1 byte/element wire size as int8,
    host codec only (device kernel path stays int8, mirroring the
    reference's hardware gating)."""

    def test_codec_round_trip(self):
        rng = np.random.default_rng(3)
        a = rng.standard_normal((16, 256)).astype(np.float32)
        scales, payload = q.quantize(a, q.WIRE_FP8)
        assert payload.itemsize == 1
        out = q.dequantize(scales, payload, a.shape, a.dtype)
        # e4m3 relative step is 2^-3 of the exponent bucket; bound per
        # element by absmax/448 * (448/|x| rounding) <= |x| * 2^-3 + lsb
        bound = np.abs(a) * (2.0 ** -3) + (
            np.abs(a).max(axis=1, keepdims=True) / 448.0
        )
        assert np.all(np.abs(out - a) <= bound + 1e-7)

    def test_pack_unpack_fp8(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        scales, payload = q.quantize(a, q.WIRE_FP8)
        s2, p2 = q.unpack(
            q.pack(scales, payload, q.WIRE_FP8), 3, 4, q.WIRE_FP8
        )
        np.testing.assert_array_equal(scales, s2)
        np.testing.assert_array_equal(
            payload.view(np.uint8), p2.view(np.uint8)
        )

    def test_allreduce_fp8_wire(self, store):  # noqa: F811
        world = 2
        pgs = make_group(store, world, prefix="fp8ar")
        rng = np.random.default_rng(11)
        data = [
            [rng.standard_normal((40, 50)).astype(np.float32)]
            for _ in range(world)
        ]
        expected = sum(d[0] for d in data)

        def run(rank, _):
            w = allreduce_quantized(
                data[rank], REDUCE_SUM, pgs[rank], wire_dtype=q.WIRE_FP8
            )
            out = w.wait(timeout=30)
            return out, w.wire_bytes, w.wire_dtype

        results = run_parallel(world, run)
        for (got,), wire_bytes, wd in results:
            assert wd == q.WIRE_FP8
            rel = np.abs(got - expected).max() / np.abs(expected).max()
            assert rel < 0.1, f"fp8 error too large: {rel}"
        # identical wire size to the int8 leg (1 byte payload + f32 scales)
        def run_int8(rank, _):
            w = allreduce_quantized(data[rank], REDUCE_SUM, pgs[rank])
            w.wait(timeout=30)
            return w.wire_bytes

        int8_bytes = run_parallel(world, run_int8)
        assert results[0][1] == int8_bytes[0]
        for pg in pgs:
            pg.shutdown()

    def test_reduce_scatter_fp8(self, store):  # noqa: F811
        world = 2
        pgs = make_group(store, world, prefix="fp8rs")
        rng = np.random.default_rng(12)
        data = [rng.standard_normal((8, 6)).astype(np.float32) for _ in range(world)]
        expected = sum(data)

        def run(rank, _):
            return reduce_scatter_quantized(
                data[rank], REDUCE_SUM, pgs[rank], wire_dtype=q.WIRE_FP8
            ).wait(timeout=30)

        results = run_parallel(world, run)
        for rank, got in enumerate(results):
            want = expected[rank * 4 : (rank + 1) * 4]
            rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
            assert rel < 0.1
        for pg in pgs:
            pg.shutdown()

    def test_device_quantize_rejects_fp8(self, store):  # noqa: F811
        (pg,) = make_group(store, 1, prefix="fp8dev")
        with pytest.raises(ValueError, match="int8 wire only"):
            allreduce_quantized(
                [np.ones(4, np.float32)], REDUCE_SUM, pg,
                device_quantize=True, wire_dtype=q.WIRE_FP8,
            )
        pg.shutdown()

    def test_env_default_wire(self, store, monkeypatch):  # noqa: F811
        monkeypatch.setenv("TORCHFT_QUANT_WIRE", q.WIRE_FP8)
        world = 2
        pgs = make_group(store, world, prefix="fp8env")

        def run(rank, _):
            w = allreduce_quantized(
                [np.full(8, float(rank + 1), np.float32)], REDUCE_SUM, pgs[rank]
            )
            w.wait(timeout=30)
            return w.wire_dtype

        assert set(run_parallel(world, run)) == {q.WIRE_FP8}
        for pg in pgs:
            pg.shutdown()

    def test_unknown_wire_rejected(self, store):  # noqa: F811
        (pg,) = make_group(store, 1, prefix="badwire")
        with pytest.raises(ValueError, match="wire_dtype"):
            allreduce_quantized(
                [np.ones(4, np.float32)], REDUCE_SUM, pg, wire_dtype="int4"
            )
        pg.shutdown()

    def test_wire_mismatch_fails_loudly(self):
        # divergent TORCHFT_QUANT_WIRE across ranks must error at unpack,
        # never silently decode the other grid (the on-wire header check)
        a = np.arange(8, dtype=np.float32).reshape(2, 4)
        buf = q.pack(*q.quantize(a, q.WIRE_FP8), q.WIRE_FP8)
        with pytest.raises(ValueError, match="wire format mismatch"):
            q.unpack(buf, 2, 4, q.WIRE_INT8)
        buf8 = q.pack(*q.quantize(a))
        with pytest.raises(ValueError, match="wire format mismatch"):
            q.unpack(buf8, 2, 4, q.WIRE_FP8)

    def test_reduce_scatter_env_default(self, store, monkeypatch):  # noqa: F811
        monkeypatch.setenv("TORCHFT_QUANT_WIRE", q.WIRE_FP8)
        world = 2
        pgs = make_group(store, world, prefix="fp8rsenv")
        data = [np.full((4, 4), float(r + 1), np.float32) for r in range(world)]

        def run(rank, _):
            return reduce_scatter_quantized(
                data[rank], REDUCE_SUM, pgs[rank]
            ).wait(timeout=30)

        for rank, got in enumerate(run_parallel(world, run)):
            np.testing.assert_allclose(got, 3.0, rtol=0.1)
        for pg in pgs:
            pg.shutdown()

    def test_cross_rank_wire_mismatch_fails_loudly(self, store):  # noqa: F811
        # two ranks with DIVERGENT wire settings (the partial-rollout
        # hazard): the allreduce must error on the header check, never
        # resolve with silently mis-decoded gradients
        world = 2
        pgs = make_group(store, world, prefix="wiremix", timeout=5.0)
        data = [np.ones(64, np.float32) for _ in range(world)]

        def run(rank, _):
            wd = q.WIRE_FP8 if rank == 0 else q.WIRE_INT8
            try:
                out = allreduce_quantized(
                    [data[rank]], REDUCE_SUM, pgs[rank], wire_dtype=wd
                ).wait(timeout=10)
            except Exception as e:  # noqa: BLE001
                return e
            return out

        results = run_parallel(world, run)
        assert all(isinstance(r, Exception) for r in results), results
        assert any("wire format mismatch" in str(r) for r in results), results
        for pg in pgs:
            pg.shutdown()

    def test_contribution_snapshotted_at_call_time(self, store):  # noqa: F811
        """Mutating the input array AFTER submitting the collective must
        not change any rank's contribution: peer slices quantize
        synchronously and the own slice is snapshotted at call time (it
        enters the reduce as raw f32 later, asynchronously)."""
        world = 2
        pgs = make_group(store, world, prefix="qsnap")
        data = [np.full(4096, 1.0 + r, dtype=np.float32) for r in range(world)]
        expected = np.full(4096, 3.0, dtype=np.float32)
        barrier = threading.Barrier(world)

        def run(rank, _):
            w = allreduce_quantized([data[rank]], REDUCE_SUM, pgs[rank])
            data[rank][:] = -999.0  # caller reuses its buffer immediately
            barrier.wait(timeout=10)
            return w.wait(timeout=30)

        for result in run_parallel(world, run):
            rel = np.abs(result[0] - expected).max() / 3.0
            assert rel < 0.05, f"mutated input leaked into the reduction: {rel}"
        for pg in pgs:
            pg.shutdown()

    def test_reduce_scatter_contribution_snapshotted(self, store):  # noqa: F811
        world = 2
        pgs = make_group(store, world, prefix="qsnaprs")
        data = [np.full((8, 512), 1.0 + r, dtype=np.float32) for r in range(world)]

        def run(rank, _):
            w = reduce_scatter_quantized(data[rank], REDUCE_SUM, pgs[rank])
            data[rank][:] = -999.0
            return w.wait(timeout=30)

        for rank, got in enumerate(run_parallel(world, run)):
            rel = np.abs(got - 3.0).max() / 3.0
            assert rel < 0.05, f"mutated input leaked into the reduction: {rel}"
        for pg in pgs:
            pg.shutdown()

    def test_reduce_scatter_wire_accounting(self, store):  # noqa: F811
        world = 2
        pgs = make_group(store, world, prefix="qrsw")
        data = [np.ones((8, 512), dtype=np.float32) for _ in range(world)]

        def run(rank, _):
            w = reduce_scatter_quantized(data[rank], REDUCE_SUM, pgs[rank])
            w.wait(timeout=30)
            return w.wire_bytes, w.unquantized_wire_bytes, w.wire_dtype

        for wire, unq, dt in run_parallel(world, run):
            assert dt == "int8"
            # half the rows cross the wire, quantized ~4x smaller
            assert unq == 4 * 4 * 512  # f32 bytes of the peer's slice
            assert 0 < wire < unq / 3.5, (wire, unq)
        for pg in pgs:
            pg.shutdown()


# ---------------------------------------------------------------------------
# chunked overlapped pipeline (the r6 rebuild)
# ---------------------------------------------------------------------------

# Big enough that the (rows, 2048) flat matrix yields multi-row rank
# slices (slice_rows ~ 49 at world 3), so small TORCHFT_QUANT_CHUNK_ROWS
# values produce real multi-chunk pipelines including a padded-tail chunk
# (total is NOT a multiple of 2048, and rows pad up to a world multiple).
_PIPE_SHAPES = ((100, 501), (50_000,))


def _pipe_data(world: int, seed: int = 5):
    rng = np.random.default_rng(seed)
    return [
        [rng.standard_normal(s).astype(np.float32) for s in _PIPE_SHAPES]
        for _ in range(world)
    ]


def _run_quantized(pgs, data, wire_dtype, op=REDUCE_SUM):
    def run(rank, _):
        w = allreduce_quantized(
            data[rank], op, pgs[rank], wire_dtype=wire_dtype
        )
        out = w.wait(timeout=30)
        return out, dict(w.quant_stats), w.wire_bytes

    return run_parallel(len(pgs), run)


class TestFp8VsInt8Accuracy:
    """The measured fp8_e4m3 justification (ROADMAP item 1 tail /
    ISSUE 8 satellite): on HEAVY-TAILED pseudogradients — rows whose
    absmax is dominated by outliers, the regime DiLoCo pseudograds drift
    into as fragments diverge — int8's uniform grid burns its 8 bits on
    the outlier range and fp8's exponent grid wins decisively.  On
    well-conditioned (near-Gaussian) rows int8 keeps the better RMSE, so
    int8 stays the default wire.  docs/benchmarks.md carries the
    measured table this test pins."""

    @staticmethod
    def _codec_err(a: np.ndarray, wire: str) -> "tuple[float, float]":
        scales, payload = q.quantize(a, wire)
        out = q.dequantize(scales, payload, a.shape, a.dtype)
        e = out - a
        rmse = float(np.sqrt(np.mean(e**2)))
        mean_rel = float(np.mean(np.abs(e) / (np.abs(a) + 1e-12)))
        return rmse, mean_rel

    def test_fp8_wins_on_heavy_tailed_rows(self):
        rng = np.random.default_rng(42)
        # student-t(2): infinite variance — every row carries outliers
        heavy = rng.standard_t(2, (256, 2048)).astype(np.float32)
        i8_rmse, i8_rel = self._codec_err(heavy, q.WIRE_INT8)
        f8_rmse, f8_rel = self._codec_err(heavy, q.WIRE_FP8)
        # measured margins (seed 42): rmse 0.249 vs 0.067, mean rel
        # 0.316 vs 0.023 — assert the conservative halves of those gaps
        assert f8_rmse < i8_rmse / 2, (f8_rmse, i8_rmse)
        assert f8_rel < i8_rel / 4, (f8_rel, i8_rel)

    def test_fp8_wins_on_outlier_spiked_rows(self):
        rng = np.random.default_rng(7)
        # laplace body with 0.1% 50x outliers: the "one huge coordinate
        # per row" shape that wrecks absmax-scaled uniform grids
        a = (
            rng.laplace(0, 1, (256, 2048))
            * (1 + 50 * (rng.random((256, 2048)) < 1e-3))
        ).astype(np.float32)
        i8_rmse, i8_rel = self._codec_err(a, q.WIRE_INT8)
        f8_rmse, f8_rel = self._codec_err(a, q.WIRE_FP8)
        assert f8_rmse < i8_rmse / 2, (f8_rmse, i8_rmse)
        assert f8_rel < i8_rel / 4, (f8_rel, i8_rel)

    def test_int8_stays_default_on_gaussian_rows(self):
        rng = np.random.default_rng(42)
        gauss = rng.standard_normal((256, 2048)).astype(np.float32)
        i8_rmse, _ = self._codec_err(gauss, q.WIRE_INT8)
        f8_rmse, _ = self._codec_err(gauss, q.WIRE_FP8)
        # uniform grid fits the compact range ~3x better in RMSE — the
        # reason int8 remains the default for well-conditioned grads
        assert i8_rmse < f8_rmse / 2, (i8_rmse, f8_rmse)


class TestChunkedPipeline:
    """Bitwise parity of the chunked pipeline vs the monolithic codec
    (K=1), bufpool steady-state, and the overlap accounting surface."""

    @pytest.mark.parametrize("wire_dtype", [q.WIRE_INT8, q.WIRE_FP8])
    def test_chunked_bitwise_parity_world3(
        self, store, monkeypatch, wire_dtype  # noqa: F811
    ):
        """Chunked vs monolithic output must be BIT-identical for both
        wire formats — world 3 exercises uneven global row slicing and a
        zero-padded tail chunk."""
        world = 3
        data = _pipe_data(world)
        pgs = make_group(store, world, prefix=f"pmono{wire_dtype}")
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        mono = _run_quantized(pgs, data, wire_dtype)
        for pg in pgs:
            pg.shutdown()
        assert mono[0][1]["n_chunks"] == 1

        pgs = make_group(store, world, prefix=f"pchunk{wire_dtype}")
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "4")
        chunked = _run_quantized(pgs, data, wire_dtype, op=REDUCE_AVG)
        # AVG vs SUM differ; rerun monolithic AVG for the comparison
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs2 = make_group(store, world, prefix=f"pmonoA{wire_dtype}")
        mono_avg = _run_quantized(pgs2, data, wire_dtype, op=REDUCE_AVG)
        for pg in pgs + pgs2:
            pg.shutdown()

        assert chunked[0][1]["n_chunks"] > 2, chunked[0][1]
        for (mono_out, _, _), (chunk_out, _, _) in zip(mono_avg, chunked):
            for m, c in zip(mono_out, chunk_out):
                np.testing.assert_array_equal(m, c)

    @pytest.mark.parametrize("wire_dtype", [q.WIRE_INT8, q.WIRE_FP8])
    def test_chunked_parity_numpy_fallback(
        self, store, monkeypatch, wire_dtype  # noqa: F811
    ):
        """The numpy codec path must satisfy the same chunked-vs-
        monolithic bit identity for BOTH wire formats (its per-row math
        is shared, but the row-range plumbing — incl. the fp8 astype
        widen leg — differs)."""
        monkeypatch.setenv("TORCHFT_NO_NATIVE_QUANT", "1")
        world = 2
        data = _pipe_data(world, seed=9)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix=f"pnpm{wire_dtype}")
        mono = _run_quantized(pgs, data, wire_dtype)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "7")
        pgs2 = make_group(store, world, prefix=f"pnpc{wire_dtype}")
        chunked = _run_quantized(pgs2, data, wire_dtype)
        for pg in pgs + pgs2:
            pg.shutdown()
        assert chunked[0][1]["n_chunks"] > 2
        for (mono_out, _, _), (chunk_out, _, _) in zip(mono, chunked):
            for m, c in zip(mono_out, chunk_out):
                np.testing.assert_array_equal(m, c)

    def test_chunked_device_path_parity(
        self, store, monkeypatch  # noqa: F811
    ):
        """Device (Pallas) quantize feeds the same chunk queue: chunked
        device-path output is bit-identical to monolithic device-path
        output (one kernel launch either way; per-chunk device→host
        copies must not change a byte)."""
        import jax.numpy as jnp

        world = 2
        data = _pipe_data(world, seed=11)

        def run_dev(pgs):
            def run(rank, _):
                arrays = [jnp.asarray(a) for a in data[rank]]
                w = allreduce_quantized(
                    arrays, REDUCE_SUM, pgs[rank], device_quantize=True
                )
                return w.wait(timeout=60), dict(w.quant_stats)

            return run_parallel(world, run)

        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix="pdevm")
        mono = run_dev(pgs)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs2 = make_group(store, world, prefix="pdevc")
        chunked = run_dev(pgs2)
        for pg in pgs + pgs2:
            pg.shutdown()
        assert chunked[0][1]["n_chunks"] > 1
        for (mono_out, _), (chunk_out, _) in zip(mono, chunked):
            for m, c in zip(mono_out, chunk_out):
                np.testing.assert_array_equal(np.asarray(m), np.asarray(c))

    def test_chunked_reduce_scatter_parity(
        self, store, monkeypatch  # noqa: F811
    ):
        world = 2
        rng = np.random.default_rng(3)
        data = [
            rng.standard_normal((64, 700)).astype(np.float32)
            for _ in range(world)
        ]

        def run_rs(pgs):
            def run(rank, _):
                return reduce_scatter_quantized(
                    data[rank], REDUCE_SUM, pgs[rank]
                ).wait(timeout=30)

            return run_parallel(world, run)

        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix="prsm")
        mono = run_rs(pgs)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "7")
        pgs2 = make_group(store, world, prefix="prsc")
        chunked = run_rs(pgs2)
        for pg in pgs + pgs2:
            pg.shutdown()
        for m, c in zip(mono, chunked):
            np.testing.assert_array_equal(m, c)

    def test_wire_accounting_independent_of_chunking(
        self, store, monkeypatch  # noqa: F811
    ):
        """Per-chunk headers aside, wire bytes must not balloon with K,
        and the ~4x reduction vs f32 holds at any chunking."""
        world = 2
        data = _pipe_data(world, seed=2)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", str(10**9))
        pgs = make_group(store, world, prefix="pwm")
        mono = _run_quantized(pgs, data, q.WIRE_INT8)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "4")
        pgs2 = make_group(store, world, prefix="pwc")
        chunked = _run_quantized(pgs2, data, q.WIRE_INT8)
        for pg in pgs + pgs2:
            pg.shutdown()
        wire_mono, wire_chunk = mono[0][2], chunked[0][2]
        k = chunked[0][1]["n_chunks"]
        assert k > 2
        # chunking adds exactly (K-1) extra 4-byte pack headers per hop
        # direction pair vs the monolithic buffer
        assert wire_mono < wire_chunk <= wire_mono + 2 * (world - 1) * 4 * k
        total = sum(int(np.prod(s)) for s in _PIPE_SHAPES)
        assert wire_chunk < 4 * total / 3.0  # still ~4x under f32

    def test_overlap_stats_surface(self, store, monkeypatch):  # noqa: F811
        """quant_stats carries the pipeline accounting bench consumes."""
        world = 2
        data = _pipe_data(world, seed=4)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs = make_group(store, world, prefix="postats")
        results = _run_quantized(pgs, data, q.WIRE_INT8)
        for pg in pgs:
            pg.shutdown()
        for _, stats, _ in results:
            assert stats["n_chunks"] >= 1
            assert stats["codec_s"] >= 0.0
            assert stats["wire_s"] >= 0.0
            assert stats["wall_s"] > 0.0
            assert 0.0 <= stats["overlap_efficiency"] <= 1.0

    def test_bufpool_steady_state_no_growth(
        self, store, monkeypatch  # noqa: F811
    ):
        """After one warm collective of a given shape, a repeat takes
        every staging buffer — wire bufs, accumulators, reduced pieces,
        pool-backed receives — from the pool: zero new allocations
        (misses) in steady state.

        Cross-rank give/take ordering can jitter by one buffer under
        full-suite load (a taker racing the previous round's returner),
        so the zero-growth bar is required of ANY repeat out of three,
        not the first: a genuinely non-recycling staging buffer misses
        on EVERY repeat, so detection power is unchanged while one-off
        scheduling jitter stops failing the suite."""
        from torchft_tpu.utils.bufpool import POOL

        world = 2
        data = _pipe_data(world, seed=6)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs = make_group(store, world, prefix="ppool")
        _run_quantized(pgs, data, q.WIRE_INT8)  # warm: populates the pool
        growth: "list[int]" = []
        try:
            for _attempt in range(3):
                misses_before = POOL.misses
                results = _run_quantized(pgs, data, q.WIRE_INT8)
                growth.append(POOL.misses - misses_before)
                assert results[0][1]["n_chunks"] > 2
                if growth[-1] == 0:
                    break
        finally:
            for pg in pgs:
                pg.shutdown()
        assert growth[-1] == 0, (
            f"steady-state pool misses grew on every repeat: {growth} "
            f"(a staging buffer is not recycling)"
        )


class TestChunkedChaos:
    def test_fault_mid_pipeline_drains_and_recovers(
        self, store, monkeypatch  # noqa: F811
    ):
        """An injected pg.allreduce.chunk failure MID-pipeline (step =
        chunk index 1: after chunk 0's alltoall is already on the wire)
        must fail the Work promptly on every rank — abort drains the
        codec workers, nothing deadlocks (tier-1 runs with
        TORCHFT_LOCKCHECK=1 armed) — and the SAME process groups must
        complete a clean collective afterwards (op streams left in
        sync)."""
        from torchft_tpu.utils import faults
        from torchft_tpu.utils.faults import FaultRule, InjectedFault

        world = 2
        data = _pipe_data(world, seed=8)
        monkeypatch.setenv("TORCHFT_QUANT_CHUNK_ROWS", "8")
        pgs = make_group(store, world, prefix="pchaos")
        # pg.allreduce.chunk carries the CHUNK index (the pg.allreduce
        # site keeps its training-step namespace); times=world lets BOTH
        # ranks' drivers (sharing this process's registry) inject at
        # chunk 1 and stop submitting at the same point in the op stream
        faults.FAULTS.configure(
            [FaultRule(site="pg.allreduce.chunk", step=1, times=world)],
            seed=1,
        )

        def run(rank, _):
            w = allreduce_quantized([data[rank][1]], REDUCE_SUM, pgs[rank])
            t0 = time.perf_counter()
            try:
                w.wait(timeout=30)
                return None, 0.0
            except Exception as e:  # noqa: BLE001
                return e, time.perf_counter() - t0

        results = run_parallel(world, run)
        for exc, elapsed in results:
            assert isinstance(exc, InjectedFault), exc
            assert elapsed < 20.0, "mid-pipeline abort did not drain promptly"
        assert faults.FAULTS.injected("pg.allreduce.chunk") == world

        # recovery on the SAME pgs: both ranks aborted at the same chunk,
        # so the sockets' op streams are still in lockstep
        faults.FAULTS.configure([], seed=0)
        expected = [sum(d[1] for d in data)]
        clean = _run_quantized(pgs, [[d[1]] for d in data], q.WIRE_INT8)
        for out, _, _ in clean:
            rel = np.abs(out[0] - expected[0]).max() / (
                np.abs(expected[0]).max() + 1e-9
            )
            assert rel < 0.05, rel
        for pg in pgs:
            pg.shutdown()
