"""Unit tests for the unified retry policy (torchft_tpu/utils/retry.py):
jitter bounds, deadline budgets never exceeded, exception classification,
attempt accounting, and the abort-on-attempt-timeout wiring."""

import random
import threading
import time

import pytest

from torchft_tpu.utils import metrics
from torchft_tpu.utils.retry import RetryPolicy


class Flaky:
    """Raises ``exc`` for the first ``failures`` calls, then returns ok."""

    def __init__(self, failures: int, exc: BaseException = ConnectionError("boom")):
        self.failures = failures
        self.exc = exc
        self.calls = 0
        self.budgets = []

    def __call__(self, budget):
        self.calls += 1
        self.budgets.append(budget)
        if self.calls <= self.failures:
            raise self.exc
        return "ok"


class TestBackoff:
    def test_full_jitter_bounds(self):
        policy = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=1.0)
        rng = random.Random(0)
        for attempt in range(20):
            cap = min(1.0, 0.1 * 2.0**attempt)
            for _ in range(50):
                d = policy.backoff(attempt, rng)
                assert 0.0 <= d <= cap, (attempt, d, cap)

    def test_jitter_disabled_is_deterministic_cap(self):
        policy = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=1.0, jitter=False)
        assert policy.backoff(0) == pytest.approx(0.1)
        assert policy.backoff(2) == pytest.approx(0.4)
        assert policy.backoff(10) == pytest.approx(1.0)  # capped

    def test_backoff_seeded_reproducible(self):
        policy = RetryPolicy()
        a = [policy.backoff(i, random.Random(5)) for i in range(8)]
        b = [policy.backoff(i, random.Random(5)) for i in range(8)]
        assert a == b


class TestDeadline:
    def test_total_budget_never_exceeded(self):
        policy = RetryPolicy(base_delay=0.02, max_delay=0.05)
        fn = Flaky(failures=10**9)
        t0 = time.monotonic()
        with pytest.raises(TimeoutError) as ei:
            policy.run(fn, timeout=0.4, op="test.budget")
        elapsed = time.monotonic() - t0
        # sleeps are clamped to the remaining budget, so overshoot is at
        # most one (fast) attempt's duration
        assert elapsed < 0.4 + 0.2, elapsed
        assert fn.calls >= 2
        assert isinstance(ei.value.__cause__, ConnectionError)

    def test_zero_budget_raises_before_first_attempt(self):
        policy = RetryPolicy()
        fn = Flaky(failures=0)
        with pytest.raises(TimeoutError):
            policy.run(fn, timeout=0.0)
        assert fn.calls == 0

    def test_attempts_receive_remaining_budget(self):
        policy = RetryPolicy(base_delay=0.01, max_delay=0.01, jitter=False)
        fn = Flaky(failures=2)
        assert policy.run(fn, timeout=5.0) == "ok"
        assert len(fn.budgets) == 3
        # budgets monotonically shrink toward the shared deadline
        assert fn.budgets[0] <= 5.0
        assert fn.budgets[0] > fn.budgets[1] > fn.budgets[2]

    def test_attempt_timeout_clamped_to_remaining(self):
        policy = RetryPolicy(attempt_timeout=10.0)
        fn = Flaky(failures=0)
        policy.run(fn, timeout=1.0)
        assert fn.budgets[0] <= 1.0  # clamped below attempt_timeout

    def test_unbounded_run_passes_none_budget(self):
        policy = RetryPolicy()
        fn = Flaky(failures=0)
        assert policy.run(fn) == "ok"
        assert fn.budgets == [None]


class TestClassification:
    def test_non_retryable_raises_immediately(self):
        policy = RetryPolicy(retryable=(ConnectionError,))
        fn = Flaky(failures=5, exc=ValueError("not transient"))
        with pytest.raises(ValueError):
            policy.run(fn, timeout=5.0)
        assert fn.calls == 1

    def test_max_attempts_reraises_original(self):
        policy = RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.001)
        fn = Flaky(failures=10)
        with pytest.raises(ConnectionError):
            policy.run(fn, timeout=5.0)
        assert fn.calls == 3

    def test_retry_if_predicate_overrides_types(self):
        class Weird(Exception):
            pass

        policy = RetryPolicy(
            base_delay=0.001,
            max_delay=0.001,
            retry_if=lambda e: isinstance(e, Weird),
        )
        ok = Flaky(failures=2, exc=Weird())
        assert policy.run(ok, timeout=5.0) == "ok"
        # the predicate replaces the type tuple entirely
        no = Flaky(failures=2, exc=ConnectionError("x"))
        with pytest.raises(ConnectionError):
            policy.run(no, timeout=5.0)
        assert no.calls == 1

    def test_attempt_timeout_retryable_by_default_but_not_when_narrowed(self):
        # TimeoutError subclasses OSError (PEP 3151), so the default tuple
        # retries per-attempt socket timeouts...
        policy = RetryPolicy(base_delay=0.001, max_delay=0.001)
        fn = Flaky(failures=3, exc=TimeoutError("attempt timed out"))
        assert policy.run(fn, timeout=5.0) == "ok"
        # ...while deadline-owning policies narrow to ConnectionError and
        # surface the expiry immediately (the manager.quorum stance)
        narrow = RetryPolicy(retryable=(ConnectionError,))
        fn2 = Flaky(failures=3, exc=TimeoutError("attempt timed out"))
        with pytest.raises(TimeoutError):
            narrow.run(fn2, timeout=5.0)
        assert fn2.calls == 1


class TestObservability:
    def test_retry_counter_and_on_retry(self):
        before = metrics.RETRIES.labels(op="test.obs").get()
        seen = []
        policy = RetryPolicy(base_delay=0.001, max_delay=0.001)
        fn = Flaky(failures=2)
        policy.run(
            fn,
            timeout=5.0,
            op="test.obs",
            on_retry=lambda e, n, d: seen.append((type(e).__name__, n, d)),
        )
        assert metrics.RETRIES.labels(op="test.obs").get() == before + 2
        assert [s[:2] for s in seen] == [("ConnectionError", 1), ("ConnectionError", 2)]
        assert all(d >= 0 for _, _, d in seen)


class TestAbortCallback:
    def test_abort_cb_fires_on_attempt_timeout(self):
        """A wedged attempt must be actively cancelled: abort_cb (the
        pg.abort analog) fires at the attempt deadline and unwedges it."""
        aborted = threading.Event()
        unwedge = threading.Event()

        def abort():
            aborted.set()
            unwedge.set()

        calls = []

        def fn(budget):
            calls.append(budget)
            if len(calls) == 1:
                # simulate a wedged socket wait that only the abort releases
                assert unwedge.wait(timeout=5.0), "abort_cb never fired"
                raise ConnectionError("aborted mid-attempt")
            return "ok"

        policy = RetryPolicy(
            base_delay=0.001, max_delay=0.001, attempt_timeout=0.1
        )
        assert policy.run(fn, timeout=10.0, abort_cb=abort) == "ok"
        assert aborted.is_set()
        assert len(calls) == 2
