"""Flight recorder unit tests: ring semantics, in-flight ops, dumps,
env knobs, and the hot-path overhead budget (acceptance: ~2 us/record,
the same bar as the metrics layer's observe)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from torchft_tpu.utils import flightrecorder as fr


class TestRing:
    def test_record_and_snapshot_order(self):
        rec = fr.FlightRecorder(capacity=8)
        for i in range(5):
            rec.record("op", step=i)
        snap = rec.snapshot()
        assert [r["step"] for r in snap] == [0, 1, 2, 3, 4]
        assert all(r["status"] == "ok" for r in snap)
        assert all(r["end_ns"] >= r["start_ns"] for r in snap)

    def test_ring_wraps_keeping_newest(self):
        rec = fr.FlightRecorder(capacity=4)
        for i in range(10):
            rec.record("op", step=i)
        snap = rec.snapshot()
        assert [r["step"] for r in snap] == [6, 7, 8, 9]
        assert rec.total_recorded() == 10

    def test_env_ring_capacity(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_RING", "3")
        rec = fr.FlightRecorder()
        for i in range(5):
            rec.record("op", step=i)
        assert [r["step"] for r in rec.snapshot()] == [2, 3, 4]
        monkeypatch.setenv("TORCHFT_FLIGHT_RING", "bogus")
        assert fr.FlightRecorder()._cap == 512  # falls back to the default

    def test_clear(self):
        rec = fr.FlightRecorder(capacity=4)
        rec.record("op")
        rec.start("open_op")
        rec.clear()
        assert rec.snapshot() == []


class TestFlightOp:
    def test_inflight_visible_then_completed(self):
        rec = fr.FlightRecorder(capacity=8)
        op = rec.start("allreduce", rank=0, world=2, replica_id="r0")
        snap = rec.snapshot()
        assert len(snap) == 1 and snap[0]["status"] == "inflight"
        op.update(recv_peer=1, recv_tag=100)
        op.add_bytes(4096)
        op.add_bytes(4096)
        done = op.finish("error", reason="peer closed")
        assert done["bytes_done"] == 8192
        assert done["recv_peer"] == 1
        snap = rec.snapshot()
        assert len(snap) == 1 and snap[0]["status"] == "error"
        assert snap[0]["end_ns"] >= snap[0]["start_ns"]

    def test_double_finish_is_noop(self):
        rec = fr.FlightRecorder(capacity=8)
        op = rec.start("x")
        first = op.finish("ok")
        second = op.finish("error")  # ignored
        assert second["status"] == "ok" == first["status"]
        assert len(rec.snapshot()) == 1

    def test_track_context_manager(self):
        fr.RECORDER.clear()
        with fr.track("op.ok", step=1) as flight:
            flight.add_bytes(10)
        with pytest.raises(ValueError):
            with fr.track("op.bad", step=2):
                raise ValueError("boom")
        by_op = {r["op"]: r for r in fr.snapshot() if r["op"].startswith("op.")}
        assert by_op["op.ok"]["status"] == "ok"
        assert by_op["op.ok"]["bytes_done"] == 10
        assert by_op["op.bad"]["status"] == "error"
        assert "boom" in by_op["op.bad"]["error"]

    def test_update_after_finish_ignored(self):
        rec = fr.FlightRecorder(capacity=8)
        op = rec.start("x")
        op.finish("ok")
        op.update(peer=9)
        op.add_bytes(10)
        assert "peer" not in rec.snapshot()[0]
        assert "bytes_done" not in rec.snapshot()[0]


class TestDump:
    def test_dump_without_sink_is_noop(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_FLIGHT_FILE", raising=False)
        rec = fr.FlightRecorder(capacity=4)
        rec.record("op")
        assert rec.dump("why") is None

    def test_dump_appends_meta_and_records(self, tmp_path, monkeypatch):
        path = tmp_path / "flight.jsonl"
        monkeypatch.setenv("TORCHFT_FLIGHT_FILE", str(path))
        rec = fr.FlightRecorder(capacity=8)
        rec.record("allreduce", status="error", step=3, replica_id="r1")
        open_op = rec.start("recv", replica_id="r1", src=0)
        assert rec.dump("collective failed", trigger="pg_abort") == str(path)
        # second dump appends (crash-durability: each trigger snapshots)
        assert rec.dump("again") == str(path)
        open_op.finish("ok")

        lines = [json.loads(l) for l in path.read_text().splitlines()]
        metas = [l for l in lines if l["flight"] == "meta"]
        recs = [l for l in lines if l["flight"] == "rec"]
        assert len(metas) == 2
        assert metas[0]["reason"] == "collective failed"
        assert metas[0]["trigger"] == "pg_abort"
        assert metas[0]["pid"] == os.getpid()
        # both dumps carried the error record AND the in-flight op
        assert sum(1 for r in recs if r["status"] == "error") == 2
        assert sum(1 for r in recs if r["status"] == "inflight") == 2

    def test_dump_rotates_at_max_bytes(self, tmp_path, monkeypatch):
        path = tmp_path / "flight.jsonl"
        monkeypatch.setenv("TORCHFT_FLIGHT_MAX_BYTES", "4096")
        rec = fr.FlightRecorder(capacity=64)
        for i in range(64):
            rec.record("op", step=i, payload="x" * 64)
        for _ in range(4):  # each dump ~64 records * ~130B > 4 KiB
            rec.dump("why", path=str(path))
        rotated = tmp_path / "flight.jsonl.1"
        assert rotated.exists(), "no rotation happened"
        # the live file was rotated, not truncated mid-line: both parse
        for p in (path, rotated):
            for line in p.read_text().splitlines():
                json.loads(line)

    def test_dump_counts_metric(self, tmp_path, monkeypatch):
        from torchft_tpu.utils import metrics

        path = tmp_path / "flight.jsonl"
        before = metrics.FLIGHT_DUMPS.labels(trigger="manual").get()
        rec = fr.FlightRecorder(capacity=4)
        rec.record("op")
        rec.dump("why", path=str(path))
        assert metrics.FLIGHT_DUMPS.labels(trigger="manual").get() == before + 1
        # no sink -> no metric movement
        monkeypatch.delenv("TORCHFT_FLIGHT_FILE", raising=False)
        rec.dump("why")
        assert metrics.FLIGHT_DUMPS.labels(trigger="manual").get() == before + 1


class TestSignalHook:
    def test_sigterm_dumps_in_subprocess(self, tmp_path):
        """A SIGTERM'd process (how schedulers kill replicas) must leave
        its flight ring on disk before dying with the signal."""
        path = tmp_path / "flight.jsonl"
        script = textwrap.dedent(
            f"""
            import os, signal, sys, time
            sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
            os.environ["TORCHFT_FLIGHT_FILE"] = {str(path)!r}
            from torchft_tpu.utils import flightrecorder as fr
            fr.record("train.step", step=7, replica_id="victim")
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(10)  # unreachable: SIGTERM must terminate us
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=60, capture_output=True
        )
        # died by SIGTERM (default disposition re-delivered after the dump)
        assert proc.returncode == -signal.SIGTERM, proc.stderr.decode()
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert any(
            l["flight"] == "meta" and l["trigger"] == "signal" for l in lines
        )
        assert any(
            l["flight"] == "rec" and l.get("step") == 7 for l in lines
        )


class TestHotPathBudget:
    def test_record_overhead_under_budget(self):
        """Acceptance bar: <= ~2 us per record() on the hot path.  Best of
        several batches so a loaded 1-core CI host doesn't flake the
        measurement; the implementation is one dict build + one lock +
        one slot assignment (~0.5-1 us typical).

        The bar is for the production configuration: the tier-1 harness
        runs with TORCHFT_LOCKCHECK=1 (conftest), whose instrumented
        locks deliberately trade ~3 us for order checking, so this
        recorder is built with the detector off."""
        from torchft_tpu.utils import lockcheck

        was = lockcheck.enabled()
        lockcheck.set_enabled(False)
        try:
            rec = fr.FlightRecorder(capacity=512)
        finally:
            lockcheck.set_enabled(was)
        n = 20_000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(n):
                rec.record(
                    "ring", step=i, quorum_id=1, replica_id="replica_0"
                )
            best = min(best, (time.perf_counter() - t0) / n)
        assert best <= 2.5e-6, f"record() hot path {best*1e6:.2f} us/record"
