"""utils/rwlock.py unit tests (previously untested): reader concurrency,
writer exclusion, timeout semantics, non-reentrancy documentation, and
contention under the lockcheck wrapper."""

import threading
import time

import pytest

from torchft_tpu.utils.rwlock import RWLock


class TestBasics:
    def test_readers_are_concurrent(self):
        rw = RWLock(timeout=5)
        inside = threading.Barrier(3, timeout=5)
        done = []

        def reader():
            with rw.r_lock():
                inside.wait()  # all 3 readers in the critical section at once
                done.append(1)

        threads = [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert done == [1, 1, 1]

    def test_writer_excludes_readers(self):
        rw = RWLock(timeout=5)
        rw.acquire_write()
        try:
            with pytest.raises(TimeoutError):
                rw.acquire_read(timeout=0.1)
        finally:
            rw.release_write()
        # released: reads flow again
        with rw.r_lock(timeout=1):
            pass

    def test_reader_excludes_writer(self):
        rw = RWLock(timeout=5)
        rw.acquire_read()
        try:
            with pytest.raises(TimeoutError):
                rw.acquire_write(timeout=0.1)
        finally:
            rw.release_read()
        with rw.w_lock(timeout=1):
            pass

    def test_writer_excludes_writer(self):
        rw = RWLock(timeout=5)
        with rw.w_lock():
            with pytest.raises(TimeoutError):
                rw.acquire_write(timeout=0.1)

    def test_release_read_without_acquire_asserts(self):
        rw = RWLock()
        with pytest.raises(AssertionError):
            rw.release_read()

    def test_read_reentrancy_from_same_thread(self):
        """Nested r_lock on one thread works while no writer waits (the
        reader count, not thread identity, gates the writer lock)."""
        rw = RWLock(timeout=2)
        with rw.r_lock():
            with rw.r_lock():
                pass
        # fully released: a writer can take it
        with rw.w_lock(timeout=1):
            pass

    def test_default_timeout_applies(self):
        rw = RWLock(timeout=0.1)
        rw.acquire_write()
        try:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                rw.acquire_write()  # uses the constructor default
            assert time.monotonic() - t0 < 5
        finally:
            rw.release_write()

    def test_acquire_read_timeout_bounds_total_wait(self):
        """The read acquisition crosses TWO mutexes; the timeout must
        bound the sum, not each stage."""
        rw = RWLock(timeout=5)
        rw.acquire_write()
        try:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError):
                rw.acquire_read(timeout=0.2)
            assert time.monotonic() - t0 < 2
        finally:
            rw.release_write()


class TestContention:
    def test_interleaved_readers_writers_converge(self):
        """8 readers + 2 writers hammering a shared counter: writers see
        exclusive access (no torn increments), readers never observe a
        mid-write value, everything terminates within timeouts.  Runs
        under the lockcheck wrapper when tier-1's TORCHFT_LOCKCHECK=1."""
        rw = RWLock(timeout=10)
        state = {"v": 0, "writing": False}
        errors = []

        def writer():
            for _ in range(20):
                with rw.w_lock():
                    state["writing"] = True
                    old = state["v"]
                    time.sleep(0.0005)
                    state["v"] = old + 1
                    state["writing"] = False

        def reader():
            for _ in range(40):
                with rw.r_lock():
                    if state["writing"]:
                        errors.append("read during write")

        threads = [threading.Thread(target=writer) for _ in range(2)] + [
            threading.Thread(target=reader) for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert state["v"] == 40

    def test_no_false_cycle_under_lockcheck(self):
        """The two-mutex dance (first reader takes the writer gate, last
        reader — possibly another thread — drops it) must not register a
        false reader<->writer cycle: the writer side is a lockcheck
        *gate*, hold-time instrumented but outside the order graph."""
        from torchft_tpu.utils import lockcheck

        if not lockcheck.enabled():
            pytest.skip("TORCHFT_LOCKCHECK disabled")
        lockcheck.reset()
        rw = RWLock(timeout=2)
        for _ in range(3):
            with rw.r_lock():
                pass
            with rw.w_lock():
                pass
        assert not any("rwlock" in n for c in lockcheck.cycles() for n in c)
        assert "rwlock.writer_gate" not in lockcheck.edges()
