"""Live distributed-tracing round trip (`make trace-smoke`).

Acceptance for the fleet-wide tracing leg: a real 2-replica +
lighthouse run (threads-as-replicas, the test_manager_integ pattern)
with a forced heal, read back ENTIRELY from the ``TORCHFT_TRACE_FILE``
span sink:

- ONE trace id per step across the fleet — both managers' ``quorum_round``
  roots, their phase children, and the native lighthouse's ``rpc.quorum``
  server span share the step's deterministic trace id;
- the heal's source and destination land in one trace, parented to the
  healing replica's root (``heal.send`` from the source's HTTP server,
  ``heal_recv`` phase from the destination);
- chaos variant: an injected ``manager.quorum`` fault marks the victim's
  span ``ok=false`` and ``torchft-diagnose --trace`` names the faulted
  replica from the trace file alone.
"""

import json
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List

import numpy as np
import pytest

from torchft_tpu.coordination import LighthouseServer
from torchft_tpu.manager import Manager, PROTOCOL_PHASES
from torchft_tpu.parallel.process_group import ProcessGroupTCP
from torchft_tpu.utils import faults, tracing
from torchft_tpu.utils.faults import FaultRule, InjectedFault


@pytest.fixture(autouse=True)
def clean_faults():
    faults.FAULTS.configure([], seed=0)
    yield
    faults.FAULTS.configure([])


@pytest.fixture
def trace_file(tmp_path, monkeypatch):
    """Install a file-sink tracer for the duration of one test; yields
    the sink path (spans are readable after uninstall closes it)."""
    path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("TORCHFT_TRACE_FILE", str(path))
    monkeypatch.delenv("TORCHFT_USE_OTEL", raising=False)
    tracing.uninstall_tracer()
    tracer = tracing.maybe_install_from_env()
    assert tracer is not None and tracer.sink is not None
    yield path
    tracing.uninstall_tracer()


@pytest.fixture
def lighthouse():
    server = LighthouseServer(
        min_replicas=2, join_timeout_ms=100, heartbeat_timeout_ms=1000
    )
    yield server
    server.shutdown()


def _train_replica(
    replica_id: int, lighthouse_addr: str, total_steps: int, attempts: int = 3
) -> dict:
    """One replica group running the toy DDP loop (fresh params per
    (re)start so a crash forces a real heal)."""
    last_exc = None
    for _ in range(attempts):
        try:
            return _train_once(replica_id, lighthouse_addr, total_steps)
        except InjectedFault as e:
            last_exc = e
            continue
    raise RuntimeError(f"replica {replica_id} exhausted attempts") from last_exc


def _train_once(replica_id: int, lighthouse_addr: str, total_steps: int) -> dict:
    params = {"w": np.zeros(4, dtype=np.float32)}

    def load_state_dict(sd):
        params["w"] = np.array(sd["params"]["w"])

    def state_dict():
        return {"params": {"w": params["w"].copy()}}

    pg = ProcessGroupTCP(timeout=10.0)
    manager = Manager(
        pg=pg,
        min_replica_size=1,
        load_state_dict=load_state_dict,
        state_dict=state_dict,
        lighthouse_addr=lighthouse_addr,
        replica_id=f"replica_{replica_id}",
        group_rank=0,
        group_world_size=1,
        timeout=20.0,
        quorum_timeout=20.0,
    )
    try:
        while manager.current_step() < total_steps:
            step = manager.current_step()
            faults.check(
                "train.step", replica=f"replica_{replica_id}", step=step
            )
            manager.start_quorum()
            grads = {"w": np.full(4, float(step + 1), dtype=np.float32)}
            avg = manager.allreduce(grads).wait(timeout=30)
            if manager.should_commit():
                params["w"] = params["w"] - 0.1 * avg["w"]
        return {"replica_id": replica_id, "w": params["w"].copy()}
    finally:
        manager.shutdown()


def _run_fleet(lighthouse, total_steps: int, n: int = 2) -> List[dict]:
    with ThreadPoolExecutor(max_workers=n) as ex:
        futs = [
            ex.submit(_train_replica, i, lighthouse.address(), total_steps)
            for i in range(n)
        ]
        return [f.result(timeout=120) for f in futs]


def _load_spans(path) -> List[dict]:
    return [
        json.loads(line)
        for line in path.read_text().splitlines()
        if line.strip()
    ]


def _base(rid: str) -> str:
    return rid.split(":", 1)[0]


class TestLiveRoundTrip:
    def test_one_trace_id_spans_fleet_and_heal(self, lighthouse, trace_file):
        faults.FAULTS.configure(
            [FaultRule(site="train.step", replica="replica_1", step=2)]
        )
        _run_fleet(lighthouse, total_steps=4)
        tracing.uninstall_tracer()  # flush/close the sink before reading
        spans = _load_spans(trace_file)
        assert spans, "file sink is empty"

        by_trace: Dict[str, List[dict]] = defaultdict(list)
        for s in spans:
            by_trace[s["trace_id"]].append(s)

        # --- one trace per step, spanning lighthouse + both managers ----
        roots_by_step: Dict[int, List[dict]] = defaultdict(list)
        for s in spans:
            if s["name"] == "quorum_round":
                roots_by_step[s["attributes"]["step"]].append(s)
        both = [
            step
            for step, roots in sorted(roots_by_step.items())
            if {_base(r["attributes"]["replica_id"]) for r in roots}
            >= {"replica_0", "replica_1"}
        ]
        assert both, f"no step has roots from both replicas: {roots_by_step}"
        step = both[-1]
        roots = roots_by_step[step]
        # deterministic derivation: every root of this step shares the id
        expected = tracing.step_trace_id(step)
        assert {r["trace_id"] for r in roots} == {expected}
        trace = by_trace[expected]
        # the native lighthouse served this step's quorum in the SAME trace
        lh = [
            s
            for s in trace
            if s["name"] == "rpc.quorum"
            and s["attributes"].get("server") == "lighthouse"
        ]
        assert lh, f"no lighthouse rpc.quorum span in step-{step} trace"
        # every root has phase children parented to it
        for root in roots:
            kids = [
                s for s in trace if s.get("parent_span_id") == root["span_id"]
            ]
            phase_names = {s["name"] for s in kids} & set(PROTOCOL_PHASES)
            assert phase_names, (
                f"root of {root['attributes']['replica_id']} has no phase "
                f"children"
            )
        # native manager server spans joined too (same trace)
        assert any(
            s["name"].startswith("rpc.")
            and s["attributes"].get("server") == "manager"
            for s in trace
        )

        # --- heal: source and destination spans in one trace ------------
        heal_sends = [s for s in spans if s["name"] == "heal.send"]
        assert heal_sends, "no heal.send span (forced heal did not trace)"
        root_by_span = {
            s["span_id"]: s for s in spans if s["name"] == "quorum_round"
        }
        parented = [
            s for s in heal_sends if s.get("parent_span_id") in root_by_span
        ]
        assert parented, "heal.send is not parented to any round root"
        send = parented[-1]
        dest_root = root_by_span[send["parent_span_id"]]
        assert send["trace_id"] == dest_root["trace_id"]
        # the destination's own heal_recv phase hangs off the same root
        dest_kids = {
            s["name"]
            for s in spans
            if s.get("parent_span_id") == dest_root["span_id"]
        }
        assert "heal_recv" in dest_kids, (
            f"destination root has children {dest_kids}, no heal_recv"
        )

    def test_store_rpcs_join_the_trace(self, lighthouse, trace_file):
        """PG configure's store barrier RPCs run inside the round: their
        rpc.* server spans (server=store) land in the step trace."""
        _run_fleet(lighthouse, total_steps=2)
        tracing.uninstall_tracer()
        spans = _load_spans(trace_file)
        assert any(
            s["attributes"].get("server") == "store"
            and s["name"].startswith("rpc.")
            for s in spans
        )


class TestChaosTrace:
    def test_faulted_round_marks_span_and_ledger_names_culprit(
        self, lighthouse, trace_file, capsys
    ):
        faults.FAULTS.configure(
            [FaultRule(site="manager.quorum", replica="replica_1", step=1)]
        )
        _run_fleet(lighthouse, total_steps=3)
        assert faults.FAULTS.injected() == 1
        tracing.uninstall_tracer()
        spans = _load_spans(trace_file)

        failed = [
            s
            for s in spans
            if s["name"] == "quorum_round" and not s.get("ok", True)
        ]
        assert failed, "no ok=false root span for the faulted round"
        assert all(
            _base(s["attributes"]["replica_id"]) == "replica_1" for s in failed
        )

        # the ledger names the culprit FROM THE TRACE FILE ALONE
        from torchft_tpu import diagnose

        rc = diagnose.main(["--trace", str(trace_file), "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        culprit = report["culprit"]
        assert culprit is not None
        assert culprit["signal"] == "trace_error"
        assert _base(culprit["replica_id"]) == "replica_1"
        ledger = report["trace_ledger"]
        assert ledger["steps"], "ledger has no steps"
        for row in ledger["steps"]:
            assert row["dominant"] in (
                "compute", "codec", "wire", "protocol", "straggler-wait",
            ) or row["dominant"] is None
        # healthy steps name a dominant contributor
        assert ledger["dominant_overall"] is not None
